"""GPT with SPMD pipeline parallelism: pp x mp x dp in one pjit program.

Mirrors the reference's PipelineLayer + 1F1B recipe (fleet/meta_parallel/
pp_layers.py, pipeline_parallel.py) the TPU way: the transformer body is
stacked per-stage parameters sharded over the 'pp' mesh axis, and the
schedule is a scan + ppermute micro-batch pipeline INSIDE one XLA program —
no per-stage processes or host-driven p2p.

Run on >= 2 devices:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python train_gpt_pipeline.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.models import GPTConfig, GPTForPretrainingPipe


def main():
    import jax

    n = jax.device_count()
    if n < 2:
        raise SystemExit("pipeline parallelism needs >= 2 devices "
                         "(set --xla_force_host_platform_device_count)")
    pp = 2
    mp = 2 if n % 4 == 0 else 1
    dp = n // (pp * mp)

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": pp, "mp_degree": mp, "dp_degree": dp}
    fleet.init(is_collective=True, strategy=strategy)
    print("topology:", fleet.get_hybrid_communicate_group().topology())

    paddle.seed(0)
    on_tpu = jax.default_backend() == "tpu"
    cfg = GPTConfig(vocab_size=50304 if on_tpu else 1024,
                    hidden_size=1024 if on_tpu else 128,
                    num_layers=24 if on_tpu else 4,
                    num_heads=16 if on_tpu else 4,
                    max_seq_len=1024 if on_tpu else 128,
                    dropout=0.0, attention_dropout=0.0)
    model = GPTForPretrainingPipe(cfg, num_microbatches=4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)

    rng = np.random.RandomState(0)
    batch = max(8, 4 * dp)
    batch += (-batch) % (4 * max(1, dp))  # micro-batches x dp must divide batch
    ids = rng.randint(0, cfg.vocab_size, (batch, cfg.max_seq_len)).astype(np.int64)
    labels = np.roll(ids, -1, 1)

    with paddle.amp.auto_cast(enable=on_tpu, dtype="bfloat16"):
        for step in range(6):
            loss = engine.step(paddle.to_tensor(ids), paddle.to_tensor(labels))
            if step % 2 == 0:
                print(f"step {step}: loss {float(loss.item()):.4f}")


if __name__ == "__main__":
    main()

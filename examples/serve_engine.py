"""Continuous-batching inference with the serving engine (CPU, hermetic).

Mixed traffic — varied prompt lengths, mixed greedy/sampling configs, an
early-EOS request — served through TWO resident executables per shape
class (bucketed prefill + single-token decode step) instead of one
monolithic compile per request shape. Telemetry (TTFT, tokens/s, slot
occupancy, queue depth) streams through a StepTelemetry-style sink.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPTForPretraining, gpt_tiny
from paddle_tpu.observability import InMemorySink
from paddle_tpu.serving import ServingEngine


def main():
    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    model.eval()
    rng = np.random.RandomState(0)

    sink = InMemorySink()
    engine = ServingEngine(model, slot_count=3, ladder=(8, 16, 32),
                           max_new_cap=16, steps_per_dispatch=4, sink=sink)

    # probe an eos token greedy decoding actually emits -> early completion
    short = rng.randint(0, 1024, (5,)).astype(np.int64)
    eos = int(model.generate(paddle.to_tensor(short[None]), max_new_tokens=3,
                             temperature=0).numpy()[0, -1])

    reqs = [
        engine.submit(short, max_new_tokens=12, temperature=0.0,
                      eos_token_id=eos),                       # retires early
        engine.submit(rng.randint(0, 1024, (7,)).astype(np.int64),
                      max_new_tokens=8, temperature=0.0),      # greedy
        engine.submit(rng.randint(0, 1024, (13,)).astype(np.int64),
                      max_new_tokens=8, temperature=0.8, top_k=50, seed=7),
        engine.submit(rng.randint(0, 1024, (21,)).astype(np.int64),
                      max_new_tokens=8, temperature=0.9, top_p=0.85, seed=3),
        engine.submit(rng.randint(0, 1024, (9,)).astype(np.int64),
                      max_new_tokens=8, temperature=0.0),      # queued: 4th
    ]
    engine.run()

    for r in reqs:
        print(f"req {r.id}: prompt {len(r.prompt_ids)} -> bucket {r.bucket}, "
              f"{len(r.tokens)} tokens ({r.finish_reason}), "
              f"ttft {r.ttft_s * 1e3:.1f} ms: {r.tokens[:6]}")
    recs = [x for x in sink.records if x["event"] == "serve_request"]
    stats = engine.stats()
    assert all(r.done for r in reqs) and len(recs) == len(reqs)
    assert reqs[0].finish_reason == "eos"
    print(f"executables: {stats['prefill_executables']} prefill "
          f"(ladder {stats['ladder']}) + {stats['decode_executables']} "
          f"decode for {len(reqs)} mixed requests")
    print("serving ok:", stats["completed"], "requests,",
          stats["steps"], "decode steps")


if __name__ == "__main__":
    main()

"""GPT pretraining with hybrid parallelism: dp x mp (x sharding) over the mesh.

Mirrors the reference's fleet hybrid-parallel GPT recipe: strategy declares the
topology, mp_layers give every parameter its PartitionSpec, and the whole train
step (fwd+bwd+clip+AdamW) compiles to ONE donated pjit program — GSPMD inserts
the collectives the reference codes as c_allreduce/c_identity ops.

Run on N devices (virtual CPU mesh works too):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python train_gpt_hybrid.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.models import GPTConfig, GPTForPretraining


def main():
    import jax

    n = jax.device_count()
    mp = 2 if n % 2 == 0 and n > 1 else 1
    dp = n // mp

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    print("topology:", hcg.topology())

    paddle.seed(0)
    on_tpu = jax.default_backend() == "tpu"
    cfg = GPTConfig(vocab_size=50304 if on_tpu else 1024,
                    hidden_size=768 if on_tpu else 128,
                    num_layers=12 if on_tpu else 2,
                    num_heads=12 if on_tpu else 4,
                    max_seq_len=1024 if on_tpu else 128)
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), weight_decay=0.01,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    engine = fleet.distributed_engine(model, opt)

    rng = np.random.RandomState(0)
    batch, seq = max(8, 2 * dp), cfg.max_seq_len
    batch += (-batch) % dp  # round up: the batch dim shards over dp
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = np.roll(ids, -1, 1)

    with paddle.amp.auto_cast(enable=on_tpu, dtype="bfloat16"):
        for step in range(10):
            loss = engine.step(paddle.to_tensor(ids), paddle.to_tensor(labels))
            if step % 2 == 0:
                print(f"step {step}: loss {float(loss.item()):.4f}")


if __name__ == "__main__":
    main()

"""Dygraph quickstart: LeNet on MNIST (synthetic fallback), save/load.

Mirrors the reference's dygraph MNIST tutorial: eager per-op execution with
the autograd tape, a multiprocess-capable DataLoader, and paddle.save/load.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def main():
    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    train = DataLoader(MNIST(mode="train", size=512), batch_size=64, shuffle=True)
    for epoch in range(3):
        losses = []
        for imgs, labels in train:
            loss = loss_fn(model(imgs), labels.squeeze(-1))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    paddle.save(model.state_dict(), "/tmp/lenet.pdparams")
    model2 = LeNet()
    model2.set_state_dict(paddle.load("/tmp/lenet.pdparams"))

    imgs, labels = next(iter(train))
    pred = model2(imgs).argmax(-1)
    acc = float((pred == labels.squeeze(-1)).astype("float32").mean().item())
    print(f"reloaded model batch accuracy: {acc:.2%}")


if __name__ == "__main__":
    main()

"""Static graph + the C++ data pipeline: Program IR, InMemoryDataset,
Executor.train_from_dataset (the reference's trainer/device-worker flow).
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import static


def write_data(path, rows, seed):
    """MultiSlot text format: '<n> v1..vn' per slot per line (x: 4 floats,
    y: 1 float)."""
    rs = np.random.RandomState(seed)
    w = np.array([0.5, -1.0, 2.0, 0.25])
    with open(path, "w") as f:
        for _ in range(rows):
            x = rs.rand(4)
            y = float(x @ w + 0.1)
            f.write("4 " + " ".join(f"{v:.4f}" for v in x) + f" 1 {y:.5f}\n")


def main():
    tmp = tempfile.mkdtemp()
    for i in range(4):
        write_data(os.path.join(tmp, f"part-{i}"), 64, i)

    ds = dist.InMemoryDataset()
    ds.init(batch_size=16, thread_num=4, use_var=[("x", "f"), ("y", "f")])
    ds.set_filelist([os.path.join(tmp, f"part-{i}") for i in range(4)])
    ds.load_into_memory()          # C++ multithreaded parse
    ds.global_shuffle(seed=0)
    print("loaded rows:", ds.get_memory_data_size())

    main_prog, startup = static.Program(), static.Program()
    with static.program_guard(main_prog, startup):
        x = static.data("x", [-1, 4], "float32")
        y = static.data("y", [-1, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - y) ** 2)
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    for epoch in range(20):
        out = exe.train_from_dataset(main_prog, ds, fetch_list=[loss],
                                     fetch_info=["mse"], print_period=0)
    print("final mse:", float(out[0]))
    infer = exe.infer_from_dataset(main_prog, ds, fetch_list=[loss],
                                   print_period=0)
    print("eval mse (no update):", float(infer[0]))


if __name__ == "__main__":
    main()

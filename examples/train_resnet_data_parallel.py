"""ResNet DataParallel: fleet engine with a separate loss_fn.

Single process uses every visible device as the dp axis; under
`python -m paddle_tpu.distributed.launch --nproc_per_node N` each process owns
one device and the mesh spans processes (gloo store rendezvous on CPU).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def main():
    import jax

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": jax.device_count()}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    model = paddle.vision.models.resnet18(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.02, momentum=0.9,
                                    parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt,
                                      loss_fn=paddle.nn.CrossEntropyLoss())

    rng = np.random.RandomState(0)
    batch = 8 * jax.device_count()
    imgs = paddle.to_tensor(rng.randn(batch, 3, 32, 32).astype(np.float32))
    labels = paddle.to_tensor(rng.randint(0, 10, (batch,)).astype(np.int64))
    for step in range(8):
        loss = engine.step(imgs, labels)
        if step % 2 == 0:
            print(f"[rank {dist.get_rank()}] step {step}: "
                  f"loss {float(loss.item()):.4f}")


if __name__ == "__main__":
    main()

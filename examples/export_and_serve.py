"""Deploy pipeline: jit.save -> portable StableHLO artifact -> Predictor.

Mirrors the reference's jit.save + AnalysisPredictor flow: the artifact
(.pdmodel = serialized StableHLO + meta, .pdiparams = weights) loads and runs
WITHOUT the model's Python class — the XLA program is the model.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.static import InputSpec
from paddle_tpu.vision.models import LeNet


def main():
    paddle.seed(0)
    model = LeNet()
    model.eval()
    path = "/tmp/lenet_infer"
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([4, 1, 28, 28], "float32")])
    print("exported:", path + ".pdmodel")

    # ---- serve (no model code needed) ----
    cfg = Config(path + ".pdmodel", path + ".pdiparams")
    predictor = create_predictor(cfg)
    in_name = predictor.get_input_names()[0]
    out_name = predictor.get_output_names()[0]

    imgs = np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32)
    handle = predictor.get_input_handle(in_name)
    handle.copy_from_cpu(imgs)
    predictor.run()
    logits = predictor.get_output_handle(out_name).copy_to_cpu()
    print("served logits shape:", logits.shape)

    # parity with the in-process model
    ref = model(paddle.to_tensor(imgs)).numpy()
    np.testing.assert_allclose(logits, ref, rtol=2e-4, atol=2e-4)
    print("predictor output matches eager forward")


if __name__ == "__main__":
    main()

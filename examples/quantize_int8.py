"""Int8 quantization walkthrough: QAT fine-tune -> convert -> calibrated PTQ.

Run: python examples/quantize_int8.py  (CPU or TPU)

Covers the three deployment modes of paddle_tpu.incubate.quantization:
1. quantization-aware training (fake-quant noise, straight-through grads),
2. conversion of the QAT model to true int8 layers,
3. calibration-based post-training quantization of an untouched model.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate.quantization import (ImperativeQuantAware,
                                              PostTrainingQuantization,
                                              QuantizedLinear)


def make_net():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))


def main():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 16).astype("float32"))
    target = paddle.to_tensor(rng.randn(64, 4).astype("float32"))

    # --- 1) QAT: train WITH int8 grid noise ------------------------------
    net = make_net()
    qat = ImperativeQuantAware()
    qat.quantize(net)
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=net.parameters())
    net.train()
    for step in range(40):
        loss = ((net(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    print(f"qat final loss: {float(loss.item()):.4f}")

    # --- 2) convert to true int8 (static scales from the QAT run) --------
    net.eval()
    ref = net(x).numpy()
    qat.convert(net, mode="static_int8")
    assert isinstance(net[0], QuantizedLinear)
    drift = np.abs(net(x).numpy() - ref).mean() / (np.abs(ref).mean() + 1e-9)
    print(f"int8 conversion drift vs qat model: {drift:.4f}")

    # --- 3) calibrated PTQ on an untouched float model -------------------
    fresh = make_net()
    ptq = PostTrainingQuantization(fresh)
    for i in range(4):  # representative batches
        ptq.collect(paddle.to_tensor(rng.randn(32, 16).astype("float32")))
    q = ptq.convert(mode="static_int8")
    print(f"ptq calibrated {len(ptq.scales)} layers; "
          f"scales: {sorted(round(v, 4) for v in ptq.scales.values())}")
    out = q(x)
    print(f"ptq int8 output shape ok: {tuple(out.shape)}")


if __name__ == "__main__":
    main()

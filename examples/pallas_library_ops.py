"""Direct-call Pallas library ops: the retired-but-retained kernels.

Round 5 retired the online LM-head cross-entropy and fused LayerNorm Pallas
kernels from the TRAINING path (BASELINE.md: compile pathology / no measured
headroom against the 91 TFLOP/s chunked fused-CE) — but both remain in the
library as direct-call ops with pinned math. This example is their living
caller (VERDICT r5 next #6): it invokes each against a dense reference, in
Pallas interpret mode on CPU (automatic — `ops/pallas/_common.interpret()`)
and as real Mosaic kernels on a TPU.

    JAX_PLATFORMS=cpu python examples/pallas_library_ops.py
"""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.layer_norm import layer_norm
from paddle_tpu.ops.pallas.lm_loss import lm_head_cross_entropy, supported


def main():
    rng = np.random.RandomState(0)

    # ---- online LM-head cross-entropy (block-n tiled over vocab) ----
    # shapes must satisfy supported(); block_n=256 is the documented safe
    # default (1024 is the recorded Mosaic compile hazard at bench vocab —
    # see the lm_head_cross_entropy docstring before raising it)
    N, V, H = 1024, 1024, 128   # N must tile the 1024-wide 1D row blocks
    assert supported(N, V, H)
    h = jnp.asarray(rng.randn(N, H).astype(np.float32))
    w = jnp.asarray((rng.randn(V, H) * 0.05).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))

    loss = lm_head_cross_entropy(h, w, labels, block_n=256)
    logits = h @ w.T
    ref = (jax.nn.logsumexp(logits, axis=-1)
           - logits[jnp.arange(N), labels])
    err = float(jnp.abs(loss - ref).max())
    assert err < 1e-3, err
    # the kernel differentiates through its custom vjp like any op
    g_h = jax.grad(lambda a: lm_head_cross_entropy(
        a, w, labels, block_n=256).mean())(h)
    assert g_h.shape == h.shape
    print(f"lm_head_cross_entropy ok: mean loss {float(loss.mean()):.4f}, "
          f"max |kernel - dense| {err:.2e}")

    # ---- fused LayerNorm ----
    B, S, Hd = 4, 64, 256
    x = jnp.asarray(rng.randn(B, S, Hd).astype(np.float32))
    weight = jnp.asarray(1.0 + 0.1 * rng.randn(Hd).astype(np.float32))
    bias = jnp.asarray(0.1 * rng.randn(Hd).astype(np.float32))

    out = layer_norm(x, weight, bias)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    ref_ln = (x - mu) / jnp.sqrt(var + 1e-5) * weight + bias
    err_ln = float(jnp.abs(out - ref_ln).max())
    assert err_ln < 1e-4, err_ln
    g_x = jax.grad(lambda a: layer_norm(a, weight, bias).sum())(x)
    assert g_x.shape == x.shape
    print(f"pallas layer_norm ok: max |kernel - dense| {err_ln:.2e} "
          f"(backend={jax.default_backend()}, interpret on cpu)")


if __name__ == "__main__":
    main()

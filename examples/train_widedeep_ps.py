"""Wide&Deep in parameter-server mode: C++ sparse tables + dense compute.

Mirrors the reference's fleet PS workflow: servers host sharded embedding
tables behind a TCP service (core/native/ps_table.cc); trainers pull/push
sparse rows around the dense train step.

Launch a real 1-server + 1-trainer pod on this host:

  python -m paddle_tpu.distributed.launch --server_num 1 --trainer_num 1 \
      examples/train_widedeep_ps.py

Standalone (no launcher env) it self-hosts an in-process server — the
reference's ps_local_client mode.
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (DistributedEmbedding, PSClient,
                                       PSServer, SparseTableConfig,
                                       TheOnePSRuntime)

TABLES = [
    SparseTableConfig(table_id=0, dim=1, learning_rate=0.1),   # wide
    SparseTableConfig(table_id=1, dim=8, learning_rate=0.1),   # deep
]


def train(client, barrier=None):
    from paddle_tpu.models import WideDeep

    paddle.seed(0)
    model = WideDeep(sparse_feature_dim=100000, embedding_dim=8, num_fields=8,
                     dense_dim=4, use_ps=True, client=client)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    bce = paddle.nn.BCEWithLogitsLoss()

    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 100000, (32, 8)).astype(np.int64)
    dense_np = rng.rand(32, 4).astype(np.float32)
    lab_np = ((ids_np.sum(1) % 3 == 0)[:, None]).astype(np.float32)
    for step in range(10):
        ids = paddle.to_tensor(ids_np)
        dense = paddle.to_tensor(dense_np)
        labels = paddle.to_tensor(lab_np)
        loss = bce(model(ids, dense), labels)
        loss.backward()     # sparse grads push to the tables
        opt.step()          # dense params update locally
        opt.clear_grad()
        if step % 2 == 0:
            print(f"step {step}: loss {float(loss.item()):.4f}")


def main():
    if os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST"):
        # launcher mode: real multi-process pod
        runtime = TheOnePSRuntime(sparse_tables=TABLES)
        if runtime.is_server():
            runtime.init_server()
            runtime.run_server()
            return
        client = runtime.init_worker()
        train(client)
        runtime.barrier_worker(generation=1)
        runtime.stop_worker()
    else:
        # standalone: in-process server (reference ps_local_client analogue)
        server = PSServer(0, TABLES, [])
        client = PSClient([f"127.0.0.1:{server.port}"])
        for t in TABLES:
            client.register_table_dim(t.table_id, t.dim)
        try:
            train(client)
        finally:
            client.close()
            server.stop()


if __name__ == "__main__":
    main()

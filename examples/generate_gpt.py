"""Text generation: KV-cache autoregressive decode + ONNX export.

Mirrors the reference's generation/deploy workflow: train (briefly), decode
with the cached sampler (one compiled prefill+scan program), and export the
model to ONNX — all hermetic (random weights, tiny config).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPTForPretraining, gpt_tiny


def main():
    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    # a few steps so decode isn't pure noise
    rng = np.random.RandomState(0)
    for step in range(3):
        ids = rng.randint(0, 1024, (4, 32)).astype(np.int64)
        labels = np.roll(ids, -1, 1)
        loss = model(paddle.to_tensor(ids), paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        print(f"step {step}: loss {float(loss.item()):.4f}")

    model.eval()
    prompt = rng.randint(0, 1024, (2, 8)).astype(np.int64)
    greedy = model.generate(paddle.to_tensor(prompt), max_new_tokens=16,
                            temperature=0)
    sampled = model.generate(paddle.to_tensor(prompt), max_new_tokens=16,
                             temperature=0.8, top_k=50, seed=7)
    print("greedy  :", greedy.numpy()[0, 8:].tolist())
    print("sampled :", sampled.numpy()[0, 8:].tolist())
    assert greedy.shape == [2, 24] and sampled.shape == [2, 24]
    print("decode ok: prompt", prompt.shape, "->", list(greedy.shape))


if __name__ == "__main__":
    main()

"""FasterTokenizer (C++ wordpiece, core/native/tokenizer.cc) vs the Python
fallback and reference semantics (faster_tokenizer_op.h BertTokenizer)."""
import numpy as np
import pytest

from paddle_tpu.text import FasterTokenizer
from paddle_tpu.text.faster_tokenizer import (_NativeTok, _basic_tokenize,
                                              wordpiece_tokenize)

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown", "fox",
         "jump", "##ed", "##s", "over", "lazy", "dog", "!", ",", "a",
         "un", "##aff", "##able", "你", "好", "caf", "##e"]


@pytest.fixture(scope="module")
def tok():
    return FasterTokenizer(VOCAB)


def test_native_backend_built(tok):
    assert tok._native is not None, "C++ tokenizer should build in this image"


def test_basic_wordpiece(tok):
    ids, tt = tok("The quick brown fox jumped over the lazy dog!")
    row = ids.numpy()[0].tolist()
    v = {t: i for i, t in enumerate(VOCAB)}
    assert row[0] == v["[CLS]"] and row[-1] == v["[SEP]"]
    assert row[1:-1] == [v[t] for t in
                         ["the", "quick", "brown", "fox", "jump", "##ed",
                          "over", "the", "lazy", "dog", "!"]]
    assert (tt.numpy() == 0).all()


def test_unknown_word_collapses_to_unk(tok):
    ids, _ = tok("the zebra")
    v = {t: i for i, t in enumerate(VOCAB)}
    assert ids.numpy()[0].tolist() == [v["[CLS]"], v["the"], v["[UNK]"], v["[SEP]"]]


def test_cjk_isolated_and_accent_fold(tok):
    ids, _ = tok("Café 你好")  # Café 你好
    v = {t: i for i, t in enumerate(VOCAB)}
    assert ids.numpy()[0].tolist() == [
        v["[CLS]"], v["caf"], v["##e"], v["你"], v["好"], v["[SEP]"]]


def test_pairs_truncation_padding(tok):
    ids, tt = tok(["the quick fox", "a dog"],
                  text_pair=["over a lazy dog", "the fox"],
                  max_seq_len=10, pad_to_max_seq_len=True)
    assert list(ids.shape) == [2, 10] and list(tt.shape) == [2, 10]
    a, b = ids.numpy(), tt.numpy()
    v = {t: i for i, t in enumerate(VOCAB)}
    # row 1: [CLS] a dog [SEP] the fox [SEP] + pad
    assert a[1].tolist()[:7] == [v["[CLS]"], v["a"], v["dog"], v["[SEP]"],
                                 v["the"], v["fox"], v["[SEP]"]]
    assert (a[1][7:] == v["[PAD]"]).all()
    assert b[1].tolist()[:7] == [0, 0, 0, 0, 1, 1, 1]
    # truncation respected
    assert (np.sum(a[0] != v["[PAD]"])) <= 10


def test_native_matches_python_fallback(tok):
    texts = ["The QUICK brown fox!", "unaffable", "café, 你好 dog",
             "the the the", "", "zebra unaffable !"]
    v = tok.vocab
    for t in texts:
        native = tok._native.tokenize(t)
        py = []
        for w in _basic_tokenize(t, True):
            py.extend(wordpiece_tokenize(w, v, tok.unk_id))
        assert native == py, (t, native, py)


def test_native_matches_python_on_exotic_unicode(tok):
    """ADVICE r1 (medium): the python fallback's whitespace/fold predicates
    must mirror the C++ tables EXACTLY — str.isspace() covers U+1680/U+205F/
    U+2029 etc. which the C++ is_ws does not, silently producing different
    token ids per machine. Sweep the divergence-prone codepoints."""
    exotic = ["a\u1680b", "a\u205fb", "a\u2028b", "a\u2029b", "a\u2007b",
              "a\u200ab", "a\u3000b", "a\x0bb", "a\x0cb", "a\x85b",
              "\u0391\u0392 \u03b1\u03b2",   # Greek upper/lower
              "\u0416\u0423 \u0436\u0443",   # Cyrillic upper/lower
              "\u0130stanbul \u0131",          # Turkish dotted/dotless I
              "\ufb01 \ufb02 ligatures",       # fi/fl ligature codepoints
              "caf\xe9 CAF\xc9 \xdcber",      # Latin-1 fold targets
              "\uff21\uff22\uff1a\uff23",    # fullwidth forms
              "a\u200bb", "a\ufeffb"]          # zero-width space / BOM
    v = tok.vocab
    for t in exotic:
        native = tok._native.tokenize(t)
        py = []
        for w in _basic_tokenize(t, True):
            py.extend(wordpiece_tokenize(w, v, tok.unk_id))
        assert native == py, (t, native, py)


def test_wordpiece_greedy_longest():
    v = {t: i for i, t in enumerate(VOCAB)}
    assert wordpiece_tokenize("unaffable", v, 1) == [v["un"], v["##aff"], v["##able"]]
    assert wordpiece_tokenize("jumps", v, 1) == [v["jump"], v["##s"]]
    assert wordpiece_tokenize("x" * 200, v, 1) == [1]  # max_chars -> unk


def test_tokenizer_feeds_ernie():
    """The reference's faster_tokenizer->ERNIE pipeline: text in, encoder out."""
    import paddle_tpu as paddle
    from paddle_tpu.models.ernie import ErnieConfig, ErnieModel

    paddle.seed(0)
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok(["the quick brown fox", "你 好 dog"],
                  max_seq_len=16, pad_to_max_seq_len=True)
    cfg = ErnieConfig(vocab_size=len(VOCAB), hidden_size=32, num_layers=2,
                      num_heads=2, max_seq_len=16)
    model = ErnieModel(cfg)
    seq_out, pooled = model(ids, token_type_ids=tt)
    assert list(seq_out.shape) == [2, 16, 32]
    assert list(pooled.shape) == [2, 32]


def test_dict_vocab_ids_preserved():
    """Caller-assigned ids (gaps, non-zero base) must survive — both backends."""
    v = {"[PAD]": 0, "[UNK]": 100, "[CLS]": 7, "[SEP]": 9, "hello": 7007}
    tok = FasterTokenizer(v)
    ids, _ = tok("hello zzz")
    assert ids.numpy()[0].tolist() == [7, 7007, 100, 9]
    if tok._native is not None:
        assert tok._native.tokenize("hello") == [7007]


def test_max_seq_len_too_small_raises():
    tok = FasterTokenizer(VOCAB)
    with pytest.raises(ValueError, match="cannot hold"):
        tok("a", text_pair="dog", max_seq_len=2)

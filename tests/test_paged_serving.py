"""Paged KV cache + radix prefix reuse + replica router (ISSUE 13).

The contracts that must never drift:
- numerics: the paged layout is token-identical to the contiguous engine
  (greedy AND sampled — sampling keys on (seed, position), not layout),
  under prefix hits, pool-pressure eviction, and int8 page quantization;
- reuse: a cached full prefix skips prefill entirely (replay seat), a
  partial hit prefills only the unshared tail at its small rung, and
  eviction can only take refcount-zero pages — never a live slot's;
- fleet: the router stops admitting to a draining replica immediately
  while its active slots finish, and no request is lost.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.models import GPTForPretraining, gpt_tiny
from paddle_tpu.serving import (
    PagePool, PoolExhausted, RadixPrefixCache, ReplicaRouter, ServingEngine,
)
from paddle_tpu.serving.kv_pages import (
    RESERVED_PAGES, quantize_kv_int8, resolve_store_dtype,
)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


def _counter(name):
    return monitor.registry().report().get(name, {}).get("value", 0)


def _paged(model, pool_pages=None, dtype=None, **kw):
    kw.setdefault("slot_count", 3)
    kw.setdefault("ladder", (8, 16, 32))
    kw.setdefault("max_new_cap", 8)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("steps_per_dispatch", 4)
    return ServingEngine(model, kv_layout="paged", kv_page_tokens=8,
                         kv_num_pages=pool_pages, kv_cache_dtype=dtype, **kw)


def _dense(model, **kw):
    kw.setdefault("slot_count", 3)
    kw.setdefault("ladder", (8, 16, 32))
    kw.setdefault("max_new_cap", 8)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("steps_per_dispatch", 4)
    return ServingEngine(model, **kw)


def _mixed_work(rng, n=6):
    """Half greedy, half sampled — sampled must also be layout-invariant."""
    work = []
    for i in range(n):
        plen = int(rng.choice([5, 8, 11, 14, 17, 23]))
        work.append({
            "prompt": rng.randint(0, 1024, (plen,)).astype(np.int64),
            "temperature": 0.0 if i % 2 == 0 else 0.8,
            "top_k": 0 if i % 2 == 0 else 50,
            "seed": 1000 + i,
        })
    return work


def _run(eng, work, max_new=5):
    reqs = [eng.submit(w["prompt"], max_new_tokens=max_new,
                       temperature=w["temperature"], top_k=w["top_k"],
                       seed=w["seed"]) for w in work]
    eng.run()
    return [list(r.output_ids()) for r in reqs]


# ------------------------------------------------------------ allocator
def test_page_pool_refcount_lifecycle():
    pool = PagePool(8)
    assert pool.free_count == 8 - RESERVED_PAGES
    a = pool.alloc()
    b = pool.alloc()
    assert a >= RESERVED_PAGES and b != a
    pool.incref(a)
    pool.decref(a)
    pool.decref(a)
    pool.release(a)          # refcount hit 0 -> releasable
    assert pool.free_count == 8 - RESERVED_PAGES - 1
    with pytest.raises(RuntimeError):
        pool.release(b)      # still referenced: not releasable
    while pool.free_count:
        pool.alloc()
    with pytest.raises(PoolExhausted):
        pool.alloc()


def test_pool_exhaustion_is_loud(model):
    """An engine whose pool can never fit one request must raise, not hang."""
    eng = _paged(model, pool_pages=RESERVED_PAGES + 1)
    eng.submit(np.arange(16, dtype=np.int64), max_new_tokens=4,
               temperature=0.0)
    with pytest.raises(PoolExhausted):
        eng.run()


# ----------------------------------------------------------- radix trie
def test_radix_trie_match_insert_evict():
    pool = PagePool(16)
    trie = RadixPrefixCache(pool, page_tokens=4)
    toks = list(range(12))
    pages = [pool.alloc() for _ in range(3)]
    trie.insert(toks, pages)
    for p in pages:          # trie holds weakly: caller's ref is dropped
        trie.release(p)
    assert pool.cached == 3 and pool.in_use == 0
    # peek has no side effects; match increfs the whole path
    assert trie.peek(toks) == 12
    assert pool.in_use == 0
    got = trie.match(toks[:8] + [99, 98])
    assert got == pages[:2]
    assert pool.in_use == 2 and pool.cached == 1
    # only the refcount-zero leaf is evictable; the live path never is
    assert trie.evict(3) == 1
    assert trie.peek(toks) == 8
    for p in pages[:2]:
        trie.release(p)
    assert trie.evict(4) == 2 and pool.cached == 0
    assert trie.peek(toks) == 0


def test_quantize_kv_int8_roundtrip_bound():
    rng = np.random.RandomState(0)
    x = rng.randn(6, 8, 4, 16).astype(np.float32) * 3.0
    q, scale = quantize_kv_int8(x)
    assert q.dtype == np.int8 and scale.shape == x.shape[:-1]
    err = np.abs(np.asarray(q, np.float32) * np.asarray(scale)[..., None] - x)
    # absmax/127 per (…, head) group: half a quantization step + rounding
    bound = np.abs(x).max(-1) / 127 * 0.5 + 1e-6
    assert (err <= bound[..., None] + 1e-6).all()
    assert resolve_store_dtype("auto", np.float32)[1] is False
    assert resolve_store_dtype("int8", np.float32)[1] is True


# ------------------------------------------------------------- numerics
def test_paged_matches_contiguous_greedy_and_sampled(model):
    """Acceptance: token-identical output across layouts on a mixed
    greedy+sampled workload."""
    work = _mixed_work(np.random.RandomState(2))
    ref = _run(_dense(model), work)
    got = _run(_paged(model), work)
    assert got == ref


def test_prefix_full_hit_skips_prefill(model):
    """A page-aligned repeat prompt replays from cached pages: zero prefill
    dispatches, one prefill skip, tokens identical to the dense engine."""
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 1024, (16,)).astype(np.int64)  # 2 full pages
    eng = _paged(model)
    dense = _dense(model)

    def once(e, seed):
        r = e.submit(prompt, max_new_tokens=5, temperature=0.0, seed=seed)
        e.run()
        return list(r.output_ids())

    first = once(eng, 7)
    d0, s0 = _counter("serving.prefill_dispatches"), \
        _counter("serving.prefill_skips")
    second = once(eng, 7)
    assert _counter("serving.prefill_dispatches") == d0, \
        "full prefix hit still dispatched a prefill"
    assert _counter("serving.prefill_skips") == s0 + 1
    assert first == second == once(dense, 7)
    assert eng.stats()["prefix"]["full_hits"] >= 1


def test_partial_hit_prefills_only_tail(model):
    """Shared prefix + fresh suffix: exactly one prefill dispatch (the
    unshared tail at its small rung), tokens still layout-identical."""
    rng = np.random.RandomState(4)
    prefix = rng.randint(0, 1024, (16,)).astype(np.int64)
    sfx_a = rng.randint(0, 1024, (4,)).astype(np.int64)
    sfx_b = rng.randint(0, 1024, (4,)).astype(np.int64)
    eng, dense = _paged(model), _dense(model)

    def once(e, sfx):
        r = e.submit(np.concatenate([prefix, sfx]), max_new_tokens=4,
                     temperature=0.0)
        e.run()
        return list(r.output_ids())

    once(eng, sfx_a)
    d0 = _counter("serving.prefill_dispatches")
    got = once(eng, sfx_b)
    assert _counter("serving.prefill_dispatches") == d0 + 1
    assert eng.stats()["prefix"]["partial_hits"] >= 1
    assert got == once(dense, sfx_b)


def test_eviction_never_corrupts_live_slots(model):
    """A pool sized to force LRU eviction of cached prefixes mid-workload
    must still produce exactly the unconstrained engine's tokens."""
    rng = np.random.RandomState(5)
    work = _mixed_work(rng, n=8)
    ref = _run(_paged(model), work)
    small = _paged(model, pool_pages=RESERVED_PAGES + 9)
    got = _run(small, work)
    assert got == ref
    assert small.stats()["prefix"]["evicted_pages"] > 0, (
        "pool was not small enough to exercise eviction")


def test_int8_pages_bounded_error_and_smaller_cache(model):
    """kv_cache_dtype=int8 quarters the pool bytes; per-page scales keep
    greedy decoding on the tiny model token-identical to f32 pages."""
    rng = np.random.RandomState(6)
    work = [{"prompt": rng.randint(0, 1024, (n,)).astype(np.int64),
             "temperature": 0.0, "top_k": 0, "seed": 0}
            for n in (5, 9, 14, 20)]
    f32 = _paged(model)
    q8 = _paged(model, dtype="int8")
    assert _run(q8, work) == _run(f32, work)
    assert q8.kv_cache_bytes() < f32.kv_cache_bytes() / 2
    bf16 = _paged(model, dtype="bf16")
    assert _run(bf16, work, max_new=3)  # completes; numerics are cast-level


# ---------------------------------------------------------------- fleet
def test_router_drains_replica_to_zero_admissions(model):
    rng = np.random.RandomState(8)
    prefix = rng.randint(0, 1024, (16,)).astype(np.int64)
    prompts = [np.concatenate(
        [prefix, rng.randint(0, 1024, (4,)).astype(np.int64)])
        for _ in range(8)]
    router = ReplicaRouter({"a": _paged(model, slot_count=2),
                            "b": _paged(model, slot_count=2)})
    reqs = [router.submit(p, max_new_tokens=4, temperature=0.0)
            for p in prompts[:4]]
    router.step()
    routed_a = router.routed["a"]
    replaced = router.begin_drain("a")
    more = [router.submit(p, max_new_tokens=4, temperature=0.0)
            for p in prompts[4:]]
    router.run()
    assert router.drained("a")
    # routed credit for never-admitted requests moves with the re-placement
    # (the capacity controller's counter audit, ISSUE 16); admissions after
    # the drain would make it larger, never smaller
    assert router.routed["a"] == routed_a - len(replaced), \
        "draining replica kept admitting"
    assert router.routed["b"] >= len(more)
    survivors = [r for r in reqs if r.done] + replaced + more
    assert {tuple(r.prompt_ids) for r in survivors} == \
        {tuple(p) for p in prompts}
    assert all(len(r.tokens) == 4 for r in survivors)
    with pytest.raises(RuntimeError):
        router.begin_drain("b") or router.submit(
            prompts[0], max_new_tokens=2)


# ----------------------------------------------- contracts + telemetry
def test_paged_contracts_donate_pool_and_analyze_clean(model):
    from paddle_tpu.serving.kv_pages import pool_state_bytes

    eng = _paged(model)
    _run(eng, _mixed_work(np.random.RandomState(9), n=3))
    contracts = {c.name: c for c in eng.default_contracts()}
    labels = [n for n in contracts if "cache-donation" in n]
    assert any("decode" in n for n in labels)
    assert any("prefill" in n for n in labels)
    pool_bytes = pool_state_bytes(eng._pool_state)
    for name in labels:
        if "decode" in name:
            # decode donates the whole pool state: pools + scales + tables
            assert contracts[name].donated_bytes >= pool_bytes
    rep = eng.analyze()
    assert rep.ok, [str(v) for v in rep.violations]


def test_paged_gauges_reach_registry_and_prometheus(model):
    from paddle_tpu.observability import metrics

    reg = metrics.enable()
    try:
        eng = _paged(model)
        rng = np.random.RandomState(10)
        prompt = rng.randint(0, 1024, (16,)).astype(np.int64)
        for _ in range(2):
            eng.submit(prompt, max_new_tokens=3, temperature=0.0)
            eng.run()
        snap = reg.snapshot()
        for g in ("serve.pages_in_use", "serve.pages_cached",
                  "serve.prefix_hit_rate"):
            assert g in snap["gauges"], sorted(snap["gauges"])
        assert snap["gauges"]["serve.prefix_hit_rate"] > 0
        text = reg.to_prometheus()
        assert "serve_pages_in_use" in text.replace(".", "_")
    finally:
        metrics.disable()
        metrics.reset()

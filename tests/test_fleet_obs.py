"""Fleet observability (ISSUE 14 tentpole): cross-process metrics
federation + distributed trace propagation.

Pinned contracts:
- histogram/registry merge is lossless over the log-bucket representation:
  merged count == sum of per-worker counts exactly, merged min/max exact,
  merged percentiles recomputed from merged buckets land within one bucket
  width of a pooled-sample recompute;
- the publisher/collector pair federates over the same store the elastic
  membership layer uses: generation-scoped keys, wall-clock deadlines (a
  dead publisher is evicted by the collector's read), gc_generation sweeps
  fleet keys with the rest of a retired generation;
- trace context threads router -> engine: the route.place span's minted
  span id is the parent_span of every engine-side span of that request,
  and the request_id tags them end to end;
- dark by default: no active registry -> publish_once() is a no-op that
  never touches the store.
"""
import json
import math
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.distributed.store import FileStore
from paddle_tpu.observability import (exporter, fleet, flight_recorder,
                                      metrics, tracer)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_observability():
    """Fleet wiring rides the same process-globals as the rest of
    observability: start dark, leave dark."""
    def _reset():
        exporter.stop_exporter()
        metrics.reset()
        flight_recorder.disable()
        fleet.uninstall_collector()
        tr = tracer.get_tracer()
        tr.disable()
        tr.clear()
        tr.clear_stats()

    _reset()
    yield
    _reset()


def _fill(h, values):
    for v in values:
        h.observe(v)
    return h


# -------------------------------------------------------------- merge math

def test_counter_and_histogram_merge_match_pooled():
    """a.merge(b) must equal one histogram that observed both streams:
    bucket counts / count / min / max exactly, sum up to float summation
    order, percentiles identical (same buckets + same clamps)."""
    rng = np.random.RandomState(3)
    xs = list(np.exp(rng.randn(400)) * 5.0)
    ys = list(np.exp(rng.randn(300)) * 40.0)

    a = _fill(metrics.Histogram("m"), xs)
    b = _fill(metrics.Histogram("m"), ys)
    pooled = _fill(metrics.Histogram("m"), xs + ys)
    a.merge(b)
    sa, sp = a.snapshot(), pooled.snapshot()
    assert sa["counts"] == sp["counts"]
    assert sa["count"] == sp["count"] == 700
    assert sa["min"] == sp["min"] and sa["max"] == sp["max"]
    assert math.isclose(sa["sum"], sp["sum"], rel_tol=1e-12)
    for q in (0.5, 0.9, 0.99):
        assert metrics.estimate_percentile(sa, q) == \
            metrics.estimate_percentile(sp, q)

    ca, cb = metrics.Counter("c"), metrics.Counter("c")
    ca.inc(3), cb.inc(4.5)
    ca.merge(cb)
    assert ca.value == 7.5


def test_histogram_merge_boundary_mismatch_raises():
    a = metrics.Histogram("m", boundaries=(1.0, 2.0))
    b = metrics.Histogram("m", boundaries=(1.0, 4.0))
    with pytest.raises(ValueError):
        a.merge(b)
    with pytest.raises(ValueError):
        metrics.merge_histogram_snapshots([a.snapshot(), b.snapshot()])


def test_merge_histogram_snapshots_edges():
    """Empty input / all-None -> None; a single snapshot round-trips; fully
    disjoint ranges merge with exact global min/max."""
    assert metrics.merge_histogram_snapshots([]) is None
    assert metrics.merge_histogram_snapshots([None, None]) is None

    solo = _fill(metrics.Histogram("m"), [3.0]).snapshot()
    m = metrics.merge_histogram_snapshots([None, solo])
    assert m["count"] == 1 and m["min"] == m["max"] == 3.0
    assert m["counts"] == solo["counts"]

    lo = _fill(metrics.Histogram("m"), [0.2, 0.4]).snapshot()
    hi = _fill(metrics.Histogram("m"), [5000.0, 9000.0]).snapshot()
    m = metrics.merge_histogram_snapshots([lo, hi])
    assert m["count"] == 4
    assert m["min"] == 0.2 and m["max"] == 9000.0
    assert sum(m["counts"]) == 4


def test_merged_percentiles_within_one_bucket_of_pooled_numpy():
    """The federation acceptance bound: split a lognormal stream over 4
    'workers', merge the snapshots, and the merged p50/p90/p99 must land
    within the containing bucket's width of numpy's pooled answer."""
    rng = np.random.RandomState(11)
    pooled = np.exp(rng.randn(4000)) * 12.0
    parts = np.array_split(pooled, 4)
    snaps = [_fill(metrics.Histogram("m"), p).snapshot() for p in parts]
    m = metrics.merge_histogram_snapshots(snaps)
    assert m["count"] == 4000 == sum(s["count"] for s in snaps)
    import bisect
    bs = m["boundaries"]
    for q in (50, 90, 99):
        est = m[f"p{q}"]
        truth = float(np.percentile(pooled, q))
        i = bisect.bisect_left(bs, truth)
        lo = bs[i - 1] if i > 0 else m["min"]
        hi = bs[i] if i < len(bs) else m["max"]
        assert abs(est - truth) <= (hi - lo), (q, est, truth)


def test_merge_registry_snapshots_sums_and_merges():
    reg_a = {"counters": {"c": 2.0}, "gauges": {"g": 1.5},
             "histograms": {"h": _fill(metrics.Histogram("h"),
                                       [1.0, 2.0]).snapshot()},
             "monitor": {"s": {"value": 3.0, "peak": 5.0}}}
    reg_b = {"counters": {"c": 5.0, "d": 1.0}, "gauges": {"g": 0.5},
             "histograms": {"h": _fill(metrics.Histogram("h"),
                                       [4.0]).snapshot()},
             "monitor": {"s": {"value": 2.0, "peak": 9.0}}}
    m = fleet.merge_registry_snapshots([reg_a, None, reg_b])
    assert m["counters"] == {"c": 7.0, "d": 1.0}
    assert m["gauges"] == {"g": 2.0}
    assert m["histograms"]["h"]["count"] == 3
    assert m["monitor"]["s"] == {"value": 5.0, "peak": 9.0}


# ------------------------------------------------- publisher / collector

def test_publisher_collector_roundtrip_filestore(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    reg = metrics.enable()
    _fill(reg.histogram("train.step_ms"), [10.0, 20.0, 30.0])
    reg.counter("train.steps").inc(3)
    pub = fleet.FleetPublisher(store, "w0", interval_s=0.1, deadline_s=5.0)
    assert pub.publish_once() is True
    coll = fleet.FleetCollector(store)
    snap = coll.collect()
    assert list(snap["workers"]) == ["w0"]
    assert snap["workers"]["w0"]["age_s"] < 5.0
    assert snap["merged"]["counters"]["train.steps"] == 3.0
    assert snap["merged"]["histograms"]["train.step_ms"]["count"] == 3
    assert snap["per_worker"]["w0"]["histograms"]["train.step_ms"][
        "count"] == 3
    assert snap["evicted"] == []


def test_dark_by_default_no_store_writes(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    pub = fleet.FleetPublisher(store, "w0", interval_s=0.1)
    assert metrics.active_registry() is None
    assert pub.payload() is None
    assert pub.publish_once() is False
    assert store.list_keys(fleet.FLEET_PREFIX) == []
    assert pub.publishes == 0


def test_oversized_publish_sheds_spans_then_drops(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    reg = metrics.enable()
    tr = tracer.get_tracer()
    tr.enable()
    for i in range(50):
        tr.instant("noise", i=i, blob="x" * 64)
    _fill(reg.histogram("h"), [1.0])
    # bound fits the snapshot alone, not snapshot+spans: tail is shed
    base = len(fleet.FleetPublisher(store, "w0", span_tail=0).payload())
    pub = fleet.FleetPublisher(store, "w0", interval_s=0.1,
                               max_bytes=base + 8)
    assert pub.publish_once() is True
    doc = fleet._decode(store.get(fleet.snap_key(0, "w0"), wait=False))
    assert doc["spans"] == [] and doc["snapshot"]["histograms"]
    # bound below even the span-less payload: drop + counter
    pub2 = fleet.FleetPublisher(store, "w1", interval_s=0.1, max_bytes=16)
    assert pub2.publish_once() is False
    assert pub2.drops == 1
    assert reg.snapshot()["counters"]["fleet.publish_drops"] == 1.0
    assert store.list_keys(fleet.snap_key(0, "w1")) == []


def test_collector_evicts_dead_publisher(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    metrics.enable()
    live = fleet.FleetPublisher(store, "alive", interval_s=0.1,
                                deadline_s=30.0)
    dead = fleet.FleetPublisher(store, "dead", interval_s=0.1,
                                deadline_s=0.05)
    assert live.publish_once() and dead.publish_once()
    time.sleep(0.1)  # the dead worker's deadline lapses, no re-publish
    coll = fleet.FleetCollector(store)
    snap = coll.collect()
    assert snap["evicted"] == ["dead"]
    assert list(snap["workers"]) == ["alive"]
    # evicted means deleted from the store, not just skipped
    assert store.list_keys(fleet.snap_key(0, "dead")) == []
    assert coll.evictions == 1


def test_gc_generation_sweeps_fleet_keys(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    store.set(fleet.snap_key(1, "w0"), b"old")
    store.set(fleet.snap_key(2, "w0"), b"new")
    removed = store.gc_generation(1)
    assert removed >= 1
    assert store.list_keys("__fleet__/gen1/") == []
    assert store.list_keys("__fleet__/gen2/") == [fleet.snap_key(2, "w0")]


def test_two_process_federation_roundtrip(tmp_path):
    """A real second process publishes over the FileStore; the driver's
    collector merges its registry with the local one exactly."""
    child = (
        "import sys\n"
        "from paddle_tpu.distributed.store import FileStore\n"
        "from paddle_tpu.observability import fleet, metrics\n"
        "store = FileStore(sys.argv[1], timeout=5.0)\n"
        "reg = metrics.enable()\n"
        "h = reg.histogram('train.step_ms')\n"
        "for v in (100.0, 200.0, 300.0): h.observe(v)\n"
        "reg.counter('train.steps').inc(3)\n"
        "pub = fleet.FleetPublisher(store, 'remote', interval_s=0.1,\n"
        "                           deadline_s=30.0)\n"
        "assert pub.publish_once()\n"
        "print('PUBLISHED')\n")
    store = FileStore(str(tmp_path), timeout=5.0)
    env = {**os.environ, "PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([sys.executable, "-c", child, str(tmp_path)],
                         env=env, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert "PUBLISHED" in out.stdout

    reg = metrics.enable()
    _fill(reg.histogram("train.step_ms"), [10.0, 20.0])
    reg.counter("train.steps").inc(2)
    fleet.FleetPublisher(store, "local", interval_s=0.1,
                         deadline_s=30.0).publish_once()
    snap = fleet.FleetCollector(store).collect()
    assert sorted(snap["workers"]) == ["local", "remote"]
    assert snap["workers"]["remote"]["pid"] != os.getpid()
    merged = snap["merged"]
    assert merged["counters"]["train.steps"] == 5.0
    h = merged["histograms"]["train.step_ms"]
    assert h["count"] == 5
    assert h["min"] == 10.0 and h["max"] == 300.0


# ---------------------------------------------------- trace propagation

@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


def test_router_placement_span_parents_engine_spans(model):
    """ISSUE 14 acceptance: a routed request produces route.place whose
    span_id is the parent_span of that request's queue-wait/prefill/decode
    spans, all tagged with one request_id, in causal order."""
    from paddle_tpu.serving import ReplicaRouter, ServingEngine

    tr = tracer.get_tracer()
    tr.enable()
    tr.clear()
    rng = np.random.RandomState(5)
    engines = [ServingEngine(model, slot_count=2, ladder=(8, 16),
                             max_new_cap=8, steps_per_dispatch=2)
               for _ in range(2)]
    router = ReplicaRouter(engines)
    reqs = [router.submit(rng.randint(0, 1024, (4 + i,)).astype(np.int64),
                          max_new_tokens=3, temperature=0.0)
            for i in range(4)]
    router.run()
    events = tr.events()

    places = [e for e in events if e["name"] == "route.place"]
    assert len(places) == 4
    for req in reqs:
        assert req.done
        ctx = req.trace_ctx
        assert ctx is not None and ctx.parent_span is not None
        place = next(p for p in places
                     if p["args"]["request_id"] == ctx.request_id)
        assert place["args"]["span_id"] == ctx.parent_span
        children = [e for e in events if e["name"].startswith("serve.")
                    and (e.get("args") or {}).get("request") == req.id]
        assert {e["name"] for e in children} >= {
            "serve.queue_wait", "serve.prefill", "serve.decode",
            "serve.request", "serve.retire"}
        for ev in children:
            assert ev["args"]["request_id"] == ctx.request_id
            assert ev["args"]["parent_span"] == ctx.parent_span
        qw = next(e for e in children if e["name"] == "serve.queue_wait")
        assert place["ts"] <= qw["ts"]  # placement precedes admission
    # distinct requests got distinct parents (no span-id reuse)
    assert len({p["args"]["span_id"] for p in places}) == 4
    # placement tail recorded for flight dumps, request ids included
    tail = router.recent_placements()
    assert len(tail) == 4 and all("request_id" in p for p in tail)


def test_merged_chrome_trace_single_timeline(tmp_path):
    """Two publishers' span tails stitch into one chrome trace with one
    pid row per worker and the request id preserved in span args."""
    store = FileStore(str(tmp_path), timeout=2.0)
    metrics.enable()
    tr = tracer.get_tracer()
    tr.enable()
    rid = fleet.new_request_id()
    tr.instant("route.place", request_id=rid)
    with tr.span("serve.prefill", request_id=rid):
        pass
    fleet.FleetPublisher(store, "w0", interval_s=0.1,
                         deadline_s=30.0).publish_once()
    fleet.FleetPublisher(store, "w1", interval_s=0.1,
                         deadline_s=30.0).publish_once()
    coll = fleet.FleetCollector(store)
    coll.collect()
    doc = coll.merged_chrome_trace()
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"]
    assert sorted(names) == ["fleet:w0", "fleet:w1"]
    tagged = [e for e in doc["traceEvents"]
              if (e.get("args") or {}).get("request_id") == rid]
    # both workers republished the same process tail here; what matters is
    # the id survives the roundtrip and X/i phases are well-formed
    assert tagged and all(e["ph"] in ("X", "i") for e in tagged)


def test_reformation_events_become_spans(tmp_path):
    """generation_bump / pause / reshard / commit land as first-class
    spans with the new generation in their args, in causal order."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import TrainStepEngine
    from paddle_tpu.distributed.membership import (ElasticCoordinator,
                                                   WorkerAgent)
    from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                             set_hybrid_communicate_group)

    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    agents = [WorkerAgent(store, f"w{i}", lease_s=5.0) for i in range(4)]
    for a in agents:
        a.register()

    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=4, devices=jax.devices()[:4])
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    eng = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                          hcg=hcg)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (32,)).astype(np.int64))
    eng.step(x, y)

    tr = tracer.get_tracer()
    tr.enable()
    tr.clear()
    agents[3].announce_leave("sigterm")
    agents[2].announce_leave("sigterm")
    assert coord.maybe_reform(eng) is True
    events = {e["name"]: e for e in tr.events()}
    assert {"elastic.generation_bump", "elastic.pause", "elastic.reshard",
            "elastic.commit"} <= set(events)
    gen = coord.generation()
    for name in ("elastic.generation_bump", "elastic.reshard",
                 "elastic.commit"):
        assert events[name]["args"]["generation"] == gen
    bump, pause, rs, commit = (events["elastic.generation_bump"],
                               events["elastic.pause"],
                               events["elastic.reshard"],
                               events["elastic.commit"])
    assert pause["ts"] <= bump["ts"] <= rs["ts"] <= commit["ts"]
    assert pause["ts"] + pause["dur"] <= commit["ts"] + 1e-6
    assert commit["args"]["world_size"] == 2
    assert pause["dur"] * 1000.0 == pytest.approx(coord.last_pause_ms,
                                                  rel=0.5)


# -------------------------------------------- exporter / flight / tools

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode("utf-8")


def test_exporter_fleet_routes(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    reg = metrics.enable()
    _fill(reg.histogram("train.step_ms"), [10.0, 20.0])
    fleet.FleetPublisher(store, "w0", interval_s=0.1,
                         deadline_s=30.0).publish_once()
    ex = exporter.start_exporter(port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ex.url + "/fleet/metrics")
        assert ei.value.code == 404  # no collector installed yet

        fleet.install_collector(fleet.FleetCollector(store))
        status, body = _get(ex.url + "/fleet/metrics")
        assert status == 200
        assert "paddle_tpu_fleet_workers 1" in body
        assert "paddle_tpu_fleet_train_step_ms_count 2" in body
        assert 'paddle_tpu_fleet_train_step_ms_count{worker="w0"} 2' in body
        assert "paddle_tpu_fleet_train_step_ms_p99" in body

        status, body = _get(ex.url + "/fleet/metrics.json")
        doc = json.loads(body)
        assert doc["merged"]["histograms"]["train.step_ms"]["count"] == 2
        assert list(doc["workers"]) == ["w0"]

        status, body = _get(ex.url + "/fleet/trace")
        trace = json.loads(body)
        assert any(e.get("name") == "process_name"
                   for e in trace["traceEvents"])
    finally:
        exporter.stop_exporter()


def test_flight_state_embeds_fleet_context(tmp_path):
    store = FileStore(str(tmp_path / "store"), timeout=2.0)
    reg = metrics.enable()
    _fill(reg.histogram("train.step_ms"), [10.0])
    fleet.FleetPublisher(store, "w0", interval_s=0.1,
                         deadline_s=30.0).publish_once()
    coll = fleet.install_collector(fleet.FleetCollector(store))
    coll.collect()
    fr = flight_recorder.enable(str(tmp_path / "flight"))
    fr.record({"step": 1, "loss": 0.5})
    out = fr.dump("unit")
    state = json.loads(
        (open(os.path.join(out, "state.json"))).read())
    assert state["fleet"]["generation"] == 0
    assert list(state["fleet"]["workers"]) == ["w0"]
    merged = state["fleet"]["merged"]["histograms"]["train.step_ms"]
    assert merged["count"] == 1
    assert "counts" not in merged  # compact form, not raw buckets


def test_trace_summary_fleet_mode(tmp_path):
    """Two worker dirs -> one merged report: per-worker rows + merged step
    stats + merged snapshot, as a single fleet_merged summary line."""
    for wid, n in (("wa", 3), ("wb", 2)):
        d = tmp_path / wid
        d.mkdir()
        with open(d / "steps.jsonl", "w") as f:
            for i in range(n):
                f.write(json.dumps({
                    "event": "train_step", "step": i, "loss": 1.0,
                    "step_ms": 10.0 + i, "tokens_per_sec": 100.0}) + "\n")
        reg = metrics.enable()
        _fill(reg.histogram("train.step_ms"), [10.0 + i for i in range(n)])
        with open(d / "metrics.json", "w") as f:
            f.write(json.dumps(reg.snapshot(include_monitor=True)))
        metrics.reset()
    env = {**os.environ, "PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_summary.py"),
         str(tmp_path / "wa"), str(tmp_path / "wb")],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])["summary"]
    assert summary["kind"] == "fleet_merged"
    assert summary["sources"] == 2
    assert set(summary["workers"]) == {"wa", "wb"}
    assert summary["merged"]["steps"] == 5
    assert summary["merged_snapshot"]["percentiles"][
        "train.step_ms"]["n"] == 5

"""paddle.utils.dlpack interchange (reference python/paddle/utils/dlpack.py:26,62).

Round-trips paddle <-> numpy <-> torch through the DLPack protocol, both the
modern __dlpack__ object path and the legacy capsule path.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack


class TestDlpack:
    def test_capsule_round_trip(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        cap = to_dlpack(x)
        assert type(cap).__name__ == "PyCapsule"
        y = from_dlpack(cap)
        np.testing.assert_array_equal(y.numpy(), x.numpy())
        assert y.dtype == x.dtype

    def test_from_numpy_zero_copy_protocol(self):
        a = np.arange(6, dtype=np.int32).reshape(2, 3)
        t = from_dlpack(a)
        np.testing.assert_array_equal(t.numpy(), a)
        assert str(t.dtype).endswith("int32")

    def test_numpy_imports_paddle_tensor(self):
        x = paddle.to_tensor(np.ones((4,), np.float32) * 3)
        back = np.from_dlpack(x)  # Tensor.__dlpack__ producer path
        np.testing.assert_array_equal(back, x.numpy())

    def test_torch_round_trip(self):
        torch = pytest.importorskip("torch")
        src = torch.arange(8, dtype=torch.float32).reshape(2, 4)
        t = from_dlpack(src)
        np.testing.assert_array_equal(t.numpy(), src.numpy())
        back = torch.from_dlpack(t)
        np.testing.assert_array_equal(back.numpy(), t.numpy())

    def test_torch_capsule_legacy_path(self):
        torch = pytest.importorskip("torch")
        cap = torch.utils.dlpack.to_dlpack(torch.ones(3, 3))
        t = from_dlpack(cap)
        np.testing.assert_array_equal(t.numpy(), np.ones((3, 3), np.float32))

    def test_to_dlpack_type_error(self):
        with pytest.raises(TypeError):
            to_dlpack(np.zeros(3))

    def test_from_dlpack_type_error(self):
        with pytest.raises(TypeError):
            from_dlpack("not a tensor")

    def test_dtype_preservation(self):
        for dt in (np.float32, np.float64, np.int64, np.uint8, np.bool_):
            a = np.zeros((2, 2), dt)
            t = from_dlpack(a)
            assert t.numpy().dtype == dt, dt

"""BASELINE config 1: MNIST LeNet dygraph end-to-end — loss decreases, accuracy above chance.
(Reference book test: recognize_digits; loss-parity harness per SURVEY.md §4.)"""
import pytest
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

def test_lenet_mnist_training():
    paddle.seed(42)
    train_ds = MNIST(mode="train", size=512)
    loader = DataLoader(train_ds, batch_size=64, shuffle=True, drop_last=True)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    first_losses, last_losses = [], []
    for epoch in range(3):
        for images, labels in loader:
            logits = model(images)
            loss = loss_fn(logits, labels.squeeze(-1))
            loss.backward()
            opt.step()
            opt.clear_grad()
            if epoch == 0:
                first_losses.append(float(loss.item()))
            if epoch == 2:
                last_losses.append(float(loss.item()))

    assert np.mean(last_losses) < np.mean(first_losses) * 0.7, (
        f"loss did not decrease: {np.mean(first_losses)} -> {np.mean(last_losses)}")

    # eval accuracy above chance on held-out synthetic set
    model.eval()
    test_ds = MNIST(mode="test", size=512)
    correct = total = 0
    for images, labels in DataLoader(test_ds, batch_size=128):
        pred = model(images).numpy().argmax(-1)
        correct += (pred == labels.numpy().squeeze(-1)).sum()
        total += len(pred)
    acc = correct / total
    assert acc > 0.2, f"accuracy {acc} not above chance"


def test_save_load_resume(tmp_path):
    paddle.seed(0)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    x = paddle.rand([4, 1, 28, 28])
    y = paddle.to_tensor(np.array([1, 2, 3, 4], np.int64))
    loss = nn.CrossEntropyLoss()(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()

    paddle.save(model.state_dict(), str(tmp_path / "m.pdparams"))
    paddle.save(opt.state_dict(), str(tmp_path / "m.pdopt"))

    model2 = LeNet(num_classes=10)
    model2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
    opt2 = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model2.parameters())
    opt2.set_state_dict(paddle.load(str(tmp_path / "m.pdopt")))

    out1 = model(x).numpy()
    out2 = model2(x).numpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-5)

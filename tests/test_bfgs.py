"""minimize_bfgs / minimize_lbfgs (reference python/paddle/incubate/optimizer/
functional/{bfgs,lbfgs}.py + unittests test_bfgs.py / test_lbfgs.py):
quasi-Newton with strong-Wolfe line search, compiled as one lax.while_loop."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.optimizer.functional import (minimize_bfgs,
                                                      minimize_lbfgs)


def quad(x):
    return paddle.dot(x, x)


def rosen(x):
    a = x[1] - x[0] * x[0]
    b = 1.0 - x[0]
    return 100.0 * a * a + b * b


@pytest.mark.parametrize("minimize", [minimize_bfgs, minimize_lbfgs],
                         ids=["bfgs", "lbfgs"])
def test_quadratic_converges(minimize):
    x0 = paddle.to_tensor(np.array([1.3, 2.7], "float32"))
    r = minimize(quad, x0)
    assert bool(r[0].numpy())                       # is_converge
    assert int(r[1].numpy()) >= 1                   # num_func_calls
    np.testing.assert_allclose(r[2].numpy(), [0.0, 0.0], atol=1e-5)
    assert float(r[3].numpy()) < 1e-8               # objective value
    np.testing.assert_allclose(r[4].numpy(), [0.0, 0.0], atol=1e-5)  # grad


@pytest.mark.parametrize("minimize,kw", [
    (minimize_bfgs, {"max_iters": 100}),
    (minimize_lbfgs, {"max_iters": 120, "history_size": 6}),
], ids=["bfgs", "lbfgs"])
def test_rosenbrock_converges(minimize, kw):
    x0 = paddle.to_tensor(np.array([-1.2, 1.0], "float32"))
    r = minimize(rosen, x0, **kw)
    assert bool(r[0].numpy())
    np.testing.assert_allclose(r[2].numpy(), [1.0, 1.0], atol=1e-3)


def test_bfgs_returns_inverse_hessian():
    """6th return slot (reference bfgs.py return signature) is the inverse
    Hessian estimate: symmetric positive definite by the BFGS update
    invariant. (It need not equal the true I/2 — the solve converges in a
    couple of steps, before the estimate matures.)"""
    x0 = paddle.to_tensor(np.array([1.0, -2.0, 3.0], "float32"))
    r = minimize_bfgs(quad, x0, max_iters=60)
    assert len(r) == 6
    H = r[5].numpy()
    np.testing.assert_allclose(H, H.T, atol=1e-6)
    assert np.linalg.eigvalsh(H).min() > 0


def test_lbfgs_high_dim_and_history_wrap():
    """history_size smaller than iteration count exercises the circular
    buffer + two-loop recursion wrap-around."""
    rng = np.random.RandomState(0)
    diag = paddle.to_tensor(np.linspace(1.0, 10.0, 20).astype("float32"))

    def f(x):
        return paddle.dot(x * diag, x)

    x0 = paddle.to_tensor(rng.randn(20).astype("float32"))
    r = minimize_lbfgs(f, x0, history_size=4, max_iters=80)
    assert bool(r[0].numpy())
    assert np.abs(r[2].numpy()).max() < 1e-4


def test_float64_dtype():
    x0 = paddle.to_tensor(np.array([0.7, -0.3], "float64"))
    r = minimize_bfgs(quad, x0, dtype="float64")
    assert r[2].numpy().dtype == np.float64
    np.testing.assert_allclose(r[2].numpy(), [0.0, 0.0], atol=1e-10)


def test_validation_errors():
    x0 = paddle.to_tensor(np.array([1.0], "float32"))
    with pytest.raises(ValueError):
        minimize_bfgs(quad, x0, dtype="float16")
    with pytest.raises(NotImplementedError):
        minimize_lbfgs(quad, x0, line_search_fn="hager_zhang")


def test_initial_inverse_hessian_validation():
    x0 = paddle.to_tensor(np.array([1.0, 1.0], "float32"))
    with pytest.raises(ValueError):  # not symmetric
        minimize_bfgs(quad, x0, initial_inverse_hessian_estimate=np.array(
            [[1.0, 0.5], [0.0, 1.0]], "float32"))
    with pytest.raises(ValueError):  # not positive definite
        minimize_lbfgs(quad, x0, initial_inverse_hessian_estimate=np.array(
            [[1.0, 0.0], [0.0, -1.0]], "float32"))


def test_lbfgs_applies_anisotropic_h0():
    """A preconditioner matching the problem's curvature must not collapse
    to a scalar: with H0 = inv(Hessian) the first step is (nearly) exact."""
    diag = np.array([100.0, 1.0], "float32")

    def f(x):
        return paddle.dot(x * paddle.to_tensor(diag), x)

    h0 = np.diag(0.5 / diag).astype("float32")  # true inverse Hessian
    x0 = paddle.to_tensor(np.array([1.0, -1.0], "float32"))
    r = minimize_lbfgs(f, x0, initial_inverse_hessian_estimate=h0,
                       max_iters=10)
    assert bool(r[0].numpy())
    assert int(r[1].numpy()) <= 5  # near-Newton: converges in ~1 step
    assert np.abs(r[2].numpy()).max() < 1e-5

"""paddle.signal (stft/istft roundtrip, frame/overlap_add) + small namespace
modules (regularizer, hub, reader, callbacks, sysconfig, compat, onnx gate)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        x = np.arange(16, dtype=np.float32)
        frames = paddle.signal.frame(t(x), frame_length=4, hop_length=4)
        assert frames.shape == [4, 4]  # [frame_length, n_frames]
        back = paddle.signal.overlap_add(frames, hop_length=4)
        np.testing.assert_allclose(back.numpy(), x)

    def test_frame_values(self):
        x = np.arange(8, dtype=np.float32)
        frames = paddle.signal.frame(t(x), frame_length=4, hop_length=2).numpy()
        np.testing.assert_array_equal(frames[:, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(frames[:, 1], [2, 3, 4, 5])

    def test_stft_matches_numpy(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 64).astype(np.float32)
        n_fft, hop = 16, 8
        win = np.hanning(n_fft).astype(np.float32)
        out = paddle.signal.stft(t(x), n_fft, hop_length=hop,
                                 window=t(win), center=False).numpy()
        # manual reference
        n_frames = 1 + (64 - n_fft) // hop
        ref = np.stack([np.fft.rfft(x[0, f * hop:f * hop + n_fft] * win)
                        for f in range(n_frames)], axis=-1)
        np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-5)

    def test_stft_istft_roundtrip(self):
        rs = np.random.RandomState(1)
        x = rs.randn(2, 128).astype(np.float32)
        n_fft, hop = 32, 8
        win = np.hanning(n_fft).astype(np.float32)
        spec = paddle.signal.stft(t(x), n_fft, hop_length=hop, window=t(win))
        back = paddle.signal.istft(spec, n_fft, hop_length=hop, window=t(win),
                                   length=128)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)


class TestSmallNamespaces:
    def test_regularizer(self):
        r = paddle.regularizer.L2Decay(1e-4)
        assert r.coeff == 1e-4 and r._coeff == 1e-4
        l1 = paddle.regularizer.L1Decay(0.1)
        p = t(np.array([1.0, -2.0], np.float32))
        g = l1.apply(p, np.zeros(2, np.float32))
        np.testing.assert_allclose(np.asarray(g), [0.1, -0.1], rtol=1e-6)

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(scale=2):\n"
            "    'docs here'\n"
            "    return ('model', scale)\n")
        assert paddle.hub.list(str(tmp_path)) == ["tiny_model"]
        assert "docs" in paddle.hub.help(str(tmp_path), "tiny_model")
        assert paddle.hub.load(str(tmp_path), "tiny_model", scale=3) == ("model", 3)
        with pytest.raises(RuntimeError, match="zero-egress"):
            paddle.hub.load("user/repo", "m", source="github")

    def test_reader_decorators(self):
        base = lambda: iter(range(10))
        assert len(list(paddle.reader.firstn(base, 3)())) == 3
        shuffled = list(paddle.reader.shuffle(base, 5)())
        assert sorted(shuffled) == list(range(10))
        chained = list(paddle.reader.chain(base, base)())
        assert len(chained) == 20
        mapped = list(paddle.reader.map_readers(lambda a, b: a + b, base, base)())
        assert mapped[3] == 6

    def test_callbacks_namespace(self):
        assert paddle.callbacks.EarlyStopping is not None
        assert paddle.callbacks.ModelCheckpoint is not None

    def test_sysconfig(self):
        assert paddle.sysconfig.get_include().endswith("include")
        assert paddle.sysconfig.get_lib().endswith("libs")

    def test_compat(self):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]

    def test_onnx_gated(self):
        with pytest.raises((RuntimeError, NotImplementedError)):
            paddle.onnx.export(None, "/tmp/x")

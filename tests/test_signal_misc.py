"""paddle.signal (stft/istft roundtrip, frame/overlap_add) + small namespace
modules (regularizer, hub, reader, callbacks, sysconfig, compat, onnx gate)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        x = np.arange(16, dtype=np.float32)
        frames = paddle.signal.frame(t(x), frame_length=4, hop_length=4)
        assert frames.shape == [4, 4]  # [frame_length, n_frames]
        back = paddle.signal.overlap_add(frames, hop_length=4)
        np.testing.assert_allclose(back.numpy(), x)

    def test_frame_values(self):
        x = np.arange(8, dtype=np.float32)
        frames = paddle.signal.frame(t(x), frame_length=4, hop_length=2).numpy()
        np.testing.assert_array_equal(frames[:, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(frames[:, 1], [2, 3, 4, 5])

    def test_stft_matches_numpy(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 64).astype(np.float32)
        n_fft, hop = 16, 8
        win = np.hanning(n_fft).astype(np.float32)
        out = paddle.signal.stft(t(x), n_fft, hop_length=hop,
                                 window=t(win), center=False).numpy()
        # manual reference
        n_frames = 1 + (64 - n_fft) // hop
        ref = np.stack([np.fft.rfft(x[0, f * hop:f * hop + n_fft] * win)
                        for f in range(n_frames)], axis=-1)
        np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-5)

    def test_frame_rejects_middle_axis(self):
        with pytest.raises(ValueError, match="axis"):
            paddle.signal.frame(t(np.zeros((2, 8, 2), np.float32)), 4, 2, axis=1)
        with pytest.raises(ValueError, match="axis"):
            paddle.signal.overlap_add(t(np.zeros((2, 4, 3), np.float32)), 2,
                                      axis=1)

    def test_istft_return_complex(self):
        rs = np.random.RandomState(2)
        x = (rs.randn(32) + 1j * rs.randn(32)).astype(np.complex64)
        spec = paddle.signal.stft(t(x), 16, hop_length=4, onesided=False)
        back = paddle.signal.istft(spec, 16, hop_length=4, onesided=False,
                                   return_complex=True, length=32)
        assert paddle.is_complex(back)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        rs = np.random.RandomState(1)
        x = rs.randn(2, 128).astype(np.float32)
        n_fft, hop = 32, 8
        win = np.hanning(n_fft).astype(np.float32)
        spec = paddle.signal.stft(t(x), n_fft, hop_length=hop, window=t(win))
        back = paddle.signal.istft(spec, n_fft, hop_length=hop, window=t(win),
                                   length=128)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)


class TestSmallNamespaces:
    def test_regularizer(self):
        r = paddle.regularizer.L2Decay(1e-4)
        assert r.coeff == 1e-4 and r._coeff == 1e-4
        l1 = paddle.regularizer.L1Decay(0.1)
        p = t(np.array([1.0, -2.0], np.float32))
        g = l1.apply(p, np.zeros(2, np.float32))
        np.testing.assert_allclose(np.asarray(g), [0.1, -0.1], rtol=1e-6)

    def test_l1_decay_actually_applies_in_step(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False)
        lin.weight.set_value(np.array([[1.0, -1.0], [2.0, -2.0]], np.float32))
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=lin.parameters(),
            weight_decay=paddle.regularizer.L1Decay(0.5))
        x = t(np.zeros((1, 2), np.float32))
        lin(x).sum().backward()  # zero grads: only the L1 term moves weights
        w0 = lin.weight.numpy().copy()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.5 * np.sign(w0), rtol=1e-6)

    def test_l1_per_param_regularizer(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False,
                        weight_attr=paddle.ParamAttr(
                            regularizer=paddle.regularizer.L1Decay(0.25)))
        lin.weight.set_value(np.array([[4.0, -4.0], [4.0, -4.0]], np.float32))
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=lin.parameters())
        lin(t(np.zeros((1, 2), np.float32))).sum().backward()
        w0 = lin.weight.numpy().copy()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.25 * np.sign(w0), rtol=1e-6)

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(scale=2):\n"
            "    'docs here'\n"
            "    return ('model', scale)\n")
        assert paddle.hub.list(str(tmp_path)) == ["tiny_model"]
        assert "docs" in paddle.hub.help(str(tmp_path), "tiny_model")
        assert paddle.hub.load(str(tmp_path), "tiny_model", scale=3) == ("model", 3)
        with pytest.raises(RuntimeError, match="zero-egress"):
            paddle.hub.load("user/repo", "m", source="github")

    def test_reader_decorators(self):
        base = lambda: iter(range(10))
        assert len(list(paddle.reader.firstn(base, 3)())) == 3
        shuffled = list(paddle.reader.shuffle(base, 5)())
        assert sorted(shuffled) == list(range(10))
        chained = list(paddle.reader.chain(base, base)())
        assert len(chained) == 20
        mapped = list(paddle.reader.map_readers(lambda a, b: a + b, base, base)())
        assert mapped[3] == 6

    def test_callbacks_namespace(self):
        assert paddle.callbacks.EarlyStopping is not None
        assert paddle.callbacks.ModelCheckpoint is not None

    def test_sysconfig(self):
        assert paddle.sysconfig.get_include().endswith("include")
        assert paddle.sysconfig.get_lib().endswith("libs")

    def test_compat(self):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
        # py2 semantics: half away from zero, float result
        assert paddle.compat.round(2.5) == 3.0
        assert paddle.compat.round(-2.5) == -3.0
        assert isinstance(paddle.compat.round(2.5), float)

    def test_compose_alignment(self):
        short = lambda: iter(range(3))
        long_ = lambda: iter(range(5))
        with pytest.raises(paddle.reader.ComposeNotAligned):
            list(paddle.reader.compose(short, long_)())
        ok = list(paddle.reader.compose(short, short)())
        assert ok == [(0, 0), (1, 1), (2, 2)]

    def test_hub_sibling_import(self, tmp_path):
        (tmp_path / "helpers.py").write_text("VALUE = 42\n")
        (tmp_path / "hubconf.py").write_text(
            "import helpers\n"
            "def get():\n    return helpers.VALUE\n")
        assert paddle.hub.load(str(tmp_path), "get") == 42

    def test_onnx_requires_input_spec(self):
        # export is real now (test_onnx_export.py); missing spec errors clearly
        with pytest.raises(ValueError, match="input_spec"):
            paddle.onnx.export(None, "/tmp/x")

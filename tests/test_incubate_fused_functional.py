"""incubate.nn.functional fused transformer ops vs independent numpy
references (reference incubate/nn/functional/fused_transformer.py pseudo
code; unittests test_fused_attention_op.py / test_fused_feedforward_op.py
use the same compose-then-compare strategy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn.functional import (fused_feedforward,
                                               fused_multi_head_attention)


def np_layer_norm(x, scale, bias, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale + bias


def test_fused_feedforward_matches_numpy():
    rng = np.random.RandomState(0)
    b, s, d, dff = 2, 6, 16, 64
    x = rng.randn(b, s, d).astype("float32")
    w1 = (rng.randn(d, dff) * 0.1).astype("float32")
    b1 = (rng.randn(dff) * 0.1).astype("float32")
    w2 = (rng.randn(dff, d) * 0.1).astype("float32")
    b2 = (rng.randn(d) * 0.1).astype("float32")
    scale = rng.rand(d).astype("float32") + 0.5
    bias = rng.randn(d).astype("float32")

    # pre_layer_norm: residual + linear2(relu(linear1(ln(x))))
    ref = x + (np.maximum(np_layer_norm(x, scale, bias) @ w1 + b1, 0)
               @ w2 + b2)
    out = fused_feedforward(
        paddle.to_tensor(x), paddle.to_tensor(w1), paddle.to_tensor(w2),
        linear1_bias=paddle.to_tensor(b1), linear2_bias=paddle.to_tensor(b2),
        ln1_scale=paddle.to_tensor(scale), ln1_bias=paddle.to_tensor(bias),
        dropout1_rate=0.0, dropout2_rate=0.0, pre_layer_norm=True,
        training=False)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)

    # post-layer_norm variant: ln(residual + ffn(x))
    ref2 = np_layer_norm(x + (np.maximum(x @ w1 + b1, 0) @ w2 + b2),
                         scale, bias)
    out2 = fused_feedforward(
        paddle.to_tensor(x), paddle.to_tensor(w1), paddle.to_tensor(w2),
        linear1_bias=paddle.to_tensor(b1), linear2_bias=paddle.to_tensor(b2),
        ln2_scale=paddle.to_tensor(scale), ln2_bias=paddle.to_tensor(bias),
        dropout1_rate=0.0, dropout2_rate=0.0, pre_layer_norm=False,
        training=False)
    np.testing.assert_allclose(out2.numpy(), ref2, rtol=2e-5, atol=2e-5)


def test_fused_multi_head_attention_matches_numpy():
    rng = np.random.RandomState(1)
    b, s, nh, hd = 2, 5, 4, 8
    d = nh * hd
    x = rng.randn(b, s, d).astype("float32")
    qkv_w = (rng.randn(3, nh, hd, d) * 0.1).astype("float32")
    qkv_b = (rng.randn(3, nh, hd) * 0.1).astype("float32")
    lin_w = (rng.randn(d, d) * 0.1).astype("float32")
    lin_b = (rng.randn(d) * 0.1).astype("float32")
    scale = np.ones(d, "float32")
    bias = np.zeros(d, "float32")

    # numpy reference: qkv proj -> per-head softmax attention -> out proj
    w2 = qkv_w.reshape(3 * d, d)
    qkv = x @ w2.T + qkv_b.reshape(-1)
    qkv = qkv.reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    logits = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    attn = (p @ v).transpose(0, 2, 1, 3).reshape(b, s, d)
    ref = np_layer_norm(x + (attn @ lin_w + lin_b), scale, bias)

    out = fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        qkv_bias=paddle.to_tensor(qkv_b), linear_bias=paddle.to_tensor(lin_b),
        ln_scale=paddle.to_tensor(scale), ln_bias=paddle.to_tensor(bias),
        pre_layer_norm=False, dropout_rate=0.0, attn_dropout_rate=0.0,
        training=False)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_unsupported_modes_raise():
    x = paddle.to_tensor(np.zeros((1, 2, 8), "float32"))
    qkv_w = paddle.to_tensor(np.zeros((3, 2, 4, 8), "float32"))
    lin_w = paddle.to_tensor(np.zeros((8, 8), "float32"))
    with pytest.raises(NotImplementedError):
        fused_multi_head_attention(x, qkv_w, lin_w, ring_id=2)
    with pytest.raises(NotImplementedError):
        fused_multi_head_attention(x, qkv_w, lin_w, cache_kv=object())

"""Parameter server: C++ tables/service, client sharding, PS-backed training.

Mirrors reference PS tests (ps/table tests, ps_local_client single-process mode,
Wide&Deep-style convergence under test_dist_fleet_ps*.py)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (DenseTableConfig, DistributedEmbedding,
                                       PSClient, PSServer, SparseTableConfig,
                                       TheOnePSRuntime, distributed_lookup_table)
from paddle_tpu.distributed.ps.runtime import DenseSync



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

@pytest.fixture()
def cluster():
    """Two in-process servers + one client (reference ps_local_client mode)."""
    sparse = [SparseTableConfig(table_id=0, dim=4, optimizer="sgd",
                                learning_rate=0.5)]
    dense = [DenseTableConfig(table_id=1, dim=6, optimizer="sgd",
                              learning_rate=0.5),
             DenseTableConfig(table_id=2, dim=3, optimizer="adam",
                              learning_rate=0.1)]
    servers = [PSServer(0, sparse, dense), PSServer(0, sparse, dense)]
    client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
    for t in sparse + dense:
        client.register_table_dim(t.table_id, t.dim)
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


def test_sparse_pull_deterministic_init(cluster):
    servers, client = cluster
    ids = np.array([1, 2, 3, 2 ** 40 + 7], dtype=np.uint64)
    rows1 = client.pull_sparse(0, ids)
    rows2 = client.pull_sparse(0, ids)
    np.testing.assert_array_equal(rows1, rows2)  # stable across pulls
    assert rows1.shape == (4, 4)
    assert np.abs(rows1).max() <= 0.1  # initial_range
    assert not np.allclose(rows1[0], rows1[1])  # per-id init differs


def test_sparse_push_applies_sgd(cluster):
    servers, client = cluster
    ids = np.array([10, 11], dtype=np.uint64)
    before = client.pull_sparse(0, ids)
    grads = np.ones((2, 4), dtype=np.float32)
    client.push_sparse(0, ids, grads)
    after = client.pull_sparse(0, ids)
    np.testing.assert_allclose(after, before - 0.5 * grads, rtol=1e-6)


def test_sparse_ids_shard_across_servers(cluster):
    servers, client = cluster
    ids = np.arange(100, dtype=np.uint64)
    client.pull_sparse(0, ids)  # touch 100 ids -> rows created on their shard
    sizes = [s.sparse_size(0) for s in servers]
    assert sum(sizes) == 100
    assert all(sz == 50 for sz in sizes)  # id % 2 split


def test_dense_push_pull_and_param_set(cluster):
    servers, client = cluster
    init = np.arange(6, dtype=np.float32)
    client.push_dense_param(1, init)
    np.testing.assert_array_equal(client.pull_dense(1), init)
    client.push_dense(1, np.ones(6, dtype=np.float32))
    np.testing.assert_allclose(client.pull_dense(1), init - 0.5, rtol=1e-6)


def test_dense_adam_moves_param(cluster):
    servers, client = cluster
    client.push_dense_param(2, np.zeros(3, dtype=np.float32))
    for _ in range(3):
        client.push_dense(2, np.ones(3, dtype=np.float32))
    out = client.pull_dense(2)
    assert (out < 0).all()  # adam steps moved params against the gradient


def test_save_load_roundtrip(cluster, tmp_path):
    servers, client = cluster
    ids = np.array([5, 6, 7], dtype=np.uint64)
    grads = np.full((3, 4), 2.0, dtype=np.float32)
    client.push_sparse(0, ids, grads)
    snap = client.pull_sparse(0, ids)
    dense_snap = client.pull_dense(1)
    client.save(str(tmp_path / "ckpt"))

    # perturb, then load back
    client.push_sparse(0, ids, grads)
    client.push_dense(1, np.ones(6, dtype=np.float32))
    client.load(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(client.pull_sparse(0, ids), snap, rtol=1e-6)
    np.testing.assert_allclose(client.pull_dense(1), dense_snap, rtol=1e-6)


def test_lookup_layer_trains_embeddings(cluster):
    """distributed_lookup_table: backward pushes merged grads to the server."""
    servers, client = cluster
    paddle.seed(0)
    ids = paddle.to_tensor(np.array([[1, 2], [2, 3]], dtype=np.int64))
    before = client.pull_sparse(0, np.array([1, 2, 3], dtype=np.uint64))

    rows = distributed_lookup_table(ids, client, table_id=0, dim=4)
    assert tuple(rows.shape) == (2, 2, 4)
    loss = rows.sum()
    loss.backward()

    after = client.pull_sparse(0, np.array([1, 2, 3], dtype=np.uint64))
    # d(sum)/d(row) = 1 per occurrence; id 2 appears twice -> grad 2
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 1, rtol=1e-5)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 2, rtol=1e-5)
    np.testing.assert_allclose(after[2], before[2] - 0.5 * 1, rtol=1e-5)


def test_wide_deep_style_convergence(cluster):
    """Sparse embeddings on the PS + dense net on the trainer: loss decreases."""
    servers, client = cluster
    paddle.seed(0)
    emb = DistributedEmbedding(table_id=0, embedding_dim=4, client=client)
    dense = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=dense.parameters())
    rng = np.random.RandomState(0)
    ids_all = rng.randint(0, 50, (64, 2)).astype(np.int64)
    labels_all = ((ids_all.sum(1) % 2)).astype(np.int64)
    loss_fn = paddle.nn.CrossEntropyLoss()

    losses = []
    for epoch in range(15):
        total = 0.0
        for i in range(0, 64, 16):
            ids = paddle.to_tensor(ids_all[i:i + 16])
            labels = paddle.to_tensor(labels_all[i:i + 16])
            feat = emb(ids).reshape([16, 8])
            loss = loss_fn(dense(feat), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            total += float(loss.item())
        losses.append(total)
    assert losses[-1] < losses[0] * 0.8, losses


def test_dense_sync_flow(cluster):
    """DenseSync pushes trainer grads to the server optimizer and pulls params."""
    servers, client = cluster
    paddle.seed(0)
    lin = paddle.nn.Linear(2, 3)
    w = lin.weight
    tid = 1  # dim 6 == w.size
    sync = DenseSync(client, {tid: w}, pull_interval=1)
    np.testing.assert_allclose(client.pull_dense(1).reshape(w.shape), w.numpy(),
                               rtol=1e-6)
    x = paddle.to_tensor(np.ones((4, 2), dtype="float32"))
    before = w.numpy().copy()
    loss = lin(x).sum()
    loss.backward()
    sync.step()
    after = w.numpy()
    assert not np.allclose(before, after)  # server applied the update, pull got it


_PS_CLUSTER_SCRIPT = """
    import os
    import numpy as np
    import jax; jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps import (SparseTableConfig, TheOnePSRuntime,
                                           DistributedEmbedding)

    runtime = TheOnePSRuntime(
        sparse_tables=[SparseTableConfig(table_id=0, dim=4, learning_rate=0.5)])
    if runtime.is_server():
        runtime.init_server()
        runtime.run_server()
    else:
        client = runtime.init_worker()
        emb = DistributedEmbedding(0, 4)
        runtime.bind_model(emb)
        ids = paddle.to_tensor(np.array([[1, 2, 3]], dtype=np.int64))
        out = emb(ids)
        out.sum().backward()
        runtime.barrier_worker()
        rows = client.pull_sparse(0, np.array([1], dtype=np.uint64))
        print("TRAINER_OK", rows.shape)
        runtime.barrier_worker(generation=1)
        runtime.stop_worker()
"""


def test_subprocess_ps_cluster(tmp_path):
    """Launcher PS mode: 2 servers + 2 trainers over real TCP, full flow."""
    script = tmp_path / "ps_train.py"
    script.write_text(textwrap.dedent(_PS_CLUSTER_SCRIPT))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--run_mode", "ps", "--server_num", "2", "--trainer_num", "2",
         "--log_dir", str(tmp_path / "log"), str(script)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=180)
    assert res.returncode == 0, (res.stdout, res.stderr)
    for t in range(2):
        log = (tmp_path / "log" / f"trainer.{t}.log").read_text()
        assert "TRAINER_OK" in log, log


def test_barrier_is_reusable(cluster):
    """Same barrier key must synchronize every step, not only the first."""
    servers, client = cluster
    import threading

    client2 = PSClient([f"127.0.0.1:{servers[0].port}"])
    results = []
    for step in range(3):
        t = threading.Thread(
            target=lambda: (client2._lib.ps_barrier(client2._conns[0], 7, 2),
                            results.append(step)))
        t.start()
        client.barrier(7, 2)  # via server[0]
        t.join(timeout=10)
        assert not t.is_alive(), f"barrier round {step} did not release"
    assert results == [0, 1, 2]
    client2.close()


def test_push_to_unknown_table_keeps_connection_usable(cluster):
    servers, client = cluster
    ids = np.array([1, 2], dtype=np.uint64)
    with pytest.raises(RuntimeError, match="rc=-2"):
        client.push_sparse(99, ids, np.ones((2, 4), dtype=np.float32), dim=4)
    # connection must still speak the protocol after the error
    rows = client.pull_sparse(0, ids)
    assert rows.shape == (2, 4)
    with pytest.raises(RuntimeError, match="rc=-2"):
        client.push_dense(99, np.ones(6, dtype=np.float32))
    client.push_dense_param(1, np.zeros(6, dtype=np.float32))
    np.testing.assert_array_equal(client.pull_dense(1), np.zeros(6))

"""paddle.cost_model parity (reference python/paddle/cost_model/cost_model.py
+ unittests/test_cost_model.py): build_program / profile_measure /
static_cost_data / get_static_op_time, backed by XLA cost analysis instead of
CUPTI + a pre-measured GPU benchmark JSON."""
import numpy as np
import pytest

import paddle_tpu as paddle

CostModel = paddle.cost_model.CostModel  # the reference's import surface


@pytest.fixture(autouse=True)
def _back_to_dygraph():
    yield
    paddle.disable_static()


def test_build_program_and_profile_measure():
    cm = CostModel()
    startup, main = cm.build_program()
    cost = cm.profile_measure(startup, main, device="tpu",
                              fetch_cost_list=["time"])
    assert cost["time"] > 0
    # the XLA analysis keys ride along (flops of fc+mean+sgd step > 0)
    assert cost.get("flops", 0) > 0
    assert cost.get("bytes_accessed", 0) > 0


def test_executor_cost_analysis_direct():
    import paddle_tpu.static as static

    paddle.enable_static()
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main_program=main, startup_program=startup):
        x = static.data(name="X", shape=[4, 8], dtype="float32")
        y = paddle.mean(x * 2.0)
    exe = static.Executor()
    analysis = exe.cost_analysis(
        main, feed={"X": np.zeros((4, 8), "float32")}, fetch_list=[y])
    assert analysis.get("flops", 0) > 0
    # repeat call reuses the cached AOT executable (no recompile): same dict
    assert exe.cost_analysis(
        main, feed={"X": np.zeros((4, 8), "float32")},
        fetch_list=[y]) == analysis
    # a non-train program with no fetches would DCE to an empty computation —
    # that must be an error, not a silent zero-cost report
    with pytest.raises(ValueError):
        exe.cost_analysis(main, feed={"X": np.zeros((4, 8), "float32")})


def test_static_cost_data_and_op_time():
    cm = CostModel()
    data = cm.static_cost_data()
    assert {e["op"] for e in data} >= {"matmul", "add", "softmax"}
    mm = cm.get_static_op_time("matmul")
    assert mm["op_time"] > 0
    mm_bwd = cm.get_static_op_time("matmul", forward=False)
    assert mm_bwd["op_time"] > 0
    # a matmul moves more flops than an elementwise add at the same shape
    entries = {e["op"]: e for e in data}
    assert entries["matmul"]["flops"] > entries["add"]["flops"]
    with pytest.raises(ValueError):
        cm.get_static_op_time(None)
    assert cm.get_static_op_time("nonexistent_op") == {}

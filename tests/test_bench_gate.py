"""tools/bench_gate.py self-test: the perf-trajectory gate over synthetic
history/baseline files, plus a live run against the repo's real
BENCH_HISTORY.jsonl + tools/bench_baseline.json (which must always pass —
a red gate at HEAD means either a regression landed or the baseline was
not re-pinned after a deliberate perf change)."""
import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import bench_gate  # noqa: E402


def _write_history(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _write_baseline(path, baselines):
    with open(path, "w") as f:
        json.dump({"baselines": baselines}, f)


def _row(value, **extra):
    return {"metric": "toks_per_sec", "value": value, "extra": extra or None}


@pytest.fixture
def files(tmp_path):
    hist = str(tmp_path / "history.jsonl")
    base = str(tmp_path / "baseline.json")

    def run(rows, baselines, *flags):
        _write_history(hist, rows)
        _write_baseline(base, baselines)
        return bench_gate.main(["--history", hist, "--baseline", base,
                                *flags])

    run.hist, run.base = hist, base
    return run


def test_newest_matching_row_wins(files):
    """File order is recency: the gate must judge the LAST matching row,
    not the first — an old slow row followed by a recovered one passes."""
    rows = [_row(50.0, cfg="a"), _row(100.0, cfg="b"), _row(99.0, cfg="a")]
    base = [{"name": "a", "metric": "toks_per_sec", "match": {"cfg": "a"},
             "value": 100.0, "direction": "higher", "rel_tol": 0.05}]
    assert files(rows, base) == 0


def test_regression_fails_with_nonzero_exit(files, capsys):
    rows = [_row(100.0, cfg="a"), _row(60.0, cfg="a")]  # newest is -40%
    base = [{"name": "a", "metric": "toks_per_sec", "match": {"cfg": "a"},
             "value": 100.0, "direction": "higher", "rel_tol": 0.2}]
    assert files(rows, base) == 1
    summary = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])["summary"]
    assert summary["regressed"] == ["a"] and summary["failed"] is True


def test_lower_is_better_direction(files):
    """Latency-style metrics gate in the other direction."""
    base = [{"name": "lat", "metric": "toks_per_sec", "match": {"cfg": "a"},
             "value": 10.0, "direction": "lower", "rel_tol": 0.1}]
    assert files([_row(10.5, cfg="a")], base) == 0   # within +10%
    assert files([_row(12.0, cfg="a")], base) == 1   # 20% slower


def test_none_matches_null_and_absent(files):
    """A baseline pinning {knob: None} must accept both rows that write
    null for the disabled knob and older rows that omit the key entirely —
    but never a row where the knob is set."""
    base = [{"name": "plain", "metric": "toks_per_sec",
             "match": {"cfg": "a", "knob": None}, "value": 100.0,
             "direction": "higher", "rel_tol": 0.1}]
    assert files([_row(100.0, cfg="a", knob=None)], base) == 0
    assert files([_row(100.0, cfg="a")], base) == 0
    # knob set -> no matching row at all (missing, non-strict default ok)
    assert files([_row(5.0, cfg="a", knob="on")], base) == 0
    assert files([_row(5.0, cfg="a", knob="on")], base, "--strict") == 1


def test_missing_row_strict_vs_default(files, capsys):
    base = [{"name": "ghost", "metric": "toks_per_sec",
             "match": {"cfg": "never"}, "value": 1.0}]
    assert files([_row(1.0, cfg="a")], base) == 0
    summary = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])["summary"]
    assert summary["missing"] == ["ghost"]
    assert files([_row(1.0, cfg="a")], base, "--strict") == 1


def test_update_repins_to_newest(files):
    """--update rewrites the baseline values from the newest matching rows;
    the rewritten file then gates green against the same history."""
    rows = [_row(100.0, cfg="a"), _row(42.0, cfg="a")]
    base = [{"name": "a", "metric": "toks_per_sec", "match": {"cfg": "a"},
             "value": 100.0, "direction": "higher", "rel_tol": 0.05}]
    assert files(rows, base, "--update") == 0
    doc = json.load(open(files.base))
    assert doc["baselines"][0]["value"] == 42.0
    assert bench_gate.main(["--history", files.hist,
                            "--baseline", files.base]) == 0


def test_real_repo_gate_is_green(capsys):
    """The committed baselines must pass against the committed history."""
    assert bench_gate.main([]) == 0
    summary = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])["summary"]
    assert summary["ok"] == summary["baselines"] >= 3

"""Eager dispatch fast lane (FLAGS_eager_fast_path) + micro-fusion
(FLAGS_eager_fusion): results must be bit-identical to the general path,
laziness must never be observable as a wrong value, and every guard flag
must close the lane.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import dispatch
from paddle_tpu.core import eager_fusion as ef
from paddle_tpu.core.tensor import Tensor

import jax.numpy as jnp


def setup_function(_):
    dispatch._clear_rule_cache()


# ---- fast lane ----

def test_fast_lane_hit_after_one_general_dispatch():
    a = Tensor(jnp.ones((4, 4), jnp.float32))
    h0 = dispatch._FAST_HITS.get()
    paddle.tanh(a)                       # general path resolves + publishes
    assert len(dispatch._FAST_CACHE) >= 1
    assert dispatch._FAST_HITS.get() == h0
    out = paddle.tanh(a)                 # second call rides the lane
    assert dispatch._FAST_HITS.get() == h0 + 1
    np.testing.assert_array_equal(out.numpy(), np.tanh(np.ones((4, 4),
                                                               np.float32)))


def test_fast_lane_bit_identical_to_general_path():
    rng = np.random.RandomState(0)
    xn = rng.randn(16, 16).astype(np.float32)

    def run():
        x = paddle.to_tensor(xn, stop_gradient=False)
        y = paddle.tanh(x * 2.0 + 1.0)
        loss = (y * y).mean()
        loss.backward()
        return loss.numpy(), x.grad.numpy()

    l_fast, g_fast = run()
    l_fast2, g_fast2 = run()             # steady state: lane hits
    paddle.set_flags({"eager_fast_path": False})
    l_slow, g_slow = run()
    np.testing.assert_array_equal(l_fast, l_slow)
    np.testing.assert_array_equal(g_fast, g_slow)
    np.testing.assert_array_equal(l_fast2, l_slow)
    np.testing.assert_array_equal(g_fast2, g_slow)


def test_fast_lane_closed_under_amp_and_debug_flags():
    a = Tensor(jnp.ones((4, 4)))
    paddle.tanh(a)
    h0 = dispatch._FAST_HITS.get()
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        paddle.tanh(a)                   # AMP ctx: must take the general path
    assert dispatch._FAST_HITS.get() == h0
    paddle.set_flags({"check_nan_inf": True})
    try:
        assert not dispatch._FAST_LANE_OK
        bad = Tensor(jnp.asarray([np.inf], jnp.float32))
        with pytest.raises(FloatingPointError):
            paddle.exp(bad)              # the sentinel still fires
    finally:
        paddle.set_flags({"check_nan_inf": False})
    assert dispatch._FAST_LANE_OK


def test_fast_lane_scalar_closure_not_aliased():
    """The python-scalar binary fast path bakes the scalar into the kernel's
    defaults — two different scalars must resolve to two lane entries."""
    a = Tensor(jnp.ones((4,)))
    o2 = (a * 2.0).numpy()
    o3 = (a * 3.0).numpy()
    o2b = (a * 2.0).numpy()              # steady-state hit
    np.testing.assert_array_equal(o2, 2 * np.ones(4, np.float32))
    np.testing.assert_array_equal(o3, 3 * np.ones(4, np.float32))
    np.testing.assert_array_equal(o2b, o2)


def test_fast_lane_value_dependent_kernel_stays_eager():
    ids = Tensor(jnp.asarray(np.array([0, 0, 1], np.int64)))

    def kernel(i):
        n = int(jnp.max(i)) + 1          # concretization: untraceable
        return jnp.zeros((n,))

    o1 = dispatch.apply("t_fp_valdep", kernel, [ids], differentiable=False)
    o2 = dispatch.apply("t_fp_valdep", kernel, [ids], differentiable=False)
    assert list(o1.shape) == list(o2.shape) == [2]
    # the lane remembers the kernel is uncacheable, never retries the rules
    assert any(v is None for v in dispatch._FAST_CACHE.values())


def test_any_flag_change_drops_fast_cache():
    a = Tensor(jnp.ones((4,)))
    paddle.tanh(a)
    assert len(dispatch._FAST_CACHE) >= 1
    paddle.set_flags({"tpu_matmul_precision": "highest"})
    try:
        assert len(dispatch._FAST_CACHE) == 0
    finally:
        paddle.set_flags({"tpu_matmul_precision": "default"})


# ---- micro-fusion ----

def _fusion(on=True):
    paddle.set_flags({"eager_fusion": on})


def test_fusion_off_by_default_returns_plain_tensors():
    a = Tensor(jnp.ones((4,)))
    assert type(paddle.tanh(a)) is Tensor


def test_fusion_chain_defers_then_matches_eager():
    rng = np.random.RandomState(0)
    xn = rng.randn(32, 32).astype(np.float32)
    x = paddle.to_tensor(xn)
    _fusion(True)
    try:
        y = x
        for _ in range(6):
            y = paddle.tanh(y) * 1.01
        assert type(y) is ef.LazyTensor and y.is_pending
        # metadata answers WITHOUT forcing
        assert y.shape == [32, 32]
        assert y.dtype == np.float32
        assert y.is_pending
        got = y.numpy()
        assert not y.is_pending
    finally:
        _fusion(False)
    ref = x
    for _ in range(6):
        ref = paddle.tanh(ref) * 1.01
    np.testing.assert_allclose(got, ref.numpy(), rtol=2e-6, atol=1e-7)


def test_fusion_diamond_delivers_every_live_tensor():
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    _fusion(True)
    try:
        a = paddle.exp(x)
        b = a * 2.0
        c = a + 1.0                      # a has two consumers
        bn = b.numpy()                   # forces {a, b}; a stays observable
        cn = c.numpy()
        an = a.numpy()
    finally:
        _fusion(False)
    ref = np.exp(np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(an, ref, rtol=1e-6)
    np.testing.assert_allclose(bn, ref * 2, rtol=1e-6)
    np.testing.assert_allclose(cn, ref + 1, rtol=1e-6)


def test_fusion_nonfusable_consumer_forces_chain():
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 8).astype(np.float32))
    _fusion(True)
    try:
        s = paddle.tanh(x)
        m = paddle.sum(s)                # not fusable: forces s transparently
    finally:
        _fusion(False)
    np.testing.assert_allclose(m.numpy(), np.tanh(x.numpy()).sum(),
                               rtol=1e-4, atol=1e-6)


def test_fusion_chain_cap_bounds_graph():
    x = paddle.to_tensor(np.ones(16, np.float32))
    _fusion(True)
    try:
        c0 = ef._FUSED_CHAINS.get()
        y = x
        for _ in range(3 * ef.MAX_CHAIN):
            y = y * 1.0001
        # the cap forced intermediate segments without any explicit access
        assert ef._FUSED_CHAINS.get() > c0
        got = y.numpy()
    finally:
        _fusion(False)
    np.testing.assert_allclose(
        got, np.float32(1.0001) ** (3 * ef.MAX_CHAIN) * np.ones(16),
        rtol=1e-5)


def test_fusion_structure_cache_reused_across_iterations():
    x = paddle.to_tensor(np.ones(16, np.float32))
    _fusion(True)
    try:
        for _ in range(3):               # identical chain structure each time
            y = x
            for _ in range(5):
                y = paddle.tanh(y) + 0.5
            y.numpy()
        assert len(ef._FUSION_CACHE) == 1
    finally:
        _fusion(False)


@pytest.mark.parametrize("make_arg", [
    lambda: paddle.to_tensor(np.arange(4)),                     # int dtype
    lambda: paddle.to_tensor(np.ones(4, np.float32),
                             stop_gradient=False),              # needs grad
])
def test_fusion_ineligible_inputs_fall_through(make_arg):
    _fusion(True)
    try:
        t = make_arg()
        out = t + 1
        assert type(out) is Tensor       # executed eagerly, not deferred
    finally:
        _fusion(False)


def test_fusion_grad_flows_through_forced_chain_boundary():
    """A lazy (stop-grad) chain feeding a differentiable op must force and
    then participate in autograd like any constant input."""
    xn = np.random.RandomState(0).randn(8).astype(np.float32)
    w = paddle.to_tensor(np.ones(8, np.float32), stop_gradient=False)
    x = paddle.to_tensor(xn)
    _fusion(True)
    try:
        feat = paddle.tanh(x) * 2.0      # lazy, stop_gradient
        loss = (feat * w).sum()
        loss.backward()
    finally:
        _fusion(False)
    np.testing.assert_allclose(w.grad.numpy(), np.tanh(xn) * 2, rtol=1e-5)


def test_fusion_scale_op_attrs():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32))
    _fusion(True)
    try:
        y = paddle.scale(paddle.scale(x, scale=2.0), scale=3.0, bias=1.0)
        got = y.numpy()
    finally:
        _fusion(False)
    np.testing.assert_allclose(got, np.arange(4, dtype=np.float32) * 6 + 1,
                               rtol=1e-6)


# ---- matmul terminator ----

def test_fusion_matmul_terminator_bit_identical():
    """A matmul closing an elementwise prologue compiles as ONE composite,
    and the result must be bit-identical to the unfused path — fusion is a
    dispatch optimization, never a numerics change."""
    rng = np.random.RandomState(7)
    x = paddle.to_tensor(rng.randn(16, 32).astype(np.float32))
    w = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))

    def chain():
        return paddle.matmul(paddle.tanh(x) * 0.5 + 0.25, w)

    ref = chain().numpy()                # fusion off: op-by-op
    _fusion(True)
    try:
        c0 = ef._FUSED_CHAINS.get()
        got = chain().numpy()
        # prologue + terminating contraction forced as one segment
        assert ef._FUSED_CHAINS.get() == c0 + 1
    finally:
        _fusion(False)
    np.testing.assert_array_equal(got, ref)


def test_fusion_matmul_transpose_variants_keyed_separately():
    """transpose_x/transpose_y ride in the node key (via the frozen attr
    key), so composite cache hits can never cross transpose variants."""
    a = paddle.to_tensor(np.random.RandomState(8)
                         .randn(8, 8).astype(np.float32))
    ref_plain = paddle.matmul(paddle.tanh(a), a).numpy()
    ref_trans = paddle.matmul(paddle.tanh(a), a, transpose_y=True).numpy()
    _fusion(True)
    try:
        plain = paddle.matmul(paddle.tanh(a), a).numpy()
        trans = paddle.matmul(paddle.tanh(a), a, transpose_y=True).numpy()
    finally:
        _fusion(False)
    np.testing.assert_array_equal(plain, ref_plain)
    np.testing.assert_array_equal(trans, ref_trans)


def test_fusion_standalone_matmul_skips_lazy_detour():
    """A matmul with no pending operand gains nothing from the lazy window
    and must take the normal dispatch path untouched."""
    a = paddle.to_tensor(np.ones((4, 4), np.float32))
    _fusion(True)
    try:
        c0 = ef._FUSED_CHAINS.get()
        out = paddle.matmul(a, a)
        assert type(out) is Tensor       # not deferred, not recorded
        assert ef._FUSED_CHAINS.get() == c0
    finally:
        _fusion(False)
    np.testing.assert_allclose(out.numpy(), np.full((4, 4), 4, np.float32))

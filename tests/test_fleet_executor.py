"""Fleet executor (actor runtime) tests: native message bus, interceptor DAG,
credit-based flow control, and 2-process distributed inference over TCP
(reference fleet_executor/: carrier/interceptor/message_bus/dist_model)."""
import pickle
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet_executor import (
    DATA_IS_READY, Carrier, ComputeInterceptor, DistModel, FleetExecutor,
    TaskNode, _make_bus)



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestBus:
    def test_native_bus_loads(self):
        from paddle_tpu.core.native import load_library

        assert load_library("fleet_executor") is not None

    def test_local_send_recv(self):
        bus = _make_bus()
        bus.register(7)
        bus.send(1, 7, DATA_IS_READY, b"hello")
        src, mtype, payload = bus.recv(7, timeout_ms=1000)
        assert (src, mtype, payload) == (1, DATA_IS_READY, b"hello")
        assert bus.recv(7, timeout_ms=50) is None  # timeout -> None
        bus.stop()

    def test_cross_bus_tcp(self):
        """Two buses in one process exchange through real sockets."""
        p0, p1 = _free_port(), _free_port()
        eps = [f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"]
        b0 = _make_bus(rank=0, nranks=2, port=p0, endpoints=eps)
        b1 = _make_bus(rank=1, nranks=2, port=p1, endpoints=eps)
        b0.register(100)
        b1.register(200)
        b0.route(200, 1)
        b1.route(100, 0)
        b0.send(100, 200, DATA_IS_READY, b"ping" * 1000)
        got = b1.recv(200, timeout_ms=3000)
        assert got is not None and got[2] == b"ping" * 1000
        b1.send(200, 100, DATA_IS_READY, b"pong")
        got = b0.recv(100, timeout_ms=3000)
        assert got is not None and got[2] == b"pong"
        b0.stop()
        b1.stop()


class TestExecutorDAG:
    def test_linear_pipeline(self):
        """source -> double -> +1 -> sink over 4 micro-batches."""

        def double(p):
            return pickle.dumps(pickle.loads(p) * 2)

        def plus1(p):
            return pickle.dumps(pickle.loads(p) + 1)

        nodes = [
            TaskNode(task_id=0, run_fn=double, downstream=[1], max_run_times=4),
            TaskNode(task_id=1, run_fn=plus1, downstream=[], max_run_times=4),
        ]
        exe = FleetExecutor(nodes)
        outs = exe.run(pickle.dumps(21), num_micro_batches=4)
        assert [pickle.loads(o) for o in outs] == [43, 43, 43, 43]
        exe.shutdown()

    def test_diamond_dag(self):
        """fan-out then join: both branch payloads reach the join node."""
        seen = []

        def branch_a(p):
            return b"A" + p

        def branch_b(p):
            return b"B" + p

        def join(pa, pb):
            seen.append((pa, pb))
            return pa + pb

        nodes = [
            TaskNode(task_id=0, run_fn=lambda p: p, downstream=[1, 2],
                     max_run_times=2),
            TaskNode(task_id=1, run_fn=branch_a, downstream=[3], max_run_times=2),
            TaskNode(task_id=2, run_fn=branch_b, downstream=[3], max_run_times=2),
            TaskNode(task_id=3, run_fn=join, downstream=[], max_run_times=2),
        ]
        exe = FleetExecutor(nodes)
        outs = exe.run(b"x", num_micro_batches=2)
        assert sorted(outs) == [b"AxBx", b"AxBx"]
        exe.shutdown()

    def test_backpressure_credits(self):
        """A slow consumer throttles the producer to buffer_size in flight."""
        import threading

        inflight = []
        lock = threading.Lock()
        gate = threading.Event()

        def fast(p):
            with lock:
                inflight.append(1)
            return p

        def slow(p):
            gate.wait(5)
            with lock:
                inflight.append(-1)
            return p

        nodes = [
            TaskNode(task_id=0, run_fn=fast, downstream=[1], max_run_times=8,
                     buffer_size=2),
            TaskNode(task_id=1, run_fn=slow, downstream=[], max_run_times=8),
        ]
        exe = FleetExecutor(nodes)
        for _ in range(8):
            exe.carrier.bus.send(-1, 0, DATA_IS_READY, b"m")
        time.sleep(0.5)
        with lock:
            produced_before_release = sum(1 for v in inflight if v == 1)
        assert produced_before_release <= 2, produced_before_release
        gate.set()
        outs = [exe.carrier.wait_result(timeout=10) for _ in range(8)]
        assert len(outs) == 8
        exe.shutdown()


_DIST_SCRIPT = """
    import os, pickle, sys
    import numpy as np
    import jax; jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed.fleet_executor import DistModel

    stage = int(os.environ["STAGE"])
    eps = os.environ["EPS"].split(",")
    port = int(eps[stage].split(":")[1])

    def fn(x):
        # stage 0 doubles, stage 1 adds 5 — composed = 2x + 5
        return x * 2 if stage == 0 else x + 5

    dm = DistModel(fn, stage, 2, eps, port=port)
    if stage == 0:
        dm.run(np.arange(4))
        dm.run(np.arange(4) + 10)
        print("STAGE0_DONE", flush=True)
    else:
        out1 = dm.run(None)
        out2 = dm.run(None)
        assert (out1 == np.arange(4) * 2 + 5).all(), out1
        assert (out2 == (np.arange(4) + 10) * 2 + 5).all(), out2
        print("STAGE1_OK", flush=True)
    dm.shutdown()
"""


def test_dist_model_two_processes(tmp_path):
    script = tmp_path / "dist_model.py"
    script.write_text(textwrap.dedent(_DIST_SCRIPT))
    p0, p1 = _free_port(), _free_port()
    eps = f"127.0.0.1:{p0},127.0.0.1:{p1}"
    procs = []
    for stage in range(2):
        env = {"STAGE": str(stage), "EPS": eps, "JAX_PLATFORMS": "cpu",
               "PATH": "/usr/bin:/bin"}
        import os

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, **env}
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = repo_root + (os.pathsep + existing if existing else "")
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=300)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert "STAGE0_DONE" in outs[0], outs[0]
    assert "STAGE1_OK" in outs[1], outs[1]

"""TrainStepEngine.run_steps: K steps fused in one lax.scan dispatch.

Reference analogue: fleet_executor runs max_run_times iterations inside one
Executor dispatch (paddle/fluid/distributed/fleet_executor/
compute_interceptor.cc LoopCounter); here the loop is a compiled lax.scan so
K steps cost one PJRT execute.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.engine import TrainStepEngine


def _make(seed=0):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss())


def _batch(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 16).astype(np.float32)
    y = rng.randint(0, 4, (n,)).astype(np.int64)
    return paddle.to_tensor(x), paddle.to_tensor(y)


def test_run_steps_matches_step_loop():
    x, y = _batch()
    e1 = _make()
    loop_losses = [float(e1.step(x, y).item()) for _ in range(5)]

    e2 = _make()
    scan_losses = e2.run_steps(x, y, steps=5)
    assert scan_losses.shape == [5]
    np.testing.assert_allclose(np.asarray(scan_losses._data), loop_losses,
                               rtol=2e-4, atol=1e-5)
    # step counters advanced identically (ckpt/resume consistency)
    assert e2._step_count == e1._step_count == 5
    assert e2.optimizer._step_count == 5


def test_run_steps_stacked_batches_and_resume():
    x, y = _batch()
    xs = paddle.to_tensor(np.stack([np.asarray(x._data)] * 3))
    ys = paddle.to_tensor(np.stack([np.asarray(y._data)] * 3))
    e = _make()
    l1 = e.run_steps(xs, ys)          # leading [K] axis form
    l2 = e.run_steps(x, y, steps=3)   # continues from the same state
    assert e._step_count == 6
    # training continues to make progress across the two dispatches
    assert float(l2._data[-1]) < float(l1._data[0])


def test_warm_scan_preserves_state():
    x, y = _batch()
    e1, e2 = _make(), _make()
    ref = np.asarray(e1.run_steps(x, y, steps=3)._data)
    e2.warm_scan(x, y, steps=3)          # compiles + runs on copies
    assert e2._step_count == 0
    got = np.asarray(e2.run_steps(x, y, steps=3)._data)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_run_steps_rejects_indivisible_batch():
    from paddle_tpu.distributed.mesh import (
        HybridCommunicateGroup, set_hybrid_communicate_group)
    if len(__import__("jax").devices()) < 2:
        pytest.skip("needs multi-device mesh")
    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=2)
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    e = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                        hcg=hcg)
    x = paddle.to_tensor(np.ones((3, 16), np.float32))  # 3 % dp2 != 0
    y = paddle.to_tensor(np.zeros((3,), np.int64))
    with pytest.raises(ValueError, match="not divisible"):
        e.run_steps(x, y, steps=2)


def test_run_steps_interleaves_with_step():
    x, y = _batch()
    e = _make()
    a = float(e.step(x, y).item())
    ls = e.run_steps(x, y, steps=4)
    b = float(e.step(x, y).item())
    assert e._step_count == 6
    assert b < a  # loss still decreasing through mixed dispatch modes
    assert ls.shape == [4]

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_default_dtypes():
    assert paddle.to_tensor(1).dtype == paddle.int64
    assert paddle.to_tensor(1.5).dtype == paddle.float32
    assert paddle.to_tensor(np.float64(1.5)).dtype == paddle.float32
    assert paddle.to_tensor(True).dtype == paddle.bool


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2, 2], 7).numpy()[0, 0] == 7
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
    assert paddle.eye(3).numpy()[1, 1] == 1


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1])


def test_scalar_keeps_dtype():
    a = paddle.to_tensor([1.0, 2.0], dtype="bfloat16")
    assert (a * 2.0).dtype == paddle.bfloat16
    assert (a + 1).dtype == paddle.bfloat16


def test_comparison():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    m = a > 1.5
    assert m.dtype == paddle.bool
    np.testing.assert_array_equal(m.numpy(), [False, True, True])


def test_indexing():
    a = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(a[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(a[1, 2].numpy(), 6)
    np.testing.assert_allclose(a[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(a[0:2, ::2].numpy(), [[0, 2], [4, 6]])
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(a[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])
    mask = paddle.to_tensor([True, False, True])
    assert a[mask].shape == [2, 4]


def test_setitem():
    a = paddle.zeros([3, 3])
    a[1, 1] = 5.0
    assert a.numpy()[1, 1] == 5.0
    a[0] = paddle.ones([3])
    np.testing.assert_allclose(a.numpy()[0], [1, 1, 1])


def test_item_and_conversions():
    t = paddle.to_tensor(3.5)
    assert t.item() == 3.5
    assert float(t) == 3.5
    assert int(paddle.to_tensor(7)) == 7
    assert len(paddle.zeros([5, 2])) == 5


def test_astype_cast():
    a = paddle.to_tensor([1.7, 2.3])
    b = a.astype("int32")
    assert b.dtype == paddle.int32
    c = paddle.cast(a, "float64")
    assert str(c.dtype) in ("float64", "float32")  # f64 may be demoted without x64


def test_set_value_and_clone():
    a = paddle.ones([2, 2])
    a.set_value(np.zeros((2, 2), np.float32))
    assert a.numpy().sum() == 0
    b = paddle.clone(a)
    b.set_value(np.ones((2, 2), np.float32))
    assert a.numpy().sum() == 0


def test_shape_ops():
    a = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert paddle.reshape(a, [6, 4]).shape == [6, 4]
    assert paddle.reshape(a, [-1]).shape == [24]
    assert paddle.transpose(a, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(a, 1).shape == [2, 12]
    assert paddle.unsqueeze(a, 0).shape == [1, 2, 3, 4]
    assert paddle.squeeze(paddle.unsqueeze(a, 0), 0).shape == [2, 3, 4]
    assert paddle.concat([a, a], axis=1).shape == [2, 6, 4]
    assert paddle.stack([a, a]).shape == [2, 2, 3, 4]
    parts = paddle.split(a, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = paddle.split(a, [1, -1], axis=1)
    assert parts[1].shape == [2, 2, 4]
    assert paddle.tile(a, [1, 2, 1]).shape == [2, 6, 4]
    assert paddle.expand(paddle.ones([1, 3]), [5, 3]).shape == [5, 3]


def test_where_nonzero():
    a = paddle.to_tensor([1.0, -1.0, 2.0])
    out = paddle.where(a > 0, a, paddle.zeros_like(a))
    np.testing.assert_allclose(out.numpy(), [1, 0, 2])
    nz = paddle.nonzero(a > 0)
    np.testing.assert_array_equal(nz.numpy(), [[0], [2]])


def test_gather_scatter():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    g = paddle.gather(x, paddle.to_tensor([0, 2]))
    np.testing.assert_allclose(g.numpy(), [[0, 1, 2], [6, 7, 8]])
    upd = paddle.to_tensor(np.ones((2, 3), np.float32))
    s = paddle.scatter(x, paddle.to_tensor([1, 3]), upd)
    np.testing.assert_allclose(s.numpy()[1], [1, 1, 1])
    np.testing.assert_allclose(s.numpy()[3], [1, 1, 1])


def test_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
    v, i = paddle.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [5, 4])
    np.testing.assert_array_equal(i.numpy(), [4, 2])
    np.testing.assert_allclose(paddle.sort(x).numpy(), [1, 1, 3, 4, 5])


def test_repr():
    t = paddle.ones([2, 2])
    assert "Tensor" in repr(t)

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_default_dtypes():
    assert paddle.to_tensor(1).dtype == paddle.int64
    assert paddle.to_tensor(1.5).dtype == paddle.float32
    assert paddle.to_tensor(np.float64(1.5)).dtype == paddle.float32
    assert paddle.to_tensor(True).dtype == paddle.bool


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2, 2], 7).numpy()[0, 0] == 7
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
    assert paddle.eye(3).numpy()[1, 1] == 1


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1])


def test_scalar_keeps_dtype():
    a = paddle.to_tensor([1.0, 2.0], dtype="bfloat16")
    assert (a * 2.0).dtype == paddle.bfloat16
    assert (a + 1).dtype == paddle.bfloat16


def test_comparison():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    m = a > 1.5
    assert m.dtype == paddle.bool
    np.testing.assert_array_equal(m.numpy(), [False, True, True])


def test_indexing():
    a = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(a[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(a[1, 2].numpy(), 6)
    np.testing.assert_allclose(a[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(a[0:2, ::2].numpy(), [[0, 2], [4, 6]])
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(a[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])
    mask = paddle.to_tensor([True, False, True])
    assert a[mask].shape == [2, 4]


def test_setitem():
    a = paddle.zeros([3, 3])
    a[1, 1] = 5.0
    assert a.numpy()[1, 1] == 5.0
    a[0] = paddle.ones([3])
    np.testing.assert_allclose(a.numpy()[0], [1, 1, 1])


def test_item_and_conversions():
    t = paddle.to_tensor(3.5)
    assert t.item() == 3.5
    assert float(t) == 3.5
    assert int(paddle.to_tensor(7)) == 7
    assert len(paddle.zeros([5, 2])) == 5


def test_astype_cast():
    a = paddle.to_tensor([1.7, 2.3])
    b = a.astype("int32")
    assert b.dtype == paddle.int32
    c = paddle.cast(a, "float64")
    assert str(c.dtype) in ("float64", "float32")  # f64 may be demoted without x64


def test_set_value_and_clone():
    a = paddle.ones([2, 2])
    a.set_value(np.zeros((2, 2), np.float32))
    assert a.numpy().sum() == 0
    b = paddle.clone(a)
    b.set_value(np.ones((2, 2), np.float32))
    assert a.numpy().sum() == 0


def test_shape_ops():
    a = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert paddle.reshape(a, [6, 4]).shape == [6, 4]
    assert paddle.reshape(a, [-1]).shape == [24]
    assert paddle.transpose(a, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(a, 1).shape == [2, 12]
    assert paddle.unsqueeze(a, 0).shape == [1, 2, 3, 4]
    assert paddle.squeeze(paddle.unsqueeze(a, 0), 0).shape == [2, 3, 4]
    assert paddle.concat([a, a], axis=1).shape == [2, 6, 4]
    assert paddle.stack([a, a]).shape == [2, 2, 3, 4]
    parts = paddle.split(a, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = paddle.split(a, [1, -1], axis=1)
    assert parts[1].shape == [2, 2, 4]
    assert paddle.tile(a, [1, 2, 1]).shape == [2, 6, 4]
    assert paddle.expand(paddle.ones([1, 3]), [5, 3]).shape == [5, 3]


def test_where_nonzero():
    a = paddle.to_tensor([1.0, -1.0, 2.0])
    out = paddle.where(a > 0, a, paddle.zeros_like(a))
    np.testing.assert_allclose(out.numpy(), [1, 0, 2])
    nz = paddle.nonzero(a > 0)
    np.testing.assert_array_equal(nz.numpy(), [[0], [2]])


def test_gather_scatter():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    g = paddle.gather(x, paddle.to_tensor([0, 2]))
    np.testing.assert_allclose(g.numpy(), [[0, 1, 2], [6, 7, 8]])
    upd = paddle.to_tensor(np.ones((2, 3), np.float32))
    s = paddle.scatter(x, paddle.to_tensor([1, 3]), upd)
    np.testing.assert_allclose(s.numpy()[1], [1, 1, 1])
    np.testing.assert_allclose(s.numpy()[3], [1, 1, 1])


def test_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
    v, i = paddle.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [5, 4])
    np.testing.assert_array_equal(i.numpy(), [4, 2])
    np.testing.assert_allclose(paddle.sort(x).numpy(), [1, 1, 3, 4, 5])


def test_repr():
    t = paddle.ones([2, 2])
    assert "Tensor" in repr(t)


def test_diag_embed_matches_torch():
    import torch

    x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    for off, d1, d2 in [(0, -2, -1), (1, -2, -1), (-2, -2, -1), (0, 0, 2),
                        (1, 1, 0)]:
        got = paddle.diag_embed(paddle.to_tensor(x), offset=off,
                                dim1=d1, dim2=d2).numpy()
        want = torch.diag_embed(torch.tensor(x), offset=off,
                                dim1=d1, dim2=d2).numpy()
        np.testing.assert_allclose(got, want, err_msg=str((off, d1, d2)))


def _ref_fill_diagonal(x, value, offset=0, wrap=False):
    """Numpy oracle transcribing the reference kernel exactly
    (fill_diagonal_op.cc:102-118): flat stride = sum_d prod(dims[d+1:]),
    size capped at dims[1]^2 unless wrap, write at i+offset while
    0 <= i % dims[1] + offset < dims[1]."""
    out = x.copy()
    dims = x.shape
    stride, prod = 0, 1
    for d in range(x.ndim - 1, -1, -1):
        stride += prod
        prod *= dims[d]
    # the dims[1]^2 cap only for 2-D: applied to cubes (as the reference
    # literally does) it fills a single element — a reference kernel bug we
    # deliberately do NOT reproduce (torch parity asserted above instead)
    size = x.size if wrap or x.ndim != 2 else min(x.size, dims[1] * dims[1])
    flat = out.reshape(-1)
    for i in range(0, size, stride):
        if 0 <= i % dims[1] + offset < dims[1]:
            flat[i + offset] = value
    return out


def test_fill_diagonal_matches_torch_and_reference_kernel():
    import torch

    for wrap in (False, True):
        x = np.random.RandomState(1).randn(7, 3).astype(np.float32)
        t = paddle.to_tensor(x.copy())
        t.fill_diagonal_(5.0, wrap=wrap)
        tt = torch.tensor(x.copy())
        tt.fill_diagonal_(5.0, wrap=wrap)
        np.testing.assert_allclose(t.numpy(), tt.numpy(),
                                   err_msg=f"wrap={wrap}")
    x3 = np.random.RandomState(2).randn(3, 3, 3).astype(np.float32)
    t = paddle.to_tensor(x3.copy())
    t.fill_diagonal_(9.0)
    tt = torch.tensor(x3.copy())
    tt.fill_diagonal_(9.0)
    np.testing.assert_allclose(t.numpy(), tt.numpy())
    # offset/wrap combinations torch does not support: pin against a numpy
    # transcription of the reference kernel (round-4 review: wrap+offset
    # wrote one extra element, negative offsets dropped the nc^2 cap)
    for shape, offset, wrap in [((7, 3), 1, True), ((7, 3), -1, True),
                                ((7, 3), -1, False), ((7, 3), 2, False),
                                ((3, 9), 2, False), ((3, 3, 3), 1, False),
                                ((3, 3, 3), -1, False)]:
        x = np.random.RandomState(3).randn(*shape).astype(np.float32)
        t = paddle.to_tensor(x.copy())
        t.fill_diagonal_(5.0, offset=offset, wrap=wrap)
        np.testing.assert_allclose(
            t.numpy(), _ref_fill_diagonal(x, 5.0, offset, wrap),
            err_msg=f"{shape} offset={offset} wrap={wrap}")
    # ndim>2 with unequal dims is rejected, as in the reference InferShape
    with pytest.raises(ValueError, match="dimensions equal"):
        paddle.to_tensor(np.zeros((2, 3, 4), np.float32)).fill_diagonal_(1.0)


def test_fill_diagonal_tensor_semantics():
    x = np.zeros((4, 5), np.float32)
    y = np.arange(4, dtype=np.float32)
    want = x.copy()
    for i in range(4):
        want[i, i] = y[i]
    got = paddle.to_tensor(x).fill_diagonal_tensor(paddle.to_tensor(y))
    np.testing.assert_allclose(got.numpy(), want)
    # offset diagonal
    want2 = x.copy()
    for i in range(4):
        want2[i, i + 1] = i
    got2 = paddle.to_tensor(x).fill_diagonal_tensor(
        paddle.to_tensor(y), offset=1)
    np.testing.assert_allclose(got2.numpy(), want2)
    # in-place variant mutates
    t = paddle.to_tensor(x.copy())
    t.fill_diagonal_tensor_(paddle.to_tensor(y))
    np.testing.assert_allclose(t.numpy(), want)

"""paddle.onnx.export: emitted bytes are decoded by an INDEPENDENT reader
(tests/onnx_runner.py) and executed with numpy against eager outputs —
validating both the hand-rolled protobuf wire format and the jaxpr->ONNX op
mapping (VERDICT r1 item #9: the ONNX stub had to become real or die)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from onnx_runner import load_model, run_model


def test_mlp_export_runs_identically(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Sigmoid())
    x = np.random.RandomState(0).rand(3, 8).astype(np.float32)
    path = paddle.onnx.export(net, str(tmp_path / "mlp"),
                              input_spec=[paddle.to_tensor(x)])
    assert path.endswith(".onnx")
    eager = net(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)


def test_lenet_export_runs_identically(tmp_path):
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    net.eval()
    x = np.random.RandomState(1).rand(2, 1, 28, 28).astype(np.float32)
    path = paddle.onnx.export(net, str(tmp_path / "lenet"),
                              input_spec=[paddle.to_tensor(x)])
    eager = net(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


def test_model_structure_and_opset(tmp_path):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    path = paddle.onnx.export(net, str(tmp_path / "lin"),
                              input_spec=[paddle.static.InputSpec([3, 4],
                                                                  "float32")])
    g = load_model(path)
    assert g["opset"] == 13
    assert g["inputs"] == ["input_0"]
    assert len(g["outputs"]) == 1
    assert "weight" in " ".join(g["initializers"])  # params are initializers
    ops = {n["op"] for n in g["nodes"]}
    assert "MatMul" in ops


def test_rem_and_isfinite_semantics(tmp_path):
    class M(nn.Layer):
        def forward(self, x, y):
            r = paddle.remainder(x, y)
            return paddle.where(paddle.isfinite(r), r,
                                paddle.zeros_like(r))

    x = np.array([-7.0, 7.0, np.inf, 5.5], np.float32)
    y = np.array([3.0, -3.0, 2.0, 2.0], np.float32)
    m = M()
    path = paddle.onnx.export(m, str(tmp_path / "rem"),
                              input_spec=[paddle.to_tensor(x),
                                          paddle.to_tensor(y)])
    eager = m(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    (got,) = run_model(path, {"input_0": x, "input_1": y})
    np.testing.assert_allclose(got, eager, rtol=1e-6)


def test_old_opset_rejected(tmp_path):
    with pytest.raises(ValueError, match="opset"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "o"),
                           input_spec=[paddle.static.InputSpec([1, 2],
                                                               "float32")],
                           opset_version=9)


def test_unsupported_primitive_raises_clearly(tmp_path):
    class Fancy(nn.Layer):
        def forward(self, x):
            return paddle.linalg.svd(x)[0]

    with pytest.raises(NotImplementedError, match="primitive"):
        paddle.onnx.export(Fancy(), str(tmp_path / "f"),
                           input_spec=[paddle.to_tensor(
                               np.eye(3, dtype=np.float32))])


def test_dynamic_dim_rejected(tmp_path):
    net = nn.Linear(4, 2)
    with pytest.raises(ValueError, match="dynamic"):
        paddle.onnx.export(net, str(tmp_path / "d"),
                           input_spec=[paddle.static.InputSpec([None, 4],
                                                               "float32")])


# ---- round 3 (VERDICT r2 #7): conv-transpose, dilated pooling, general
# dot_general, GPT block, golden wire-format fixtures ----

def test_conv_transpose_decoder_roundtrip(tmp_path):
    """lhs-dilated conv (the transposed-conv lowering) decomposes into
    zero-interleave + Conv — a conv-transpose DECODER must export and run."""
    paddle.seed(0)
    dec = nn.Sequential(nn.Conv2DTranspose(4, 8, 3, stride=2, padding=1),
                        nn.ReLU(),
                        nn.Conv2DTranspose(8, 1, 4, stride=2, padding=1))
    x = np.random.RandomState(0).rand(1, 4, 7, 7).astype(np.float32)
    path = paddle.onnx.export(dec, str(tmp_path / "dec"),
                              input_spec=[paddle.to_tensor(x)])
    eager = dec(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    assert got.shape == eager.shape
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


def test_dilated_max_pool_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp

    class DP(nn.Layer):
        def forward(self, x):
            from paddle_tpu.core.dispatch import apply

            def kernel(a):
                return jax.lax.reduce_window(
                    a, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 1, 1),
                    "VALID", window_dilation=(1, 1, 2, 2))

            return apply("dilated_max_pool", kernel, [x])

    xp = np.random.RandomState(2).rand(1, 2, 10, 10).astype(np.float32)
    m = DP()
    path = paddle.onnx.export(m, str(tmp_path / "dp"),
                              input_spec=[paddle.to_tensor(xp)])
    eager = m(paddle.to_tensor(xp)).numpy()
    (got,) = run_model(path, {"input_0": xp})
    np.testing.assert_allclose(got, eager, rtol=1e-6)


def test_general_einsum_roundtrip(tmp_path):
    """Multi-dim contraction + non-leading batch dims: the general
    dot_general canonicalization (transpose -> reshape -> batched MatMul)."""

    class EIN(nn.Layer):
        def forward(self, a, b):
            return paddle.einsum("bijk,bkjl->bil", a, b)

    a = np.random.RandomState(3).rand(2, 3, 4, 5).astype(np.float32)
    b = np.random.RandomState(4).rand(2, 5, 4, 6).astype(np.float32)
    path = paddle.onnx.export(EIN(), str(tmp_path / "ein"),
                              input_spec=[paddle.to_tensor(a),
                                          paddle.to_tensor(b)])
    eager = EIN()(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    (got,) = run_model(path, {"input_0": a, "input_1": b})
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)


def test_gpt_block_roundtrip(tmp_path):
    from paddle_tpu.models.gpt import GPTBlock, GPTConfig

    paddle.seed(0)
    blk = GPTBlock(GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                             num_heads=4, max_seq_len=16))
    blk.eval()
    h = np.random.RandomState(1).randn(2, 16, 32).astype(np.float32)
    path = paddle.onnx.export(blk, str(tmp_path / "blk"),
                              input_spec=[paddle.to_tensor(h)])
    eager = blk(paddle.to_tensor(h)).numpy()
    (got,) = run_model(path, {"input_0": h})
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


def _golden_model(kind):
    """Deterministic tiny models (weights from arange, not RNG) so the
    exported BYTES are reproducible across environments."""
    if kind == "mlp":
        net = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
        for lyr in (net[0], net[2]):
            w = np.arange(lyr.weight.numpy().size,
                          dtype=np.float32).reshape(lyr.weight.shape)
            lyr.weight.set_value(paddle.to_tensor(w / w.size))
            lyr.bias.set_value(paddle.to_tensor(
                np.arange(lyr.bias.numpy().size, dtype=np.float32) * 0.1))
        x = np.ones((2, 3), np.float32)
    elif kind == "conv":
        net = nn.Conv2D(1, 2, 3, padding=1)
        w = np.arange(net.weight.numpy().size,
                      dtype=np.float32).reshape(net.weight.shape)
        net.weight.set_value(paddle.to_tensor(w / w.size))
        net.bias.set_value(paddle.to_tensor(np.array([0.5, -0.5],
                                                     np.float32)))
        x = np.ones((1, 1, 5, 5), np.float32)
    elif kind == "gpt":
        # a full transformer block: pins the dot_general/attention/layernorm
        # export paths at the wire-format level (VERDICT r3 weak #7)
        from paddle_tpu.models.gpt import GPTBlock, GPTConfig

        net = GPTBlock(GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                                 num_heads=2, max_seq_len=8, dropout=0.0))
        net.eval()
        i = 0
        for _, p in sorted(net.named_parameters()):
            w = np.arange(i, i + p.numpy().size,
                          dtype=np.float32).reshape(p.shape)
            p.set_value(paddle.to_tensor(w / (10.0 * w.size)))
            i += p.numpy().size
        x = (np.arange(2 * 8 * 16, dtype=np.float32) / 256.0).reshape(2, 8, 16)
    return net, x


@pytest.mark.parametrize("kind", ["mlp", "conv", "gpt"])
def test_golden_wire_format_pinned(tmp_path, kind):
    """The emitted .onnx BYTES must match the committed golden fixture —
    pins the hand-rolled protobuf wire format against regressions
    (VERDICT r2 weak #6: no more same-author round-tripping only).

    History: golden_gpt.onnx was regenerated after the serving-engine PR's
    GPT attention rewrite (vector-offset KV-cache plumbing) moved the
    causal-mask position math from int64 to int32, changing the dtype of
    the traced iota/scalar position constants in the exported graph
    (iota_*/const_* initializers: int64 -> int32). Node list, op multiset,
    and initializer names were unchanged and the new export is numerically
    identical to eager (same max-abs-err as the old fixture), so the
    regeneration pins the new — intentional — layout."""
    import os

    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           f"golden_{kind}.onnx")
    net, x = _golden_model(kind)
    path = paddle.onnx.export(net, str(tmp_path / kind),
                              input_spec=[paddle.to_tensor(x)])
    with open(path, "rb") as f:
        got = f.read()
    assert os.path.exists(fixture), (
        f"golden fixture missing — regenerate with:\n  python -c "
        f"\"import tests.test_onnx_export as t; t.regen_goldens()\"")
    with open(fixture, "rb") as f:
        want = f.read()
    assert got == want, (
        f"golden {kind} wire bytes changed ({len(got)} vs {len(want)} B). "
        f"If the change is INTENTIONAL (new opset/layout), regenerate the "
        f"fixture and note why in the commit.")
    # and the fixture still evaluates correctly
    (out,) = run_model(fixture, {"input_0": x})
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


def regen_goldens():
    """Regenerate tests/fixtures/golden_*.onnx (call from repo root)."""
    import os
    import shutil
    import tempfile

    fdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
    os.makedirs(fdir, exist_ok=True)
    for kind in ("mlp", "conv", "gpt"):
        net, x = _golden_model(kind)
        tmp = tempfile.mkdtemp()
        path = paddle.onnx.export(net, os.path.join(tmp, kind),
                                  input_spec=[paddle.to_tensor(x)])
        shutil.copy(path, os.path.join(fdir, f"golden_{kind}.onnx"))
        print("wrote", os.path.join(fdir, f"golden_{kind}.onnx"))


def test_conv_transpose_negative_pad_roundtrip(tmp_path):
    """padding > k-1 lowers to NEGATIVE XLA conv padding (a crop) — must
    export as Slice + clamped pads, not invalid negative ONNX Conv pads."""
    paddle.seed(0)
    net = nn.Conv2DTranspose(4, 8, 3, stride=2, padding=3)
    x = np.random.RandomState(5).rand(1, 4, 9, 9).astype(np.float32)
    path = paddle.onnx.export(net, str(tmp_path / "negpad"),
                              input_spec=[paddle.to_tensor(x)])
    eager = net(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    assert got.shape == eager.shape
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


# ---- round 4 (VERDICT r3 missing #1): exporter primitive tail ---------------

def test_select_n_many_cases_roundtrip(tmp_path):
    """Integer-selector select_n with >2 cases cascades into Where chains."""
    import jax

    class SEL(nn.Layer):
        def forward(self, idx, a):
            from paddle_tpu.core.dispatch import apply

            def kernel(i, x):
                return jax.lax.select_n(i, x, x * 10.0, x - 3.0)

            return apply("sel3", kernel, [idx, a])

    idx = np.array([[0, 1], [2, 1]], np.int32)
    a = np.arange(4, dtype=np.float32).reshape(2, 2)
    m = SEL()
    path = paddle.onnx.export(m, str(tmp_path / "sel"),
                              input_spec=[paddle.to_tensor(idx),
                                          paddle.to_tensor(a)])
    eager = m(paddle.to_tensor(idx), paddle.to_tensor(a)).numpy()
    (got,) = run_model(path, {"input_0": idx, "input_1": a})
    np.testing.assert_allclose(got, eager)


def test_flattened_argmax_and_argmin_roundtrip(tmp_path):
    """argmax(axis=None) (reshape + trailing argmax) and the argmin mapping.
    (A literal multi-axis `axes` tuple is unreachable — jax's argmax_p
    itself unpacks exactly one axis — but the exporter's transpose+flatten
    fallback also serves this flattened form.)"""

    class AM(nn.Layer):
        def forward(self, x):
            return paddle.argmax(x), paddle.argmin(x, axis=1)

    x = np.random.RandomState(7).rand(3, 4, 5).astype(np.float32)
    m = AM()
    eager = [t.numpy() for t in m(paddle.to_tensor(x))]
    path = paddle.onnx.export(m, str(tmp_path / "am"),
                              input_spec=[paddle.to_tensor(x)])
    got = run_model(path, {"input_0": x})
    np.testing.assert_allclose(got[0], eager[0])
    np.testing.assert_allclose(got[1], eager[1])
    np.testing.assert_allclose(eager[0], np.argmax(x))


def test_nhwc_conv_roundtrip(tmp_path):
    """Non-NCHW layouts: spec permutations become Transposes around Conv."""
    import jax

    class NHWC(nn.Layer):
        def __init__(self):
            super().__init__()
            k = np.random.RandomState(8).randn(3, 3, 2, 4).astype(np.float32)
            self.k = paddle.to_tensor(k)  # HWIO

        def forward(self, x):
            from paddle_tpu.core.dispatch import apply

            def kernel(a, kk):
                return jax.lax.conv_general_dilated(
                    a, kk, window_strides=(1, 1), padding="SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))

            return apply("nhwc_conv", kernel, [x, self.k])

    x = np.random.RandomState(9).rand(2, 6, 6, 2).astype(np.float32)
    m = NHWC()
    path = paddle.onnx.export(m, str(tmp_path / "nhwc"),
                              input_spec=[paddle.to_tensor(x)])
    eager = m(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    assert got.shape == eager.shape
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


def test_base_dilated_max_pool_roundtrip(tmp_path):
    """base_dilation interleaves the input with the reduce identity."""
    import jax
    import jax.numpy as jnp

    class BD(nn.Layer):
        def forward(self, x):
            from paddle_tpu.core.dispatch import apply

            def kernel(a):
                return jax.lax.reduce_window(
                    a, -jnp.inf, jax.lax.max, (1, 1, 2, 2), (1, 1, 1, 1),
                    "VALID", base_dilation=(1, 1, 2, 2))

            return apply("bd_max_pool", kernel, [x])

    # negative values: a zero-fill (instead of -inf) would corrupt the max
    xp = -np.random.RandomState(3).rand(1, 2, 5, 5).astype(np.float32)
    m = BD()
    path = paddle.onnx.export(m, str(tmp_path / "bd"),
                              input_spec=[paddle.to_tensor(xp)])
    eager = m(paddle.to_tensor(xp)).numpy()
    (got,) = run_model(path, {"input_0": xp})
    np.testing.assert_allclose(got, eager, rtol=1e-6)


def test_dilated_avg_pool_roundtrip(tmp_path):
    """Dilated window SUM == depthwise Conv with a ones kernel (opset 13
    AveragePool has no dilations); avg = sum / window."""
    import jax

    class DA(nn.Layer):
        def forward(self, x):
            from paddle_tpu.core.dispatch import apply

            def kernel(a):
                s = jax.lax.reduce_window(
                    a, 0.0, jax.lax.add, (1, 1, 3, 3), (1, 1, 2, 2),
                    "VALID", window_dilation=(1, 1, 2, 2))
                return s / 9.0

            return apply("dilated_avg_pool", kernel, [x])

    xp = np.random.RandomState(4).rand(1, 3, 11, 11).astype(np.float32)
    m = DA()
    path = paddle.onnx.export(m, str(tmp_path / "da"),
                              input_spec=[paddle.to_tensor(xp)])
    eager = m(paddle.to_tensor(xp)).numpy()
    (got,) = run_model(path, {"input_0": xp})
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)

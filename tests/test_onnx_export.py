"""paddle.onnx.export: emitted bytes are decoded by an INDEPENDENT reader
(tests/onnx_runner.py) and executed with numpy against eager outputs —
validating both the hand-rolled protobuf wire format and the jaxpr->ONNX op
mapping (VERDICT r1 item #9: the ONNX stub had to become real or die)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from onnx_runner import load_model, run_model


def test_mlp_export_runs_identically(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Sigmoid())
    x = np.random.RandomState(0).rand(3, 8).astype(np.float32)
    path = paddle.onnx.export(net, str(tmp_path / "mlp"),
                              input_spec=[paddle.to_tensor(x)])
    assert path.endswith(".onnx")
    eager = net(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)


def test_lenet_export_runs_identically(tmp_path):
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    net.eval()
    x = np.random.RandomState(1).rand(2, 1, 28, 28).astype(np.float32)
    path = paddle.onnx.export(net, str(tmp_path / "lenet"),
                              input_spec=[paddle.to_tensor(x)])
    eager = net(paddle.to_tensor(x)).numpy()
    (got,) = run_model(path, {"input_0": x})
    np.testing.assert_allclose(got, eager, rtol=1e-4, atol=1e-5)


def test_model_structure_and_opset(tmp_path):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    path = paddle.onnx.export(net, str(tmp_path / "lin"),
                              input_spec=[paddle.static.InputSpec([3, 4],
                                                                  "float32")])
    g = load_model(path)
    assert g["opset"] == 13
    assert g["inputs"] == ["input_0"]
    assert len(g["outputs"]) == 1
    assert "weight" in " ".join(g["initializers"])  # params are initializers
    ops = {n["op"] for n in g["nodes"]}
    assert "MatMul" in ops


def test_rem_and_isfinite_semantics(tmp_path):
    class M(nn.Layer):
        def forward(self, x, y):
            r = paddle.remainder(x, y)
            return paddle.where(paddle.isfinite(r), r,
                                paddle.zeros_like(r))

    x = np.array([-7.0, 7.0, np.inf, 5.5], np.float32)
    y = np.array([3.0, -3.0, 2.0, 2.0], np.float32)
    m = M()
    path = paddle.onnx.export(m, str(tmp_path / "rem"),
                              input_spec=[paddle.to_tensor(x),
                                          paddle.to_tensor(y)])
    eager = m(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    (got,) = run_model(path, {"input_0": x, "input_1": y})
    np.testing.assert_allclose(got, eager, rtol=1e-6)


def test_old_opset_rejected(tmp_path):
    with pytest.raises(ValueError, match="opset"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "o"),
                           input_spec=[paddle.static.InputSpec([1, 2],
                                                               "float32")],
                           opset_version=9)


def test_unsupported_primitive_raises_clearly(tmp_path):
    class Fancy(nn.Layer):
        def forward(self, x):
            return paddle.linalg.svd(x)[0]

    with pytest.raises(NotImplementedError, match="primitive"):
        paddle.onnx.export(Fancy(), str(tmp_path / "f"),
                           input_spec=[paddle.to_tensor(
                               np.eye(3, dtype=np.float32))])


def test_dynamic_dim_rejected(tmp_path):
    net = nn.Linear(4, 2)
    with pytest.raises(ValueError, match="dynamic"):
        paddle.onnx.export(net, str(tmp_path / "d"),
                           input_spec=[paddle.static.InputSpec([None, 4],
                                                               "float32")])

"""Serving engine (ISSUE 4 tentpole): bucketed prefill + slot KV cache +
continuous-batching decode.

The two contracts that must never drift:
- numerics: engine greedy output is token-identical to legacy generate()
  at matching shapes, and per-slot EOS retirement never alters surviving
  slots' tokens;
- shape stability: total prefill/decode compiles for a mixed-length
  workload are bounded by the bucket ladder, never by the number of
  distinct prompt shapes (the regression alarm for accidental re-keying).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.models import GPTForPretraining, gpt_tiny
from paddle_tpu.observability import InMemorySink
from paddle_tpu.serving import (
    ServingEngine, bucket_for, clip_ladder, filter_topk_topp, sample_tokens,
)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


def _counter(name):
    return monitor.registry().report().get(name, {}).get("value", 0)


def _legacy_greedy(model, prompt, n_new, eos=None):
    out = model.generate(paddle.to_tensor(prompt[None]),
                         max_new_tokens=n_new, temperature=0,
                         eos_token_id=eos).numpy()[0]
    return out


# ---------------------------------------------------------------- numerics
def test_engine_greedy_matches_legacy_generate(model):
    """Acceptance: token-identical greedy output at matching shapes, across
    mixed prompt lengths and slot placements."""
    rng = np.random.RandomState(0)
    eng = ServingEngine(model, slot_count=3, ladder=(8, 16, 32),
                        max_new_cap=16, steps_per_dispatch=4)
    prompts = [rng.randint(0, 1024, (n,)).astype(np.int64)
               for n in (5, 7, 9, 12, 3, 17)]
    reqs = [eng.submit(p, max_new_tokens=6, temperature=0.0)
            for p in prompts]
    eng.run()
    for p, r in zip(prompts, reqs):
        assert r.done and r.finish_reason == "length"
        ref = _legacy_greedy(model, p, 6)
        np.testing.assert_array_equal(r.output_ids(), ref)


def test_eos_retirement_never_alters_survivors(model):
    """Acceptance: a slot retiring mid-flight (early EOS) must not change
    any other slot's tokens — each request's stream depends only on its own
    (prompt, seed), pinned against a solo run AND legacy generate()."""
    rng = np.random.RandomState(1)
    pA = rng.randint(0, 1024, (6,)).astype(np.int64)
    pB = rng.randint(0, 1024, (9,)).astype(np.int64)
    # an eos greedy decoding of A actually emits early
    eosA = int(_legacy_greedy(model, pA, 2)[-1])

    eng1 = ServingEngine(model, slot_count=2, ladder=(8, 16),
                         max_new_cap=16, steps_per_dispatch=4)
    rB_alone = eng1.submit(pB, max_new_tokens=10, temperature=0.0)
    eng1.run()

    eng2 = ServingEngine(model, slot_count=2, ladder=(8, 16),
                         max_new_cap=16, steps_per_dispatch=4)
    rA = eng2.submit(pA, max_new_tokens=10, temperature=0.0,
                     eos_token_id=eosA)
    rB = eng2.submit(pB, max_new_tokens=10, temperature=0.0)
    eng2.run()
    assert rA.finish_reason == "eos" and len(rA.tokens) < 10
    assert rA.tokens[-1] == eosA
    assert rB.tokens == rB_alone.tokens
    np.testing.assert_array_equal(rB.output_ids(),
                                  _legacy_greedy(model, pB, 10))


def test_sampling_deterministic_and_slot_independent(model):
    """Same (prompt, seed) -> same tokens regardless of neighbors or slot;
    different seed diverges. Prefill (first token) and decode step (rest)
    share one RNG/sampling convention, so the stream cannot depend on
    which program emitted the token."""
    rng = np.random.RandomState(2)
    p = rng.randint(0, 1024, (6,)).astype(np.int64)
    other = rng.randint(0, 1024, (11,)).astype(np.int64)

    eng1 = ServingEngine(model, slot_count=2, ladder=(8, 16),
                         max_new_cap=16, steps_per_dispatch=4)
    solo = eng1.submit(p, max_new_tokens=8, temperature=0.8, top_k=50,
                       top_p=0.9, seed=7)
    eng1.run()

    eng2 = ServingEngine(model, slot_count=3, ladder=(8, 16),
                         max_new_cap=16, steps_per_dispatch=4)
    # neighbors with different sampling configs, seated first (different slot)
    n1 = eng2.submit(other, max_new_tokens=8, temperature=0.0)
    n2 = eng2.submit(other, max_new_tokens=8, temperature=1.2, top_k=5,
                     seed=3)
    crowded = eng2.submit(p, max_new_tokens=8, temperature=0.8, top_k=50,
                          top_p=0.9, seed=7)
    reseeded = eng2.submit(p, max_new_tokens=8, temperature=0.8, top_k=50,
                           top_p=0.9, seed=8)
    eng2.run()
    assert crowded.tokens == solo.tokens
    assert reseeded.tokens != solo.tokens
    assert n1.done and n2.done
    v = model.config.vocab_size
    for r in (solo, crowded, reseeded, n2):
        assert all(0 <= t < v for t in r.tokens)


# ------------------------------------------------------- shape stability
def test_compile_count_bounded_by_ladder(model):
    """Regression alarm: >= 8 distinct prompt lengths through the engine
    must cost at most |ladder| prefill executables + 1 decode executable
    (<= ladder size total here) — if this grows, something re-keyed on
    prompt length or max_new_tokens."""
    rng = np.random.RandomState(3)
    ladder = (8, 16, 32, 48)
    p0, d0 = _counter("serving.prefill_compiles"), \
        _counter("serving.decode_compiles")
    eng = ServingEngine(model, slot_count=4, ladder=ladder, max_seq_len=64,
                        max_new_cap=16, steps_per_dispatch=4)
    lengths = [3, 5, 7, 9, 11, 14, 18, 25, 28, 30]   # 10 distinct, 3 rungs
    assert len(set(bucket_for(n, ladder) for n in lengths)) == 3
    reqs = [eng.submit(rng.randint(0, 1024, (n,)).astype(np.int64),
                       max_new_tokens=5 + (i % 4), temperature=0.0)
            for i, n in enumerate(lengths)]
    eng.run()
    assert all(r.done for r in reqs)
    prefills = _counter("serving.prefill_compiles") - p0
    decodes = _counter("serving.decode_compiles") - d0
    assert prefills == 3          # one per rung actually used
    assert decodes == 1           # one executable, all max_new/slots/steps
    assert prefills + decodes <= len(ladder)
    # second mixed wave: everything stays warm, ZERO new compiles
    reqs2 = [eng.submit(rng.randint(0, 1024, (n,)).astype(np.int64),
                        max_new_tokens=7, temperature=0.0)
             for n in (4, 6, 13, 26)]
    eng.run()
    assert all(r.done for r in reqs2)
    assert _counter("serving.prefill_compiles") - p0 == prefills
    assert _counter("serving.decode_compiles") - d0 == decodes


def test_decode_families_bounded(model):
    """Mixed greedy + sampling traffic compiles at most TWO decode
    executables (the sampling-family split), with per-slot sampling params
    traced — not one program per config."""
    rng = np.random.RandomState(4)
    eng = ServingEngine(model, slot_count=3, ladder=(8, 16), max_new_cap=8,
                        steps_per_dispatch=2)
    d0 = _counter("serving.decode_compiles")
    configs = [dict(temperature=0.0),
               dict(temperature=0.7, top_k=20),
               dict(temperature=1.3, top_p=0.8, seed=5),
               dict(temperature=0.5, top_k=7, top_p=0.95, seed=9),
               dict(temperature=0.0)]
    for i, kw in enumerate(configs):
        eng.submit(rng.randint(0, 1024, (5 + i,)).astype(np.int64),
                   max_new_tokens=6, **kw)
    eng.run()
    assert eng.stats()["decode_executables"] <= 2
    assert _counter("serving.decode_compiles") - d0 <= 2


# ----------------------------------------------- sampling shared semantics
def test_filter_topk_topp_matches_legacy_reference():
    """Combined top-k+top-p support equivalence between the traced per-slot
    filter (shared by prefill and decode-step programs) and legacy
    sample()'s static filtering."""
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    logits = rng.randn(4, 50).astype(np.float32) * 3

    def legacy_mask(row, top_k, top_p):
        row = row.copy()
        if top_k and top_k > 0:
            k_eff = min(int(top_k), row.shape[-1])
            kth = np.sort(row)[-k_eff]
            row = np.where(row < kth, -np.inf, row)
        if top_p < 1.0:
            srt = np.sort(row)[::-1]
            e = np.exp(srt - srt[0])
            probs = e / e.sum()
            cum = np.cumsum(probs)
            cutoff_idx = int((cum < top_p).sum())
            cutoff = srt[min(cutoff_idx, row.shape[-1] - 1)]
            row = np.where(row < cutoff, -np.inf, row)
        return np.isinf(row)

    cases = [(0, 1.0), (10, 1.0), (0, 0.7), (10, 0.7)]
    top_k = jnp.asarray([c[0] for c in cases], jnp.int32)
    top_p = jnp.asarray([c[1] for c in cases], jnp.float32)
    got = np.asarray(filter_topk_topp(jnp.asarray(logits), top_k, top_p))
    for i, (k, p) in enumerate(cases):
        np.testing.assert_array_equal(
            np.isinf(got[i]), legacy_mask(logits[i], k, p),
            err_msg=f"case top_k={k} top_p={p}")


def test_sample_tokens_traced_params():
    """Greedy rows argmax; top_k clamps past vocab; full-support sampling
    stays in range; rows are independent."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(6)
    logits = jnp.asarray(rng.randn(3, 17).astype(np.float32))
    keys = jax.random.split(jax.random.key(0), 3)
    toks = np.asarray(sample_tokens(
        logits, keys,
        jnp.asarray([0.0, 1.0, 0.9], jnp.float32),
        jnp.asarray([0, 10_000, 3], jnp.int32),      # 10k >> vocab: clamped
        jnp.asarray([1.0, 1.0, 0.5], jnp.float32)))
    assert toks[0] == int(np.argmax(np.asarray(logits)[0]))
    assert all(0 <= t < 17 for t in toks)
    # row 2 must come from its own top-3 support
    top3 = set(np.argsort(np.asarray(logits)[2])[-3:])
    assert toks[2] in top3


# --------------------------------------------------------- engine plumbing
def test_continuous_batching_queue_and_telemetry(model):
    """More requests than slots: all complete, telemetry carries TTFT /
    tokens-per-sec / occupancy / queue depth, and slots are reused."""
    rng = np.random.RandomState(7)
    sink = InMemorySink()
    eng = ServingEngine(model, slot_count=2, ladder=(8, 16), max_new_cap=8,
                        steps_per_dispatch=2, sink=sink)
    reqs = [eng.submit(rng.randint(0, 1024, (4 + i,)).astype(np.int64),
                       max_new_tokens=4, temperature=0.0) for i in range(5)]
    eng.run()
    assert all(r.done for r in reqs)
    req_recs = [r for r in sink.records if r["event"] == "serve_request"]
    step_recs = [r for r in sink.records if r["event"] == "serve_step"]
    assert len(req_recs) == 5 and step_recs
    for rec in req_recs:
        assert rec["ttft_s"] > 0 and rec["tokens_per_sec"] > 0
        assert rec["bucket"] in (8, 16)
        assert 0 <= rec["slot"] < 2
    assert any(rec["queue_depth_at_submit"] > 0 for rec in req_recs)
    for rec in step_recs:
        assert 0 < rec["occupancy"] <= 1.0
        assert rec["steps_per_dispatch"] == 2
    # 5 requests over 2 slots: some slot served >= 3 requests
    slots_used = [rec["slot"] for rec in req_recs]
    assert max(slots_used.count(s) for s in set(slots_used)) >= 3


def test_engine_validation_and_bucketing(model):
    eng = ServingEngine(model, slot_count=2, ladder=(8, 16), max_new_cap=8)
    with pytest.raises(ValueError, match="ladder"):
        eng.submit(np.zeros(100, np.int64))        # prompt exceeds rungs
    assert bucket_for(5, (8, 16)) == 8
    assert bucket_for(9, (8, 16)) == 16
    with pytest.raises(ValueError):
        bucket_for(17, (8, 16))
    assert clip_ladder((8, 16, 64, 128), 64, reserve=16) == (8, 16)
    assert clip_ladder((64, 128), 32) == (32,)     # largest feasible length
    with pytest.raises(ValueError, match="slot_count"):
        ServingEngine(model, slot_count=0)
    # max_new clamped to cache room: bucket 16 in max_seq_len 24 leaves 8
    eng2 = ServingEngine(model, slot_count=1, ladder=(8, 16),
                         max_seq_len=24, max_new_cap=8)
    r = eng2.submit(np.zeros(10, np.int64), max_new_tokens=100)
    assert r.max_new_tokens == 8
    eng2.run()
    assert r.done and len(r.tokens) == 8


# ------------------------------------------------- observability (ISSUE 7)
def test_serve_span_lifecycle_ordering(model):
    """Every request's span lifecycle lands in causal order: enqueue ->
    queue_wait -> prefill -> decode -> request envelope -> retire, all
    tagged with the request id."""
    from paddle_tpu.observability import get_tracer

    rng = np.random.RandomState(11)
    tr = get_tracer()
    tr.enable()
    tr.clear()
    try:
        eng = ServingEngine(model, slot_count=2, ladder=(8, 16),
                            max_new_cap=8, steps_per_dispatch=2)
        reqs = [eng.submit(rng.randint(0, 1024, (4 + i,)).astype(np.int64),
                           max_new_tokens=4, temperature=0.0)
                for i in range(4)]  # 4 requests / 2 slots -> real queueing
        eng.run()
        events = tr.events()
    finally:
        tr.disable()
        tr.clear()
        tr.clear_stats()

    assert {e["name"] for e in events} >= {
        "serve.enqueue", "serve.queue_wait", "serve.prefill", "serve.decode",
        "serve.request", "serve.retire", "serve.decode_step"}
    for req in reqs:
        evs = {e["name"]: e for e in events
               if (e.get("args") or {}).get("request") == req.id}
        assert set(evs) == {"serve.enqueue", "serve.queue_wait",
                            "serve.prefill", "serve.decode", "serve.request",
                            "serve.retire"}

        def end(e):
            return e["ts"] + e["dur"]

        qw, pf, dec, env = (evs["serve.queue_wait"], evs["serve.prefill"],
                            evs["serve.decode"], evs["serve.request"])
        # queue_wait starts at submit; the enqueue instant fires just after
        assert qw["ts"] <= evs["serve.enqueue"]["ts"]
        assert end(qw) == pytest.approx(pf["ts"])       # admit boundary
        assert end(pf) == pytest.approx(dec["ts"])      # first-token boundary
        # envelope spans submit -> done and contains every phase
        assert env["ts"] == pytest.approx(qw["ts"])
        assert end(dec) == pytest.approx(end(env))
        assert evs["serve.retire"]["ts"] >= end(dec) - 1e-6
        assert env["args"]["finish"] == req.finish_reason
        assert evs["serve.decode"]["args"]["tokens"] == len(req.tokens)
    # later-submitted requests genuinely waited for a slot
    waits = [e["dur"] for e in events if e["name"] == "serve.queue_wait"]
    assert len(waits) == 4 and max(waits) > min(waits)


def test_serve_metrics_scrape_acceptance(model, monkeypatch):
    """ISSUE 7 acceptance: a ServingEngine run with PADDLE_TPU_METRICS_PORT
    set serves a scrape where the TTFT/TPOT/queue-wait histogram counts
    equal the number of completed requests."""
    import urllib.request

    from paddle_tpu.observability import exporter, metrics

    exporter.stop_exporter()
    metrics.reset()
    monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")  # ephemeral bind
    try:
        rng = np.random.RandomState(13)
        eng = ServingEngine(model, slot_count=2, ladder=(8, 16),
                            max_new_cap=8, steps_per_dispatch=2)
        ex = exporter.get_exporter()
        assert ex is not None and ex.running  # engine autostarted it
        reqs = [eng.submit(rng.randint(0, 1024, (5 + i,)).astype(np.int64),
                           max_new_tokens=4, temperature=0.0)
                for i in range(4)]
        eng.run()
        assert all(r.done for r in reqs)
        with urllib.request.urlopen(ex.url + "/metrics", timeout=10) as resp:
            body = resp.read().decode("utf-8")
        n = len(reqs)
        assert f"paddle_tpu_serve_ttft_ms_count {n}" in body
        assert f"paddle_tpu_serve_tpot_ms_count {n}" in body
        assert f"paddle_tpu_serve_queue_wait_ms_count {n}" in body
        assert f"paddle_tpu_serve_prefill_ms_count {n}" in body
        assert "paddle_tpu_serve_decode_step_ms_bucket" in body
        assert "paddle_tpu_serve_occupancy_count" in body
        # JSON twin agrees with the text exposition
        with urllib.request.urlopen(ex.url + "/metrics.json",
                                    timeout=10) as resp:
            import json as _json
            doc = _json.loads(resp.read().decode("utf-8"))
        assert doc["histograms"]["serve.ttft_ms"]["count"] == n
        assert doc["histograms"]["serve.ttft_ms"]["min"] > 0
    finally:
        exporter.stop_exporter()
        metrics.reset()

"""Draft-model speculative decoding (ISSUE 17 tentpole): k-token draft
propose + ONE shape-stable [slots, k+1] target verify dispatch + KV
rollback of rejected rows.

The contracts that must never drift:
- numerics: greedy speculative output is token-identical to legacy
  generate() — acceptance only moves WHICH dispatch scores a position,
  never what it scores — across mixed spec/non-spec slot populations,
  both KV layouts (incl. paged replay seats), and EOS inside the verify
  window;
- shape stability: verify executables are bounded by the spec ladder x
  sampling families, never by request count or acceptance history;
- contracts: the verify executables donate both models' caches and stay
  host-transfer-free (analyze() green with default contracts).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.models import GPTForPretraining, gpt_tiny
from paddle_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft(model):
    paddle.seed(1)                       # different weights, same vocab
    d = GPTForPretraining(gpt_tiny())
    d.eval()
    return d


def _counter(name):
    return monitor.registry().report().get(name, {}).get("value", 0)


def _legacy_greedy(model, prompt, n_new, eos=None):
    out = model.generate(paddle.to_tensor(prompt[None]),
                         max_new_tokens=n_new, temperature=0,
                         eos_token_id=eos).numpy()[0]
    return out


def _spec_engine(model, draft_model, **kw):
    kw.setdefault("slot_count", 3)
    kw.setdefault("ladder", (8, 16, 32))
    kw.setdefault("max_new_cap", 16)
    kw.setdefault("steps_per_dispatch", 4)
    kw.setdefault("spec_ladder", (4,))
    return ServingEngine(model, draft_model=draft_model, **kw)


# ---------------------------------------------------------------- numerics
def test_spec_greedy_matches_legacy_generate(model, draft):
    """Acceptance: greedy speculative output token-identical to legacy
    generate(), with spec and non-spec requests sharing the same verify
    dispatches (non-spec rows ride with an empty window)."""
    rng = np.random.RandomState(0)
    eng = _spec_engine(model, draft)
    prompts = [rng.randint(0, 1024, (n,)).astype(np.int64)
               for n in (5, 7, 9, 12, 3, 17)]
    v0 = _counter("serving.verify_dispatches")
    p0 = _counter("serving.spec.proposed")
    reqs = [eng.submit(p, max_new_tokens=8, temperature=0.0,
                       speculate_k=4 if i % 2 == 0 else 0)
            for i, p in enumerate(prompts)]
    eng.run()
    for p, r in zip(prompts, reqs):
        assert r.done and r.finish_reason == "length"
        np.testing.assert_array_equal(r.output_ids(),
                                      _legacy_greedy(model, p, 8))
    assert _counter("serving.verify_dispatches") > v0
    assert _counter("serving.spec.proposed") > p0
    assert (_counter("serving.spec.accepted")
            <= _counter("serving.spec.proposed"))


def test_spec_self_draft_reduces_target_forwards(model):
    """draft == target is the training-free oracle: every in-window
    proposal agrees, so the request finishes in strictly fewer target
    forwards than emitted tokens (the whole point of the optimisation)."""
    rng = np.random.RandomState(1)
    eng = _spec_engine(model, model)
    p = rng.randint(0, 1024, (6,)).astype(np.int64)
    s0 = _counter("serving.steps")
    r = eng.submit(p, max_new_tokens=12, temperature=0.0, speculate_k=4)
    eng.run()
    forwards = _counter("serving.steps") - s0
    np.testing.assert_array_equal(r.output_ids(),
                                  _legacy_greedy(model, p, 12))
    assert forwards < len(r.tokens)
    assert r.spec_proposed > 0
    assert r.spec_accepted == r.spec_proposed  # oracle: nothing rejected


def test_spec_eos_inside_verify_window(model):
    """EOS emitted mid-window must cut the accepted prefix exactly there:
    same tokens and finish_reason as sequential greedy with the same eos."""
    rng = np.random.RandomState(2)
    p = rng.randint(0, 1024, (6,)).astype(np.int64)
    gen = _legacy_greedy(model, p, 10)[len(p):]  # unconstrained stream
    eos = int(gen[2])                            # fires mid-decode
    cut = int(np.where(gen == eos)[0][0]) + 1
    eng = _spec_engine(model, model)
    r = eng.submit(p, max_new_tokens=10, temperature=0.0,
                   eos_token_id=eos, speculate_k=4)
    eng.run()
    assert r.finish_reason == "eos"
    assert r.tokens[-1] == eos
    assert len(r.tokens) == cut < 10
    np.testing.assert_array_equal(r.tokens, gen[:cut])


def test_spec_paged_matches_dense_including_replay_seat(model, draft):
    """Paged spec decode (page-table rollback) is token-identical to the
    contiguous engine (offset rewind), including a full-prefix-hit replay
    seat where the draft cache is rebuilt without a target prefill."""
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 1024, (16,)).astype(np.int64)  # 2 full pages
    others = [rng.randint(0, 1024, (n,)).astype(np.int64) for n in (5, 11)]

    def run(paged):
        kw = dict(slot_count=3, ladder=(8, 16, 32), max_new_cap=8,
                  max_seq_len=48, steps_per_dispatch=4,
                  draft_model=draft, spec_ladder=(4,))
        if paged:
            eng = ServingEngine(model, kv_layout="paged",
                                kv_page_tokens=8, **kw)
        else:
            eng = ServingEngine(model, **kw)
        outs = []
        for _ in range(2):  # pass 2 re-submits: paged replays the prefix
            reqs = [eng.submit(prompt, max_new_tokens=5, temperature=0.0,
                               speculate_k=4)]
            reqs += [eng.submit(o, max_new_tokens=5, temperature=0.0)
                     for o in others]
            eng.run()
            outs.append([list(r.output_ids()) for r in reqs])
        if paged:
            assert eng.stats()["prefix"]["full_hits"] >= 1
        return outs

    paged_outs = run(True)
    assert paged_outs == run(False)
    np.testing.assert_array_equal(paged_outs[0][0],
                                  _legacy_greedy(model, prompt, 5))


def test_nonspec_sampled_rows_unchanged_by_spec_neighbors(model, draft):
    """A sampled NON-spec request seated next to a speculative one must be
    bit-identical to the same request in a plain engine: sampling keys on
    (seed, position), and a non-spec row's verify column 0 reuses the
    exact sequential-decode RNG stream."""
    rng = np.random.RandomState(7)
    p = rng.randint(0, 1024, (6,)).astype(np.int64)
    other = rng.randint(0, 1024, (9,)).astype(np.int64)

    plain = ServingEngine(model, slot_count=2, ladder=(8, 16),
                          max_new_cap=16, steps_per_dispatch=4)
    solo = plain.submit(p, max_new_tokens=8, temperature=0.8, top_k=50,
                        top_p=0.9, seed=7)
    plain.run()

    eng = _spec_engine(model, draft, slot_count=2, ladder=(8, 16))
    spec_n = eng.submit(other, max_new_tokens=8, temperature=0.0,
                        speculate_k=4)
    crowd = eng.submit(p, max_new_tokens=8, temperature=0.8, top_k=50,
                       top_p=0.9, seed=7)
    eng.run()
    assert crowd.tokens == solo.tokens
    np.testing.assert_array_equal(spec_n.output_ids(),
                                  _legacy_greedy(model, other, 8))


# ---------------------------------------------------------- shape stability
def test_spec_compile_count_bounded_by_ladder_and_families(model, draft):
    """Verify executables <= sampling families (2) x spec ladder rungs —
    never a function of request count, window history, or acceptance."""
    rng = np.random.RandomState(5)
    eng = _spec_engine(model, draft, spec_ladder=(2, 4))
    for i in range(6):
        p = rng.randint(0, 1024, (4 + 3 * i,)).astype(np.int64)
        eng.submit(p, max_new_tokens=6,
                   temperature=0.0 if i % 2 else 0.8,
                   top_k=0 if i % 2 else 50, seed=100 + i,
                   speculate_k=2 if i % 3 == 0 else 4)
    eng.run()
    st = eng.stats()
    assert st["verify_executables"] <= 2 * len(eng.spec_ladder)
    assert st["draft_prefill_executables"] <= len(eng.ladder)
    assert st["spec_ladder"] == (2, 4)


# ---------------------------------------------------------------- contracts
def test_spec_executables_lint_clean(model, draft):
    """HLO gate: verify programs donate BOTH models' caches (steady-state
    holds one copy of each) and make zero host transfers."""
    rng = np.random.RandomState(6)
    eng = _spec_engine(model, draft)
    eng.submit(rng.randint(0, 1024, (5,)).astype(np.int64),
               max_new_tokens=6, temperature=0.0, speculate_k=4)
    eng.run()
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert any(lbl.startswith("serve.verify_") for lbl in rep.checked)
    assert any(lbl.startswith("serve.dprefill_b") for lbl in rep.checked)


# ---------------------------------------------------------------- validation
def test_spec_draft_vocab_mismatch_raises(model):
    from paddle_tpu.models.gpt import GPTConfig

    paddle.seed(2)
    bad = GPTForPretraining(GPTConfig(vocab_size=512, hidden_size=128,
                                      num_layers=2, num_heads=4,
                                      max_seq_len=128))
    bad.eval()
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, draft_model=bad, spec_ladder=(4,))


def test_submit_speculate_without_draft_raises(model):
    eng = ServingEngine(model, slot_count=2, ladder=(8,), max_new_cap=4)
    with pytest.raises(ValueError, match="draft"):
        eng.submit(np.arange(5, dtype=np.int64), speculate_k=4)


def test_spec_bad_ladder_raises(model, draft):
    with pytest.raises(ValueError, match="spec_ladder"):
        ServingEngine(model, draft_model=draft, spec_ladder=())

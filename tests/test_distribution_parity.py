"""paddle.distribution vs torch.distributions: log_prob, entropy, and KL
parity (reference python/paddle/distribution.py + unittests
test_distribution.py use hand-numpy references; torch.distributions is a
stronger independent implementation of the same math)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Beta, Categorical, Dirichlet,
                                     Normal, Uniform, kl_divergence)

RTOL, ATOL = 2e-5, 2e-6


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


def test_normal_parity():
    loc, scale = np.float32(0.7), np.float32(1.3)
    p = Normal(loc, scale)
    t = torch.distributions.Normal(torch.tensor(loc), torch.tensor(scale))
    v = np.linspace(-3, 3, 7).astype("float32")
    np.testing.assert_allclose(_np(p.log_prob(paddle.to_tensor(v))),
                               t.log_prob(torch.from_numpy(v)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(p.entropy()), t.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)
    q = Normal(np.float32(-0.5), np.float32(0.8))
    tq = torch.distributions.Normal(torch.tensor(-0.5), torch.tensor(0.8))
    np.testing.assert_allclose(
        _np(kl_divergence(p, q)),
        torch.distributions.kl_divergence(t, tq).numpy(),
        rtol=RTOL, atol=ATOL)


def test_uniform_parity():
    p = Uniform(np.float32(-1.0), np.float32(2.0))
    t = torch.distributions.Uniform(torch.tensor(-1.0), torch.tensor(2.0))
    v = np.array([-0.5, 0.0, 1.5], "float32")
    np.testing.assert_allclose(_np(p.log_prob(paddle.to_tensor(v))),
                               t.log_prob(torch.from_numpy(v)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(p.entropy()), t.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)


def test_categorical_and_bernoulli_parity():
    logits = np.array([[0.2, -1.0, 0.7], [1.5, 0.1, -0.4]], "float32")
    p = Categorical(paddle.to_tensor(logits))
    t = torch.distributions.Categorical(logits=torch.from_numpy(logits))
    v = np.array([2, 0], "int64")
    np.testing.assert_allclose(_np(p.log_prob(paddle.to_tensor(v))),
                               t.log_prob(torch.from_numpy(v)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(p.entropy()), t.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)

    pb = Bernoulli(np.float32(0.3))
    tb = torch.distributions.Bernoulli(torch.tensor(0.3))
    vb = np.array([0.0, 1.0], "float32")
    np.testing.assert_allclose(_np(pb.log_prob(paddle.to_tensor(vb))),
                               tb.log_prob(torch.from_numpy(vb)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(pb.entropy()), tb.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)


def test_beta_dirichlet_parity():
    p = Beta(np.float32(2.0), np.float32(3.0))
    t = torch.distributions.Beta(torch.tensor(2.0), torch.tensor(3.0))
    v = np.array([0.2, 0.5, 0.8], "float32")
    np.testing.assert_allclose(_np(p.log_prob(paddle.to_tensor(v))),
                               t.log_prob(torch.from_numpy(v)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(p.entropy()), t.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)

    conc = np.array([1.5, 2.5, 3.0], "float32")
    pd_ = Dirichlet(paddle.to_tensor(conc))
    td = torch.distributions.Dirichlet(torch.from_numpy(conc))
    x = np.array([0.2, 0.3, 0.5], "float32")
    np.testing.assert_allclose(_np(pd_.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.from_numpy(x)).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(_np(pd_.entropy()), td.entropy().numpy(),
                               rtol=RTOL, atol=ATOL)


def test_sampling_moments():
    """Samples are RNG-specific across frameworks; check moments instead."""
    paddle.seed(0)
    s = Normal(np.float32(2.0), np.float32(0.5)).sample([20000])
    arr = _np(s)
    assert abs(arr.mean() - 2.0) < 0.02
    assert abs(arr.std() - 0.5) < 0.02

"""paddle.fft / paddle.distribution / paddle.sparse / paddle.text surfaces.

Mirrors reference tests under fluid/tests/unittests/fft/, distribution/, and
the sparse + text dataset tests — numpy-referenced where numpy has the op."""
import numpy as np
import pytest

import paddle_tpu as paddle


# ---- fft ----
def test_fft_roundtrip_and_numpy_parity():
    x = np.random.RandomState(0).randn(4, 16).astype("complex64")
    out = paddle.fft.fft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, np.fft.fft(x), rtol=1e-4, atol=1e-4)
    back = paddle.fft.ifft(paddle.to_tensor(out)).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)


def test_rfft_and_shift():
    x = np.random.RandomState(1).randn(8, 32).astype("float32")
    out = paddle.fft.rfft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, np.fft.rfft(x), rtol=1e-3, atol=1e-4)
    f = paddle.fft.fftfreq(8, d=0.5).numpy()
    np.testing.assert_allclose(f, np.fft.fftfreq(8, 0.5), rtol=1e-6)
    sh = paddle.fft.fftshift(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(sh, np.fft.fftshift(x), rtol=1e-6)


def test_fft2_grad_flows():
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, 4).astype("float32"))
    x.stop_gradient = False
    y = paddle.fft.fft2(x)
    loss = paddle.abs(y).sum()
    loss.backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


# ---- distribution ----
def test_normal_sampling_and_density():
    paddle.seed(0)
    d = paddle.distribution.Normal(loc=1.0, scale=2.0)
    s = d.sample([5000]).numpy()
    assert abs(s.mean() - 1.0) < 0.15 and abs(s.std() - 2.0) < 0.15
    lp = d.log_prob(paddle.to_tensor(np.float32(1.0))).numpy()
    np.testing.assert_allclose(lp, -np.log(2.0 * np.sqrt(2 * np.pi)), rtol=1e-5)
    ent = d.entropy().numpy()
    np.testing.assert_allclose(ent, 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0),
                               rtol=1e-5)


def test_uniform_categorical_bernoulli():
    paddle.seed(0)
    u = paddle.distribution.Uniform(low=0.0, high=4.0)
    s = u.sample([2000]).numpy()
    assert 0 <= s.min() and s.max() < 4
    np.testing.assert_allclose(u.entropy().numpy(), np.log(4.0), rtol=1e-6)

    c = paddle.distribution.Categorical(
        logits=paddle.to_tensor(np.log(np.array([0.1, 0.2, 0.7], "float32"))))
    cs = c.sample([4000]).numpy()
    assert abs((cs == 2).mean() - 0.7) < 0.05
    np.testing.assert_allclose(
        c.log_prob(paddle.to_tensor(np.array(2))).numpy(), np.log(0.7), rtol=1e-4)

    b = paddle.distribution.Bernoulli(probs=0.25)
    assert abs(b.sample([4000]).numpy().mean() - 0.25) < 0.05


def test_beta_dirichlet_multinomial():
    paddle.seed(0)
    beta = paddle.distribution.Beta(2.0, 5.0)
    np.testing.assert_allclose(beta.mean().numpy(), 2 / 7, rtol=1e-6)
    assert 0 < beta.sample([10]).numpy().min() < 1

    dir_ = paddle.distribution.Dirichlet(paddle.to_tensor(
        np.array([1.0, 2.0, 3.0], "float32")))
    s = dir_.sample([100]).numpy()
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-4)

    m = paddle.distribution.Multinomial(10, paddle.to_tensor(
        np.array([0.2, 0.3, 0.5], "float32")))
    ms = m.sample([50]).numpy()
    np.testing.assert_allclose(ms.sum(-1), 10.0)


def test_kl_divergence_registry():
    p = paddle.distribution.Normal(0.0, 1.0)
    q = paddle.distribution.Normal(1.0, 2.0)
    kl = paddle.distribution.kl_divergence(p, q).numpy()
    # closed form: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 0.5
    expect = np.log(2.0) + (1 + 1) / 8 - 0.5
    np.testing.assert_allclose(kl, expect, rtol=1e-5)
    with pytest.raises(NotImplementedError):
        paddle.distribution.kl_divergence(p, paddle.distribution.Uniform(0, 1))


# ---- sparse ----
def test_sparse_coo_roundtrip():
    indices = np.array([[0, 1, 2], [1, 2, 0]])
    values = np.array([1.0, 2.0, 3.0], "float32")
    s = paddle.sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
    assert s.nnz() == 3 and s.is_sparse_coo()
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), "float32")
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_array_equal(dense, expect)
    np.testing.assert_array_equal(s.indices().numpy(), indices)
    np.testing.assert_array_equal(s.values().numpy(), values)


def test_sparse_matmul_and_ops():
    indices = np.array([[0, 1], [1, 0]])
    s = paddle.sparse.sparse_coo_tensor(indices, np.array([2.0, 4.0], "float32"),
                                        shape=[2, 2])
    y = paddle.to_tensor(np.eye(2, dtype="float32"))
    out = paddle.sparse.matmul(s, y).numpy()
    np.testing.assert_array_equal(out, s.to_dense().numpy())
    r = paddle.sparse.relu(paddle.sparse.sparse_coo_tensor(
        indices, np.array([-1.0, 5.0], "float32"), shape=[2, 2]))
    np.testing.assert_array_equal(r.values().numpy(), [0.0, 5.0])


def test_sparse_csr_and_add():
    crows = np.array([0, 1, 2])
    cols = np.array([1, 0])
    s = paddle.sparse.sparse_csr_tensor(crows, cols,
                                        np.array([3.0, 7.0], "float32"), [2, 2])
    np.testing.assert_array_equal(s.to_dense().numpy(),
                                  np.array([[0, 3], [7, 0]], "float32"))
    two = paddle.sparse.add(s, s)
    np.testing.assert_array_equal(two.to_dense().numpy(),
                                  np.array([[0, 6], [14, 0]], "float32"))


# ---- text datasets ----
def test_text_datasets_shapes():
    imdb = paddle.text.Imdb(mode="train", size=64)
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label.shape == (1,)
    assert len(imdb.word_idx()) > 0

    ngram = paddle.text.Imikolov(mode="test", window_size=5, size=64)
    sample = ngram[0]
    assert len(sample) == 5

    ml = paddle.text.Movielens(mode="train", size=32)
    assert len(ml[0]) == 8

    uci = paddle.text.UCIHousing(mode="train")
    x, y = uci[0]
    assert x.shape == (13,) and y.shape == (1,)

    srl = paddle.text.Conll05st(size=16)
    words, pred, labels = srl[0]
    assert words.shape == labels.shape


def test_uci_housing_learnable():
    """fit_a_line (the reference's book/ test) on the synthetic UCIHousing."""
    paddle.seed(0)
    ds = paddle.text.UCIHousing(mode="train")
    net = paddle.nn.Linear(13, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.5, parameters=net.parameters())
    loss_fn = paddle.nn.MSELoss()
    from paddle_tpu.io import DataLoader

    first = last = None
    for epoch in range(15):
        tot = 0.0
        for x, y in DataLoader(ds, batch_size=64):
            loss = loss_fn(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            tot += float(loss.item())
        first = first or tot
        last = tot
    assert last < first * 0.2, (first, last)


def test_fft_accepts_name_kwarg():
    x = paddle.to_tensor(np.ones((4,), "float32"))
    out = paddle.fft.fft(x, name="my_fft")
    np.testing.assert_allclose(out.numpy(), np.fft.fft(np.ones(4)), atol=1e-5)


def test_sparse_tensor_generic_op_densifies():
    s = paddle.sparse.sparse_coo_tensor(np.array([[0], [1]]),
                                        np.array([5.0], "float32"), [2, 2])
    out = s * 2  # generic Tensor op: dense fallback, not a crash
    np.testing.assert_array_equal(out.numpy(),
                                  np.array([[0, 10], [0, 0]], "float32"))


def test_incubate_namespace_wired():
    assert hasattr(paddle, "incubate")
    assert callable(paddle.incubate.asp.create_mask)


def test_sparse_set_value_keeps_views_consistent():
    s = paddle.sparse.sparse_coo_tensor(np.array([[0], [1]]),
                                        np.array([5.0], "float32"), [2, 2])
    new = np.array([[1.0, 0.0], [0.0, 2.0]], "float32")
    s.set_value(new)
    np.testing.assert_array_equal(s.to_dense().numpy(), new)
    np.testing.assert_array_equal(np.sort(s.values().numpy()), [1.0, 2.0])

"""Tracing-hazard source linter (paddle_tpu/analysis/source_lint.py).

Per-rule fixtures (each seeded hazard caught by exactly its rule, clean
twins stay clean), the scoped-tracedness regression (a public method
sharing a name with an inner jitted closure must NOT inherit its
tracedness — the false positive the first repo run surfaced), both
burn-down directions of the baseline comparison, the tier-1 repo-wide
gate against tools/lint_tracing_baseline.txt, and the
tools/lint_tracing.py CLI exit codes.
"""
import json
import os
import subprocess
import sys
import textwrap

from paddle_tpu.analysis.source_lint import (compare_to_baseline,
                                             lint_source, lint_tree,
                                             load_baseline)

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_BASELINE = os.path.join(_REPO, "tools", "lint_tracing_baseline.txt")


def _rules(src, relpath="paddle_tpu/x.py", **kw):
    return [(f.rule, f.token) for f in
            lint_source(textwrap.dedent(src), relpath, **kw)]


# ----------------------------------------------------------- rule fixtures

def test_host_sync_in_decorator_jitted_body():
    src = """
    import jax

    @jax.jit
    def step(x):
        lr = float(x.mean())
        return x * lr
    """
    assert _rules(src) == [("host-sync", "float")]


def test_host_sync_item_and_np_asarray_in_name_traced_body():
    """The name-passed-to-jit form: `jax.jit(step)` marks `step` traced."""
    src = """
    import jax
    import numpy as np

    def step(x):
        y = x.mean().item()
        z = np.asarray(x)
        return y, z

    fast = jax.jit(step)
    """
    assert _rules(src) == [("host-sync", ".item"),
                           ("host-sync", "np.asarray")]


def test_host_sync_via_scan_body_and_nested_fn():
    """lax.scan(body, ...) traces `body`, and functions nested inside a
    traced one are traced too."""
    src = """
    from jax import lax

    def body(carry, x):
        def inner(v):
            return int(v)
        return carry, inner(x)

    out = lax.scan(body, 0, xs)
    """
    assert _rules(src) == [("host-sync", "int")]


def test_float_of_literal_not_flagged():
    src = """
    import jax

    @jax.jit
    def step(x):
        return x * float(1e-3) + int("8")
    """
    assert _rules(src) == []


def test_untraced_code_may_sync_freely():
    src = """
    def report(x):
        return float(x.mean())
    """
    assert _rules(src) == []


def test_host_time_and_random_in_traced_body():
    src = """
    import time, random
    import numpy as np
    import jax

    @jax.jit
    def step(x):
        t = time.perf_counter()
        r = random.random()
        n = np.random.randn()
        return x + t + r + n
    """
    assert _rules(src) == [("host-time", "time.perf_counter"),
                           ("host-random", "random.random"),
                           ("host-random", "np.random.randn")]


def test_jax_random_is_not_host_random():
    src = """
    import jax

    @jax.jit
    def step(x, key):
        return x + jax.random.normal(key, x.shape)
    """
    assert _rules(src) == []


def test_mutable_default_in_public_api_only():
    src = """
    def submit(x, queue=[]):
        queue.append(x)
        return queue

    def _internal(x, acc={}):
        return acc
    """
    assert _rules(src) == [("mutable-default", "queue")]
    # non-library files (tests/, scripts) are exempt from the API rule
    assert _rules(src, relpath="tests/x.py") == []


def test_bare_lock_flagged_with_statement_clean():
    src = """
    import threading

    _lock = threading.Lock()

    def bad():
        _lock.acquire()
        try:
            pass
        finally:
            _lock.release()

    def good():
        with _lock:
            pass
    """
    assert _rules(src) == [("bare-lock", "_lock.acquire")]


def test_scoped_tracedness_regression():
    """THE false positive from the first repo-wide run: a class's public
    `step` method dispatches a jitted inner closure also named `step`.
    Only the closure is traced; the method may sync/time freely."""
    src = """
    import time
    import jax

    class Engine:
        def _build(self):
            def step(params, x):
                return params, x * 2
            return jax.jit(step)

        def step(self, x):
            t0 = time.perf_counter()
            out = self._build()(self.params, x)
            return float(out[1].mean()), time.perf_counter() - t0
    """
    assert _rules(src) == []


def test_parse_error_is_a_finding_not_a_crash():
    fs = lint_source("def broken(:\n", "paddle_tpu/x.py")
    assert [f.rule for f in fs] == ["parse-error"]


# --------------------------------------------------------------- baseline

def test_baseline_burns_down_both_directions(tmp_path):
    src = """
    import jax

    @jax.jit
    def step(x):
        return float(x)
    """
    findings = lint_source(textwrap.dedent(src), "paddle_tpu/x.py")
    key = findings[0].key
    assert key == "paddle_tpu/x.py:host-sync:step:float"

    # not baselined -> new
    new, stale = compare_to_baseline(findings, {})
    assert [f.key for f in new] == [key] and stale == []
    # baselined with justification -> accepted
    p = tmp_path / "baseline.txt"
    p.write_text(f"# comment\n\n{key}  # deliberate: startup probe\n")
    bl = load_baseline(str(p))
    assert bl == {key: "deliberate: startup probe"}
    new, stale = compare_to_baseline(findings, bl)
    assert new == [] and stale == []
    # finding fixed but line kept -> stale (paid-off debt must be deleted)
    new, stale = compare_to_baseline([], bl)
    assert new == [] and stale == [key]


def test_missing_baseline_file_is_empty():
    assert load_baseline("/nonexistent/baseline.txt") == {}


# ------------------------------------------------------- tier-1 repo gate

def test_repo_tree_lints_clean_against_baseline():
    """The satellite-2 acceptance, kept green forever: every hazard the
    linter finds across paddle_tpu/ + tools/ is either fixed or justified
    in tools/lint_tracing_baseline.txt — and nothing in the baseline is
    stale. On failure: fix the new finding (preferred) or add its key with
    a `# justification`, and delete any stale line."""
    findings = lint_tree(_REPO)
    baseline = load_baseline(_BASELINE)
    new, stale = compare_to_baseline(findings, baseline)
    msg = ["tracing-hazard lint drifted from tools/lint_tracing_baseline.txt:"]
    msg += [f"  NEW {f}" for f in new]
    msg += [f"  STALE (finding fixed — delete the line): {k}" for k in stale]
    assert not new and not stale, "\n".join(msg)


def test_lint_tracing_cli_exit_codes(tmp_path):
    """0 = clean vs baseline; 1 = drift (forced via an empty --root with a
    fabricated baseline, which makes every entry stale)."""
    tool = os.path.join(_REPO, "tools", "lint_tracing.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    clean = subprocess.run([sys.executable, tool], capture_output=True,
                           text=True, env=env)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    summary = json.loads(clean.stdout.strip().splitlines()[-1])["summary"]
    assert summary["kind"] == "lint_tracing" and summary["ok"]

    (tmp_path / "empty").mkdir()
    fake = tmp_path / "baseline.txt"
    fake.write_text("gone.py:host-sync:f:float\n")
    drift = subprocess.run(
        [sys.executable, tool, "--root", str(tmp_path / "empty"),
         "--baseline", str(fake)],
        capture_output=True, text=True, env=env)
    assert drift.returncode == 1, drift.stdout + drift.stderr
    summary = json.loads(drift.stdout.strip().splitlines()[-1])["summary"]
    assert not summary["ok"]
    assert summary["stale"] == ["gone.py:host-sync:f:float"]

"""Distributed stack tests on the 8-device virtual CPU mesh (SURVEY.md §4 level 2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import HybridCommunicateGroup, set_hybrid_communicate_group


@pytest.fixture(autouse=True)
def reset_hcg():
    yield
    set_hybrid_communicate_group(None)


def test_mesh_degrees():
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=4)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.nranks == 8
    assert hcg.mesh.shape["mp"] == 4


def test_mesh_auto_fill_dp():
    hcg = HybridCommunicateGroup(mp_degree=2)  # dp auto = 4
    assert hcg.get_data_parallel_world_size() == 4


def test_mesh_bad_degrees():
    with pytest.raises(ValueError):
        HybridCommunicateGroup(dp_degree=3, mp_degree=5)


def test_fleet_init_topology():
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                               "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.topology()["dp"] == 2
    assert hcg.topology()["sharding"] == 2
    assert hcg.get_parallel_mode() == "sharding_parallel"


def _make_sharded(arr_np, axis_name, hcg):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr_np, NamedSharding(hcg.mesh, P(axis_name)))


def test_all_reduce_eager_sharded():
    import jax

    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    # global array [8, 4]: shard i = "rank i's tensor"
    data = np.arange(32, dtype=np.float32).reshape(8, 4)
    x = _make_sharded(data, "mp", hcg)
    t = paddle.Tensor(x)
    dist.all_reduce(t, group=hcg.get_model_parallel_group())
    expect = np.tile(data.sum(0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(t._data), expect)


def test_all_reduce_max_and_avg():
    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    data = np.random.RandomState(0).rand(8, 3).astype(np.float32)
    t = paddle.Tensor(_make_sharded(data, "mp", hcg))
    dist.all_reduce(t, op=dist.ReduceOp.MAX, group=hcg.get_model_parallel_group())
    np.testing.assert_allclose(np.asarray(t._data),
                               np.tile(data.max(0, keepdims=True), (8, 1)), rtol=1e-6)
    t2 = paddle.Tensor(_make_sharded(data, "mp", hcg))
    dist.all_reduce(t2, op=dist.ReduceOp.AVG, group=hcg.get_model_parallel_group())
    np.testing.assert_allclose(np.asarray(t2._data),
                               np.tile(data.mean(0, keepdims=True), (8, 1)), rtol=1e-6)


def test_reduce_scatter_eager():
    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    data = np.ones((8, 8), np.float32)
    t = paddle.Tensor(_make_sharded(data, "mp", hcg))
    out = dist.reduce_scatter(t, t, group=hcg.get_model_parallel_group())
    # rank-major: out[i] = sum over ranks of segment i -> global [8, 1] of 8.0
    np.testing.assert_allclose(np.asarray(out._data), np.full((8, 1), 8.0), rtol=1e-6)


def test_broadcast_eager():
    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    data = np.arange(8, dtype=np.float32).reshape(8, 1)
    t = paddle.Tensor(_make_sharded(data, "mp", hcg))
    dist.broadcast(t, src=3, group=hcg.get_model_parallel_group())
    np.testing.assert_allclose(np.asarray(t._data), np.full((8, 1), 3.0))


def test_all_gather_eager():
    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    data = np.arange(8, dtype=np.float32).reshape(8, 1)
    t = paddle.Tensor(_make_sharded(data, "mp", hcg))
    outs = []
    dist.all_gather(outs, t, group=hcg.get_model_parallel_group())
    assert len(outs) == 8


def test_identity_world1():
    set_hybrid_communicate_group(HybridCommunicateGroup(dp_degree=8))
    t = paddle.ones([4])
    g = dist.get_hybrid_communicate_group().get_model_parallel_group()  # degree 1
    out = dist.all_reduce(t, group=g)
    np.testing.assert_allclose(out.numpy(), 1.0)


def test_engine_dp_training_decreases_loss():
    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    class Reg(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 1)

        def forward(self, x, y):
            return nn.functional.mse_loss(self.fc(x), y)

    model = Reg()
    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    xs = rng.rand(32, 16).astype(np.float32)
    w_true = rng.rand(16, 1).astype(np.float32)
    ys = xs @ w_true
    losses = []
    for _ in range(30):
        losses.append(float(engine.step(paddle.to_tensor(xs), paddle.to_tensor(ys)).item()))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_engine_mp_matches_single_device():
    """TP parity: same seed model trained 3 steps on mp=4 mesh vs 1 device — same loss."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (4, 32)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)

    def run(degrees):
        paddle.seed(123)
        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = degrees
        fleet.init(is_collective=True, strategy=strategy)
        model = GPTForPretraining(gpt_tiny())
        opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
        engine = fleet.distributed_engine(model, opt)
        losses = []
        for _ in range(3):
            losses.append(float(engine.step(paddle.to_tensor(ids),
                                            paddle.to_tensor(labels)).item()))
        return losses

    base = run({"dp_degree": 1, "mp_degree": 1, "sharding_degree": 1})
    mp = run({"dp_degree": 2, "mp_degree": 4, "sharding_degree": 1})
    np.testing.assert_allclose(base, mp, rtol=2e-3, atol=2e-4)


def test_engine_sharding_stage2():
    """ZeRO: opt state sharded over the sharding axis; training still converges."""
    from paddle_tpu.distributed.meta_parallel import (
        GroupShardedOptimizerStage2, GroupShardedStage2,
    )

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.sharding = True
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "sharding_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)

    class Reg(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 64)
            self.fc2 = nn.Linear(64, 1)

        def forward(self, x, y):
            return nn.functional.mse_loss(self.fc2(nn.functional.relu(self.fc1(x))), y)

    model = Reg()
    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
    opt_sharded = GroupShardedOptimizerStage2(model.parameters(), opt)
    model_sharded = GroupShardedStage2(model, opt_sharded)
    engine = fleet.distributed_engine(model, opt)
    # opt state of fc1.weight [16, 64] must be sharded over 'sharding'
    spec = engine.opt_specs["fc1.weight"]
    assert "sharding" in [e for e in spec if e is not None], spec
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 16).astype(np.float32)
    ys = (xs @ rng.rand(16, 1)).astype(np.float32)
    losses = [float(engine.step(paddle.to_tensor(xs), paddle.to_tensor(ys)).item())
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.3


def test_gpt_hybrid_dp_mp_sp():
    """3-axis hybrid (dp=2, mp=2, sp=2) GPT step runs and produces finite loss."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(5)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters(), weight_decay=0.01)
    engine = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 1024, (4, 64)).astype(np.int64)
    labels = np.roll(ids, -1, 1)
    l1 = float(engine.step(paddle.to_tensor(ids), paddle.to_tensor(labels)).item())
    l2 = float(engine.step(paddle.to_tensor(ids), paddle.to_tensor(labels)).item())
    assert np.isfinite(l1) and np.isfinite(l2)
    assert l2 < l1  # same batch twice -> loss must drop


def test_engine_state_dict_roundtrip():
    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    model = nn.Linear(4, 4)

    class Wrap(nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, x, y):
            return nn.functional.mse_loss(self.m(x), y)

    wrap = Wrap(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=wrap.parameters())
    engine = fleet.distributed_engine(wrap, opt)
    x = paddle.rand([8, 4])
    y = paddle.rand([8, 4])
    engine.step(x, y)
    sd = engine.state_dict()
    assert "m.weight" in sd
    engine.sync_to_model()
    np.testing.assert_allclose(model.weight.numpy(), sd["m.weight"].numpy())


def test_data_parallel_wrapper_api():
    set_hybrid_communicate_group(HybridCommunicateGroup(dp_degree=8))
    model = nn.Linear(2, 2)
    from paddle_tpu.distributed.meta_parallel import DataParallel

    dp = DataParallel(model)
    out = dp(paddle.ones([1, 2]))
    assert out.shape == [1, 2]
    with dp.no_sync():
        assert not dp._enable_sync
    assert dp._enable_sync
    sd = dp.state_dict()
    assert "weight" in sd


def test_moe_layer_eager():
    from paddle_tpu.distributed.meta_parallel import MoELayer

    paddle.seed(0)
    moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2, capacity_factor=2.0)
    x = paddle.rand([2, 8, 16])
    x.stop_gradient = False
    out = moe(x)
    assert out.shape == [2, 8, 16]
    out.sum().backward()
    assert moe.experts.w1.grad is not None
    assert moe.gate.gate.weight.grad is not None


def test_moe_dispatch_matches_dense_routing():
    """With capacity ample enough that no token drops, top-k dense dispatch must equal
    the per-token weighted sum of expert outputs (regression: 1st- and 2nd-choice
    tokens once collided in the same capacity slot and got summed together)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.meta_parallel import MoELayer

    paddle.seed(0)
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=2, top_k=2,
                   capacity_factor=4.0, activation="relu")
    x = paddle.rand([1, 6, 8])
    out = np.asarray(moe(x).numpy())

    # dense reference: every token goes to its top-2 experts, gated by softmax probs
    tok = jnp.asarray(x.numpy().reshape(6, 8))
    logits = tok @ jnp.asarray(moe.gate.gate.weight.numpy()) + \
        jnp.asarray(moe.gate.gate.bias.numpy())
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, 2)
    w1, b1 = jnp.asarray(moe.experts.w1.numpy()), jnp.asarray(moe.experts.b1.numpy())
    w2, b2 = jnp.asarray(moe.experts.w2.numpy()), jnp.asarray(moe.experts.b2.numpy())
    ref = np.zeros((6, 8), np.float32)
    for t in range(6):
        for kk in range(2):
            e = int(topi[t, kk])
            h = jnp.maximum(tok[t] @ w1[e] + b1[e, 0], 0.0)
            ref[t] += float(topv[t, kk]) * np.asarray(h @ w2[e] + b2[e, 0])
    np.testing.assert_allclose(out.reshape(6, 8), ref, rtol=1e-4, atol=1e-4)


def test_pipeline_layer_segmentation():
    from paddle_tpu.distributed.meta_parallel import LayerDesc, PipelineLayer

    set_hybrid_communicate_group(HybridCommunicateGroup(pp_degree=4, dp_degree=2))
    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]
    pp = PipelineLayer(descs, num_stages=4)
    assert pp.segment_parts == [0, 2, 4, 6, 8]
    out = pp(paddle.ones([2, 8]))  # eager sequential fallback
    assert out.shape == [2, 8]
    stage_layers = pp.get_stage_layers(1)
    assert len(stage_layers) == 2


def test_recompute_eager_matches_direct():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
    x = paddle.rand([4, 8])
    x.stop_gradient = False

    direct = model(x)
    direct.sum().backward()
    g_direct = model[0].weight.grad.numpy().copy()
    x_g_direct = x.grad.numpy().copy()

    for p in model.parameters():
        p.clear_grad()
    x2 = paddle.to_tensor(x.numpy())
    x2.stop_gradient = False
    out = fleet.recompute(model, x2)
    np.testing.assert_allclose(out.numpy(), direct.numpy(), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(model[0].weight.grad.numpy(), g_direct, rtol=1e-5)
    np.testing.assert_allclose(x2.grad.numpy(), x_g_direct, rtol=1e-5)


def test_gpt_recompute_in_engine():
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(7)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    cfg = gpt_tiny(use_recompute=True)
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int64)
    loss = engine.step(paddle.to_tensor(ids), paddle.to_tensor(np.roll(ids, -1, 1)))
    assert np.isfinite(float(loss.item()))


def test_engine_with_lamb():
    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)

    class Reg(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 1)

        def forward(self, x, y):
            return nn.functional.mse_loss(self.fc(x), y)

    model = Reg()
    opt = paddle.optimizer.Lamb(learning_rate=0.01, parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)
    x = paddle.rand([8, 8])
    y = paddle.rand([8, 1])
    loss = engine.step(x, y)
    assert np.isfinite(float(loss.item()))
    assert opt._step_count == 1  # engine writes step back (ckpt consistency)


def test_all_reduce_prod_and_get_group():
    hcg = set_hybrid_communicate_group(HybridCommunicateGroup(mp_degree=8))
    data = np.full((8, 2), 2.0, np.float32)
    t = paddle.Tensor(_make_sharded(data, "mp", hcg))
    dist.all_reduce(t, op=dist.ReduceOp.PROD, group=hcg.get_model_parallel_group())
    np.testing.assert_allclose(np.asarray(t._data), np.full((8, 2), 256.0))
    g = dist.new_group([0, 1, 2])
    from paddle_tpu.distributed.collective import get_group

    assert get_group(g.id) is g


def _spawn_check():
    import os

    assert os.environ["PADDLE_TRAINERS_NUM"] == "2"


def test_spawn_multiprocess():
    # spawn start method (fork deadlocks under multithreaded JAX), so the target
    # must be picklable: a module-level function
    import paddle_tpu.distributed as pdist

    procs = pdist.spawn(_spawn_check, nprocs=2, join=True)
    assert all(p.exitcode == 0 for p in procs)


def test_engine_num_model_inputs_override():
    """Multi-input self-supervised model: num_model_inputs routes BOTH batch
    args to the model while loss_fn sees only the outputs."""
    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, a, b):
            return self.fc(a) - self.fc(b)

    model = TwoIn()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    from paddle_tpu.distributed.engine import TrainStepEngine
    engine = TrainStepEngine(model, opt,
                             loss_fn=lambda out: (out ** 2).mean(),
                             num_model_inputs=2)
    rng = np.random.RandomState(0)
    a = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
    b = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
    loss = float(engine.step(a, b).item())
    assert np.isfinite(loss)

    import pytest as _pytest
    from paddle_tpu.distributed.engine import model_input_count
    assert model_input_count(3) == 2
    assert model_input_count(1) == 1
    assert model_input_count(3, 3) == 3
    with _pytest.raises(ValueError):
        model_input_count(2, 5)

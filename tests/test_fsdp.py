"""Full FSDP: sharded-resident parameters with per-layer gather/compute
overlap (ISSUE 19 tentpole).

The composition matrix under test, layer by layer:

- **bit-exactness**: with parameters living ONLY as contiguous 1/N flat
  f32 shards between steps (per-layer all-gather just before use,
  reduce-scatter of grads onto the owning shard, shard-local update, NO
  trailing param all-gather), the trajectory reproduces the replicated
  fused-all-reduce engine bit for bit — loss AND gathered params AND
  gathered opt state — at dp4 and dp8.
- **HLO gate**: exactly L per-bucket all-gathers + ONE reduce-scatter per
  optimizer step independent of microbatch count K, ZERO full-buffer
  all-reduces, microbatch scan while-loop intact — with health partials
  riding the same program. Skipped on backends that combine collectives
  (exact per-bucket counts would be rewritten), the shared
  analysis.backend probe.
- **checkpointing**: an engaged fsdp engine checkpoints as ordinary
  per-parameter manifest sections, so a save at dp8 restores bit-equal
  into an fsdp engine at dp4 (cross-dp reslice) AND into a replicated
  engine; live_reshard dp4 -> dp2 -> dp4 is bit-identical to the
  save/restore path with zero committed steps lost.
- **health attribution**: a NaN injected into one parameter is named even
  though that parameter's bucket shards live on OTHER replicas — the
  per-replica [4P] partials ride the step outputs as a sharded [nrep,4P]
  slab and are summed host-side (no extra collective).
- **low precision**: bf16 reduce-scatter with error feedback equals the
  replicated bf16 engine exactly; int8 rides the scales all-to-all
  (2 all-to-alls, 0 reduce-scatters).
- **fallbacks**: non-pure-dp meshes and non-uniform optimizer rules warn
  ONCE ("fsdp requested ...") and run the replicated path bit-identically;
  run_steps refuses an active fsdp engine.
- **memory**: exec_introspect argument bytes drop by the analytic
  param+opt sharded-state delta of engine.fsdp_memory_model() and land
  strictly below the ZeRO executable (which still holds replicated
  params).
"""
import os
import tempfile
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis as an
from paddle_tpu.core import monitor
from paddle_tpu.distributed import grad_comm
from paddle_tpu.distributed.elastic import (CheckpointManager, live_reshard,
                                            restore_latest)
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)
from paddle_tpu.observability import (exec_introspect, flight_recorder,
                                      health, metrics)


@pytest.fixture(autouse=True)
def _observability_cleanup():
    yield
    metrics.reset()
    flight_recorder.disable()
    health.reset()
    exec_introspect.reset()


def _dp(n=8):
    set_hybrid_communicate_group(None)
    return HybridCommunicateGroup(dp_degree=n, devices=jax.devices()[:n])


def _make(k=2, mode="fsdp", hcg=None, seed=0, width=32, in_dim=16,
          optimizer="adamw"):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(in_dim, width),
                               paddle.nn.ReLU(),
                               paddle.nn.Linear(width, 4))
    if optimizer == "adamw":
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     parameters=net.parameters())
    else:
        opt = paddle.optimizer.Lars(learning_rate=0.01,
                                    parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           hcg=hcg if hcg is not None else _dp(),
                           microbatches=k,
                           zero_update=(mode == "zero"),
                           fsdp=(mode == "fsdp"))


def _batch(n=32, in_dim=16):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, in_dim).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def _losses(engine, x, y, steps=3):
    return [float(engine.step(x, y).item()) for _ in range(steps)]


def _fsdp_compiled(eng):
    (label, (fn, avals)), = [kv for kv in eng._exec_stash.items()
                             if kv[0].startswith("train.fsdp")]
    return label, fn.lower(*avals).compile()


def _skip_if_backend_combines():
    """Exact per-bucket all-gather counts only hold on backends that do NOT
    combine collectives — the shared analysis.backend probe (the inverse of
    test_hlo_perf_gates' combining-required gates)."""
    if an.collective_combining_reason() is None:
        pytest.skip("backend combines collectives; exact per-bucket "
                    "all-gather counts are rewritten")


# ----------------------------------------------------------- bit-exactness

@pytest.mark.parametrize("dp", [4, 8])
def test_f32_fsdp_bit_equal_to_replicated(dp):
    """Sharded-resident params, per-bucket gathers, grad reduce-scatter,
    shard-local update — and the trajectory is STILL bit-equal to the
    replicated fused-all-reduce engine: loss, params, and opt state, for
    five steps with K=2 microbatches."""
    hcg = _dp(dp)
    x, y = _batch()
    er = _make(k=2, mode=None, hcg=hcg)
    ef = _make(k=2, hcg=hcg)
    lr, lf = _losses(er, x, y, steps=5), _losses(ef, x, y, steps=5)
    assert lf == lr  # exact float equality, not allclose

    # fsdp engaged: flat shards own ALL state, the replicated dicts are gone
    assert ef._fsdp_params is not None and ef.params is None
    assert ef.opt_state is None and ef._zero_opt is None

    pf, of = ef._gather_fsdp_params(), ef._gather_fsdp_opt()
    for n in er.params:
        np.testing.assert_array_equal(np.asarray(er.params[n]),
                                      np.asarray(pf[n]), err_msg=n)
        for a, b in zip(er.opt_state[n], of[n]):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))


# -------------------------------------- gather-prefetch window (ISSUE 20)

@pytest.mark.parametrize("dp,k", [(4, 2), (4, 4), (8, 2), (8, 4)])
@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_prefetch_depth2_bit_equal_to_jit(dp, k, dtype):
    """The overlap-ahead window is identity on VALUES: the depth-2
    double-buffered trajectory equals the depth-0 just-in-time one bit for
    bit — loss, gathered params, gathered opt state — across dp, microbatch
    count, and wire dtype (and depth 0 is already pinned against the
    replicated engine above, so depth 2 is transitively bit-equal to it
    too). The window pins are dead select branches, never taken."""
    if dtype == "bf16":
        paddle.set_flags({"grad_comm_dtype": "bf16",
                          "grad_comm_error_feedback": True})
    hcg = _dp(dp)
    x, y = _batch()
    paddle.set_flags({"fsdp_prefetch": 0})
    e0 = _make(k=k, hcg=hcg)
    l0 = _losses(e0, x, y, steps=3)
    paddle.set_flags({"fsdp_prefetch": 2})
    e2 = _make(k=k, hcg=hcg)
    l2 = _losses(e2, x, y, steps=3)
    assert l2 == l0  # exact float equality, not allclose
    p0, p2 = e0._gather_fsdp_params(), e2._gather_fsdp_params()
    o0, o2 = e0._gather_fsdp_opt(), e2._gather_fsdp_opt()
    for n in p0:
        np.testing.assert_array_equal(np.asarray(p0[n]), np.asarray(p2[n]),
                                      err_msg=n)
        for a, b in zip(o0[n], o2[n]):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))


def test_prefetch_window_helpers_clamp_and_byte_math():
    """fsdp_window_bytes / fsdp_prefetch_depth / fsdp_prefetch_ahead_bytes:
    the analytic window is the max adjacent run of padded f32 gather bytes,
    the requested depth is clamped so the live window never exceeds the
    depth-2 (two largest adjacent buckets) bound, and the ahead-bytes delta
    counts exactly the buckets held across the scan."""
    buckets = [{"pad": 64}, {"pad": 16}, {"pad": 48}, {"pad": 8}]
    # gather bytes per bucket: [256, 64, 192, 32]
    assert grad_comm.fsdp_window_bytes(buckets, 0) == 256  # jit: one bucket
    assert grad_comm.fsdp_window_bytes(buckets, 1) == 256
    assert grad_comm.fsdp_window_bytes(buckets, 2) == 320  # 256 + 64
    assert grad_comm.fsdp_window_bytes(buckets, 3) == 512  # 256 + 64 + 192
    assert grad_comm.fsdp_window_bytes(buckets, 99) == 544  # whole run
    assert grad_comm.fsdp_window_bytes([], 2) == 0

    assert grad_comm.fsdp_prefetch_ahead_bytes(buckets, 0) == 0
    assert grad_comm.fsdp_prefetch_ahead_bytes(buckets, 1) == 0
    assert grad_comm.fsdp_prefetch_ahead_bytes(buckets, 2) == 64
    assert grad_comm.fsdp_prefetch_ahead_bytes(buckets, 3) == 64 + 192

    for req in (0, -3):
        assert grad_comm.fsdp_prefetch_depth(buckets, req) == 0
    assert grad_comm.fsdp_prefetch_depth(buckets, 1) == 1
    assert grad_comm.fsdp_prefetch_depth(buckets, 2) == 2  # always fits
    for req in (3, 99):  # window(3) = 512 > 320 cap: clamp back to 2
        assert grad_comm.fsdp_prefetch_depth(buckets, req) == 2
    # a head-heavy layout whose deeper windows stay under the cap keeps
    # the requested depth
    shrink = [{"pad": 100}, {"pad": 10}, {"pad": 0}, {"pad": 0}]
    assert grad_comm.fsdp_prefetch_depth(shrink, 4) == 4


def test_prefetch_window_gauge_matches_memory_model():
    """The exec.train.fsdp_* introspection stats carry the window gauges —
    prefetch depth, analytic live-window bytes, ahead (across-scan) bytes —
    and they agree with fsdp_memory_model() and the grad_comm analytic
    helpers on the engine's real bucket layout."""
    ef = _make(k=2)  # FLAGS_fsdp_prefetch default: 2
    x, y = _batch()
    ef.step(x, y)
    buckets = ef._fsdp_layout()
    mm = ef.fsdp_memory_model()
    assert mm["prefetch"] == 2
    assert mm["window_bytes"] == grad_comm.fsdp_window_bytes(buckets, 2)
    assert mm["window_bytes_jit"] == grad_comm.fsdp_window_bytes(buckets, 0)
    assert mm["ahead_bytes"] == grad_comm.fsdp_prefetch_ahead_bytes(
        buckets, 2)
    assert mm["window_bytes"] > mm["window_bytes_jit"] > 0
    assert mm["ahead_bytes"] == mm["window_bytes"] - max(
        int(b["pad"]) * 4 for b in buckets[:1])

    stats = ef.introspect_executables()["train.fsdp_k2_f32"]
    assert stats["fsdp_prefetch"] == 2
    assert stats["fsdp_window_bytes"] == mm["window_bytes"]
    assert stats["fsdp_ahead_bytes"] == mm["ahead_bytes"]


def test_prefetch_flag_keys_executable_cache_append_only():
    """Flipping FLAGS_fsdp_prefetch mid-life rebuilds the compiled step
    under a NEW cache key — the fsdp key appends (True, depth) to the
    shared 6-tuple — and the trajectory stays bit-continuous across the
    flip (the window is value-identity). Non-fsdp keys keep the original
    6-tuple shape: the extension is append-only."""
    hcg = _dp()
    x, y = _batch()
    ef = _make(k=2, hcg=hcg)
    la = [float(ef.step(x, y).item())]
    paddle.set_flags({"fsdp_prefetch": 0})
    la.append(float(ef.step(x, y).item()))
    keys = list(ef._accum_fns)
    assert len(keys) == 2
    assert all(len(kk) == 8 and kk[6] is True for kk in keys)
    assert sorted(kk[7] for kk in keys) == [0, 2]
    # the flip is bit-continuous: a never-flipped depth-0 engine walks the
    # exact same trajectory
    e0 = _make(k=2, hcg=hcg)
    assert _losses(e0, x, y, steps=2) == la

    er = _make(k=2, mode=None, hcg=hcg)
    er.step(x, y)
    assert all(len(kk) == 6 for kk in er._accum_fns)


# ---------------------------------------------------------------- HLO gate

@pytest.mark.parametrize("k", [2, 4])
def test_hlo_per_bucket_gathers_one_reduce_scatter_no_all_reduce(k):
    """The compiled step holds exactly L per-bucket all-gathers and ONE
    reduce-scatter independent of K, zero full-buffer all-reduces and zero
    all-to-alls (f32), keeps the microbatch scan while-loop — and there is
    NO trailing param all-gather (L gathers total, not L+1) — with health
    partials riding the same program."""
    _skip_if_backend_combines()
    ef = _make(k=k)
    ef.enable_health(interval=1)
    x, y = _batch()
    ef.step(x, y)
    label, comp = _fsdp_compiled(ef)
    assert label == f"train.fsdp_k{k}_f32"
    L = len(ef._fsdp_layout())
    assert L >= 2  # per-layer, not one monolithic slab
    rep = an.check_compiled(label, comp, an.ProgramContract(
        collectives={"all-gather": L, "reduce-scatter": 1,
                     "all-reduce": 0, "all-to-all": 0},
        while_loops=(1, None),
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, f"fsdp decomposition contract broken:\n{rep.format()}"
    assert ef._health.recent()  # health rode the same program
    ef.disable_health()


def test_int8_rides_scales_all_to_all():
    """int8 payloads exchange chunk scales through the two all-to-alls of
    the quantized path (no reduce-scatter op), still L all-gathers, and the
    losses stay finite."""
    _skip_if_backend_combines()
    paddle.set_flags({"grad_comm_dtype": "int8", "grad_comm_chunk": 16})
    ef = _make(k=2)
    x, y = _batch()
    li = _losses(ef, x, y, steps=3)
    assert all(np.isfinite(li))
    label, comp = _fsdp_compiled(ef)
    rep = an.check_compiled(label, comp, an.ProgramContract(
        collectives={"all-gather": len(ef._fsdp_layout()),
                     "reduce-scatter": 0, "all-to-all": 2, "all-reduce": 0},
        while_loops=(1, None),
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, rep.format()


# ----------------------------------------------- bf16 + error feedback

def test_bf16_error_feedback_equals_replicated_bf16():
    """bf16 reduce-scatter with error feedback: the fsdp trajectory equals
    the replicated bf16 engine EXACTLY (both quantize identically), the
    residual is carried sharded state and is donated each step."""
    paddle.set_flags({"grad_comm_dtype": "bf16",
                      "grad_comm_error_feedback": True})
    hcg = _dp()
    x, y = _batch()
    er = _make(k=2, mode=None, hcg=hcg)
    ef = _make(k=2, hcg=hcg)
    ef.step(x, y)
    res0 = ef._grad_residual
    assert res0 is not None
    er.step(x, y)  # keep the two engines on the same step index
    la = [float(er.step(x, y).item()) for _ in range(3)]
    lb = [float(ef.step(x, y).item()) for _ in range(3)]
    assert lb == la
    assert res0.is_deleted()  # donated through the step
    assert not ef._grad_residual.is_deleted()


# ------------------------------------------------------------ checkpointing

def test_checkpoint_cross_dp_reslice_restore_bit_equal():
    """A save from an ENGAGED fsdp dp8 engine restores bit-equal into an
    engaged fsdp dp4 engine (different bucket pads — the manifest carries
    ordinary per-parameter sections, resliced on re-engage) and the two
    continue bit-identically to a replicated dp4 engine restored from the
    same checkpoint."""
    x, y = _batch()
    src = _make(k=2, hcg=_dp(8))
    _losses(src, x, y, steps=3)
    src_params = {n: np.asarray(v).tobytes()
                  for n, v in src._gather_fsdp_params().items()}
    src_opt = {n: tuple(np.asarray(s, np.float32).tobytes() for s in sl)
               for n, sl in src._gather_fsdp_opt().items()}
    with tempfile.TemporaryDirectory() as td:
        mgr = CheckpointManager(td, async_save=False)
        mgr.save(src, block=True)
        mgr.close()

        ef4 = _make(k=2, hcg=_dp(4), seed=7)
        _losses(ef4, x, y, steps=1)  # engage the dp4 shard layout first
        restore_latest(ef4, td)
        got_p = {n: np.asarray(v).tobytes()
                 for n, v in (ef4.params
                              if ef4.params is not None
                              else ef4._gather_fsdp_params()).items()}
        assert got_p == src_params

        er4 = _make(k=2, mode=None, hcg=_dp(4), seed=9)
        restore_latest(er4, td)
        lf, lr = _losses(ef4, x, y, steps=3), _losses(er4, x, y, steps=3)
        assert lf == lr
        got_o = {n: tuple(np.asarray(s, np.float32).tobytes() for s in sl)
                 for n, sl in ef4._gather_fsdp_opt().items()}
        ctl_o = {n: tuple(np.asarray(s, np.float32).tobytes() for s in sl)
                 for n, sl in er4.opt_state.items()}
        assert got_o == ctl_o
        # both resumed from the same bytes: step counts advanced in lockstep
        assert ef4._step_count == er4._step_count


def test_live_reshard_bit_identical_to_restore():
    """live_reshard of an engaged fsdp engine dp4 -> dp2 -> dp4 re-slices
    the flat shards in memory; at every leg the state and the continued
    losses are bit-identical to a control engine restored from a
    synchronous checkpoint onto the same topology — zero committed steps
    lost."""
    x, y = _batch()

    def param_bytes(eng):
        ps = eng.params if eng.params is not None \
            else eng._gather_fsdp_params()
        return {n: np.asarray(ps[n]).tobytes() for n in eng._param_names}

    def opt_bytes(eng):
        o = eng._gather_fsdp_opt() if eng._fsdp_params is not None \
            else eng.opt_state
        return {n: tuple(np.asarray(s, np.float32).tobytes() for s in o[n])
                for n in eng._param_names}

    with tempfile.TemporaryDirectory() as td:
        live = _make(k=2, hcg=_dp(4))
        _losses(live, x, y, steps=3)
        committed = live._step_count
        for leg, dp in enumerate((2, 4)):
            ckdir = os.path.join(td, f"leg{leg}")
            mgr = CheckpointManager(ckdir, async_save=False)
            mgr.save(live, block=True)
            mgr.close()
            ctrl = _make(k=2, hcg=_dp(dp), seed=7 + leg)
            _losses(ctrl, x, y, steps=1)  # engage the target layout
            restore_latest(ctrl, ckdir)
            pause_ms = live_reshard(live, _dp(dp))
            assert pause_ms >= 0.0 and live.hcg.degrees["dp"] == dp
            assert live._fsdp_params is not None and live.params is None
            assert live._step_count == committed
            assert param_bytes(live) == param_bytes(ctrl)
            ll, lc = _losses(live, x, y, steps=3), _losses(ctrl, x, y,
                                                           steps=3)
            assert ll == lc, (leg, ll, lc)
            assert opt_bytes(live) == opt_bytes(ctrl)
            committed = live._step_count


# ----------------------------------------------------- health attribution

class _Probe(paddle.nn.Layer):
    """Loss = mse + sum((tail.weight * s.mean())**2): the `s` batch column
    drives tail.weight's gradient to inf without touching any other
    parameter — data-driven injection into the compiled step."""

    def __init__(self):
        super().__init__()
        self.body = paddle.nn.Linear(8, 8)
        self.tail = paddle.nn.Linear(8, 8)

    def forward(self, x, y, s):
        h = self.tail(self.body(x))
        mse = ((h - y) ** 2).mean()
        canary = ((self.tail.weight * s.mean()) ** 2).sum()
        return mse + canary


def test_health_attribution_names_param_across_shard_owners():
    """tail.weight's bucket shards are spread over all 8 replicas; the
    per-replica partial stats ride the step outputs as a sharded [nrep,4P]
    slab (NO extra collective) and the host-side sum still attributes the
    injected inf to tail.weight by name, and to no other parameter."""
    paddle.set_flags({"grad_comm_chunk": 16})
    hcg = _dp(8)
    paddle.seed(0)
    net = _Probe()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    ef = TrainStepEngine(net, opt, loss_fn=None, hcg=hcg, microbatches=2,
                         fsdp=True)
    ef.enable_health(interval=1)
    assert len(ef._fsdp_layout()) >= 2  # body and tail in separate buckets

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8).astype("float32"))
    y = jnp.asarray(rng.randn(16, 8).astype("float32"))
    healthy = jnp.zeros((16,), jnp.float32)
    poisoned = jnp.full((16,), 1e25, jnp.float32)
    ef.step(x, y, healthy)
    ef.step(x, y, healthy)
    ef.step(x, y, poisoned)

    recs = ef._health.recent()
    assert [r["step"] for r in recs] == [1, 2, 3]
    assert recs[1]["nonfinite_count"] == 0
    bad = recs[2]
    assert bad["nonfinite_count"] > 0
    assert bad["first_nonfinite_param"] == "tail.weight"
    for name, pp in bad["per_param"].items():
        if name != "tail.weight":
            assert pp["nonfinite"] == 0, f"{name} wrongly flagged"
    ef.disable_health()


def test_health_stats_parity_with_replicated():
    """The host-summed fsdp health stats agree with the replicated engine's
    in-program psum stats (f32 sum order differs, so allclose — the
    attribution test above pins the exact names)."""
    hcg = _dp(8)
    x, y = _batch()
    er = _make(k=2, mode=None, hcg=hcg)
    ef = _make(k=2, hcg=hcg)
    er.enable_health(interval=1)
    ef.enable_health(interval=1)
    for _ in range(2):
        er.step(x, y)
        ef.step(x, y)
    rr, rf = er._health.recent()[-1], ef._health.recent()[-1]
    np.testing.assert_allclose(rr["grad_norm"], rf["grad_norm"], rtol=1e-5)
    assert rr["nonfinite_count"] == rf["nonfinite_count"] == 0
    er.disable_health()
    ef.disable_health()


# --------------------------------------------------------------- fallbacks

def test_mp_mesh_falls_back_with_single_warning():
    """A non-pure-dp topology can't own contiguous flat shards per dp
    replica; the engine warns ONCE ('fsdp requested ...') and runs the
    replicated path — same losses as the plain engine, params stay
    resident replicated."""
    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=4, mp_degree=2)
    x, y = _batch()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ef = _make(k=2, hcg=hcg)
        lm = _losses(ef, x, y, steps=3)
    fsdp_warns = [m for m in w if "fsdp requested" in str(m.message)]
    assert len(fsdp_warns) == 1, [str(m.message) for m in w]
    assert ef._fsdp_params is None and ef.params is not None
    assert ef._fsdp_warned  # and won't warn again
    assert all(len(key) == 6 for key in ef._accum_fns)  # never engaged
    lr = _losses(_make(k=2, mode=None, hcg=hcg), x, y, steps=3)
    np.testing.assert_allclose(lm, lr, rtol=1e-5)


def test_non_uniform_optimizer_rule_falls_back_bit_identical():
    """lars trust ratios aren't a uniform elementwise rule over a flat
    slice — same eligibility gate as ZeRO. fsdp warns once and the
    trajectory is bit-identical to the plain replicated lars engine."""
    hcg = _dp()
    x, y = _batch()
    lr = _losses(_make(k=2, mode=None, hcg=hcg, optimizer="lars"), x, y,
                 steps=3)
    with pytest.warns(UserWarning, match="fsdp requested"):
        ef = _make(k=2, hcg=hcg, optimizer="lars")
        lf = _losses(ef, x, y, steps=3)
    assert lf == lr
    assert ef._fsdp_params is None


def test_run_steps_rejects_active_fsdp():
    """run_steps is the fused K-OPTIMIZER-step scan lane over the
    replicated state dict; silently running it with sharded-resident
    params would diverge from step() semantics, so it raises."""
    x, y = _batch()
    ef = _make(k=1)
    with pytest.raises(ValueError, match="fsdp"):
        ef.run_steps(x, y, steps=2)


def test_fsdp_supersedes_zero_update():
    """fsdp=True + zero_update=True: fsdp wins (it strictly dominates —
    sharded params AND opt), the zero path never engages, and the
    trajectory still matches replicated bit for bit."""
    hcg = _dp()
    x, y = _batch()
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    eb = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                         hcg=hcg, microbatches=2, zero_update=True,
                         fsdp=True)
    lb = _losses(eb, x, y, steps=3)
    assert eb._fsdp_params is not None and eb._zero_opt is None
    lr = _losses(_make(k=2, mode=None, hcg=hcg), x, y, steps=3)
    assert lb == lr


# --------------------------------------------------- memory + byte counters

def test_param_opt_arg_bytes_scale_one_over_n_and_undercut_zero():
    """exec_introspect: the fsdp executable's per-device argument bytes
    drop by the analytic param+opt sharded-state delta that
    fsdp_memory_model() predicts (~1/N with bucket padding) and land
    STRICTLY below the ZeRO executable, which still holds replicated
    params."""
    paddle.set_flags({"grad_comm_chunk": 64})
    hcg = _dp()
    x, y = _batch(n=32, in_dim=128)
    er = _make(k=2, mode=None, hcg=hcg, width=128, in_dim=128)
    ez = _make(k=2, mode="zero", hcg=hcg, width=128, in_dim=128)
    ef = _make(k=2, hcg=hcg, width=128, in_dim=128)
    er.step(x, y)
    ez.step(x, y)
    ef.step(x, y)

    mm = ef.fsdp_memory_model()
    assert mm["opt_slots"] == 2 and mm["replicas"] == 8
    repl_state = mm["replicated_param_bytes"] + mm["replicated_opt_bytes"]
    shard_state = (mm["sharded_param_bytes_per_device"]
                   + mm["sharded_opt_bytes_per_device"])
    # big model + small chunk: padding is noise, sharded ~= replicated/8
    assert shard_state < repl_state / 6

    rep = er.introspect_executables()["train.accum_k2_f32"]
    zer = ez.introspect_executables()["train.zero_k2_f32"]
    fsd = ef.introspect_executables()["train.fsdp_k2_f32"]
    measured = (rep["argument_size_in_bytes"]
                - fsd["argument_size_in_bytes"])
    assert measured == pytest.approx(repl_state - shard_state, rel=0.05)
    assert fsd["argument_size_in_bytes"] < zer["argument_size_in_bytes"] \
        < rep["argument_size_in_bytes"]


def test_rs_ag_byte_counters_and_telemetry():
    """grad_comm.rs_bytes / ag_bytes count the fsdp collective payloads
    (K-independent per step) and surface as counter deltas in step
    telemetry records, which carry the fsdp marker."""
    from paddle_tpu.observability.step_telemetry import StepTelemetry

    ef = _make(k=4)
    ef.telemetry = StepTelemetry(collect_memory=False)
    rs0 = monitor.stat("grad_comm.rs_bytes").get()
    ag0 = monitor.stat("grad_comm.ag_bytes").get()
    x, y = _batch()
    ef.step(x, y)
    ef.step(x, y)
    shards = [b["shard"] for b in ef._fsdp_layout()]
    rs_b, ag_b, per_layer = grad_comm.fsdp_payload_bytes(
        shards, 8, "f32", grad_comm.chunk_size())
    assert len(per_layer) == len(shards)
    assert monitor.stat("grad_comm.rs_bytes").get() - rs0 == 2 * rs_b
    assert monitor.stat("grad_comm.ag_bytes").get() - ag0 == 2 * ag_b
    rec = ef.telemetry.sink.records[-1]
    assert rec["fsdp"] is True
    assert rec["microbatches"] == 4
    assert rec["grad_comm_bytes"] == rs_b + ag_b
    assert rec["fsdp_prefetch"] == 2  # FLAGS_fsdp_prefetch default depth
    assert rec["fsdp_window_bytes"] == grad_comm.fsdp_window_bytes(
        ef._fsdp_layout(), 2)

"""Trainer/DeviceWorker stack: Executor.train_from_dataset over the C++ feed.

Reference (#12): trainer.h:59 MultiTrainer + device_worker.h:249 HogwildWorker
driven from executor.py train_from_dataset; here the loop is
static/trainer.py's prefetch-queue + fused-XLA-step design.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import static



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

def _write_dense_file(path, rows, seed):
    """Slots: x (4 dense floats), y (1 float). y = x @ w_true + 0.1."""
    rs = np.random.RandomState(seed)
    w = np.array([0.5, -1.0, 2.0, 0.25])
    lines = []
    for _ in range(rows):
        x = rs.rand(4).round(4)
        y = float(x @ w + 0.1)
        lines.append("4 " + " ".join(f"{v:.4f}" for v in x) + f" 1 {y:.5f}")
    path.write_text("\n".join(lines) + "\n")


@pytest.fixture()
def dense_dataset(tmp_path):
    for i in range(2):
        _write_dense_file(tmp_path / f"part-{i}", 32, i)
    ds = dist.InMemoryDataset()
    ds.init(batch_size=8, thread_num=2, use_var=[("x", "f"), ("y", "f")])
    ds.set_filelist([str(tmp_path / "part-0"), str(tmp_path / "part-1")])
    ds.load_into_memory()
    return ds


def _build_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 4], "float32")
        y = static.data("y", [-1, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - y) ** 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    return main, startup, loss


def test_train_from_dataset_learns(dense_dataset, capsys):
    paddle.seed(0)
    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)

    first = exe.train_from_dataset(main, dense_dataset, fetch_list=[loss],
                                   fetch_info=["loss"], print_period=4)
    for _ in range(25):  # more epochs over the in-memory set
        last = exe.train_from_dataset(main, dense_dataset, fetch_list=[loss],
                                      print_period=0)
    assert float(last[0]) < float(first[0])
    assert float(last[0]) < 0.05
    out = capsys.readouterr().out
    assert "[step 4] loss:" in out  # print_period fetch reporting


def test_infer_from_dataset_no_update(dense_dataset):
    paddle.seed(0)
    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)
    exe.train_from_dataset(main, dense_dataset, print_period=0)

    w_name = [n for n in main._captures][0]
    before = np.asarray(main._captures[w_name]._data).copy()
    out = exe.infer_from_dataset(main, dense_dataset, fetch_list=[loss],
                                 print_period=0)
    after = np.asarray(main._captures[w_name]._data)
    np.testing.assert_array_equal(before, after)  # no parameter updates
    assert out is not None


def test_sparse_slot_padding():
    from paddle_tpu.static.trainer import _assemble_feed

    vals = np.array([5, 6, 7, 8, 9], np.uint64)
    offs = np.array([0, 2, 2, 5], np.int64)  # rows of width 2, 0, 3
    feed = _assemble_feed({"ids": (vals, offs)}, ["ids", "ids.lens"])
    assert feed["ids"].shape == (3, 4)  # maxlen 3 -> bucket 4
    np.testing.assert_array_equal(feed["ids"][0], [5, 6, 0, 0])
    np.testing.assert_array_equal(feed["ids"][1], [0, 0, 0, 0])
    np.testing.assert_array_equal(feed["ids"][2], [7, 8, 9, 0])
    np.testing.assert_array_equal(feed["ids.lens"], [2, 0, 3])


def test_trainer_factory_dist_selection(dense_dataset):
    from paddle_tpu.static.trainer import (DistMultiTrainer, MultiTrainer,
                                           TrainerFactory)

    main, startup, loss = _build_program()
    exe = static.Executor()
    t = TrainerFactory.create(exe, main, dense_dataset, is_dist=False)
    assert isinstance(t, MultiTrainer) and not isinstance(t, DistMultiTrainer)
    t = TrainerFactory.create(exe, main, dense_dataset, is_dist=True)
    assert isinstance(t, DistMultiTrainer)


def test_producer_exception_propagates(dense_dataset):
    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)

    class Exploding:
        _thread_num = 2

        def __iter__(self):
            yield from iter(dense_dataset)
            raise OSError("corrupt feed file")

    with pytest.raises(OSError, match="corrupt feed file"):
        exe.train_from_dataset(main, Exploding(), print_period=0)


def test_multi_thread_producers(dense_dataset):
    paddle.seed(0)
    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)
    out = exe.train_from_dataset(main, dense_dataset, thread=4,
                                 fetch_list=[loss], print_period=0)
    assert out is not None


def test_device_step_exception_joins_producers(dense_dataset):
    """A failing device step must drain the queue, join producers, and raise
    (not leak threads blocked on q.put)."""
    import threading

    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)
    before = threading.active_count()

    class BoomExec:
        def run(self, *a, **k):
            raise RuntimeError("device step failed")

    from paddle_tpu.static.trainer import MultiTrainer
    t = MultiTrainer(BoomExec(), main, dense_dataset, thread_num=3)
    with pytest.raises(RuntimeError, match="device step failed"):
        t.run()
    assert threading.active_count() <= before + 1  # producers joined

"""Int8 inference quantization (incubate.quantization) — the TPU slim-quant
analogue (reference fluid/contrib/slim/quantization/): numerics within int8
tolerance of f32, s8 dot on the int8 path, Linear swap, decode integration."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.quantization import (QuantizedLinear,
                                              dynamic_int8_matmul,
                                              quantize_model, quantize_weight,
                                              weight_only_int8_matmul)


def _rand(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(
        "float32")


def test_quantize_weight_roundtrip():
    w = _rand((64, 32), 0)
    q, scale = quantize_weight(w)
    assert np.asarray(q).dtype == np.int8
    deq = np.asarray(q, np.float32) * np.asarray(scale)
    # abs-max per channel: max |error| <= scale/2 per channel
    err = np.abs(deq - w)
    assert (err <= np.asarray(scale) / 2 + 1e-7).all()


@pytest.mark.parametrize("fn", [weight_only_int8_matmul, dynamic_int8_matmul],
                         ids=["weight_only", "dynamic"])
def test_matmul_parity_within_int8_tolerance(fn):
    x = _rand((8, 64), 1)
    w = _rand((64, 32), 2)
    b = _rand((32,), 3)
    q, scale = quantize_weight(w)
    ref = x @ w + b
    out = np.asarray(fn(paddle.to_tensor(x), q, scale,
                        bias=paddle.to_tensor(b)).numpy())
    # int8 introduces ~1/127 relative error per factor; dynamic quantizes
    # both sides
    tol = 0.02 if fn is weight_only_int8_matmul else 0.04
    denom = np.abs(ref).mean()
    assert np.abs(out - ref).mean() / denom < tol


def test_dynamic_path_uses_s8_dot():
    """The dynamic path must compile to an s8 x s8 -> s32 dot (the MXU int8
    mode), not a dequantize-then-float matmul."""
    import jax

    x = _rand((16, 64), 1)
    w = _rand((64, 32), 2)
    q, scale = quantize_weight(w)

    def f(xa):
        return dynamic_int8_matmul(xa, q, scale)

    txt = jax.jit(f).lower(x).compile().as_text()
    assert "s8[" in txt and "s32[" in txt, \
        "int8 dot missing from compiled dynamic-quant matmul"


def test_quantized_linear_and_model_swap():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    x = paddle.to_tensor(_rand((4, 16), 5))
    ref = net(x).numpy()

    quantize_model(net, mode="weight_only_int8")
    assert isinstance(net[0], QuantizedLinear)
    assert isinstance(net[2], QuantizedLinear)
    assert len(list(net.parameters())) == 0  # frozen inference constants
    out = net(x).numpy()
    assert np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9) < 0.03

    with pytest.raises(ValueError):
        QuantizedLinear.from_linear(nn.Linear(4, 4), mode="int4")


def test_weight_only_decode_generate():
    """Weight-only int8 on the GPT MLP/attention projections keeps greedy
    decode sensible (same API surface as the f32 model)."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 1024, (2, 8)).astype(np.int64))
    ref = m.generate(ids, max_new_tokens=4, temperature=0).numpy()
    quantize_model(m)  # single replica: TP linear layers swap too
    assert isinstance(m.gpt.blocks[0].attn.qkv_proj, QuantizedLinear)
    assert isinstance(m.gpt.blocks[0].mlp.fc1, QuantizedLinear)
    out = m.generate(ids, max_new_tokens=4, temperature=0).numpy()
    assert out.shape == ref.shape
    assert (out[:, :8] == ref[:, :8]).all()  # prompt preserved
    # int8 projections rarely flip an untrained model's greedy argmax at
    # step 1; require the first generated token to survive quantization
    assert (out[:, 8] == ref[:, 8]).all()


def test_quantized_weights_survive_state_dict_and_save(tmp_path):
    """Quantized weights are persistable BUFFERS: paddle.save keeps them,
    and generate()'s functional_call receives them as runtime arguments
    (an empty state_dict would bake them into executables as constants)."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 8))
    x = paddle.to_tensor(_rand((2, 8), 9))
    quantize_model(net)
    sd = net.state_dict()
    assert any("_w_int8" in k for k in sd), sorted(sd)
    assert any("_scale" in k for k in sd), sorted(sd)
    ref = net(x).numpy()
    path = str(tmp_path / "q.pdparams")
    paddle.save(sd, path)
    loaded = paddle.load(path)
    paddle.seed(0)
    net2 = nn.Sequential(nn.Linear(8, 8))
    quantize_model(net2)
    net2.set_state_dict(loaded)
    np.testing.assert_allclose(net2(x).numpy(), ref, rtol=1e-6)


def test_weight_only_respects_amp_autocast():
    """Under bf16 amp the quantized matmul's activation is cast like
    nn.Linear's would be (dispatch-routed under the 'linear' op name)."""
    x = paddle.to_tensor(_rand((4, 16), 11))
    q, scale = quantize_weight(_rand((16, 8), 12))
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        out = weight_only_int8_matmul(x, q, scale)
    assert "bfloat16" in str(out.dtype)
    out_f32 = weight_only_int8_matmul(x, q, scale)
    assert "float32" in str(out_f32.dtype)


def test_quantize_model_handles_root_linear():
    import paddle_tpu.nn as nn

    lin = nn.Linear(4, 4)
    out = quantize_model(lin)
    assert isinstance(out, QuantizedLinear)


def test_fake_quant_straight_through():
    """Forward lands on the int grid; backward is identity (STE)."""
    import jax

    from paddle_tpu.incubate.quantization import fake_quant

    x = paddle.to_tensor(_rand((8,), 20))
    x.stop_gradient = False
    y = fake_quant(x, bits=8)
    err = np.abs(y.numpy() - x.numpy())
    scale = np.abs(x.numpy()).max() / 127.0
    assert (err <= scale / 2 + 1e-7).all()       # on-grid forward
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(8), rtol=1e-6)  # STE


def test_qat_train_then_convert():
    """ImperativeQuantAware: fake-quant training converges, convert()
    produces true int8 layers whose outputs track the QAT model."""
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.quantization import (ImperativeQuantAware,
                                                  QATLinear)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    qat = ImperativeQuantAware()
    qat.quantize(net)
    assert isinstance(net[0], QATLinear)
    assert len(list(net.parameters())) == 4  # still trainable floats

    x = paddle.to_tensor(_rand((32, 8), 21))
    target = paddle.to_tensor(_rand((32, 1), 22))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    net.train()
    losses = []
    for _ in range(30):
        loss = ((net(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < 0.5 * losses[0], losses[::10]  # QAT trains

    net.eval()
    ref = net(x).numpy()
    qat.convert(net)
    assert isinstance(net[0], QuantizedLinear)
    out = net(x).numpy()
    # converted int8 stays close to the fake-quant-trained model
    assert np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9) < 0.1


def test_qat_wraps_tp_layers_and_ptq_converts_qat():
    """QAT must reach the model zoo's transformer projections (TP linear
    layers, single replica), and quantize_model on a QAT-wrapped model must
    convert via the trained inner Linear instead of corrupting the wrapper."""
    from paddle_tpu.incubate.quantization import (ImperativeQuantAware,
                                                  QATLinear)
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    ImperativeQuantAware().quantize(m)
    assert isinstance(m.gpt.blocks[0].attn.qkv_proj, QATLinear)
    assert isinstance(m.gpt.blocks[0].mlp.fc2, QATLinear)

    quantize_model(m)  # PTQ over a QAT model: unwrap, don't corrupt
    assert isinstance(m.gpt.blocks[0].attn.qkv_proj, QuantizedLinear)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 1024, (2, 8)).astype(np.int64))
    m.eval()
    out = m.generate(ids, max_new_tokens=2, temperature=0)
    assert out.shape == [2, 10]


def test_qat_calibration_survives_checkpoint(tmp_path):
    """The moving-average activation scale lives in a persisted buffer and
    the calibrated/uncalibrated choice is derived from it (scale > 0), so a
    restored QAT model keeps its calibration."""
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.quantization import (ImperativeQuantAware,
                                                  QATLinear)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 4))
    ImperativeQuantAware().quantize(net)
    x = paddle.to_tensor(_rand((4, 8), 30))
    net.train()
    net(x)  # calibrates the moving average
    scale = float(net[0]._act_scale.numpy())
    assert scale > 0
    path = str(tmp_path / "qat.pdparams")
    paddle.save(net.state_dict(), path)

    paddle.seed(0)
    net2 = nn.Sequential(nn.Linear(8, 4))
    ImperativeQuantAware().quantize(net2)
    net2.set_state_dict(paddle.load(path))
    assert float(net2[0]._act_scale.numpy()) == pytest.approx(scale)
    net2.eval()
    # restored model quantizes with the trained scale, matching the source
    np.testing.assert_allclose(net2(x).numpy(), net(x).numpy(), rtol=1e-6)


def test_post_training_quantization_calibration():
    """PostTrainingQuantization: calibration hooks record per-layer
    activation abs-max scales, convert() removes hooks and swaps layers."""
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.quantization import PostTrainingQuantization

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ptq = PostTrainingQuantization(net)
    big = _rand((4, 8), 40, scale=3.0)
    ptq.collect(paddle.to_tensor(_rand((4, 8), 41)))
    ptq.collect(paddle.to_tensor(big))
    assert len(ptq.scales) == 2
    # the recorded scale is the max over calibration batches
    assert ptq.scales["0"] == pytest.approx(np.abs(big).max() / 127.0)

    q = ptq.convert(mode="dynamic_int8")
    assert isinstance(q[0], QuantizedLinear)
    out = q(paddle.to_tensor(big))
    assert out.shape == [4, 4]
    # hooks removed: further forwards must not grow the scale record
    before = dict(ptq.scales)
    q(paddle.to_tensor(_rand((4, 8), 42, scale=10.0)))
    assert ptq.scales == before


def test_static_int8_uses_calibrated_scales():
    """static_int8: activations quantize with the FIXED calibrated scale
    (no runtime abs-max); numerics stay within int8 tolerance of f32 when
    the calibration data covers the activation range."""
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.quantization import PostTrainingQuantization

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = paddle.to_tensor(_rand((8, 8), 50))
    ref = net(x).numpy()
    ptq = PostTrainingQuantization(net)
    ptq.collect(x)
    q = ptq.convert(mode="static_int8")
    assert q[0].mode == "static_int8"
    assert float(q[0]._act_scale.numpy()) > 0
    out = q(x).numpy()
    assert np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9) < 0.06

    # static mode without calibration must refuse
    with pytest.raises(ValueError):
        quantize_model(nn.Sequential(nn.Linear(4, 4)), mode="static_int8")

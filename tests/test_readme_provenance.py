"""Every throughput number README quotes must be a committed BENCH_HISTORY row.

VERDICT r4 #10: "a reader can reproduce every number in README from
committed tools". This pins the mechanical half of that promise — the
quoted tok/s figures are exact `value` / `extra.decode_tokens_per_sec`
fields of BENCH_HISTORY.jsonl rows, so README cannot drift into
aspirational numbers without this test failing. (The MFU/bandwidth
readings live in BASELINE.md tables next to the tool that produced them;
the tok/s figures are the ones a reader will try to reproduce first.)
"""
import json
import os
import re
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _history_values():
    vals = set()
    with open(os.path.join(ROOT, "BENCH_HISTORY.jsonl")) as f:
        for ln in f:
            try:
                row = json.loads(ln)
            except json.JSONDecodeError:
                continue
            v = row.get("value")
            if isinstance(v, (int, float)):
                vals.add(round(float(v), 1))
            d = (row.get("extra") or {}).get("decode_tokens_per_sec")
            if isinstance(d, (int, float)):
                vals.add(round(float(d), 1))
    return vals


def test_readme_planner_join_headline_matches_baseline():
    """VERDICT r5 weak #6/next #4: one planner-join headline across
    committed documents. README must quote the FINAL 15-pair join (12/15 =
    80.0% corrected vs 53.3% raw) — the same figures BASELINE.md records —
    and may reference the mid-round 3/3 snapshot only as superseded."""
    readme = open(os.path.join(ROOT, "README.md")).read()
    baseline = open(os.path.join(ROOT, "BASELINE.md")).read()
    for doc, name in ((readme, "README.md"), (baseline, "BASELINE.md")):
        assert "12/15" in doc and "80.0%" in doc, (
            f"{name} no longer quotes the final planner join headline "
            f"(12/15 = 80.0%)")
    # the mid-round snapshot may appear in README only labeled as such
    m = re.search(r"3/3[^.]*", readme)
    if m:
        ctx = readme[max(0, m.start() - 400):m.end() + 200]
        assert "snapshot" in ctx or "superseded" in ctx, (
            "README quotes the 3/3 mid-round figure without labeling it a "
            "superseded snapshot of the 15-pair join")


@pytest.mark.slow  # spawns a full collection subprocess (~seconds)
def test_readme_test_count_matches_collection():
    """README's quoted suite size must be the live collected count — a
    stale number is exactly the drift this gate exists for."""
    readme = open(os.path.join(ROOT, "README.md")).read()
    m = re.search(r"collects \*\*(\d+) tests\*\*", readme)
    assert m, "README no longer quotes the collected test count"
    quoted = int(m.group(1))
    res = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only", "-q",
         "-p", "no:cacheprovider"],
        capture_output=True, text=True, timeout=240, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    m2 = re.search(r"(\d+) tests collected", res.stdout)
    assert m2, res.stdout[-1500:]
    collected = int(m2.group(1))
    assert quoted == collected, (
        f"README quotes {quoted} tests; collection finds {collected} — "
        f"update the README figure")


def test_readme_round5_numbers_are_committed_history_rows():
    readme = open(os.path.join(ROOT, "README.md")).read()
    m = re.search(r"Round-5 on-chip results(.*?)\n## ", readme, re.S)
    assert m, "README round-5 results section not found"
    section = m.group(1)
    # quoted figures: thousands-separated numbers with or without decimals
    # (94,683.7 AND a rounded 95,000 must both be backed); plain unseparated
    # integers like '16 GB' / years and bracketed block pairs like
    # [512,512] are out of scope
    quoted = {float(x.replace(",", ""))
              for x in re.findall(
                  r"(?<!\[)\b(\d{1,3}(?:,\d{3})+(?:\.\d+)?)\b(?!\])",
                  section)}
    assert quoted, "no quoted tok/s figures found in the round-5 section"
    hist = _history_values()
    missing = {q for q in quoted if round(q, 1) not in hist}
    assert not missing, (
        f"README quotes figures with no committed BENCH_HISTORY row: "
        f"{sorted(missing)}")

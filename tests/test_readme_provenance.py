"""Every throughput number README quotes must be a committed BENCH_HISTORY row.

VERDICT r4 #10: "a reader can reproduce every number in README from
committed tools". This pins the mechanical half of that promise — the
quoted tok/s figures are exact `value` / `extra.decode_tokens_per_sec`
fields of BENCH_HISTORY.jsonl rows, so README cannot drift into
aspirational numbers without this test failing. (The MFU/bandwidth
readings live in BASELINE.md tables next to the tool that produced them;
the tok/s figures are the ones a reader will try to reproduce first.)
"""
import json
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _history_values():
    vals = set()
    with open(os.path.join(ROOT, "BENCH_HISTORY.jsonl")) as f:
        for ln in f:
            try:
                row = json.loads(ln)
            except json.JSONDecodeError:
                continue
            v = row.get("value")
            if isinstance(v, (int, float)):
                vals.add(round(float(v), 1))
            d = (row.get("extra") or {}).get("decode_tokens_per_sec")
            if isinstance(d, (int, float)):
                vals.add(round(float(d), 1))
    return vals


def test_readme_round5_numbers_are_committed_history_rows():
    readme = open(os.path.join(ROOT, "README.md")).read()
    m = re.search(r"Round-5 on-chip results(.*?)\n## ", readme, re.S)
    assert m, "README round-5 results section not found"
    section = m.group(1)
    # quoted figures: thousands-separated numbers with or without decimals
    # (94,683.7 AND a rounded 95,000 must both be backed); plain unseparated
    # integers like '16 GB' / years and bracketed block pairs like
    # [512,512] are out of scope
    quoted = {float(x.replace(",", ""))
              for x in re.findall(
                  r"(?<!\[)\b(\d{1,3}(?:,\d{3})+(?:\.\d+)?)\b(?!\])",
                  section)}
    assert quoted, "no quoted tok/s figures found in the round-5 section"
    hist = _history_values()
    missing = {q for q in quoted if round(q, 1) not in hist}
    assert not missing, (
        f"README quotes figures with no committed BENCH_HISTORY row: "
        f"{sorted(missing)}")

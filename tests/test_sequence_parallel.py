"""Sequence parallelism: ring attention + Ulysses vs dense reference, and
end-to-end loss parity of an sp-sharded GPT train step (SURVEY.md §5.7 —
a first-class addition; the reference has no SP)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import set_hybrid_communicate_group
from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
    ring_attention, ulysses_attention)


def dense_ref(q, k, v, causal):
    qt, kt, vt = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2)


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    return [jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32)) for _ in range(3)]


@pytest.fixture
def sp_mesh():
    return Mesh(np.array(jax.devices()).reshape(4, 2), ("sp", "dp"))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(qkv, sp_mesh, causal):
    q, k, v = qkv
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, sp_mesh, axis="sp", causal=causal))(q, k, v)
    np.testing.assert_allclose(out, dense_ref(q, k, v, causal), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads(qkv, sp_mesh, causal):
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, axis="sp", causal=causal) * v)

    def loss_dense(q, k, v):
        return jnp.sum(dense_ref(q, k, v, causal) * v)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(a, b, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_path_matches_dense(qkv, sp_mesh, causal,
                                                 monkeypatch):
    """VERDICT r1 #6: the per-shard block compute must run the Pallas flash
    kernel. PADDLE_TPU_RING_FLASH=1 opts into it on CPU (interpret mode)."""
    monkeypatch.setenv("PADDLE_TPU_RING_FLASH", "1")
    q, k, v = qkv
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, sp_mesh, axis="sp", causal=causal))(q, k, v)
    np.testing.assert_allclose(out, dense_ref(q, k, v, causal), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_path_grads(qkv, sp_mesh, causal, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RING_FLASH", "1")
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, axis="sp",
                                      causal=causal) * v)

    def loss_dense(q, k, v):
        return jnp.sum(dense_ref(q, k, v, causal) * v)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_flash_with_lse_values_and_lse_cotangent():
    """flash_attention_with_lse: lse matches the dense logsumexp, and a loss
    that reads lse backpropagates correctly (the g_lse -> delta fold)."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_with_lse

    rng = np.random.RandomState(3)
    q, k, v = [jnp.asarray(rng.randn(1, 16, 2, 16).astype(np.float32))
               for _ in range(3)]
    sm = 1.0 / np.sqrt(16)

    def dense_lse(q, k, v):
        qt, kt = jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * sm
        return jax.scipy.special.logsumexp(s, axis=-1)  # [b,h,sq]

    o, lse = flash_attention_with_lse(q, k, v)
    np.testing.assert_allclose(lse, dense_lse(q, k, v), atol=2e-5)
    np.testing.assert_allclose(o, dense_ref(q, k, v, False), atol=2e-5)

    w = jnp.asarray(rng.randn(1, 2, 16).astype(np.float32))

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v)
        return jnp.sum(lse * w) + jnp.sum(o * v)

    def loss_ref(q, k, v):
        return (jnp.sum(dense_lse(q, k, v) * w)
                + jnp.sum(dense_ref(q, k, v, False) * v))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(qkv, sp_mesh, causal):
    q, k, v = qkv
    out = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, sp_mesh, axis="sp", causal=causal))(q, k, v)
    np.testing.assert_allclose(out, dense_ref(q, k, v, causal), atol=2e-5)


def _train_losses(sep, impl="ring", steps=3):
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": sep}
    strategy.sep_impl = impl
    fleet.init(is_collective=True, strategy=strategy)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    eng = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (4, 64)).astype(np.int64)
    labels = np.roll(ids, -1, 1)
    return [float(eng.step(paddle.to_tensor(ids),
                           paddle.to_tensor(labels)).item()) for _ in range(steps)]


def test_sp_train_loss_parity():
    base = _train_losses(sep=1)
    ring = _train_losses(sep=2, impl="ring")
    np.testing.assert_allclose(base, ring, rtol=3e-4, atol=3e-4)
    assert ring[-1] < ring[0]  # it actually learns


def test_sp_ulysses_train_loss_parity():
    base = _train_losses(sep=1)
    uly = _train_losses(sep=4, impl="ulysses")
    np.testing.assert_allclose(base, uly, rtol=3e-4, atol=3e-4)

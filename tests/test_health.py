"""In-program training-health telemetry + compiled-executable introspection
(ISSUE 8 tentpole).

The contract under test, in three layers:

- **layout pin**: ``segment_layout`` must equal ``ravel_pytree``'s dict
  flatten order — the whole per-parameter attribution story rests on the
  packed stats being literal slices of grad_comm's flat buffer;
- **HLO gates**: health stats ride the SAME compiled step (zero extra
  dispatches, the dp8 accumulation step keeps exactly ONE fused gradient
  all-reduce and ONE scan while-loop), the program is bit-identical when
  health is off, and the host sees at most ONE device->host fetch per
  ``health_interval`` steps (pinned via the ``health.fetches`` counter);
- **attribution**: a NaN injected into ONE parameter's gradient mid-run is
  localized BY NAME in the health record, the health.jsonl sink, the
  metrics registry (``health.nonfinite.<param>``), and the flight-recorder
  dump the breach triggers.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)
from paddle_tpu.observability import (exec_introspect, flight_recorder,
                                      health, metrics)



@pytest.fixture(autouse=True)
def _observability_cleanup():
    yield
    metrics.reset()
    flight_recorder.disable()
    health.reset()
    exec_introspect.reset()


def _tiny_engine(microbatches=1, model=None, loss_fn="mse"):
    """Single-device engine: health numbers must not depend on the virtual
    8-CPU mesh the conftest forces for sharding tests."""
    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=1, devices=jax.devices()[:1])
    paddle.seed(0)
    if model is None:
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 8), paddle.nn.ReLU(),
                                     paddle.nn.Linear(8, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = TrainStepEngine(model, opt,
                          loss_fn=paddle.nn.MSELoss() if loss_fn == "mse"
                          else None,
                          hcg=hcg, microbatches=microbatches)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 8).astype("float32"))
    y = jnp.asarray(rng.randn(8, 8).astype("float32"))
    return eng, [x, y]


def _packed(g2, w2, u2, nf):
    return np.asarray(list(g2) + list(w2) + list(u2) + list(nf), np.float32)


# ---------------------------------------------------------------- layout pin

def test_segment_layout_matches_ravel_pytree():
    """segment_layout's (name, offset, size) triples must index ravel_pytree's
    flat vector exactly — sorted-by-name IS the dict flatten order. This is
    the load-bearing equivalence: per-parameter stats computed from the grads
    dict are per-slice stats of grad_comm's flat buffer."""
    from jax.flatten_util import ravel_pytree

    shapes = {"b.weight": (3, 2), "a.weight": (4,), "m.scale": (),
              "c.bias": (2, 2, 2)}
    tree = {}
    for i, (n, s) in enumerate(sorted(shapes.items())):
        size = int(np.prod(s, dtype=np.int64)) if s else 1
        tree[n] = (jnp.arange(size, dtype=jnp.float32) + 1000.0 * i).reshape(s)
    flat, _ = ravel_pytree(tree)
    layout = health.segment_layout(shapes)
    assert [n for n, _, _ in layout] == sorted(shapes)
    off_total = 0
    for name, off, size in layout:
        assert off == off_total
        np.testing.assert_array_equal(np.asarray(flat[off:off + size]),
                                      np.asarray(tree[name]).ravel())
        off_total += size
    assert int(flat.size) == off_total


# ------------------------------------------------- interval gating + fan-out

def test_health_interval_gates_the_single_fetch():
    """interval=2 over 5 steps -> records (and D2H fetches) at steps 2 and 4
    ONLY: the `health.fetches` counter IS the at-most-one-transfer-per-
    interval gate (each ingest does exactly one np.asarray of the packed
    buffer)."""
    eng, arrays = _tiny_engine()
    eng.enable_health(interval=2)
    metrics.enable()
    fetches0 = monitor.stat("health.fetches").get()
    for _ in range(5):
        eng.step(*arrays)
    recs = eng._health.recent()
    assert [r["step"] for r in recs] == [2, 4]
    assert monitor.stat("health.fetches").get() - fetches0 == 2
    for r in recs:
        assert r["nonfinite_count"] == 0 and r["grad_norm"] > 0
        assert set(r["per_param"]) == set(eng._param_names)
    # registry fan-out: norm histograms + last-step gauge
    reg = metrics.active_registry()
    hist = reg.histogram("train.grad_norm",
                         boundaries=health.NORM_BUCKETS).snapshot()
    assert hist["count"] == 2
    assert reg.gauge("health.last_step").value == 4
    eng.disable_health()


def test_health_jsonl_sink_and_trace_summary():
    """enable_health(path=...) writes health.jsonl records that
    tools/trace_summary.py renders as health telemetry."""
    import subprocess
    import sys
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "health.jsonl")
        eng, arrays = _tiny_engine()
        eng.enable_health(interval=1, path=p)
        for _ in range(3):
            eng.step(*arrays)
        eng.disable_health()  # closes the sink
        recs = [json.loads(ln) for ln in open(p) if ln.strip()]
        assert [r["step"] for r in recs] == [1, 2, 3]
        assert all(r["event"] == "health" for r in recs)
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "trace_summary.py"), p],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        summary = json.loads(out.stdout.strip().splitlines()[-1])["summary"]
        assert summary["kind"] == "health_telemetry"
        assert summary["records"] == 3 and summary["anomalies"] == 0


# --------------------------------------------------------- NaN localization

class _Probe(paddle.nn.Layer):
    """Loss = mse + sum((tail.weight * s.mean())**2): the `s` batch column is
    a dial that drives tail.weight's gradient (2 * s.mean()^2 * w) to inf
    WITHOUT touching any other parameter's gradient — data-driven injection,
    so the compiled step is traced once and the breach happens mid-run."""

    def __init__(self):
        super().__init__()
        self.body = paddle.nn.Linear(8, 8)
        self.tail = paddle.nn.Linear(8, 8)

    def forward(self, x, y, s):
        h = self.tail(self.body(x))
        mse = ((h - y) ** 2).mean()
        canary = ((self.tail.weight * s.mean()) ** 2).sum()
        return mse + canary


def test_nan_localization_names_exact_parameter(tmp_path):
    """Inject inf into ONE parameter's grad mid-run (step 3 of a K=2
    microbatch engine): the health record, health.jsonl, the registry
    counter, and the flight dump must all name tail.weight — and no other
    parameter may report a non-finite gradient."""
    fr = flight_recorder.enable(str(tmp_path / "flight"))
    metrics.enable()
    eng, arrays = _tiny_engine(microbatches=2, model=_Probe(), loss_fn=None)
    assert "tail.weight" in eng._param_names
    eng.enable_health(interval=1, path=str(tmp_path / "health.jsonl"))

    healthy = jnp.zeros((8,), jnp.float32)
    poisoned = jnp.full((8,), 1e25, jnp.float32)
    eng.step(*arrays, healthy)
    eng.step(*arrays, healthy)
    eng.step(*arrays, poisoned)

    recs = eng._health.recent()
    assert [r["step"] for r in recs] == [1, 2, 3]
    assert recs[0]["nonfinite_count"] == 0 and recs[1]["nonfinite_count"] == 0
    bad = recs[2]
    assert bad["nonfinite_count"] > 0
    assert bad["first_nonfinite_param"] == "tail.weight"
    assert bad["first_nonfinite_segment"] == sorted(
        eng._param_names).index("tail.weight")
    for name, pp in bad["per_param"].items():
        if name != "tail.weight":
            assert pp["nonfinite"] == 0, f"{name} wrongly flagged"

    # registry: per-parameter non-finite counter by NAME
    reg = metrics.active_registry()
    assert reg.counter("health.nonfinite.tail.weight").value == 1
    assert reg.counter("health.nonfinite_steps").value == 1

    # flight dump: reason names the parameter; state.json carries the
    # attribution extra AND the health ring tail
    dumps = [d for d in fr.dumps
             if "health_nonfinite" in os.path.basename(d)]
    assert len(dumps) == 1
    assert "tail_weight" in os.path.basename(dumps[0])
    state = json.load(open(os.path.join(dumps[0], "state.json")))
    assert state["extra"]["param"] == "tail.weight"
    assert state["extra"]["step"] == 3
    tail = state["health_tail"]
    assert tail and tail[-1]["first_nonfinite_param"] == "tail.weight"

    # jsonl sink got the same record
    eng.disable_health()
    recs = [json.loads(ln) for ln in open(tmp_path / "health.jsonl")
            if ln.strip()]
    assert recs[-1]["step"] == 3
    assert recs[-1]["first_nonfinite_param"] == "tail.weight"


# ----------------------------------------------------------- spike detection

def test_spike_detection_ema_and_dump_rate_limit(tmp_path):
    """Synthetic packed buffers straight into the host half: a grad-norm jump
    past spike_factor x EMA flags `spike`, bumps the counters, and dumps —
    but at most _DUMP_LIMIT dumps per reason, so a diverged run cannot flood
    the disk."""
    fr = flight_recorder.enable(str(tmp_path))
    m = health.TrainingHealthMonitor({"a": (2,), "b": (3,)},
                                     interval=1, spike_factor=10.0)
    spikes0 = monitor.stat("health.spikes").get()
    rec = m.on_step(1, _packed([1, 1], [4, 4], [.01, .01], [0, 0]))
    assert rec["spike"] is False  # no EMA yet -> first sample never spikes
    assert rec["grad_norm"] == pytest.approx(np.sqrt(2.0))
    assert rec["update_ratio"] == pytest.approx(np.sqrt(0.02) / np.sqrt(8.0))
    # three escalating jumps: every one is > 10x the running EMA
    for step, g2 in ((2, 1e10), (3, 1e14), (4, 1e18)):
        rec = m.on_step(step, _packed([g2, g2], [4, 4], [.01, .01], [0, 0]))
        assert rec["spike"] is True, f"step {step} not flagged"
    assert monitor.stat("health.spikes").get() - spikes0 == 3
    spike_dumps = [d for d in fr.dumps if "health_grad_spike" in d]
    assert len(spike_dumps) == 2  # rate-limited below the spike count


def test_nonfinite_attribution_from_packed_buffer():
    """Host-half decode only: the first segment with a non-finite count wins
    the attribution, and inf norms become None in the record (JSON-safe)."""
    m = health.TrainingHealthMonitor({"a": (2,), "b": (3,)}, interval=3)
    assert m.on_step(1, _packed([1, 1], [1, 1], [0, 0], [0, 0])) is None
    assert m.on_step(2, _packed([1, 1], [1, 1], [0, 0], [0, 0])) is None
    rec = m.on_step(3, _packed([np.inf, np.nan], [1, 1], [0, 0], [0, 3]))
    assert rec is not None
    assert rec["nonfinite_count"] == 3
    assert rec["first_nonfinite_param"] == "b"
    assert rec["first_nonfinite_segment"] == 1
    assert rec["grad_norm"] is None  # inf -> JSON-safe None
    assert rec["per_param"]["a"]["nonfinite"] == 0


# ------------------------------------------------------------------ HLO gates

def test_health_off_is_zero_cost():
    """Off by default means OFF: the lowered step program with health
    disabled is byte-identical before and after an enable/disable cycle, and
    only the enabled program contains the is_finite scan."""
    eng, arrays = _tiny_engine()

    def lowered_text():
        jf = eng._build(arrays)
        return jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                        jnp.int32(1), jax.random.key(0), *arrays).as_text()

    off = lowered_text()
    assert "is_finite" not in off
    eng.enable_health(interval=1)
    on = lowered_text()
    assert "is_finite" in on
    eng.disable_health()
    assert lowered_text() == off


def test_health_adds_exactly_one_output_no_extra_dispatch():
    """The packed stats buffer is ONE extra f32 [4P] output of the SAME
    program — output arity grows by exactly one, nothing else changes shape."""
    eng, arrays = _tiny_engine()
    lr, st, key = jnp.float32(1e-3), jnp.int32(1), jax.random.key(0)
    out_off = jax.eval_shape(eng._build(arrays), eng.params, eng.opt_state,
                             lr, st, key, *arrays)
    eng.enable_health(interval=1)
    out_on = jax.eval_shape(eng._build(arrays), eng.params, eng.opt_state,
                            lr, st, key, *arrays)
    assert len(out_on) == len(out_off) + 1
    packed = out_on[-1]
    assert packed.shape == (4 * len(eng._param_names),)
    assert packed.dtype == jnp.float32
    eng.disable_health()


def test_accum_health_keeps_one_allreduce_one_dispatch():
    """ISSUE 8 acceptance: a dp-mesh K-microbatch accumulated step WITH
    health enabled still compiles to exactly one fused gradient all-reduce
    and one accumulation scan while-loop — the stats are pure per-segment
    reductions of the flat grad buffer, no collectives, no extra dispatch."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet, grad_comm

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    layers = []
    for _ in range(6):
        layers += [paddle.nn.Linear(64, 64), paddle.nn.ReLU()]
    net = paddle.nn.Sequential(*layers[:-1])
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    eng = fleet.distributed_engine(net, opt, loss_fn=paddle.nn.MSELoss())
    eng.microbatches = 2
    eng.enable_health(interval=1)
    arrays = [jnp.asarray(np.random.RandomState(0).randn(64, 64)
                          .astype("float32")),
              jnp.asarray(np.random.RandomState(1).randn(64, 64)
                          .astype("float32"))]  # 64 rows: divisible by dp8*K
    jf = eng._build_accum(arrays, 2, "f32", False, grad_comm.chunk_size())
    lowered = jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                       jnp.int32(1), jax.random.key(0), *arrays)
    from paddle_tpu import analysis as an

    rep = an.check_compiled("train.accum_k2_f32", lowered.compile(),
                            an.ProgramContract(
        collectives={"all-reduce": 1}, while_loops=1,
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"health stats changed the step's collective shape (expected the "
        f"single fused all-reduce + one scan while-loop):\n{rep.format()}")
    # and the packed buffer rides as the LAST output of that one program
    out = jax.eval_shape(jf, eng.params, eng.opt_state, jnp.float32(1e-3),
                         jnp.int32(1), jax.random.key(0), *arrays)
    assert out[-1].shape == (4 * len(eng._param_names),)
    eng.disable_health()


# -------------------------------------------- compiled-executable introspect

def test_train_exec_introspection():
    """introspect_executables AOT-compiles the stashed step signature and
    returns XLA memory_analysis numbers per label (real bytes on CPU too)."""
    eng, arrays = _tiny_engine()
    eng.step(*arrays)
    stats = eng.introspect_executables()
    assert "train.step" in stats
    s = stats["train.step"]
    assert s.get("peak_bytes", 0) > 0
    assert s.get("output_size_in_bytes", 0) > 0
    rows = exec_introspect.report_rows()
    assert any(r[0] == "train.step" for r in rows)


def test_exec_introspect_flag_feeds_registry():
    """FLAGS_exec_introspect auto-captures at first dispatch and publishes
    exec.<label>.* gauges to the active metrics registry."""
    from paddle_tpu.core import flags as _flags

    metrics.enable()
    _flags.set_flags({"exec_introspect": True})
    eng, arrays = _tiny_engine()
    eng.step(*arrays)
    assert "train.step" in exec_introspect.captured()
    gauges = metrics.active_registry().snapshot()["gauges"]
    assert any(k.startswith("exec.train.step.") for k in gauges)


def test_serve_exec_introspection():
    """The serving engine stashes prefill/decode signatures the same way."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    cfg = gpt_tiny()
    model = GPTForPretraining(cfg)
    srv = ServingEngine(model, slot_count=2, max_new_cap=8,
                        steps_per_dispatch=2)
    rng = np.random.RandomState(0)
    srv.submit(rng.randint(0, cfg.vocab_size, 12).astype(np.int64),
               max_new_tokens=6)
    srv.run(max_steps=8)
    stats = srv.introspect_executables()
    assert any(k.startswith("serve.prefill_b") for k in stats)
    assert any(k.startswith("serve.decode_") for k in stats)
    assert all(v.get("peak_bytes", 0) > 0 for v in stats.values())


# --------------------------------------------------- flight-recorder counters

def test_flight_dump_counter_by_reason(tmp_path):
    """Every flight dump bumps flight.dumps and flight.dumps.<reason> in the
    active metrics registry (ops-side visibility into crash dumps)."""
    metrics.enable()
    fr = flight_recorder.enable(str(tmp_path))
    fr.dump("manual_probe")
    fr.dump("manual_probe")
    fr.dump("other_reason")
    reg = metrics.active_registry()
    assert reg.counter("flight.dumps").value == 3
    assert reg.counter("flight.dumps.manual_probe").value == 2
    assert reg.counter("flight.dumps.other_reason").value == 1

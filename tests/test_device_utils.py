"""paddle.device (memory stats, streams, custom-device registry) and
paddle.utils (custom ops, cpp_extension host ops, run_check) tests."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDeviceNamespace:
    def test_memory_stats_monotonic(self):
        import paddle_tpu.device.tpu as dtpu

        a = paddle.to_tensor(np.ones((256, 256), np.float32))
        allocated = dtpu.memory_allocated()
        assert allocated >= 0
        assert dtpu.max_memory_allocated() >= allocated or \
            dtpu.max_memory_allocated() >= 0
        assert dtpu.memory_reserved() >= 0
        del a

    def test_synchronize_and_properties(self):
        import paddle_tpu.device.tpu as dtpu

        dtpu.synchronize()
        props = dtpu.get_device_properties()
        assert "platform" in props and props["id"] >= 0
        assert isinstance(dtpu.get_device_name(), str)

    def test_cuda_parity_surface(self):
        cuda = paddle.device.cuda
        assert cuda.device_count() >= 1
        s = cuda.Stream()
        e1 = s.record_event()
        e2 = cuda.Event()
        e2.record(s)
        assert e1.elapsed_time(e2) >= 0
        with cuda.stream_guard(s):
            cuda.synchronize()
        assert cuda.current_stream() is not None
        assert cuda.memory_allocated() >= 0

    def test_device_listing(self):
        assert paddle.device.device_count() >= 1
        assert len(paddle.device.get_available_device()) >= 1
        assert paddle.device.get_cudnn_version() is None

    def test_custom_device_registry(self):
        import jax

        paddle.device.register_custom_device("mynpu", jax.devices()[0].platform)
        assert "mynpu" in paddle.device.get_all_custom_device_type()
        p = paddle.CustomPlace("mynpu", 0)
        assert p.jax_device() is jax.devices()[0]
        assert paddle.device.device_count("mynpu") >= 1


class TestCustomOps:
    def test_register_custom_op_autograd(self):
        import jax.numpy as jnp
        from paddle_tpu.utils import register_custom_op

        cube = register_custom_op("test_cube", lambda a: a ** 3)
        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
        x.stop_gradient = False
        y = cube(x)
        np.testing.assert_allclose(y.numpy(), [8.0, 27.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0, 27.0])  # 3x^2

    def test_custom_vjp(self):
        import jax.numpy as jnp
        from paddle_tpu.utils import register_custom_op

        # intentionally wrong gradient (x10) to prove the custom vjp is used
        op = register_custom_op(
            "test_double", lambda a: a * 2,
            backward=lambda g, a: g * 20.0)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        x.stop_gradient = False
        op(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [20.0])

    def test_duplicate_rejected(self):
        from paddle_tpu.utils import register_custom_op

        register_custom_op("test_once", lambda a: a)
        with pytest.raises(ValueError):
            register_custom_op("test_once", lambda a: a)

    def test_works_in_layer_and_jit(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.utils import register_custom_op

        sq = register_custom_op("test_sq", lambda a: a * a)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return sq(self.fc(x)).sum()

        m = M()
        st = paddle.jit.to_static(m)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        v_eager = float(m(x))
        v_jit = float(st(x))
        np.testing.assert_allclose(v_eager, v_jit, rtol=1e-6)


class TestCppExtension:
    def test_load_host_op(self, tmp_path):
        src = tmp_path / "myops.cc"
        src.write_text("""
            extern "C" void my_negate(const float* x, float* y, long long n) {
                for (long long i = 0; i < n; ++i) y[i] = -x[i];
            }
            extern "C" void my_half(const float* x, float* y, long long n) {
                for (long long i = 0; i < n; ++i) y[i] = x[i] * 0.5f;
            }
        """)
        from paddle_tpu.utils import cpp_extension

        mod = cpp_extension.load("myops", [str(src)],
                                 functions=["my_negate", "my_half"])
        x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        np.testing.assert_allclose(mod.my_negate(x).numpy(), [-1.0, 2.0])
        np.testing.assert_allclose(mod.my_half(x).numpy(), [0.5, -1.0])

    def test_host_op_under_jit(self, tmp_path):
        src = tmp_path / "jitop.cc"
        src.write_text("""
            extern "C" void plus_one(const float* x, float* y, long long n) {
                for (long long i = 0; i < n; ++i) y[i] = x[i] + 1.0f;
            }
        """)
        from paddle_tpu.utils import cpp_extension

        mod = cpp_extension.load("jitop", [str(src)], functions=["plus_one"])
        fn = paddle.jit.to_static(lambda t: mod.plus_one(t) * 2.0)
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(fn(x).numpy(), [4.0, 6.0])


class TestUtilsMisc:
    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "installed successfully" in capsys.readouterr().out

    def test_require_version(self):
        paddle.utils.require_version("0.0.1")
        with pytest.raises(Exception):
            paddle.utils.require_version("999.0.0")

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name

        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        assert a != b
        with unique_name.guard():
            c = unique_name.generate("fc")
            assert c == "fc_0"

    def test_try_import(self):
        m = paddle.utils.try_import("math")
        assert m.sqrt(4) == 2
        with pytest.raises(ImportError):
            paddle.utils.try_import("definitely_not_a_module_xyz")

"""hapi Model.fit/evaluate/predict + callbacks.

Mirrors reference tests python/paddle/tests/test_model.py (fit on LeNet/MNIST-style
data, evaluate/predict round-trips, callbacks, save/load)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi import EarlyStopping, Model, ModelCheckpoint
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class RandomClassDataset(Dataset):
    def __init__(self, n=64, dim=8, classes=4):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, dim).astype("float32")
        self.y = rng.randint(0, classes, (n, 1)).astype("int64")
        # make it learnable: class determined by argmax of first `classes` features
        self.y = np.argmax(self.x[:, :classes], axis=1, keepdims=True).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def make_model():
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 4))
    model = Model(net)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy())
    return model


def test_fit_reduces_loss_and_tracks_accuracy(capsys):
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset()
    history = model.fit(ds, epochs=3, batch_size=16, verbose=0)
    assert len(history) == 3
    assert history[-1]["loss"] < history[0]["loss"]
    assert history[-1]["acc"] > 0.5


def test_evaluate_and_predict():
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset()
    model.fit(ds, epochs=2, batch_size=16, verbose=0)
    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert "loss" in res and "acc" in res
    preds = model.predict(ds, batch_size=16, stack_outputs=True, verbose=0)
    assert preds[0].shape == (64, 4)
    acc = (preds[0].argmax(-1) == ds.y[:, 0]).mean()
    assert acc == pytest.approx(res["acc"], abs=1e-6)


def test_fit_with_eval_data_and_early_stopping():
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset()
    es = EarlyStopping(monitor="acc", mode="max", patience=0, save_best_model=False,
                       verbose=0)
    history = model.fit(ds, eval_data=ds, epochs=30, batch_size=32, verbose=0,
                        callbacks=[es])
    # stops once eval accuracy plateaus (it saturates at 1.0) -> fewer than 30 epochs
    assert len(history) < 30
    assert any("eval_loss" in h for h in history)


def test_save_load_roundtrip(tmp_path):
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset()
    model.fit(ds, epochs=1, batch_size=16, verbose=0)
    path = str(tmp_path / "ckpt")
    model.save(path)
    model2 = make_model()
    model2.load(path)
    p1 = model.predict(ds, batch_size=64, stack_outputs=True, verbose=0)[0]
    p2 = model2.predict(ds, batch_size=64, stack_outputs=True, verbose=0)[0]
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_model_checkpoint_callback(tmp_path):
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset(n=32)
    model.fit(ds, epochs=2, batch_size=16, verbose=0,
              save_dir=str(tmp_path), save_freq=1)
    assert (tmp_path / "0.pdparams").exists()
    assert (tmp_path / "final.pdparams").exists()


def test_train_batch_and_eval_batch():
    paddle.seed(0)
    model = make_model()
    x = np.random.randn(4, 8).astype("float32")
    y = np.zeros((4, 1), dtype="int64")
    losses, metrics = model.train_batch([x], [y])
    assert np.isfinite(losses[0])
    losses2, _ = model.eval_batch([x], [y])
    assert np.isfinite(losses2[0])


def test_summary(capsys):
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    info = paddle.summary(net, (1, 8))
    out = capsys.readouterr().out
    assert info["total_params"] == 8 * 32 + 32 + 32 * 4 + 4
    assert "Total params" in out


def test_accumulate_grad_tail_flush():
    """Epoch length not divisible by accumulate_grad_batches: tail grads are
    flushed at epoch end, nothing leaks into the next epoch."""
    paddle.seed(0)
    model = make_model()
    ds = RandomClassDataset(n=48)  # 3 batches of 16
    model.fit(ds, epochs=1, batch_size=16, verbose=0, accumulate_grad_batches=2)
    assert all(p.grad is None for p in model.parameters())


def test_fit_with_generator_train_data():
    paddle.seed(0)
    model = make_model()
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype("float32")
    y = np.argmax(x[:, :4], axis=1, keepdims=True).astype("int64")
    gen = ((x[i:i + 16], y[i:i + 16]) for i in range(0, 32, 16))
    history = model.fit(gen, epochs=3, batch_size=16, verbose=0)
    assert all("loss" in h for h in history)


def test_self_loss_network_with_metrics():
    """Network computes its own loss; metrics still receive labels."""
    class SelfLoss(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 4)

        def forward(self, x):
            return self.fc(x)

    paddle.seed(0)
    net = SelfLoss()
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), Accuracy())
    ds = RandomClassDataset(n=32)
    history = model.fit(ds, epochs=1, batch_size=16, verbose=0)
    assert "acc" in history[0]


def test_summary_tuple_of_shapes():
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 4))
    info = paddle.summary(net, ((1, 8),))
    assert info["total_params"] == 8 * 4 + 4


def test_eval_without_loss_metrics_only():
    """prepare(opt, loss=None, metrics=[Accuracy()]): metrics-only evaluation."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 4))
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
                  loss=None, metrics=Accuracy())
    res = model.evaluate(RandomClassDataset(n=32), batch_size=16, verbose=0)
    assert "acc" in res and "loss" not in res


def test_accumulate_scales_gradients():
    """Accumulated grads over k micro-batches of the same data equal the grads of
    one batch (loss is scaled by 1/k)."""
    import numpy as np

    paddle.seed(0)
    net = paddle.nn.Linear(4, 2)
    x = np.random.RandomState(0).randn(8, 4).astype("float32")
    y = np.zeros((8, 1), dtype="int64")
    loss_fn = paddle.nn.CrossEntropyLoss()

    m1 = Model(net)
    m1.prepare(paddle.optimizer.SGD(parameters=net.parameters()), loss_fn)
    m1._accumulate = 2
    m1.train_batch([x], [y], update=False)
    m1.train_batch([x], [y], update=False)
    g_acc = net.weight.grad.numpy().copy()
    net.clear_gradients() if hasattr(net, "clear_gradients") else None
    for p in net.parameters():
        p.grad = None
    del m1._accumulate
    m1.train_batch([x], [y], update=False)
    g_one = net.weight.grad.numpy()
    np.testing.assert_allclose(g_acc, g_one, rtol=1e-5, atol=1e-6)

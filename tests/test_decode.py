"""BeamSearchDecoder + dynamic_decode tests (reference fluid/layers/rnn.py:850,1260)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_gather_tree():
    # T=3, N=1, beam=2: chain built backwards through parent pointers
    ids = paddle.to_tensor(np.array(
        [[[2, 3]], [[4, 5]], [[6, 7]]], np.int64))
    parents = paddle.to_tensor(np.array(
        [[[0, 0]], [[0, 0]], [[1, 0]]], np.int64))
    out = nn.gather_tree(ids, parents).numpy()
    # beam 0 at t=2 came from parent beam 1 at t=1, which came from beam 0
    np.testing.assert_array_equal(out[:, 0, 0], [2, 5, 6])
    np.testing.assert_array_equal(out[:, 0, 1], [2, 4, 7])


class _TableCell(nn.RNNCellBase):
    """Deterministic 'LM': logits depend only on the input token (via table)."""

    def __init__(self, table):
        super().__init__()
        self.table = paddle.to_tensor(table)
        self.hidden = 4

    @property
    def state_shape(self):
        return (self.hidden,)

    def forward(self, inputs, states=None, **kwargs):
        ids = inputs.astype("int64")
        logits = self.table[ids]
        return logits, states


def test_beam_search_greedy_path():
    """With a deterministic table the best beam must follow the argmax chain."""
    vocab = 5
    # from token t, next best token is (t+1) % vocab with huge margin
    table = np.full((vocab, vocab), -10.0, np.float32)
    for t in range(vocab):
        table[t, (t + 1) % vocab] = 10.0
    cell = _TableCell(table)
    decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=4, beam_size=2)
    init_states = paddle.to_tensor(np.zeros((2, 4), np.float32))  # batch=2
    outputs, final_states = nn.dynamic_decode(decoder, inits=init_states,
                                              max_step_num=8)
    seqs = outputs.numpy()  # [N, T, beam] after batch-major transpose
    # best beam: 1, 2, 3, 4(end); once finished it pads with the end token
    # while the runner-up beam keeps exploring (never hits end), so decode
    # runs to max_step_num
    np.testing.assert_array_equal(seqs[0, :4, 0], [1, 2, 3, 4])
    np.testing.assert_array_equal(seqs[1, :4, 0], [1, 2, 3, 4])
    assert (seqs[0, 4:, 0] == 4).all()
    # the finished beam's recorded length stays at 4
    assert int(final_states.lengths.numpy()[0, 0]) == 4


def test_beam_search_with_lstm_and_embedding():
    """End-to-end API shape check with a real LSTMCell + embedding/output fns."""
    paddle.seed(0)
    vocab, hidden, beam = 7, 8, 3
    emb = nn.Embedding(vocab, hidden)
    cell = nn.LSTMCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    decoder = nn.BeamSearchDecoder(
        cell, start_token=0, end_token=1, beam_size=beam,
        embedding_fn=emb, output_fn=proj)
    batch = 2
    h0 = paddle.to_tensor(np.zeros((batch, hidden), np.float32))
    c0 = paddle.to_tensor(np.zeros((batch, hidden), np.float32))
    outputs, final_states = nn.dynamic_decode(decoder, inits=(h0, c0),
                                              max_step_num=5)
    assert outputs.shape[0] == batch
    assert outputs.shape[2] == beam
    assert outputs.shape[1] <= 5
    assert final_states.lengths.shape == [batch, beam]

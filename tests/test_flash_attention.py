"""Pallas flash-attention kernel vs the dense XLA reference (interpret mode on CPU;
the same kernel Mosaic-compiles on a real chip — exercised by bench.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import flash_attention, supported


def dense_ref(q, k, v, causal):
    qt, kt, vt = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward(causal):
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(2, 128, 2, 32).astype(np.float32))
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, dense_ref(q, k, v, causal), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads(causal):
    rng = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rng.randn(1, 64, 2, 16).astype(np.float32))
               for _ in range(3)]

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=causal) * v),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_ref(q, k, v, causal) * v),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_flash_cross_attention_lengths():
    # sq != sk (cross attention / unequal blocks)
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 32, 2, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 128, 2, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 128, 2, 16).astype(np.float32))
    out = flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(out, dense_ref(q, k, v, False), atol=2e-5)


def test_supported_predicate():
    assert supported(512, 512, 64)
    assert not supported(7, 512, 64)     # too short
    assert not supported(512, 512, 63)   # head_dim not 8-aligned


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_storage_dtype(causal):
    """bf16 inputs exercise the storage-dtype matmul path (bf16 operands,
    f32 accumulation) that real-chip amp runs; CPU f32 tests can't see it."""
    rng = np.random.RandomState(3)
    qf, kf, vf = [rng.randn(1, 128, 2, 32).astype(np.float32) for _ in range(3)]
    q, k, v = [jnp.asarray(x, jnp.bfloat16) for x in (qf, kf, vf)]

    out = flash_attention(q, k, v, causal=causal)
    assert out.dtype == jnp.bfloat16
    ref = dense_ref(jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal)
    np.testing.assert_allclose(out.astype(np.float32), ref, atol=2e-2)

    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=causal).astype(jnp.float32)),
        argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert a.dtype == jnp.bfloat16
        assert bool(jnp.isfinite(a.astype(jnp.float32)).all())

"""HLO-level performance regression gates, runnable without a TPU.

VERDICT r2 #1a: perf must be *verifiable* on CPU even when the chip is away.
Each test pins a compiler-level property that the on-chip numbers depend on:

- the dp engine step emits ONE fused (variadic) gradient all-reduce, not one
  per parameter (XLA AllReduceCombiner over the bucketed layout — the
  reference's Reducer contract, `paddle/fluid/imperative/reducer.cc`);
- the Pallas kernel flags actually route (pallas_call present in the jaxpr)
  AND the kernels Mosaic-compile for the TPU target (jax.export platforms=
  ["tpu"] embeds a tpu_custom_call) — this gate caught three real on-chip
  compile bugs in round 3 that interpret-mode tests had masked;
- recompute (remat) shrinks autodiff saved-residual bytes;
- the chunked fused LM loss avoids materializing [N, V] logits (temp bytes);
- buffer donation aliases the param+opt arguments (no double buffering).

Thresholds are pinned from measured values; regressions fail loudly.
"""
import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

# ensure the kernel SUBMODULES are importable (the package __init__ re-exports
# shadow same-named functions)
import paddle_tpu.ops.pallas.flash_attention  # noqa: F401
import paddle_tpu.ops.pallas.layer_norm  # noqa: F401
import paddle_tpu.ops.pallas.lm_loss  # noqa: F401

_FA = sys.modules["paddle_tpu.ops.pallas.flash_attention"]
_LN = sys.modules["paddle_tpu.ops.pallas.layer_norm"]
_LM = sys.modules["paddle_tpu.ops.pallas.lm_loss"]

def _collective_gate_skip_reason():
    """Backend-capability probe for the collective-shape gates — now the
    SHARED predicate in paddle_tpu/analysis/backend.py (the analyzer's
    requires_combining contracts and these gates must agree on which
    backends can pin collective shapes). Returns None when the backend
    combines (gates must run), else the skip reason; cached there."""
    from paddle_tpu.analysis.backend import collective_combining_reason

    return collective_combining_reason()


def _require_collective_combining():
    reason = _collective_gate_skip_reason()
    if reason is not None:
        pytest.skip(reason)


def _dp8_engine(n_linear=12):
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    layers = []
    for _ in range(n_linear):
        layers += [paddle.nn.Linear(64, 64), paddle.nn.ReLU()]
    net = paddle.nn.Sequential(*layers[:-1])
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    eng = fleet.distributed_engine(net, opt, loss_fn=paddle.nn.MSELoss())
    x = jnp.asarray(np.random.RandomState(0).randn(16, 64).astype("float32"))
    y = jnp.asarray(np.random.RandomState(1).randn(16, 64).astype("float32"))
    return eng, [x, y]


def _compile_step(eng, arrays):
    jf = eng._build(arrays)
    return jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                    jnp.int32(1), jax.random.key(0), *arrays).compile()


def test_dp_allreduce_is_fused():
    """24 params -> a handful of combined all-reduces, NOT one per param.
    (Declarative since ISSUE 11: the same contract rides engine.analyze().)"""
    _require_collective_combining()
    from paddle_tpu import analysis as an

    eng, arrays = _dp8_engine(n_linear=12)
    comp = _compile_step(eng, arrays)
    assert len(eng.params) == 24
    rep = an.check_compiled("train.step", comp, an.ProgramContract(
        collectives={"all-reduce": (1, 4)},
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"gradient all-reduce combining regressed (expected one variadic "
        f"fused all-reduce for 24 params):\n{rep.format()}")


def _compile_accum(eng, arrays, k, dtype="f32"):
    from paddle_tpu.distributed import grad_comm

    jf = eng._build_accum(arrays, k, dtype, False, grad_comm.chunk_size())
    return jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                    jnp.int32(1), jax.random.key(0), *arrays).compile()


@pytest.mark.parametrize("k", [2, 4])
def test_microbatch_accum_exactly_one_fused_allreduce(k):
    """The K-microbatch accumulation step must compile to EXACTLY ONE
    gradient all-reduce regardless of K — the deferred reduction over the
    flattened grad buffer after the scan (grad_comm), the structural form
    of the reference's fuse_all_reduce_ops + accumulate contract. The K
    microbatches must run as one scan while-loop (one dispatch), and the
    carried params+opt state must stay donation-aliased."""
    eng, _ = _dp8_engine(n_linear=12)
    eng.microbatches = k
    arrays = [jnp.asarray(np.random.RandomState(0).randn(64, 64)
                          .astype("float32")),
              jnp.asarray(np.random.RandomState(1).randn(64, 64)
                          .astype("float32"))]  # 64 rows: divisible by dp8*K
    from paddle_tpu import analysis as an

    comp = _compile_accum(eng, arrays, k)
    state_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in eng.params.values())
    state_bytes += sum(int(np.prod(s.shape)) * s.dtype.itemsize
                       for st in eng.opt_state.values() for s in st)
    rep = an.check_compiled(f"train.accum_k{k}_f32", comp, an.ProgramContract(
        collectives={"all-reduce": 1}, while_loops=1,
        donated_bytes=state_bytes,
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"K={k} accumulation contract broken (expected ONE deferred fused "
        f"gradient all-reduce, one scan while-loop, donated params+opt "
        f"state):\n{rep.format()}")


def test_microbatch_accum_shrinks_activation_peak():
    """At EQUAL effective batch, compiled temp memory (the activation
    high-water) must drop with K: the scan body holds one microbatch's
    activations, not the global batch's. Needs a model whose activations
    dwarf the flat f32 grad accumulator (GPT, not the Linear stack — there
    grads ~= activations and the ratio washes out). Measured K=4 ratio is
    ~0.3 at the grad_comm_bench config; gate 0.75 for headroom."""
    from paddle_tpu.distributed.engine import TrainStepEngine
    from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                             set_hybrid_communicate_group)
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 1024, (16, 128)).astype(np.int64))
    arrays = [ids, jnp.asarray(np.roll(np.asarray(ids), -1, 1))]

    def build(k):
        set_hybrid_communicate_group(None)
        hcg = HybridCommunicateGroup(dp_degree=1, devices=jax.devices()[:1])
        paddle.seed(0)
        model = GPTForPretraining(gpt_tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        return TrainStepEngine(model, opt, hcg=hcg, microbatches=k)

    t1 = _compile_step(build(1), arrays).memory_analysis().temp_size_in_bytes
    t4 = _compile_accum(build(4), arrays, 4) \
        .memory_analysis().temp_size_in_bytes
    assert t4 < 0.75 * t1, (
        f"K=4 accumulation temp {t4}B !< 0.75x single-shot {t1}B — the "
        f"microbatch scan no longer bounds activation memory")


def test_engine_donation_aliases_param_and_opt_buffers():
    """donate_argnums must alias params+opt state: peak = 1x state, not 2x."""
    from paddle_tpu import analysis as an

    eng, arrays = _dp8_engine(n_linear=4)
    comp = _compile_step(eng, arrays)
    state_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in eng.params.values())
    state_bytes += sum(int(np.prod(s.shape)) * s.dtype.itemsize
                       for st in eng.opt_state.values() for s in st)
    # per-device view: arguments are replicated here (dp), so full size
    rep = an.check_compiled("train.step", comp, an.ProgramContract(
        donated_bytes=state_bytes,
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"buffer donation regressed — training would double-buffer params "
        f"in HBM:\n{rep.format()}")


def test_train_step_flops_accounting():
    """cost_analysis flops of the fused step covers the 6*N*T analytic
    minimum the MFU claim in bench.py is computed from."""
    eng, arrays = _dp8_engine(n_linear=4)
    comp = _compile_step(eng, arrays)
    from paddle_tpu.utils.hlo_inspect import cost_analysis_dict

    flops = cost_analysis_dict(comp)["flops"]
    n_params = sum(int(np.prod(a.shape)) for a in eng.params.values())
    # cost_analysis is per-device; the batch dim is sharded over dp=8
    tokens = arrays[0].shape[0] // 8
    assert flops >= 0.5 * 6 * n_params * tokens, (
        "compiled flops below the fwd+bwd analytic bound — the step is not "
        "computing what the MFU accounting assumes")


# ---------------------------------------------------------- pallas routing ----

def _flash_jaxpr(seq=256):
    from paddle_tpu.ops import nn_functional as F

    def att(qd):
        t = Tensor(qd)
        return F.scaled_dot_product_attention(t, t, t)._data

    q = jnp.zeros((2, seq, 4, 64), jnp.float32)
    return str(jax.make_jaxpr(att)(q))


def test_flash_attention_routes_to_pallas_when_flagged():
    paddle.set_flags({"use_flash_attention": True, "pallas_interpret_ok": True})
    assert "pallas_call" in _flash_jaxpr()
    paddle.set_flags({"use_flash_attention": False})
    assert "pallas_call" not in _flash_jaxpr()


# (the layernorm / lm_loss flag-routing gates were removed in round 5 with
#  the kernels' retirement from the training path — BASELINE.md; their math
#  stays pinned by tests/test_pallas_layernorm.py / test_pallas_lm_loss.py)


# ------------------------------------------------- Mosaic TPU compilation ----

def _export_tpu(fn, *avals):
    from jax import export

    return export.export(jax.jit(fn), platforms=["tpu"])(*avals).mlir_module()


@pytest.mark.slow
def test_flash_attention_mosaic_compiles_for_tpu(monkeypatch):
    """Lower fwd+bwd for the REAL TPU target (Mosaic) from the CPU host.

    Interpret-mode tests verify numerics but not Mosaic legality; this caught
    an f64 weak-literal cast in the masked-row fix that would have failed on
    chip (flash_attention.py:_finalize)."""
    monkeypatch.setattr(_FA, "_interpret", lambda: False)
    paddle.set_flags({"use_flash_attention": True, "pallas_interpret_ok": True})
    from paddle_tpu.ops import nn_functional as F

    def att_loss(qd):
        t = Tensor(qd)
        return F.scaled_dot_product_attention(t, t, t, is_causal=True)._data.sum()

    mod = _export_tpu(jax.grad(att_loss),
                      jax.ShapeDtypeStruct((2, 256, 4, 64), jnp.float32))
    assert "tpu_custom_call" in mod


@pytest.mark.slow
def test_lm_loss_mosaic_compiles_for_tpu(monkeypatch):
    monkeypatch.setattr(_LM, "_interpret", lambda: False)
    lab = jnp.zeros((1024,), jnp.int32)

    def f(h, w):
        return _LM.lm_head_cross_entropy(h, w, lab).mean()

    mod = _export_tpu(jax.grad(f, argnums=(0, 1)),
                      jax.ShapeDtypeStruct((1024, 128), jnp.float32),
                      jax.ShapeDtypeStruct((8192, 128), jnp.float32))
    assert "tpu_custom_call" in mod


@pytest.mark.slow
def test_layer_norm_mosaic_compiles_for_tpu(monkeypatch):
    monkeypatch.setattr(_LN, "_interpret", lambda: False)

    def f(x, g, b):
        return _LN.layer_norm(x, g, b, eps=1e-5).sum()

    mod = _export_tpu(jax.grad(f, argnums=(0, 1, 2)),
                      jax.ShapeDtypeStruct((512, 256), jnp.float32),
                      jax.ShapeDtypeStruct((256,), jnp.float32),
                      jax.ShapeDtypeStruct((256,), jnp.float32))
    assert "tpu_custom_call" in mod


# -------------------------------------------------------- memory behavior ----

def _gpt_loss_fn(use_recompute, granularity="full"):
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4, num_heads=4,
                    max_seq_len=256, use_recompute=use_recompute,
                    recompute_granularity=granularity)
    model = GPTForPretraining(cfg)
    model.train()
    state = model.state_dict(include_non_persistable_buffer=True)
    arrays = {k: v._data for k, v in state.items()}
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 512, (4, 256)).astype(np.int64))
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1))

    def f(params):
        loss = functional_call(model, params, Tensor(ids), Tensor(labels))
        return loss._data if isinstance(loss, Tensor) else loss

    return f, arrays


def _saved_residual_bytes(f, arrays):
    from jax._src.ad_checkpoint import saved_residuals

    res = saved_residuals(f, arrays)
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a, _ in res if hasattr(a, "shape"))


def test_recompute_shrinks_saved_residuals():
    """use_recompute=True (jax.checkpoint per block) must cut what autodiff
    saves — the measured ratio is ~0.06; gate at 0.25 for headroom."""
    f0, a0 = _gpt_loss_fn(False)
    b_no = _saved_residual_bytes(f0, a0)
    f1, a1 = _gpt_loss_fn(True)
    b_yes = _saved_residual_bytes(f1, a1)
    assert b_yes < 0.25 * b_no, (
        f"remat saved-residuals {b_yes}B vs {b_no}B without — recompute no "
        f"longer reduces activation memory")


def test_selective_recompute_sits_between_full_and_none():
    """recompute_granularity='selective' (save matmul outputs, recompute
    elementwise — jax dots_with_no_batch_dims_saveable) must save less than
    no-remat but more than full remat, and must recompute FEWER flops than
    full remat (the matmuls are not replayed)."""
    f_none, a = _gpt_loss_fn(False)
    f_full, _ = _gpt_loss_fn(True)
    f_sel, _ = _gpt_loss_fn(True, granularity="selective")
    b_none = _saved_residual_bytes(f_none, a)
    b_full = _saved_residual_bytes(f_full, a)
    b_sel = _saved_residual_bytes(f_sel, a)
    assert b_full < b_sel < b_none, (b_full, b_sel, b_none)

    def grad_flops(f):
        from paddle_tpu.utils.hlo_inspect import cost_analysis_dict

        g = jax.jit(jax.grad(lambda p: f(p).sum()))
        return float(cost_analysis_dict(g.lower(a).compile())
                     .get("flops", 0.0))

    fl_none, fl_full, fl_sel = map(grad_flops, (f_none, f_full, f_sel))
    assert fl_none < fl_sel < fl_full, (fl_none, fl_sel, fl_full)


def test_fused_lm_loss_avoids_logits_materialization():
    """Chunked fused CE must compile to far less temp memory than the naive
    [N, V] logits path (measured 34 MB vs 134 MB at these shapes)."""
    from paddle_tpu.ops import fused as fused_mod

    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(2048, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(8192, 128).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, 8192, 2048).astype(np.int32))

    def fused(hh, ww):
        return fused_mod._fused_lce(hh, ww, lab, True, 512, -100).mean()

    def naive(hh, ww):
        logits = hh @ ww.T
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab[:, None], axis=-1)[:, 0]
        return (lse - picked).mean()

    def temp_bytes(f):
        comp = jax.jit(jax.value_and_grad(f, argnums=(0, 1))).lower(h, w).compile()
        return comp.memory_analysis().temp_size_in_bytes

    t_fused, t_naive = temp_bytes(fused), temp_bytes(naive)
    assert t_fused < 0.5 * t_naive, (
        f"fused CE temp {t_fused}B !< half of naive {t_naive}B — the chunked "
        f"loss is materializing logits again")


# ------------------------------------------------------ ICI-level gates ----

def _gpt_engine_compiled(conf, sharding=False, sep_impl=None):
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.sharding = sharding
    strategy.hybrid_configs = conf
    if sep_impl is not None:
        strategy.sep_impl = sep_impl
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    batch = max(4, 2 * hcg.degrees["dp"] * hcg.degrees["sharding"])
    ids = jnp.asarray(rng.randint(0, 1024, (batch, 64)).astype(np.int64))
    arrays = [ids, jnp.asarray(np.roll(np.asarray(ids), -1, 1))]
    tr = eng._build(arrays).trace(eng.params, eng.opt_state, jnp.float32(1e-3),
                                  jnp.int32(1), jax.random.key(0), *arrays)
    return eng, tr


def test_ring_sequence_parallel_emits_collective_permute():
    """sp=2 with sep_impl='ring' (the default is ulysses) must route
    attention through the ring (ppermute over 'sp') — the KV blocks rotate
    on ICI instead of an all-gather of the sequence."""
    eng, tr = _gpt_engine_compiled({"dp_degree": 2, "mp_degree": 2,
                                    "sep_degree": 2}, sep_impl="ring")
    assert "ppermute" in str(tr.jaxpr), "ring attention not engaged under sp=2"
    txt = tr.lower().compile().as_text()
    assert txt.count("collective-permute") >= 2, (
        "no collective-permute in the compiled sp step — the ring rotation "
        "was optimized out or replaced by sequence all-gather")


def test_default_sequence_parallel_is_ulysses_all_to_all():
    """The DEFAULT sp flavor is Ulysses (cost-model-backed, BASELINE.md):
    sp=2 with no explicit sep_impl must emit all-to-alls, not ppermutes."""
    # non-combining backends also reshard across the dp2/mp2/sp2 mesh with
    # device-order collective-permutes (identity-shuffle source_target_pairs),
    # tripping the no-ppermute assertion for reasons unrelated to the ulysses
    # routing — same reduced pipeline the probe detects
    _require_collective_combining()
    from paddle_tpu import analysis as an

    eng, tr = _gpt_engine_compiled({"dp_degree": 2, "mp_degree": 2,
                                    "sep_degree": 2})
    rep = an.check_compiled("train.step", tr.lower().compile(),
                            an.ProgramContract(
        collectives={"all-to-all": (1, None), "collective-permute": 0},
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"ulysses default regressed (expected all-to-alls, no ppermute in "
        f"the default sp step):\n{rep.format()}")


def test_zero_sharding_gathers_params_and_keeps_fused_grad_reduce():
    """ZeRO-1 signature: sharded opt update + param all-gather, with the
    gradient reduction still COMBINED (a fused handful, not per-param)."""
    _require_collective_combining()
    eng, tr = _gpt_engine_compiled({"dp_degree": 2, "sharding_degree": 4},
                                   sharding=True)
    from paddle_tpu import analysis as an

    sharded = sum(1 for s in eng.opt_specs.values()
                  if "sharding" in str(s))
    assert sharded >= 10, f"only {sharded} opt-state specs ZeRO-sharded"
    rep = an.check_compiled("train.step", tr.lower().compile(),
                            an.ProgramContract(
        collectives={"all-gather": (5, None), "all-reduce": (1, 8)},
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"ZeRO-1 signature broken (expected param all-gathers plus a "
        f"COMBINED gradient reduction):\n{rep.format()}")


def test_run_steps_scan_is_one_program_one_loop():
    """The fused K-step trainer must compile to ONE program whose steps run
    inside a single while-loop (lax.scan), with the same fused gradient
    all-reduce as the single step — not K unrolled bodies and not K
    dispatches. Donation must still alias the carried params+opt state."""
    _require_collective_combining()
    eng, arrays = _dp8_engine(n_linear=12)
    k = 5
    jf = eng._build_scan(arrays, True)
    keys = jnp.stack([jax.random.key(i) for i in range(k)])
    from paddle_tpu import analysis as an

    comp = jf.lower(eng.params, eng.opt_state, jnp.full((k,), 1e-3, jnp.float32),
                    jnp.int32(1), keys, *arrays).compile()
    state_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in eng.params.values())
    rep = an.check_compiled("train.run_steps", comp, an.ProgramContract(
        collectives={"all-reduce": (1, 4)}, while_loops=1,
        donated_bytes=state_bytes,
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, (
        f"run_steps contract broken (expected ONE scan while-loop, the "
        f"fused gradient all-reduce, donated carried params):\n"
        f"{rep.format()}")


def test_decode_loop_cache_in_place_no_weight_casts():
    """The KV-cache decode loop (GPTForPretraining.generate) must compile to a
    while loop whose body (a) updates the cache via dynamic-update-slice with
    NO cache-sized copy ops (in-place carry), and (b) contains no
    weight-sized f32->bf16 converts — under bf16 amp the weights are cast
    ONCE outside the loop and the cache is STORED in the compute dtype
    (round-3 fix: an f32 cache cost 2 cache-sized casts per layer per token,
    ~0.7 GB/step of HBM traffic at the bench config; tools/decode_hlo_probe.py).
    """
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    cfg = gpt_tiny()
    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    b, prompt, new = 2, 16, 48
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (b, prompt)).astype(np.int64)
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                       temperature=0)
        jf = next(iter(model.decode_exec_registry().values()))
        params = {k: v._data for k, v in model.state_dict(
            include_non_persistable_buffer=True).items()}
        # run(params, ids, plen, key): plen traced since the prompt-bucket
        # round (round 6) — exact-shape calls simply pass plen == prompt
        txt = jf.lower(params, ids, jnp.int32(prompt),
                       jax.random.key(0)).compile().as_text()

    from paddle_tpu.utils import hlo_inspect as hi

    assert re.search(r"\) while\(", txt), \
        "decode scan unrolled or missing — expected one while loop"
    body = hi.while_body_lines(txt)
    assert body, "no while/body-tagged ops in compiled decode program"

    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    cache_shape = f"{b},{prompt + new},{nh},{hd}"
    copies = hi.copies_of_shape(body, cache_shape)
    assert not copies, (
        f"cache-sized copies inside the decode loop (in-place DUS regressed): "
        f"{copies[:2]}")
    dus = hi.count_dynamic_update_slices(body)
    assert dus >= 2 * cfg.num_layers, (
        f"{dus} dynamic-update-slices in decode body for "
        f"{cfg.num_layers} layers — KV append path changed shape")
    # cache-shaped bf16 converts on CPU are f32-legalization noise (CPU dots
    # have no native bf16); weight-sized ones are real
    wcasts = hi.bf16_converts_of_min_size(
        body, cfg.hidden_size * cfg.hidden_size, exclude_shape_csv=cache_shape)
    assert not wcasts, (
        f"weight-sized f32->bf16 converts INSIDE the decode loop — amp cast "
        f"hoisting regressed: {wcasts[:2]}")


def test_zero_step_compiles_without_involuntary_rematerialization(capfd):
    """VERDICT r3 #4: the dp x mp x sharding (ZeRO) step must compile WITHOUT
    XLA's '[SPMD] Involuntary full rematerialization' warning. The round-3
    artifact carried two: the embedding optimizer-state spec ("mp","sharding")
    propagated backward onto the wte-grad scatter-add, demanding the [b,s,h]
    residual grad hidden-sharded — a batch->hidden reshard GSPMD can only do
    by replicate-and-repartition. The engine now pins grads to the param spec
    then the opt spec (distributed/engine.py); this gate captures the C++
    stderr via capfd during a fresh compile. (reduce-scatter counting is not
    assertable here: XLA CPU never forms reduce-scatter from all-reduce +
    dynamic-slice — that rewrite is TPU/GPU-only.)"""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    if os.environ.get("TF_CPP_MIN_LOG_LEVEL", "0") not in ("0", "1"):
        # XLA emits the remat diagnostic at WARNING; with C++ logging forced
        # quieter this gate would pass vacuously
        pytest.skip("TF_CPP_MIN_LOG_LEVEL suppresses XLA warnings")

    strategy = dist.DistributedStrategy()
    strategy.sharding = True
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 1024, (4, 64)).astype(np.int64))
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1))
    capfd.readouterr()  # drain anything queued before the compile
    compiled = _compile_step(eng, [ids, labels])
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, (
        "ZeRO step reintroduced a replicate-and-repartition reshard:\n"
        + "\n".join(ln for ln in err.splitlines()
                    if "rematerialization" in ln)[:500])
    # the partitioned step must still carry real collectives (the psums /
    # gathers of dp+mp+zero), or the topology silently degenerated
    txt = compiled.as_text()
    assert re.search(r"all-reduce", txt) and re.search(r"all-gather", txt)


def test_decode_loop_weights_precast_to_bf16():
    """Backend-independent decode-loop gate at the JAXPR level: under bf16
    amp, every weight-sized input to the decode scan must already be bf16
    (generate() pre-casts matmul weights ONCE outside the loop —
    weights-in-compute-dtype), and the scan body must contain ZERO
    weight-sized convert_element_type ops. Compiled-HLO carry checks can't
    pin this: XLA CPU upcasts bf16 dots to f32 and hoists the upcasts into
    the while carry, which on TPU would instead read f32 masters every token
    (~2x the weight traffic of the HBM-bound loop)."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.utils import hlo_inspect as hi

    cfg = gpt_tiny()
    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)).astype(np.int64)
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=48,
                       temperature=0)
        jf = next(iter(model.decode_exec_registry().values()))
        params = {k: v._data for k, v in model.state_dict(
            include_non_persistable_buffer=True).items()}
        # run(params, ids, plen, key) — see the cache-in-place gate above
        jaxpr = jax.make_jaxpr(jf)(params, ids, jnp.int32(16),
                                   jax.random.key(0))

    wmin = cfg.hidden_size * cfg.hidden_size
    big_inputs, n_converts = hi.jaxpr_loop_report(jaxpr, wmin)
    assert big_inputs, "decode scan not found in jaxpr"
    non_bf16 = [s for s in big_inputs if not s.startswith("bfloat16")]
    assert not non_bf16, (
        f"weight/cache-sized decode-loop inputs not pre-cast to bf16: "
        f"{non_bf16[:4]}")
    assert n_converts == 0, (
        f"{n_converts} weight-sized converts inside the decode scan body — "
        f"per-token weight casts regressed")


def test_flash_attention_memory_scales_linearly_with_seq():
    """Long-context gate: flash attention's compiled fwd+bwd temp memory
    must scale ~O(seq), not O(seq^2) — the property that makes seq 16k+
    single-chip configs (PADDLE_TPU_BENCH_SEQ) feasible at all. Measured
    ratio for 4x seq is ~3.9; a dense [.., s, s] materialization would be
    16x. Gate at 6x for headroom."""
    paddle.set_flags({"use_flash_attention": True, "pallas_interpret_ok": True})
    from paddle_tpu.ops import nn_functional as F

    def temp_bytes(seq):
        def att(qd):
            t = Tensor(qd)
            return F.scaled_dot_product_attention(t, t, t, is_causal=True)._data

        q = jnp.zeros((1, seq, 4, 64), jnp.float32)
        g = jax.jit(lambda x: jax.grad(lambda y: att(y).sum())(x))
        return g.lower(q).compile().memory_analysis().temp_size_in_bytes

    b1, b4 = temp_bytes(1024), temp_bytes(4096)
    assert b4 < 6 * b1, (
        f"flash temp memory grew {b4 / max(b1, 1):.1f}x for 4x seq — "
        f"attention is materializing O(s^2) state again")

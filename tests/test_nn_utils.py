"""paddle.nn.utils: weight_norm / remove_weight_norm / spectral_norm hooks +
parameters_to_vector round-trip (reference nn/utils/{weight_norm_hook,
spectral_norm_hook,transform_parameters}.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (parameters_to_vector, remove_weight_norm,
                                 spectral_norm, vector_to_parameters,
                                 weight_norm)


def test_weight_norm_forward_equivalence_and_grads():
    paddle.seed(0)
    lin = nn.Linear(6, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 6).astype("float32"))
    ref = lin(x).numpy()

    weight_norm(lin, dim=0)
    names = {n for n, _ in lin.named_parameters()}
    assert "weight_g" in names and "weight_v" in names and "weight" not in names
    out = lin(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    out.sum().backward()  # grads flow THROUGH the reparametrization
    assert float(lin.weight_g.grad.abs().sum().item()) > 0
    assert float(lin.weight_v.grad.abs().sum().item()) > 0

    remove_weight_norm(lin)
    names = {n for n, _ in lin.named_parameters()}
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError):
        remove_weight_norm(lin)  # not applied anymore


def test_weight_norm_trains():
    """Optimizing g/v must change the effective weight (the whole point)."""
    paddle.seed(0)
    lin = weight_norm(nn.Linear(4, 2))
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    before = lin(x).numpy()
    for _ in range(3):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    after = lin(x).numpy()
    assert np.abs(after - before).max() > 1e-4


def test_spectral_norm_bounds_singular_value():
    paddle.seed(0)
    lin = nn.Linear(8, 8)
    lin.weight._data = lin.weight._data * 10.0  # blow up sigma
    spectral_norm(lin, dim=1, n_power_iterations=3)
    x = paddle.to_tensor(np.eye(8, dtype="float32"))
    for _ in range(5):  # power iteration converges over calls
        out = lin(x)
    w_eff = (out.numpy() - lin.bias.numpy()[None, :])
    sigma = np.linalg.svd(w_eff, compute_uv=False)[0]
    assert sigma == pytest.approx(1.0, rel=0.05)


def test_parameters_vector_roundtrip():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(3, 5), nn.Linear(5, 2))
    params = net.parameters()
    vec = parameters_to_vector(params)
    total = sum(int(np.prod(p.shape)) for p in params)
    assert vec.shape == [total]
    doubled = paddle.to_tensor(vec.numpy() * 2.0)
    vector_to_parameters(doubled, params)
    np.testing.assert_allclose(parameters_to_vector(params).numpy(),
                               vec.numpy() * 2.0, rtol=1e-6)
    with pytest.raises(ValueError):
        vector_to_parameters(paddle.to_tensor(np.zeros(3, "float32")), params)


def test_spectral_norm_grads_reach_orig_weight():
    paddle.seed(0)
    lin = spectral_norm(nn.Linear(6, 6), dim=1)
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 6).astype("float32"))
    lin(x).sum().backward()
    assert float(lin.weight_orig.grad.abs().sum().item()) > 0


def test_weight_norm_grads_flow_inside_traced_call():
    """The property design must keep gradients flowing when the layer runs
    INSIDE a jitted functional trace (a cached pre-hook weight would be a
    trace constant with zero gradient — the failure this design prevents)."""
    import jax

    from paddle_tpu.jit import functional_call

    paddle.seed(0)
    lin = weight_norm(nn.Linear(4, 3))
    state = lin.state_dict(include_non_persistable_buffer=True)
    arrays = {k: v._data for k, v in state.items()}
    x = np.random.RandomState(0).randn(2, 4).astype("float32")

    def f(params):
        out = functional_call(lin, params, paddle.to_tensor(x))
        return (out._data ** 2).sum()

    grads = jax.jit(jax.grad(f))(arrays)
    assert float(abs(np.asarray(grads["weight_g"])).sum()) > 0
    assert float(abs(np.asarray(grads["weight_v"])).sum()) > 0


def test_weight_norm_dim_validation_and_iterables():
    lin = nn.Linear(4, 3)
    with pytest.raises(ValueError):
        weight_norm(lin, dim=5)
    with pytest.raises(ValueError):
        spectral_norm(nn.Linear(4, 3), n_power_iterations=0)
    # vector_to_parameters accepts a generator without silently no-oping
    net = nn.Sequential(nn.Linear(2, 2))
    vec = parameters_to_vector(net.parameters())
    vector_to_parameters(paddle.to_tensor(vec.numpy() * 0.0),
                         (p for p in net.parameters()))
    assert float(parameters_to_vector(net.parameters()).abs().sum()
                 .item()) == 0.0


def test_spectral_norm_default_dim_is_output_axis_for_linear():
    """dim=None auto-selects the output axis (reference default): for our
    [in, out] Linear weight that is axis 1, so u has out_features length."""
    lin = spectral_norm(nn.Linear(6, 3))
    assert lin.weight_u.shape == [3]

"""grad_comm: in-program microbatch gradient accumulation + deferred fused
all-reduce + opt-in low-precision gradient collectives
(distributed/grad_comm.py, wired through TrainStepEngine.microbatches and
hapi Model.fit(accumulate_grad_batches=K)).

Numeric contracts pinned here; the compiled-HLO structure (ONE fused
gradient all-reduce independent of K, donation aliasing, activation-peak
drop) is gated in tests/test_hlo_perf_gates.py and
tests/test_donation_safety.py.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.distributed.engine import TrainStepEngine


def _make(k=1, seed=0):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           microbatches=k)


def _batch(n=32):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def _losses(engine, x, y, steps=3):
    return [float(engine.step(x, y).item()) for _ in range(steps)]


@pytest.mark.parametrize("k", [2, 4])
def test_microbatch_step_is_loss_parity_with_single_batch(k):
    """f32 K-microbatch accumulation == the single-shot step at equal
    effective batch: losses track and the trained params agree."""
    x, y = _batch()
    e1, ek = _make(1), _make(k)
    l1, lk = _losses(e1, x, y), _losses(ek, x, y)
    np.testing.assert_allclose(lk, l1, rtol=2e-5, atol=1e-6)
    for n in e1.params:
        np.testing.assert_allclose(np.asarray(ek.params[n]),
                                   np.asarray(e1.params[n]),
                                   rtol=2e-4, atol=1e-5)
    # exactly one dispatch per optimizer step: one jitted accum fn, no
    # single-shot step fn ever built on the accum engine
    assert ek._step_fn is None and len(ek._accum_fns) == 1


def test_default_path_is_bit_identical_and_bypasses_grad_comm():
    """FLAGS_grad_comm_dtype unset + microbatches=1: the original step
    program runs — grad_comm never engages, and explicitly setting the
    default f32 value changes nothing, bit for bit."""
    x, y = _batch()
    steps0 = monitor.stat("grad_comm.steps").get()
    e_default = _make(1)
    _losses(e_default, x, y)
    assert monitor.stat("grad_comm.steps").get() == steps0
    assert e_default._accum_fns == {} and e_default._step_fn is not None

    paddle.set_flags({"grad_comm_dtype": "f32"})  # explicit default
    e_explicit = _make(1)
    _losses(e_explicit, x, y)
    for n in e_default.params:
        np.testing.assert_array_equal(np.asarray(e_default.params[n]),
                                      np.asarray(e_explicit.params[n]))


def test_bf16_allreduce_within_tolerance():
    x, y = _batch()
    e1 = _make(1)
    l1 = _losses(e1, x, y, steps=4)
    paddle.set_flags({"grad_comm_dtype": "bf16"})
    eb = _make(2)
    lb = _losses(eb, x, y, steps=4)
    # bf16 has ~3 decimal digits; training must track the f32 trajectory
    np.testing.assert_allclose(lb, l1, rtol=2e-2)
    assert lb[-1] < lb[0]  # and actually converge


def test_int8_allreduce_within_tolerance_and_fewer_bytes():
    from paddle_tpu.distributed import grad_comm

    x, y = _batch()
    e1 = _make(1)
    l1 = _losses(e1, x, y, steps=4)
    paddle.set_flags({"grad_comm_dtype": "int8"})
    ei = _make(2)
    li = _losses(ei, x, y, steps=4)
    np.testing.assert_allclose(li, l1, rtol=2e-2)
    assert li[-1] < li[0]
    # chunk-scaled int8 payload ~= a quarter of the f32 collective at real
    # model sizes (the toy engine's 676 grads are all chunk overhead)
    chunk = grad_comm.chunk_size()
    for n in (10 ** 6, 10 ** 8):
        assert grad_comm.payload_bytes(n, "int8", chunk) < \
            0.3 * grad_comm.payload_bytes(n, "f32", chunk)


def test_int8_error_feedback_residual():
    """FLAGS_grad_comm_error_feedback: the quantization error is carried
    across steps (residual allocated, donated, and non-zero) and training
    still tracks the f32 trajectory."""
    x, y = _batch()
    e1 = _make(1)
    l1 = _losses(e1, x, y, steps=5)
    paddle.set_flags({"grad_comm_dtype": "int8",
                      "grad_comm_error_feedback": True})
    ee = _make(2)
    le = _losses(ee, x, y, steps=5)
    np.testing.assert_allclose(le, l1, rtol=2e-2)
    res = np.asarray(ee._grad_residual)
    assert res.shape[-1] == ee._n_grad_elems()
    assert np.abs(res).max() > 0  # rounding error was actually captured


def test_quantize_roundtrip_error_bounded():
    """Unit contract of the EQuARX-style chunk scaling: dequant(quant(x))
    is within scale/2 = absmax/254 per chunk element."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.grad_comm import (_dequantize_int8,
                                                  _quantize_int8)

    rng = np.random.RandomState(0)
    x = jnp.asarray((rng.randn(5000) * np.logspace(-4, 0, 5000))
                    .astype(np.float32))
    q, scale = _quantize_int8(x, 256)
    back = np.asarray(_dequantize_int8(q, scale, 5000))
    err = np.abs(back - np.asarray(x))
    bound = np.repeat(np.asarray(scale), 256)[:5000] / 2 + 1e-12
    assert (err <= bound).all()


def test_accum_step_telemetry_and_counters():
    x, y = _batch()
    e = _make(2)
    tele = e.enable_telemetry()
    s0 = monitor.stat("grad_comm.steps").get()
    m0 = monitor.stat("grad_comm.microbatches").get()
    e.step(x, y)
    e.step(x, y)
    rec = tele.sink.records[-1]
    assert rec["microbatches"] == 2
    assert rec["grad_comm_dtype"] == "f32"
    assert "grad_comm_bytes" in rec
    assert rec["grad_comm_steps"] == monitor.stat("grad_comm.steps").get()
    assert monitor.stat("grad_comm.steps").get() == s0 + 2
    assert monitor.stat("grad_comm.microbatches").get() == m0 + 4


def test_batch_not_divisible_by_microbatches_raises():
    e = _make(4)
    rng = np.random.RandomState(0)
    n_dev = len(jax.devices())
    bad = n_dev * 2  # divisible by the mesh but not by mesh*K
    x = paddle.to_tensor(rng.randn(bad, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (bad,)).astype(np.int64))
    with pytest.raises(ValueError, match="microbatches"):
        e.step(x, y)


def test_bad_grad_comm_dtype_rejected():
    paddle.set_flags({"grad_comm_dtype": "fp8"})
    e = _make(2)
    x, y = _batch()
    with pytest.raises(ValueError, match="grad_comm_dtype"):
        e.step(x, y)


def test_gspmd_fallback_on_hybrid_mesh():
    """mp>1: accumulation falls back to the GSPMD scan (still one dispatch,
    K fused reduces) with loss parity, and a low-precision request warns
    and reduces in f32."""
    import warnings

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    if len(jax.devices()) < 4:
        pytest.skip("needs 4-device mesh")

    def build(k):
        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        model = GPTForPretraining(gpt_tiny())
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        return fleet.distributed_engine(model, opt, microbatches=k)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int64)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(np.roll(ids, -1, 1))
    e1, e2 = build(1), build(2)
    assert not e2._dp_pure()
    l1 = [float(e1.step(x, y).item()) for _ in range(2)]
    l2 = [float(e2.step(x, y).item()) for _ in range(2)]
    np.testing.assert_allclose(l2, l1, rtol=1e-4)

    paddle.set_flags({"grad_comm_dtype": "bf16"})
    e3 = build(2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        l3 = float(e3.step(x, y).item())
    assert any("grad_comm_dtype" in str(x.message) for x in w)
    np.testing.assert_allclose(l3, l1[0], rtol=1e-4)  # reduced in f32


def test_hapi_fit_routes_accumulation_to_engine():
    """fit(accumulate_grad_batches=K) with no metrics: K loader batches run
    as ONE engine dispatch; weights land back in the eager network."""
    from paddle_tpu.hapi.model import Model

    class DS(paddle.io.Dataset):
        def __init__(self, n=64):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype("float32")
            self.y = np.argmax(self.x[:, :4], axis=1,
                               keepdims=True).astype("int64")

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
              paddle.nn.CrossEntropyLoss())
    w0 = net[0].weight.numpy().copy()
    s0 = monitor.stat("grad_comm.steps").get()
    hist = m.fit(DS(), epochs=1, batch_size=16, verbose=0,
                 accumulate_grad_batches=2, shuffle=False)
    assert m._engine is not None
    # 4 loader batches / K=2 -> 2 accumulated optimizer steps
    assert monitor.stat("grad_comm.steps").get() == s0 + 2
    assert np.abs(net[0].weight.numpy() - w0).max() > 1e-5
    assert np.isfinite(hist[0]["loss"])


def test_hapi_fit_tail_group_and_metrics_fallback():
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.metric import Accuracy

    class DS(paddle.io.Dataset):
        def __init__(self, n):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype("float32")
            self.y = np.zeros((n, 1), dtype="int64")

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 4))
    m = Model(net)
    m.prepare(paddle.optimizer.SGD(parameters=net.parameters()),
              paddle.nn.CrossEntropyLoss())
    # 3 batches of 16, K=2: one full accumulated group (grad_comm) + a tail
    # group of 1 (a single microbatch runs as the plain fused step) ->
    # exactly 2 optimizer steps, nothing leaked into the next epoch
    s0 = monitor.stat("grad_comm.steps").get()
    m.fit(DS(48), epochs=1, batch_size=16, verbose=0,
          accumulate_grad_batches=2, shuffle=False)
    assert monitor.stat("grad_comm.steps").get() == s0 + 1
    assert m._engine._step_count == 2

    # metrics need per-batch outputs: engine path must NOT engage
    paddle.seed(0)
    net2 = paddle.nn.Sequential(paddle.nn.Linear(8, 4))
    m2 = Model(net2)
    m2.prepare(paddle.optimizer.SGD(parameters=net2.parameters()),
               paddle.nn.CrossEntropyLoss(), Accuracy())
    h = m2.fit(DS(64), epochs=1, batch_size=16, verbose=0,
               accumulate_grad_batches=2)
    assert m2._engine is None
    assert "acc" in h[0]

"""API surface guard: the committed API.spec must match the live package.

Reference: paddle/fluid/API.spec + the CI check that diffs public API
signatures so breaking changes are deliberate. Regenerate after intentional
changes with:  python tools/gen_api_spec.py > API.spec
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_spec_up_to_date():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from gen_api_spec import collect
    finally:
        sys.path.pop(0)

    live = collect()
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = [l.rstrip("\n") for l in f if l.strip()]

    missing = sorted(set(committed) - set(live))
    added = sorted(set(live) - set(committed))
    msg = []
    if missing:
        msg.append("signatures removed/changed vs API.spec:\n  " + "\n  ".join(missing[:20]))
    if added:
        msg.append("new/changed signatures not in API.spec:\n  " + "\n  ".join(added[:20]))
    assert not msg, (
        "\n".join(msg)
        + "\n\nIf intentional: python tools/gen_api_spec.py > API.spec")

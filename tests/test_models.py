"""ERNIE/BERT + recommendation model family tests: shapes, training, and the
ERNIE sharding path on the virtual 8-device mesh (BASELINE configs 3 and 5)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.models import (
    BertModel, DeepFM, ErnieForPretraining, ErnieModel, WideDeep, bert_base,
    ctr_loss, ernie_base, ernie_tiny,
)


class TestErnie:
    def test_forward_shapes(self):
        paddle.seed(0)
        cfg = ernie_tiny()
        m = ErnieModel(cfg)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int64))
        seq, pooled = m(ids)
        assert seq.shape == [2, 16, cfg.hidden_size]
        assert pooled.shape == [2, cfg.hidden_size]

    def test_attention_mask_effect(self):
        """Masked positions must not change other positions' outputs."""
        paddle.seed(0)
        cfg = ernie_tiny()
        m = ErnieModel(cfg)
        m.eval()
        rs = np.random.RandomState(0)
        ids = rs.randint(0, cfg.vocab_size, (1, 8)).astype(np.int64)
        mask = np.ones((1, 8), np.int64)
        mask[0, 4:] = 0
        seq1, _ = m(paddle.to_tensor(ids), attention_mask=paddle.to_tensor(mask))
        ids2 = ids.copy()
        ids2[0, 4:] = (ids2[0, 4:] + 7) % cfg.vocab_size  # change masked tokens
        seq2, _ = m(paddle.to_tensor(ids2), attention_mask=paddle.to_tensor(mask))
        np.testing.assert_allclose(seq1.numpy()[0, :4], seq2.numpy()[0, :4],
                                   rtol=1e-4, atol=1e-5)

    def test_pretraining_loss_decreases(self):
        paddle.seed(0)
        cfg = ernie_tiny()
        m = ErnieForPretraining(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (4, 16)).astype(np.int64))
        labels = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (4, 16)).astype(np.int64))
        losses = []
        for _ in range(8):
            loss = m(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_base_config_shapes(self):
        cfg = ernie_base()
        assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads) == (768, 12, 12)
        b = bert_base()
        assert b.vocab_size == 30522 and b.type_vocab_size == 2

    def test_engine_sharded_training(self):
        """ERNIE on the dp×mp mesh through the pjit engine (config-3 path)."""
        paddle.seed(0)
        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        cfg = ernie_tiny()
        m = ErnieForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        engine = fleet.distributed_engine(m, opt)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64))
        labels = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64))
        losses = [float(engine.step(ids, labels).item()) for _ in range(4)]
        assert losses[-1] < losses[0], losses


class TestRecModels:
    def _batch(self, rs, n=16, fields=5, dense=3, vocab=1000):
        return (paddle.to_tensor(rs.randint(0, vocab, (n, fields)).astype(np.int64)),
                paddle.to_tensor(rs.rand(n, dense).astype(np.float32)),
                paddle.to_tensor(rs.randint(0, 2, (n, 1)).astype(np.int64)))

    @pytest.mark.parametrize("cls", [WideDeep, DeepFM])
    def test_trains(self, cls):
        paddle.seed(0)
        net = cls(sparse_feature_dim=1000, num_fields=5, dense_dim=3)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        rs = np.random.RandomState(0)
        sids, dense, lab = self._batch(rs)
        losses = []
        for _ in range(25):
            loss = ctr_loss(net(sids, dense), lab)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_deepfm_fm_term(self):
        """FM 2nd-order matches the explicit pairwise-interaction sum."""
        paddle.seed(0)
        net = DeepFM(sparse_feature_dim=50, embedding_dim=4, num_fields=3,
                     dense_dim=2, hidden_sizes=(8,))
        rs = np.random.RandomState(0)
        sids = rs.randint(0, 50, (2, 3)).astype(np.int64)
        emb = net.second_emb(paddle.to_tensor(sids)).numpy()  # [2, 3, 4]
        ref = np.zeros((2, 1), np.float32)
        for i in range(3):
            for j in range(i + 1, 3):
                ref[:, 0] += (emb[:, i] * emb[:, j]).sum(-1)
        sum_sq = (emb.sum(1)) ** 2
        sq_sum = (emb ** 2).sum(1)
        fm2 = 0.5 * (sum_sq - sq_sum).sum(-1, keepdims=True)
        np.testing.assert_allclose(fm2, ref, rtol=1e-5)

    def test_ps_mode_wide_deep(self):
        """WideDeep with both sparse tables on a live (in-process) PS."""
        from paddle_tpu.distributed.ps import (PSClient, PSServer,
                                               SparseTableConfig)

        sparse = [SparseTableConfig(table_id=0, dim=1, learning_rate=0.1),
                  SparseTableConfig(table_id=1, dim=8, learning_rate=0.1)]
        server = PSServer(0, sparse, [])
        client = PSClient([f"127.0.0.1:{server.port}"])
        for t in sparse:
            client.register_table_dim(t.table_id, t.dim)
        paddle.seed(0)
        net = WideDeep(sparse_feature_dim=1000, embedding_dim=8, num_fields=4,
                       dense_dim=3, use_ps=True, wide_table_id=0, deep_table_id=1,
                       client=client)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        rs = np.random.RandomState(0)
        sids = paddle.to_tensor(rs.randint(0, 1000, (8, 4)).astype(np.int64))
        dense = paddle.to_tensor(rs.rand(8, 3).astype(np.float32))
        lab = paddle.to_tensor(rs.randint(0, 2, (8, 1)).astype(np.int64))
        losses = []
        for _ in range(20):
            loss = ctr_loss(net(sids, dense), lab)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_gpt_memorizes_fixed_batch():
    """End-to-end convergence: 120 fused engine steps on one fixed batch
    must drive the LM loss to ~0 (memorization). Catches the class of
    subtle optimizer/gradient/loss-scaling bugs that per-op numerics and
    short loss-decrease checks miss — a wrong but plausible gradient still
    reduces loss for 3 steps; it does not memorize."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    fleet.init(is_collective=True, strategy=dist.DistributedStrategy())
    engine = fleet.distributed_engine(model, opt)
    # batch divisible by the virtual 8-device dp mesh the conftest forces
    ids = np.random.RandomState(0).randint(0, 1024, (8, 64)).astype(np.int64)
    labels = np.roll(ids, -1, 1)
    first = last = None
    for _ in range(120):
        last = float(engine.step(paddle.to_tensor(ids),
                                 paddle.to_tensor(labels)).item())
        first = first if first is not None else last
    assert first > 5.0, first       # starts near ln(vocab)
    assert last < 0.05, (first, last)

"""Eager point-to-point send/recv across real processes (VERDICT r2 #3).

Reference: ProcessGroup::Send/Recv (ProcessGroup.h:104,110). Here the payload
moves device-to-device through a two-endpoint ppermute program; shape/dtype
negotiation rides the jax coordinator KV service. Single-process contract
errors are cheap; the transfer itself needs two processes (slow marker).
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def test_p2p_contract_errors_single_process():
    t = paddle.to_tensor(np.zeros((2, 2), "float32"))
    with pytest.raises(ValueError, match="multi-process"):
        dist.send(t, dst=1)
    with pytest.raises(ValueError, match="multi-process"):
        dist.recv(t, src=1)


_SCRIPT = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    rank = dist.get_rank()

    payload = np.arange(12, dtype=np.float32).reshape(3, 4) * 7.0
    if rank == 0:
        # 1: preallocated-buffer transfer
        dist.send(paddle.to_tensor(payload), dst=1)
        # 2: negotiated transfer (receiver passes None; shape/dtype from KV)
        dist.send(paddle.to_tensor(payload.astype(np.int64) + 3), dst=1)
        # 3: async pair
        task = dist.isend(paddle.to_tensor(payload * -1.0), dst=1)
        task.wait()
        print("RANK 0 SENT ok", flush=True)
    else:
        buf = paddle.to_tensor(np.zeros((3, 4), np.float32))
        dist.recv(buf, src=0)
        assert np.allclose(buf.numpy(), payload), buf.numpy()
        got = dist.recv(None, src=0)
        assert got.numpy().dtype == np.int64 and got.shape == [3, 4]
        assert np.array_equal(got.numpy(), payload.astype(np.int64) + 3)
        task = dist.irecv(paddle.to_tensor(np.zeros((3, 4), np.float32)),
                          src=0)
        out = task.wait()
        assert task.is_completed()
        assert np.allclose(out.numpy(), payload * -1.0)
        print("RANK 1 RECV ok", flush=True)
"""


def _launch(tmp_path, body, nproc):
    script = tmp_path / "p2p.py"
    script.write_text(textwrap.dedent(body))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(nproc), "--log_dir", str(tmp_path / "log"),
         str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, (res.stdout, res.stderr)
    return [(tmp_path / "log" / f"workerlog.{r}.log").read_text()
            for r in range(nproc)]


@pytest.mark.slow
def test_two_process_send_recv(tmp_path):
    logs = _launch(tmp_path, _SCRIPT, 2)
    assert "SENT ok" in logs[0], logs[0]
    assert "RECV ok" in logs[1], logs[1]


_SCRIPT_BYSTANDER = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 3}
    fleet.init(is_collective=True, strategy=strategy)
    rank = dist.get_rank()

    payload = np.full((2, 2), 5.0, np.float32)
    if rank == 0:
        dist.send(paddle.to_tensor(payload), dst=2)
        print("RANK 0 SENT ok", flush=True)
    elif rank == 2:
        got = dist.recv(None, src=0)
        assert np.allclose(got.numpy(), payload)
        print("RANK 2 RECV ok", flush=True)
    else:
        # rank 1 never touches p2p: the pair program must not require it
        print("RANK 1 BYSTANDER ok", flush=True)
"""


@pytest.mark.slow
def test_three_process_bystander_not_required(tmp_path):
    """A p2p transfer is a PAIR program — a world-sized collective here
    would deadlock because rank 1 never participates."""
    logs = _launch(tmp_path, _SCRIPT_BYSTANDER, 3)
    assert "SENT ok" in logs[0]
    assert "BYSTANDER ok" in logs[1]
    assert "RECV ok" in logs[2]


_SCRIPT_BATCH = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    rank = dist.get_rank()
    other = 1 - rank

    mine = np.full((2, 3), float(rank + 1), np.float32)
    buf = paddle.to_tensor(np.zeros((2, 3), np.float32))
    # canonical crossing exchange — deadlocks with sequential isend/irecv,
    # works as ONE fused program
    ops = [dist.P2POp("isend", paddle.to_tensor(mine), peer=other),
           dist.P2POp("irecv", buf, peer=other)]
    for t in dist.batch_isend_irecv(ops):
        t.wait()
    assert np.allclose(buf.numpy(), other + 1), buf.numpy()
    print("RANK", rank, "EXCHANGE ok", flush=True)
"""


@pytest.mark.slow
def test_batch_isend_irecv_bidirectional(tmp_path):
    logs = _launch(tmp_path, _SCRIPT_BATCH, 2)
    assert "EXCHANGE ok" in logs[0]
    assert "EXCHANGE ok" in logs[1]


_SCRIPT_LINE = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 3}
    fleet.init(is_collective=True, strategy=strategy)
    rank = dist.get_rank()

    # asymmetric pipeline line 0 -> 1 -> 2: every rank sees a DIFFERENT op
    # set; the fused batch must still compile one identical world program
    mine = np.full((2, 2), float(rank + 10), np.float32)
    ops = []
    buf = paddle.to_tensor(np.zeros((2, 2), np.float32))
    if rank == 0:
        ops = [dist.P2POp("isend", paddle.to_tensor(mine), peer=1)]
    elif rank == 1:
        ops = [dist.P2POp("irecv", buf, peer=0),
               dist.P2POp("isend", paddle.to_tensor(mine), peer=2)]
    else:
        ops = [dist.P2POp("irecv", buf, peer=1)]
    dist.batch_isend_irecv(ops)
    if rank == 1:
        assert np.allclose(buf.numpy(), 10.0), buf.numpy()
    if rank == 2:
        assert np.allclose(buf.numpy(), 11.0), buf.numpy()
    print("RANK", rank, "LINE ok", flush=True)
"""


@pytest.mark.slow
def test_batch_isend_irecv_pipeline_line(tmp_path):
    """3-rank line topology (rank op sets all differ) — the case per-pair
    program derivation deadlocks on."""
    logs = _launch(tmp_path, _SCRIPT_LINE, 3)
    for r in range(3):
        assert "LINE ok" in logs[r], logs[r]

"""Production metrics layer (ISSUE 7 tentpole): histogram registry,
Prometheus exporter, and the crash/NaN flight recorder.

Pinned contracts:
- histogram math: exact count/sum/min/max, and interpolated percentile
  estimates within one bucket width of numpy's exact answer;
- exporter: a live HTTP scrape round-trips the registry in both Prometheus
  text 0.0.4 (cumulative ``_bucket{le=}`` series) and JSON;
- flight recorder: a NaN loss step / an uncaught step exception / a
  dispatch NaN-check hit each produce a dump directory containing the
  offending step's record;
- off by default: the registry/recorder globals stay None-gated so the
  engines' hot path pays a single None check when observability is off.

The conftest autouse fixture does not reset these process-globals, so every
test that enables them cleans up in ``finally``.
"""
import json
import math
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability import exporter, flight_recorder, metrics


@pytest.fixture(autouse=True)
def _clean_observability():
    """Metrics/exporter/recorder are process-globals the shared conftest
    doesn't know about: start every test dark, leave it dark."""
    exporter.stop_exporter()
    metrics.reset()
    flight_recorder.disable()
    yield
    exporter.stop_exporter()
    metrics.reset()
    flight_recorder.disable()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode("utf-8")


# ------------------------------------------------------------- histogram math

def test_log_buckets_geometric_cover():
    bs = metrics.log_buckets(0.5, 100.0, 2.0)
    assert bs[0] == 0.5 and bs[-1] >= 100.0
    ratios = [b / a for a, b in zip(bs, bs[1:])]
    assert all(abs(r - 2.0) < 1e-12 for r in ratios)
    with pytest.raises(ValueError):
        metrics.log_buckets(0, 10)
    with pytest.raises(ValueError):
        metrics.log_buckets(1, 10, factor=1.0)


def test_histogram_exact_moments_and_bucket_counts():
    h = metrics.Histogram("t", boundaries=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 3.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(106.0)
    assert snap["min"] == 0.5 and snap["max"] == 100.0
    # le-style buckets: v == boundary lands in that boundary's bucket,
    # values past the last boundary land in the implicit +Inf overflow
    assert snap["counts"] == [2, 1, 1, 1]
    assert sum(snap["counts"]) == snap["count"]


def test_histogram_percentiles_within_one_bucket_of_numpy():
    rng = np.random.RandomState(7)
    xs = np.exp(rng.randn(5000)) * 10.0  # lognormal ms-ish latencies
    h = metrics.Histogram("lat", boundaries=metrics.DEFAULT_MS_BUCKETS)
    for v in xs:
        h.observe(float(v))
    snap = h.snapshot()
    bs = snap["boundaries"]
    for q in (50, 90, 99):
        exact = float(np.percentile(xs, q))
        est = metrics.estimate_percentile(snap, q / 100)
        # error bounded by the width of the bucket holding the exact value
        i = int(np.searchsorted(bs, exact))
        lo = bs[i - 1] if i > 0 else snap["min"]
        hi = bs[i] if i < len(bs) else snap["max"]
        assert abs(est - exact) <= (hi - lo) + 1e-9, (q, exact, est)
        # estimates are always clamped inside the observed range
        assert snap["min"] <= est <= snap["max"]
    # empty histogram -> None, not a crash
    assert metrics.estimate_percentile(
        metrics.Histogram("e").snapshot(), 0.5) is None


def test_registry_get_or_create_and_kind_mismatch():
    reg = metrics.MetricRegistry()
    c = reg.counter("hits")
    assert reg.counter("hits") is c
    with pytest.raises(TypeError):
        reg.gauge("hits")
    with pytest.raises(ValueError):
        c.inc(-1)  # counters only go up
    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2.0


def test_snapshot_absorbs_monitor_and_compact_strips_buckets():
    from paddle_tpu.core import monitor

    reg = metrics.MetricRegistry()
    reg.histogram("lat_ms").observe(5.0)
    monitor.stat("test_metrics.probe").increase(3)
    snap = reg.snapshot()
    assert snap["monitor"]["test_metrics.probe"]["value"] >= 3
    assert "counts" in snap["histograms"]["lat_ms"]
    compact = reg.snapshot(compact=True)
    h = compact["histograms"]["lat_ms"]
    assert "counts" not in h and "boundaries" not in h
    assert h["count"] == 1 and h["p50"] is not None


def test_prometheus_text_cumulative_buckets():
    reg = metrics.MetricRegistry()
    h = reg.histogram("step_ms", boundaries=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    reg.counter("requests").inc(7)
    reg.gauge("depth").set(2)
    text = reg.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE paddle_tpu_step_ms histogram" in lines
    buckets = [ln for ln in lines
               if ln.startswith("paddle_tpu_step_ms_bucket")]
    cum = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert cum == sorted(cum), "bucket series must be cumulative"
    assert buckets[-1].startswith('paddle_tpu_step_ms_bucket{le="+Inf"}')
    assert cum[-1] == 4
    assert "paddle_tpu_step_ms_count 4" in lines
    assert "paddle_tpu_step_ms_sum 555.5" in lines
    assert "paddle_tpu_requests_total 7" in lines
    assert "paddle_tpu_depth 2" in lines
    # absorbed monitor stats render as gauges with a _peak companion
    assert any(ln.startswith("paddle_tpu_monitor_") for ln in lines)


# ------------------------------------------------------------------- exporter

def test_exporter_scrape_round_trip():
    try:
        reg = metrics.enable()
        reg.histogram("probe_ms", boundaries=(1.0, 10.0)).observe(3.0)
        reg.counter("probe_hits").inc(2)
        ex = exporter.start_exporter(port=0)
        assert ex.port > 0  # ephemeral port read back after bind
        code, ctype, body = _get(ex.url + "/metrics")
        assert code == 200 and ctype == exporter.PROM_CONTENT_TYPE
        assert "paddle_tpu_probe_ms_count 1" in body
        assert 'paddle_tpu_probe_ms_bucket{le="10"} 1' in body
        assert "paddle_tpu_probe_hits_total 2" in body
        code, ctype, body = _get(ex.url + "/metrics.json")
        assert code == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["histograms"]["probe_ms"]["count"] == 1
        assert doc["counters"]["probe_hits"] == 2.0
        assert "monitor" in doc
        code, _, body = _get(ex.url + "/healthz")
        assert code == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError):
            _get(ex.url + "/nope")
    finally:
        exporter.stop_exporter()
        metrics.reset()


def test_exporter_env_autostart_is_gated(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_METRICS_PORT", raising=False)
    assert exporter.ensure_started_from_env() is None
    monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "not-a-port")
    assert exporter.ensure_started_from_env() is None
    try:
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
        ex = exporter.ensure_started_from_env()
        assert ex is not None and ex.running
        # starting the exporter activates the registry feed
        assert metrics.active_registry() is not None
        assert exporter.ensure_started_from_env() is ex  # idempotent
    finally:
        exporter.stop_exporter()
        metrics.reset()


def test_observability_off_by_default():
    """The hot-path gates: both globals are None until explicitly enabled,
    and flip back to None on disable/reset."""
    assert metrics.active_registry() is None
    assert flight_recorder.get() is None
    assert exporter.get_exporter() is None
    # module-level NaN hook is a no-op when dark
    assert flight_recorder.on_nan_inf("nobody") is None
    reg = metrics.enable()
    assert metrics.active_registry() is reg
    metrics.disable()
    assert metrics.active_registry() is None


# ------------------------------------------------------------ flight recorder

def test_flight_recorder_ring_and_explicit_dump(tmp_path):
    fr = flight_recorder.FlightRecorder(str(tmp_path), capacity=3)
    for i in range(5):
        fr.record({"event": "train_step", "step": i})
    assert [r["step"] for r in fr.records()] == [2, 3, 4]  # bounded ring
    d = fr.dump("manual probe!", extra={"note": "hi"})
    assert os.path.basename(d).endswith("manual_probe_")
    with open(os.path.join(d, "records.jsonl")) as f:
        recs = [json.loads(ln) for ln in f]
    assert [r["step"] for r in recs] == [2, 3, 4]
    with open(os.path.join(d, "state.json")) as f:
        state = json.load(f)
    assert state["reason"] == "manual probe!"
    assert state["extra"] == {"note": "hi"}
    assert "dispatch.calls" in state["counters"]


def test_flight_recorder_nan_dumps_rate_limited(tmp_path):
    fr = flight_recorder.enable(str(tmp_path), nan_dump_limit=1)
    assert flight_recorder.get() is fr
    assert fr.on_nan_inf("op_add") is not None
    assert fr.on_nan_inf("op_add") is None  # limit reached
    assert fr.dump("explicit") is not None  # explicit dumps are not limited
    assert len(fr.dumps) == 2


def _tiny_engine(seed=0):
    from paddle_tpu.distributed.engine import TrainStepEngine

    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss())


def _batch(n=8, poison=False):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 16).astype(np.float32)
    if poison:
        x[0, 0] = np.nan
    return (paddle.to_tensor(x),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def test_train_engine_dump_on_nan_loss(tmp_path):
    """Acceptance: a forced-NaN step produces a flight dump containing the
    offending step's record."""
    fr = flight_recorder.enable(str(tmp_path))
    eng = _tiny_engine()
    eng.step(*_batch())                  # healthy step -> ring only
    assert fr.dumps == []
    eng.step(*_batch(poison=True))       # NaN x -> NaN loss -> dump
    assert len(fr.dumps) == 1
    d = fr.dumps[0]
    assert "nan_inf_train_loss" in os.path.basename(d)
    with open(os.path.join(d, "records.jsonl")) as f:
        recs = [json.loads(ln) for ln in f]
    # ring holds both steps; the offending one is last and non-finite
    assert [r["step"] for r in recs] == [1, 2]
    assert math.isfinite(recs[0]["loss"])
    assert not math.isfinite(recs[1]["loss"])
    with open(os.path.join(d, "state.json")) as f:
        state = json.load(f)
    assert state["extra"] == {"step": 2}
    assert state["counters"]["engine.nan_loss_steps"]["value"] >= 1


def test_train_engine_dump_on_step_exception(tmp_path):
    fr = flight_recorder.enable(str(tmp_path))
    eng = _tiny_engine()
    eng.step(*_batch())  # builds _step_fn

    def boom(*a, **kw):
        raise RuntimeError("injected step failure")

    eng._step_fn = boom
    with pytest.raises(RuntimeError, match="injected step failure"):
        eng.step(*_batch())
    assert len(fr.dumps) == 1
    assert "train_step_exception" in os.path.basename(fr.dumps[0])
    with open(os.path.join(fr.dumps[0], "state.json")) as f:
        state = json.load(f)
    assert "injected step failure" in state["extra"]["error"]
    # the healthy step's record survived into the post-mortem ring
    with open(os.path.join(fr.dumps[0], "records.jsonl")) as f:
        assert json.loads(f.readline())["step"] == 1


def test_dispatch_nan_check_triggers_dump(tmp_path):
    fr = flight_recorder.enable(str(tmp_path))
    paddle.set_flags({"check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            _ = x / paddle.to_tensor(np.zeros(2, np.float32))
        assert len(fr.dumps) == 1
        assert os.path.basename(fr.dumps[0]).startswith(
            f"flight_{os.getpid()}_001_nan_inf_op_")
    finally:
        paddle.set_flags({"check_nan_inf": False})


# --------------------------------------------------------- engine histograms

def test_train_engine_feeds_step_histograms():
    try:
        reg = metrics.enable()
        eng = _tiny_engine()
        for _ in range(3):
            eng.step(*_batch())
        snap = reg.snapshot(include_monitor=False)
        h = snap["histograms"]["train.step_ms"]
        assert h["count"] == 3
        assert h["sum"] > 0 and h["min"] > 0
        # first step compiled -> compile_ms saw exactly the compiled steps
        assert snap["histograms"]["train.compile_ms"]["count"] >= 1
    finally:
        metrics.reset()

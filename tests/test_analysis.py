"""Program contract analyzer (ISSUE 11 tentpole): paddle_tpu/analysis.

Three layers pinned here:

- each seeded violation is caught by EXACTLY its designated pass
  (undonated engine -> donation-leak, f32 program under a bf16 contract ->
  dtype-upcast, host callback in a traced fn -> host-transfer, big baked
  literal -> constant-bloat, weak-type / Python-scalar signature ->
  recompile-hazard, broken count -> collective-contract);
- the green path: both engines' default executables lint clean against
  their own default_contracts(), analyze() is dispatch-free, and wiring
  the analyzer changed nothing about lowering (byte-identical programs);
- the observability plumbing: violation counters in monitor + metrics
  registry, the flight-recorder dump naming label+pass, and the
  tools/hlo_lint.py exit-code contract (0 clean / 1 violations / 2 error).
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis as an
from paddle_tpu.core import monitor
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)
from paddle_tpu.observability import (exec_introspect, flight_recorder,
                                      health, metrics)


@pytest.fixture(autouse=True)
def _observability_cleanup():
    yield
    metrics.reset()
    flight_recorder.disable()
    health.reset()
    exec_introspect.reset()


def _dp8_engine(donate=True, microbatches=1, zero=False):
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(64, 64), paddle.nn.ReLU(),
                               paddle.nn.Linear(64, 64))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    eng = fleet.distributed_engine(net, opt, loss_fn=paddle.nn.MSELoss(),
                                   donate=donate, microbatches=microbatches,
                                   zero_update=zero)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 64).astype("float32"))
    y = jnp.asarray(rng.randn(64, 64).astype("float32"))
    return eng, [x, y]


def _tiny_engine():
    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=1, devices=jax.devices()[:1])
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 8), paddle.nn.ReLU(),
                                 paddle.nn.Linear(8, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = TrainStepEngine(model, opt, loss_fn=paddle.nn.MSELoss(), hcg=hcg)
    rng = np.random.RandomState(0)
    return eng, [jnp.asarray(rng.randn(8, 8).astype("float32")),
                 jnp.asarray(rng.randn(8, 8).astype("float32"))]


# ------------------------------------------------------- contract language

def test_check_bound_semantics():
    assert an.check_bound(1, 1) is None
    assert an.check_bound(2, 1) == "exactly 1"
    assert an.check_bound(3, (1, 4)) is None
    assert an.check_bound(0, (1, 4)) == "in [1, 4]"
    assert an.check_bound(99, (5, None)) is None
    assert an.check_bound(4, (5, None)) == ">= 5"
    assert an.check_bound(7, None) is None


def test_contract_label_matching():
    c = an.ProgramContract(label="train.accum_*_bf16*")
    assert c.matches("train.accum_k2_bf16")
    assert c.matches("train.accum_k4_bf16_res")
    assert not c.matches("train.accum_k2_f32")
    assert an.ProgramContract().matches("anything")


def test_program_op_counting_matches_gate_semantics():
    """Op DEFINITIONS by LHS name; `-done` async halves excluded; while
    counted via `) while(` — the exact semantics of the migrated gates."""
    txt = ("  %all-reduce.1 = f32[4]{0} all-reduce(%x)\n"
           "  %all-reduce-done.1 = f32[4]{0} all-reduce-done(%s)\n"
           "  %y = f32[4]{0} add(%all-reduce.1, %all-reduce.1)\n"
           "  %w = (f32[4]) while(%t), condition=%c, body=%b\n")
    p = an.Program("t", hlo_text=txt)
    assert p.count_ops("all-reduce") == 1
    assert p.count_while_loops() == 1


# ------------------------------------------------- seeded violations (sat 3)

def test_seeded_undonated_engine_caught_by_donation_leak():
    """A deliberately undonated engine: ONLY donation-leak fires."""
    eng, arrays = _dp8_engine(donate=False)
    eng.step(*arrays)
    contracts = eng.default_contracts() + [an.ProgramContract(
        label="train.*", donated_bytes=eng._analysis_state_bytes(),
        name="seeded-donation")]
    rep = eng.analyze(contracts)
    assert not rep.ok
    assert {v.pass_name for v in rep.violations} == {"donation-leak"}
    assert rep.violations[0].label == "train.step"


def test_seeded_f32_program_under_bf16_contract_caught_by_dtype_upcast():
    """The engine's real f32 accumulation program declared as a bf16
    grad-comm region: ONLY dtype-upcast fires (and it names the f32
    all-reduce payload). comm_dtype_strict forces the check even where the
    backend couldn't keep bf16 on the wire anyway."""
    from paddle_tpu.distributed import grad_comm

    eng, _ = _dp8_engine()
    arrays = [jnp.asarray(np.random.RandomState(0).randn(64, 64)
                          .astype("float32")),
              jnp.asarray(np.random.RandomState(1).randn(64, 64)
                          .astype("float32"))]
    jf = eng._build_accum(arrays, 2, "f32", False, grad_comm.chunk_size())
    comp = jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                    jnp.int32(1), jax.random.key(0), *arrays).compile()
    rep = an.check_compiled("train.accum_k2_bf16", comp, an.ProgramContract(
        comm_dtype="bf16", comm_dtype_strict=True,
        allow_host_calls=True, max_constant_bytes=None))
    assert {v.pass_name for v in rep.violations} == {"dtype-upcast"}
    assert "f32 payload" in rep.violations[0].message


def test_bf16_contract_on_real_program_respects_backend_wire_dtype():
    """The REAL bf16-payload program under the same (non-strict) contract:
    clean on a native-bf16 wire; on this CPU pipeline — whose float
    normalization legalizes the bf16 psum to an f32 all-reduce — the check
    SKIPS with the probe's reason instead of blaming the source."""
    from paddle_tpu.distributed import grad_comm

    eng, _ = _dp8_engine()
    arrays = [jnp.asarray(np.random.RandomState(0).randn(64, 64)
                          .astype("float32")),
              jnp.asarray(np.random.RandomState(1).randn(64, 64)
                          .astype("float32"))]
    jf = eng._build_accum(arrays, 2, "bf16", False, grad_comm.chunk_size())
    comp = jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                    jnp.int32(1), jax.random.key(0), *arrays).compile()
    rep = an.check_compiled("train.accum_k2_bf16", comp, an.ProgramContract(
        comm_dtype="bf16", allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, rep.format()
    if not an.backend_keeps_bf16_on_wire():
        assert [s.pass_name for s in rep.skips] == ["dtype-upcast"]
        assert rep.skips[0].reason == an.native_bf16_collective_reason()


def test_bf16_wire_payload_passes_strict_contract():
    """A genuinely-bf16 wire payload satisfies even the strict contract —
    the pass flags f32 payloads, not bf16 traffic (synthetic HLO, so this
    holds on every backend)."""
    txt = ("  %all-reduce.1 = bf16[8320]{0} all-reduce(%g)\n"
           "  %all-reduce.2 = f32[2]{0} all-reduce(%tiny)\n")  # < min_elems
    rep = an.check_text("t", txt, an.ProgramContract(
        comm_dtype="bf16", comm_dtype_strict=True,
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, rep.format()


def test_seeded_host_callback_caught_by_host_transfer():
    """A host (python) callback inside a jitted fn: ONLY host-transfer."""

    def step(a):
        jax.debug.callback(lambda v: None, a.sum())
        return a * 2.0

    comp = jax.jit(step).lower(jnp.zeros((8, 8), jnp.float32)).compile()
    rep = an.check_compiled("seeded.callback", comp,
                            an.ProgramContract(max_constant_bytes=None))
    assert {v.pass_name for v in rep.violations} == {"host-transfer"}
    # and tolerated when the contract says so
    ok = an.check_compiled("seeded.callback", comp, an.ProgramContract(
        allow_host_calls=True, max_constant_bytes=None))
    assert ok.ok, ok.format()


def test_seeded_constant_bloat_caught():
    """A 4 MB non-uniform literal baked into the program (uniform arrays
    constant-fold to broadcasts and are free): ONLY constant-bloat."""
    big = jnp.asarray(np.random.RandomState(0).randn(512, 2048)
                      .astype("float32"))  # 4 MB, non-uniform

    def step(a):
        return a + big

    comp = jax.jit(step).lower(jnp.zeros((512, 2048), jnp.float32)).compile()
    rep = an.check_compiled("seeded.const", comp,
                            an.ProgramContract(allow_host_calls=True))
    assert {v.pass_name for v in rep.violations} == {"constant-bloat"}
    assert "4194304-byte" in rep.violations[0].message


def test_seeded_recompile_hazards_caught():
    """Weak-typed aval + Python scalar in a traced signature: ONLY
    recompile-hazard, one violation each."""
    prog = an.Program("seeded.sig", hlo_text="", avals=[
        jax.ShapeDtypeStruct((4,), jnp.float32, weak_type=True), 0.5,
        jax.ShapeDtypeStruct((4,), jnp.float32)])
    rep = an.PassManager().run([prog], [an.ProgramContract(
        allow_host_calls=True, max_constant_bytes=None)])
    assert [v.pass_name for v in rep.violations] == ["recompile-hazard"] * 2
    msgs = " | ".join(v.message for v in rep.violations)
    assert "Python scalar" in msgs and "weakly typed" in msgs


def test_collective_contract_violation_and_combining_skip():
    txt = ("  %all-reduce.1 = f32[4]{0} all-reduce(%x)\n"
           "  %all-reduce.2 = f32[4]{0} all-reduce(%y)\n")
    rep = an.check_text("t", txt, an.ProgramContract(
        collectives={"all-reduce": 1},
        allow_host_calls=True, max_constant_bytes=None))
    assert {v.pass_name for v in rep.violations} == {"collective-contract"}
    # requires_combining on this CPU backend: the check SKIPS, never fails
    rep2 = an.check_text("t", txt, an.ProgramContract(
        collectives={"all-reduce": 1}, requires_combining=True,
        allow_host_calls=True, max_constant_bytes=None))
    if an.backend_combines_collectives():
        assert not rep2.ok
    else:
        assert rep2.ok and len(rep2.skips) == 1
        assert rep2.skips[0].reason == an.collective_combining_reason()


# ------------------------------------------- schedule-order (ISSUE 20 sat 3)

# Two-bucket synthetic scheduled modules. In the "ahead" twin, bucket 1's
# all-gather is DEFINED before bucket 0's dominant (fusion) consumer — the
# shape the prefetch window produces; the "-done" line pins that async
# halves are never counted as gather definitions. In the just-in-time twin
# each gather sits immediately before its own consumer.
_AHEAD_HLO = (
    "  %all-gather.1 = f32[16]{0} all-gather(%p0), channel_id=1\n"
    "  %all-gather.2 = f32[16]{0} all-gather(%p1), channel_id=2\n"
    "  %fusion.1 = f32[4]{0} fusion(%all-gather.1), kind=kLoop\n"
    "  %fusion.2 = f32[4]{0} fusion(%all-gather.2), kind=kLoop\n"
    "  %all-gather-done.9 = f32[16]{0} all-gather-done(%s)\n")

_JIT_HLO = (
    "  %all-gather.1 = f32[16]{0} all-gather(%p0), channel_id=1\n"
    "  %fusion.1 = f32[4]{0} fusion(%all-gather.1), kind=kLoop\n"
    "  %all-gather.2 = f32[16]{0} all-gather(%p1), channel_id=2\n"
    "  %fusion.2 = f32[4]{0} fusion(%all-gather.2), kind=kLoop\n")


def _sched_contract(**kw):
    return an.ProgramContract(schedule_order="all-gather-ahead",
                              allow_host_calls=True, max_constant_bytes=None,
                              **kw)


def test_schedule_order_clean_twin_and_jit_violation():
    """The seeded violation / clean-twin pair for the schedule-order pass:
    just-in-time gather placement fails the all-gather-ahead discipline,
    the prefetch-shaped module passes it. On combining backends the pass
    SKIPS (bucket order is unreadable once gathers are fused) — the shared
    analysis.backend probe, same posture as the collective-count gates."""
    clean = an.check_text("t", _AHEAD_HLO, _sched_contract())
    jit = an.check_text("t", _JIT_HLO, _sched_contract())
    if an.backend_combines_collectives():
        assert clean.ok and jit.ok
        assert [s.pass_name for s in jit.skips] == ["schedule-order"]
        assert [s.pass_name for s in clean.skips] == ["schedule-order"]
    else:
        assert clean.ok, clean.format()
        assert {v.pass_name for v in jit.violations} == {"schedule-order"}
        msg = jit.violations[0].message
        assert "%all-gather.2" in msg and "just-in-time" in msg


def test_schedule_order_orders_buckets_by_channel_id():
    """Bucket order is channel_id order (assigned in emission = bucket
    order), NOT textual order: swapping the channel ids on the clean twin
    makes %all-gather.1 the SECOND bucket, defined after its predecessor's
    consumer? No — defined first, so the swapped module is still clean;
    swapping them on the jit twin keeps it a violation either way."""
    swapped = _AHEAD_HLO.replace("channel_id=1", "channel_id=9")
    rep = an.check_text("t", swapped, _sched_contract())
    if an.backend_combines_collectives():
        assert rep.ok
    else:
        # now AG.2 (ch2) is bucket 0; its successor AG.1 (ch9) is defined
        # BEFORE AG.2's fusion consumer -> still satisfies the discipline
        assert rep.ok, rep.format()


def test_schedule_order_unknown_discipline_is_a_violation():
    rep = an.check_text("t", _AHEAD_HLO, an.ProgramContract(
        schedule_order="bogus-discipline", allow_host_calls=True,
        max_constant_bytes=None))
    assert {v.pass_name for v in rep.violations} == {"schedule-order"}
    assert "unknown schedule_order" in rep.violations[0].message


def _fsdp_engine(prefetch, dp=8, k=2):
    paddle.set_flags({"fsdp_prefetch": prefetch})
    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=dp, devices=jax.devices()[:dp])
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    eng = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                          hcg=hcg, microbatches=k, fsdp=True)
    rng = np.random.RandomState(0)
    eng.step(jnp.asarray(rng.randn(32, 16).astype("float32")),
             jnp.asarray(rng.randint(0, 4, (32,)).astype("int64")))
    return eng


def test_fsdp_prefetch_executable_lints_clean_and_jit_program_violates():
    """ISSUE 20 acceptance, both directions on the REAL executables: the
    depth-2 fsdp step satisfies default_contracts() — the existing
    L-AG/1-RS/0-AR collective counts AND the new all-gather-ahead
    schedule-order discipline read from the scheduled optimized module —
    while the depth-0 just-in-time program, held to the same discipline by
    a forced contract, is the seeded violation (its default contracts gate
    the discipline off below depth 2, so its own analyze() stays green)."""
    eng = _fsdp_engine(prefetch=2)
    assert any(c.schedule_order == "all-gather-ahead"
               for c in eng.default_contracts())
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert any(lbl.startswith("train.fsdp_k2") for lbl in rep.checked)

    e0 = _fsdp_engine(prefetch=0)
    assert all(c.schedule_order is None for c in e0.default_contracts())
    rep0 = e0.analyze()
    assert rep0.ok, rep0.format()
    forced = an.ProgramContract("train.fsdp_*",
                                schedule_order="all-gather-ahead",
                                allow_host_calls=True,
                                max_constant_bytes=None, name="forced")
    progs = an.programs_from_stash(e0._exec_stash)
    out = an.PassManager().run(progs, [forced])
    if an.backend_combines_collectives():
        assert out.ok and [s.pass_name for s in out.skips] == [
            "schedule-order"]
    else:
        assert {v.pass_name for v in out.violations} == {"schedule-order"}
        assert "just-in-time" in out.violations[0].message


# -------------------------------------------------------------- green path

def test_train_engine_default_executables_lint_clean():
    """Acceptance: the train engine's own step + accum executables satisfy
    its default contracts (modulo backend-capability skips)."""
    eng, arrays = _dp8_engine(microbatches=1)
    eng.step(*arrays)
    eng.microbatches = 2
    eng.step(*arrays)
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert "train.step" in rep.checked
    assert any(lbl.startswith("train.accum_k2") for lbl in rep.checked)


def test_zero_engine_default_executables_lint_clean():
    eng, arrays = _dp8_engine(microbatches=2, zero=True)
    eng.step(*arrays)
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert any(lbl.startswith("train.zero_k2") for lbl in rep.checked)


def test_serving_engine_default_executables_lint_clean():
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    model.eval()
    eng = ServingEngine(model, slot_count=2, ladder=(8, 16), max_new_cap=8,
                        steps_per_dispatch=4)
    rng = np.random.RandomState(0)
    for n in (5, 12):
        eng.submit(rng.randint(0, 1024, (n,)).astype(np.int64),
                   max_new_tokens=4, temperature=0.0)
    eng.run()
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert any(lbl.startswith("serve.prefill_b") for lbl in rep.checked)
    assert any(lbl.startswith("serve.decode_") for lbl in rep.checked)


def test_analyze_is_dispatch_free_and_lowering_is_unchanged():
    """Bench sanity (satellite 5): analyze() AOT-lowers from the stashed
    ABSTRACT signatures — calling the stashed fn on ShapeDtypeStructs would
    throw, so a passing analyze() cannot have dispatched — and it leaves
    engine state and the lowered program byte-identical."""
    eng, arrays = _tiny_engine()
    eng.step(*arrays)
    step_before = eng._step_count
    loss_before = float(eng.last_loss.item())

    def lowered_text():
        jf = eng._build(arrays)
        return jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                        jnp.int32(1), jax.random.key(0), *arrays).as_text()

    before = lowered_text()
    rep = eng.analyze()
    assert rep.ok, rep.format()
    assert eng._step_count == step_before
    assert float(eng.last_loss.item()) == loss_before
    assert lowered_text() == before, (
        "engine.analyze() perturbed the lowered step program")


# ------------------------------------------------------------ observability

def test_violations_bump_monitor_and_metrics_counters():
    reg = metrics.enable()
    before = monitor.registry().report().get(
        "analysis.violations", {}).get("value", 0)
    rep = an.check_text("t", "  %all-reduce.1 = f32[4]{0} all-reduce(%x)\n",
                        an.ProgramContract(
                            collectives={"all-reduce": 0},
                            allow_host_calls=True, max_constant_bytes=None))
    assert not rep.ok
    after = monitor.registry().report()["analysis.violations"]["value"]
    assert after == before + 1
    assert reg.counter("analysis.violations").value == 1
    assert reg.counter(
        "analysis.violations.collective-contract").value == 1


def test_violation_triggers_named_flight_dump(tmp_path):
    flight_recorder.enable(str(tmp_path), capacity=8)
    rep = an.check_text("train.step",
                        "  %all-reduce.1 = f32[4]{0} all-reduce(%x)\n",
                        an.ProgramContract(
                            collectives={"all-reduce": 0},
                            allow_host_calls=True, max_constant_bytes=None))
    assert not rep.ok  # dump gated off by default flag
    assert not [d for d in os.listdir(tmp_path) if d.startswith("flight_")]
    paddle.set_flags({"analysis_flight_dump": True})
    try:
        an.check_text("train.step",
                      "  %all-reduce.1 = f32[4]{0} all-reduce(%x)\n",
                      an.ProgramContract(
                          collectives={"all-reduce": 0},
                          allow_host_calls=True, max_constant_bytes=None))
        dumps = [d for d in os.listdir(tmp_path) if d.startswith("flight_")]
        assert len(dumps) == 1
        assert "analysis_collective-contract_train_step" in dumps[0]
        state = json.load(
            open(os.path.join(tmp_path, dumps[0], "state.json")))
        assert state["extra"]["violations"][0]["pass"] == "collective-contract"
    finally:
        paddle.set_flags({"analysis_flight_dump": False})


# ------------------------------------------------------------- CLI contract

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _run_hlo_lint(*extra):
    return subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "hlo_lint.py"),
         "--seq", "64", "--batch", "2", *extra],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_hlo_lint_cli_exit_code_2_on_bad_args():
    """Exit 2 = error, distinct from 1 = violations. Bad arguments fail in
    argparse before any jax work, so this pin is cheap enough for tier-1."""
    err = _run_hlo_lint("--definitely-not-a-flag")
    assert err.returncode == 2


@pytest.mark.slow
def test_hlo_lint_cli_exit_codes_clean_and_dirty():
    """Pinned exit codes: 0 clean, 1 violations (--no-donate seeds a
    donation-leak)."""
    clean = _run_hlo_lint("--microbatches", "1")
    assert clean.returncode == 0, clean.stderr[-2000:]
    summary = json.loads(clean.stdout.strip().splitlines()[-1])["summary"]
    assert summary["kind"] == "hlo_lint" and summary["ok"]
    assert "train.step" in summary["checked"]

    dirty = _run_hlo_lint("--microbatches", "1", "--no-donate")
    assert dirty.returncode == 1, dirty.stderr[-2000:]
    summary = json.loads(dirty.stdout.strip().splitlines()[-1])["summary"]
    assert [v["pass"] for v in summary["violations"]] == ["donation-leak"]


@pytest.mark.slow
def test_hlo_lint_cli_serve_and_zero_paths():
    out = _run_hlo_lint("--microbatches", "2", "--serve", "--zero")
    assert out.returncode == 0, out.stderr[-2000:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])["summary"]
    checked = summary["checked"]
    assert any(c.startswith("train.zero_k2") for c in checked)
    assert any(c.startswith("serve.prefill_b") for c in checked)

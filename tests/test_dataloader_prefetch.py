"""Async input pipeline: DataLoader worker threads + DevicePrefetcher +
engine pre-placed batches.

Three layers under test (all JAX_PLATFORMS=cpu):
- io.DataLoader num_workers>0: a thread pool runs fetch + collate ahead of
  the consumer — sampler-order delivery, clean shutdown, exception
  propagation.
- distributed.DevicePrefetcher: bounded look-ahead of sharded device_put,
  skip for already-placed arrays, depth/h2d stats.
- TrainStepEngine: pre-placed batches train bit-identically to the sync
  path, skip the redundant device_put, and the telemetry records carry
  h2d_ms / prefetch_depth; a StepTelemetry comparison shows the prefetched
  pipeline's residual reader wait dropping vs the sync path.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset

import jax
import jax.numpy as jnp


class _IndexedDataset(Dataset):
    """Sample i is (features filled with i, label i%4) — order is checkable."""

    def __init__(self, n=64, delay=0.0):
        self.n = n
        self.delay = delay

    def __getitem__(self, i):
        if self.delay:
            time.sleep(self.delay)
        return (np.full((16,), float(i), np.float32),
                np.int64(i % 4))

    def __len__(self):
        return self.n


class _ExplodingDataset(_IndexedDataset):
    def __getitem__(self, i):
        if i == 19:
            raise RuntimeError("boom at 19")
        return super().__getitem__(i)


def _make_engine(seed=0):
    from paddle_tpu.distributed.engine import TrainStepEngine

    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss())


# ------------------------------------------------------------ DataLoader ----

def test_worker_pool_matches_sync_order():
    ds = _IndexedDataset(40)
    sync = [(np.asarray(x._data), np.asarray(y._data))
            for x, y in DataLoader(ds, batch_size=8, num_workers=0,
                                   use_buffer_reader=False)]
    pooled = [(np.asarray(x._data), np.asarray(y._data))
              for x, y in DataLoader(ds, batch_size=8, num_workers=3)]
    assert len(sync) == len(pooled) == 5
    for (xs, ys), (xp, yp) in zip(sync, pooled):
        np.testing.assert_array_equal(xs, xp)
        np.testing.assert_array_equal(ys, yp)


def test_worker_pool_exception_propagates():
    loader = DataLoader(_ExplodingDataset(40), batch_size=8, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 19"):
        for _ in loader:
            pass


def test_worker_pool_clean_shutdown_midstream():
    ds = _IndexedDataset(200, delay=0.001)
    it = iter(DataLoader(ds, batch_size=4, num_workers=2))
    next(it)  # consume one batch, then abandon the epoch
    threads = it._threads
    it.close()
    assert all(not t.is_alive() for t in threads)


def test_prefetch_iterator_close_stops_producer():
    loader = DataLoader(_IndexedDataset(400, delay=0.001), batch_size=4,
                        num_workers=0)  # buffered reader path (default)
    it = iter(loader)
    next(it)
    it.close()
    assert not it._thread.is_alive()


def test_num_workers_zero_no_buffer_is_plain_generator():
    """Disabled path: no threads, no queue — the exact inline iteration."""
    n0 = threading.active_count()
    loader = DataLoader(_IndexedDataset(16), batch_size=4, num_workers=0,
                        use_buffer_reader=False)
    batches = list(loader)
    assert len(batches) == 4
    assert threading.active_count() == n0


def test_reader_buffered_is_real_and_propagates():
    from paddle_tpu import reader as reader_mod

    produced = []

    def src():
        for i in range(10):
            produced.append(i)
            yield i

    buf = reader_mod.buffered(src, 4)
    out = list(buf())
    assert out == list(range(10))

    def bad():
        yield 1
        raise ValueError("reader died")

    with pytest.raises(ValueError, match="reader died"):
        list(reader_mod.buffered(bad, 2)())


# ------------------------------------------------------ DevicePrefetcher ----

def _cpu_sharding():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    return NamedSharding(mesh, P())


def test_device_prefetcher_depth_and_stats():
    from paddle_tpu.distributed import DevicePrefetcher

    s = _cpu_sharding()
    batches = [(np.full((4, 4), i, np.float32),) for i in range(6)]
    pf = DevicePrefetcher((s,), depth=3)
    seen = []
    for (a,) in pf.iterate(iter(batches)):
        assert pf.last_depth <= 3
        seen.append(float(np.asarray(a)[0, 0]))
    assert seen == [float(i) for i in range(6)]
    assert pf.batches == 6 and pf.puts == 6 and pf.skipped_puts == 0
    assert pf.h2d_ms_total >= 0.0
    # look-ahead was actually used: mid-stream batches had staged successors
    assert pf.last_depth >= 1


def test_device_prefetcher_skips_placed_arrays():
    from paddle_tpu.distributed import DevicePrefetcher
    from paddle_tpu.distributed.prefetcher import is_placed

    s = _cpu_sharding()
    pf = DevicePrefetcher((s,), depth=2)
    placed, _ = pf.place((np.ones((4, 4), np.float32),))
    assert pf.puts == 1 and is_placed(placed[0], s)
    again, _ = pf.place(placed)
    assert pf.puts == 1 and pf.skipped_puts == 1
    assert again[0] is placed[0]


# ------------------------------------------------------------- engine -------

def _batch_arrays(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    y = rng.randint(0, 4, (n,)).astype(np.int64)
    return x, y


def test_engine_prefetch_bit_identical_to_sync():
    from paddle_tpu.io import TensorDataset

    x, y = _batch_arrays(64)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])

    e1 = _make_engine()
    sync_losses = [float(e1.step(*b).item())
                   for b in DataLoader(ds, batch_size=16, num_workers=0,
                                       use_buffer_reader=False)]

    e2 = _make_engine()
    loader = DataLoader(ds, batch_size=16, num_workers=2)
    pre_losses = [float(e2.step(*b).item()) for b in e2.prefetch(loader)]

    assert sync_losses == pre_losses  # same program, same placement: exact
    assert e2.prefetcher is not None and e2.prefetcher.batches == 4


def test_engine_skips_put_for_preplaced_batches(monkeypatch):
    x, y = _batch_arrays(16)
    e = _make_engine()
    e.step(paddle.to_tensor(x), paddle.to_tensor(y))  # build + warm

    from paddle_tpu.distributed.prefetcher import DevicePrefetcher

    pf = DevicePrefetcher(e._shardings_for, depth=2)
    placed, _ = pf.place(e._to_arrays([paddle.to_tensor(x),
                                       paddle.to_tensor(y)]))

    calls = {"n": 0}
    real_put = jax.device_put

    def counting_put(*a, **kw):
        calls["n"] += 1
        return real_put(*a, **kw)

    monkeypatch.setattr(jax, "device_put", counting_put)
    e.step(*placed)
    assert calls["n"] == 0, "pre-placed batch must not be re-put"
    calls["n"] = 0
    e.step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert calls["n"] == 2, "sync path still places both batch arrays"


def test_telemetry_records_carry_h2d_and_depth():
    from paddle_tpu.observability.step_telemetry import (
        InMemorySink, StepTelemetry)

    x, y = _batch_arrays(32)
    e = _make_engine()
    sink = InMemorySink()
    e.telemetry = StepTelemetry(sink=sink)
    e.step(paddle.to_tensor(x[:16]), paddle.to_tensor(y[:16]))
    assert "h2d_ms" in sink.records[0]
    assert "prefetch_depth" not in sink.records[0]  # sync: no staging

    batches = [(x[:16], y[:16]), (x[16:], y[16:])]
    for b in e.prefetch(iter(batches)):
        e.step(*b)
    assert all("h2d_ms" in r and "prefetch_depth" in r
               for r in sink.records[1:])
    assert sink.records[1]["prefetch_depth"] >= 1

    # run_steps records h2d_ms too
    e.run_steps(paddle.to_tensor(x[:16]), paddle.to_tensor(y[:16]), steps=2)
    assert "h2d_ms" in sink.records[-1]


def test_prefetch_pipeline_drops_reader_wait():
    """The acceptance comparison: residual (non-overlapped) reader wait with
    the async pipeline vs the fully-sync path, recorded through
    StepTelemetry, on a small GPT config. The consumer emulates the bench
    regime (device step >> per-batch host cost) with a fixed sleep on top of
    the real engine step so the producer can run ahead."""
    from paddle_tpu.models import GPTConfig, GPTForPretraining
    from paddle_tpu.distributed.engine import TrainStepEngine
    from paddle_tpu.observability.step_telemetry import (
        InMemorySink, StepTelemetry)

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=32)

    class LMDataset(Dataset):
        def __getitem__(self, i):
            time.sleep(0.004)  # per-sample host fetch/decode cost
            rng = np.random.RandomState(i)
            ids = rng.randint(0, 128, (33,)).astype(np.int64)
            return ids[:32], ids[1:]

        def __len__(self):
            return 64

    def run(prefetched):
        paddle.seed(0)
        model = GPTForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        eng = TrainStepEngine(model, opt)
        sink = InMemorySink()
        eng.telemetry = StepTelemetry(sink=sink)
        if prefetched:
            loader = DataLoader(LMDataset(), batch_size=8, num_workers=2)
            it = eng.prefetch(loader)
        else:
            it = iter(DataLoader(LMDataset(), batch_size=8, num_workers=0,
                                 use_buffer_reader=False))
        waits = []
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            waits.append(time.perf_counter() - t0)
            eng.step(*batch)
            time.sleep(0.05)  # emulated device-bound step tail
        # skip batch 0: the pipeline has no look-ahead before the first fetch
        for w, rec in zip(waits[1:], sink.records[1:]):
            rec["reader_cost_s"] = w
        return sum(waits[1:]), sink.records

    sync_wait, sync_recs = run(prefetched=False)
    pre_wait, pre_recs = run(prefetched=True)
    assert len(sync_recs) == len(pre_recs) == 8
    # sync pays ~8 * 4ms of fetch per batch inline; the worker pool +
    # device prefetcher overlap it with the (slept) step: big margin
    assert pre_wait < 0.5 * sync_wait, (pre_wait, sync_wait)
    # prefetched steps carry the staging stats
    assert all("h2d_ms" in r and "prefetch_depth" in r for r in pre_recs[1:])


def test_engine_direct_step_path_untouched():
    """num_workers=0 / direct step(*batch): no prefetcher objects, no staged
    state — the disabled path stays the engine's plain sync behavior."""
    x, y = _batch_arrays(16)
    e = _make_engine()
    e.step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert e.prefetcher is None
    assert e._pending_h2d is None

"""Meta-optimizer suite: strategy compiler selection/chaining + each
meta-optimizer's training semantics (reference fleet/meta_optimizers/* and
strategy_compiler.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_optimizers import StrategyCompiler


def _net_and_data(seed=0, n=32):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    rs = np.random.RandomState(seed)
    x = paddle.to_tensor(rs.rand(n, 8).astype(np.float32))
    y = paddle.to_tensor((rs.rand(n, 1) > 0.5).astype(np.float32))
    return net, x, y


def _strategy(**flags):
    s = dist.DistributedStrategy()
    for k, v in flags.items():
        setattr(s, k, v)
    return s


class TestStrategyCompiler:
    def test_selection_and_order(self):
        net, _, _ = _net_and_data()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        s = _strategy(amp=True, gradient_merge=True, localsgd=True)
        s.gradient_merge_configs = {"k_steps": 4}
        final, applied = StrategyCompiler().compile(opt, s)
        # innermost-first application: comm policy (localsgd) sits inside the
        # step-frequency wrapper (gradient_merge), amp outermost
        assert applied == ["localsgd", "gradient_merge", "amp", "raw_program"]
        assert final.applied_meta_list[:3] == ["amp", "gradient_merge", "localsgd"]
        assert final._handles_dp_sync

    def test_conflict_resolution(self):
        net, _, _ = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters())
        s = _strategy(localsgd=True, dgc=True)
        final, applied = StrategyCompiler().compile(opt, s)
        # conflicting pair: exactly one survives (first in chain order wins)
        assert ("dgc" in applied) != ("localsgd" in applied)

    def test_lamb_swap(self):
        net, _, _ = _net_and_data()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        final, applied = StrategyCompiler().compile(opt, _strategy(lamb=True))
        assert "lamb" in applied
        inner = final
        while hasattr(inner, "_inner_opt"):
            inner = inner._inner_opt
        assert inner._rule == "lamb"

    def test_lars_swap(self):
        net, _, _ = _net_and_data()
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=net.parameters())
        final, applied = StrategyCompiler().compile(opt, _strategy(lars=True))
        inner = final
        while hasattr(inner, "_inner_opt"):
            inner = inner._inner_opt
        assert inner._rule == "lars"

    def test_fleet_distributed_optimizer_applies(self):
        fleet.init(is_collective=True, strategy=_strategy(gradient_merge=True))
        net, x, y = _net_and_data()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        s = _strategy(gradient_merge=True)
        s.gradient_merge_configs = {"k_steps": 2}
        wrapped = fleet.distributed_optimizer(opt, s)
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        wrapped.step()
        wrapped.clear_grad()
        assert "gradient_merge" in fleet.fleet._applied_meta_list


class TestGradientMerge:
    def test_updates_every_k(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        s = _strategy(gradient_merge=True)
        s.gradient_merge_configs = {"k_steps": 3, "avg": True}
        merged, _ = StrategyCompiler().compile(opt, s)
        w0 = net[0].weight.numpy().copy()
        for i in range(1, 7):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            merged.step()
            merged.clear_grad()
            changed = not np.allclose(net[0].weight.numpy(), w0)
            assert changed == (i % 3 == 0), (i, changed)  # updates only at 3, 6
            if changed:
                w0 = net[0].weight.numpy().copy()

    def test_merge_equals_big_batch(self):
        """k merged micro-batches ~ one batch over their union (SGD linearity)."""
        net1, x, y = _net_and_data(7, n=32)
        opt1 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net1.parameters())
        s = _strategy(gradient_merge=True)
        s.gradient_merge_configs = {"k_steps": 2, "avg": True}
        merged, _ = StrategyCompiler().compile(opt1, s)
        for half in (slice(0, 16), slice(16, 32)):
            loss = ((net1(x[half]) - y[half]) ** 2).mean()
            loss.backward()
            merged.step()
            merged.clear_grad()

        net2, x2, y2 = _net_and_data(7, n=32)  # same init, same data
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        loss = ((net2(x2) - y2) ** 2).mean()
        loss.backward()
        opt2.step()
        np.testing.assert_allclose(net1[0].weight.numpy(), net2[0].weight.numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestLocalSGD:
    def test_param_sync_noop_single_rank(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        s = _strategy(localsgd=True)
        s.localsgd_configs = {"k_steps": 2, "begin_step": 1}
        wrapped, _ = StrategyCompiler().compile(opt, s)
        for _ in range(4):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            wrapped.step()
            wrapped.clear_grad()
        assert np.isfinite(net[0].weight.numpy()).all()


class TestDGC:
    def test_sparsifies_grads(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters())
        s = _strategy(dgc=True)
        s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.75]}
        wrapped, _ = StrategyCompiler().compile(opt, s)
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        wrapped.step()
        # after step, grads were masked to ~25% density
        g = net[0].weight.grad.numpy()
        density = np.count_nonzero(g) / g.size
        assert density <= 0.30, density

    def test_residual_accumulates(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters())
        s = _strategy(dgc=True)
        s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.75]}
        wrapped, _ = StrategyCompiler().compile(opt, s)
        for _ in range(3):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            wrapped.step()
            wrapped.clear_grad()
        assert len(wrapped._residual) > 0


class TestFP16AllReduce:
    def test_grads_rounded_through_bf16(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.0,  # isolate the grad cast
                                   parameters=net.parameters())
        wrapped, applied = StrategyCompiler().compile(
            opt, _strategy(fp16_allreduce=True))
        assert "fp16_allreduce" in applied
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        g_before = net[0].weight.grad.numpy().copy()
        wrapped.step()
        g_after = net[0].weight.grad.numpy()
        import ml_dtypes

        np.testing.assert_array_equal(
            g_after, g_before.astype(ml_dtypes.bfloat16).astype(np.float32))


class TestAMPScaleContract:
    def test_fp16_unscale_only_after_scale(self):
        """step() without scale() must not divide unscaled grads (a plain
        loss.backward(); step() flow with an fp16 scaler configured)."""
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=net.parameters())
        s = _strategy(amp=True)
        s.amp_configs = {"dtype": "float16"}
        wrapped, _ = StrategyCompiler().compile(opt, s)
        assert wrapped._scaler._enable
        w0 = net[0].weight.numpy().copy()
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        g = net[0].weight.grad.numpy().copy()
        wrapped.step()          # no scale() happened -> plain step
        np.testing.assert_allclose(net[0].weight.numpy(), w0 - g, rtol=1e-5,
                                   atol=1e-7)
        wrapped.clear_grad()
        # scaled flow: scale().backward() then step() lands on the same update
        w1 = net[0].weight.numpy().copy()
        loss = ((net(x) - y) ** 2).mean()
        wrapped.scale(loss).backward()
        wrapped.step()
        g2 = net[0].weight.numpy() - w1
        # update magnitude ~ lr * grad, NOT 32768x larger (scale round-trips)
        assert np.abs(g2).max() < np.abs(g).max() * 50, np.abs(g2).max()


class TestAMPMeta:
    def test_amp_context_casts(self):
        net, x, y = _net_and_data()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        s = _strategy(amp=True)
        wrapped, applied = StrategyCompiler().compile(opt, s)
        assert "amp" in applied
        with wrapped.amp_context():
            out = net(x)
        assert out.dtype == paddle.bfloat16
        # bf16 on TPU: no loss scaling engaged
        assert not wrapped._scaler._enable
        # fp16 config turns scaling on
        s2 = _strategy(amp=True)
        s2.amp_configs = {"dtype": "float16"}
        w2, _ = StrategyCompiler().compile(opt, s2)
        assert w2._scaler._enable

    def test_engine_amp_trace(self):
        """strategy.amp reaches the pjit step: matmuls run bf16 inside."""
        fleet.init(is_collective=True, strategy=_strategy())
        net, x, y = _net_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        s = _strategy(amp=True)
        fleet.fleet._strategy = s
        engine = fleet.distributed_engine(net, opt,
                                          loss_fn=lambda out: ((out) ** 2).mean())
        l0 = float(engine.step(x).item())
        l1 = float(engine.step(x).item())
        assert np.isfinite([l0, l1]).all() and l1 < l0


class TestRecomputeMeta:
    def test_enables_model_flags(self):
        from paddle_tpu.models import GPTForPretraining, gpt_tiny

        paddle.seed(0)
        model = GPTForPretraining(gpt_tiny())
        assert not model.gpt.blocks[0].use_recompute
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        s = _strategy(recompute=True)
        fleet.init(is_collective=True, strategy=s)
        fleet.distributed_optimizer(opt, s, model=model)
        assert model.gpt.blocks[0].use_recompute


class TestLarsTraining:
    def test_lars_converges(self):
        # lr/coeff calibrated for the trust ratio: LARS scales each layer's
        # step to ~lr * lars_coeff * ||w||, so the reference default
        # coeff=0.001 moves weights ~2e-5*||w||/step — at 20 steps the loss
        # floor reachable was the constant predictor, exactly the old 0.8
        # threshold (the test failed by construction). coeff=0.1 at lr=0.1
        # converges to ~25% of the initial loss across seeds in 40 steps.
        net, x, y = _net_and_data()
        opt = paddle.optimizer.Lars(learning_rate=0.1, lars_coeff=0.1,
                                    parameters=net.parameters())
        losses = []
        for _ in range(40):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

"""jit.save/load (StableHLO artifact) + inference Predictor.

Mirrors reference tests test_jit_save_load.py / inference api tests (save an
inference model, load WITHOUT model code, outputs match eager)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.static import InputSpec


def make_net():
    paddle.seed(7)
    return paddle.nn.Sequential(
        paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
        paddle.nn.LayerNorm(32), paddle.nn.Linear(32, 5))


def test_save_load_output_parity(tmp_path):
    net = make_net()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([4, 8], "float32")])

    loaded = paddle.jit.load(path)
    x = np.random.RandomState(0).randn(4, 8).astype("float32")
    eager = net(paddle.to_tensor(x)).numpy()
    out = loaded(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, eager, rtol=2e-5, atol=1e-6)


def test_loaded_layer_needs_no_model_code(tmp_path):
    """The artifact must run via a fresh TranslatedLayer with no Layer class."""
    net = make_net()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])
    del net

    loaded = paddle.jit.load(path)
    out = loaded(np.zeros((2, 8), dtype="float32"))
    assert tuple(out.shape) == (2, 5)
    assert len(loaded.parameters()) > 0
    with pytest.raises(RuntimeError, match="inference-only"):
        loaded.train()


def test_save_respects_eval_mode_dropout(tmp_path):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.Dropout(0.9))
    net.train()  # jit.save must trace in eval mode regardless
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([3, 4], "float32")])
    assert net.training  # restored
    loaded = paddle.jit.load(path)
    x = np.ones((3, 4), dtype="float32")
    o1, o2 = loaded(x).numpy(), loaded(x).numpy()
    np.testing.assert_array_equal(o1, o2)  # no dropout randomness in the artifact


def test_predictor_handle_api(tmp_path):
    net = make_net()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([4, 8], "float32")])

    config = Config(path + ".pdmodel")
    predictor = create_predictor(config)
    names = predictor.get_input_names()
    assert names == ["input_0"]
    x = np.random.RandomState(1).randn(4, 8).astype("float32")
    predictor.get_input_handle("input_0").copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle("output_0").copy_to_cpu()
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=2e-5, atol=1e-6)


def test_predictor_run_with_inputs_shortcut(tmp_path):
    net = make_net()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 8], "float32")])
    predictor = create_predictor(Config(path))
    outs = predictor.run([np.zeros((1, 8), dtype="float32")])
    assert outs[0].shape == (1, 5)


def test_save_requires_input_spec(tmp_path):
    with pytest.raises(ValueError, match="input_spec"):
        paddle.jit.save(make_net(), str(tmp_path / "m"))


def test_dynamic_batch_dim_exports_symbolically(tmp_path):
    """InputSpec([None, 8]) must serve ANY batch size, not freeze batch=1."""
    net = make_net()
    path = str(tmp_path / "dyn")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8], "float32")])
    loaded = paddle.jit.load(path)
    for bs in (1, 4, 7):
        x = np.random.RandomState(bs).randn(bs, 8).astype("float32")
        out = loaded(x)
        assert tuple(out.shape) == (bs, 5)
        np.testing.assert_allclose(out.numpy(), net(paddle.to_tensor(x)).numpy(),
                                   rtol=2e-5, atol=1e-6)


def test_predictor_rejects_wrong_input_count(tmp_path):
    net = make_net()
    path = str(tmp_path / "m2")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 8], "float32")])
    predictor = create_predictor(Config(path))
    with pytest.raises(ValueError, match="got 2 inputs"):
        predictor.run([np.zeros((1, 8), "float32"), np.zeros((1, 8), "float32")])

"""GPT autoregressive generation: KV-cache decode in one jitted scan.

The cache path must be numerically identical to full-prefix recompute — each
greedy step's token is checked against running the whole growing sequence
through the cacheless forward.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForPretraining, gpt_tiny


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


def _greedy_reference(model, ids, n_new):
    """Cacheless oracle: recompute the full prefix each step, argmax."""
    cur = ids.copy()
    for _ in range(n_new):
        logits = model(paddle.to_tensor(cur)).numpy()  # [b, s, vocab]
        nxt = logits[:, -1].argmax(-1).astype(cur.dtype)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    return cur


def test_greedy_cache_matches_full_recompute(model):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (2, 7)).astype(np.int64)
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                         temperature=0).numpy()
    expect = _greedy_reference(model, ids, 6)
    np.testing.assert_array_equal(out, expect)


def test_generate_shapes_and_determinism(model):
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (3, 5)).astype(np.int64)
    a = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       temperature=0.8, top_k=50, seed=7).numpy()
    b = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       temperature=0.8, top_k=50, seed=7).numpy()
    assert a.shape == (3, 9)
    np.testing.assert_array_equal(a, b)       # same seed, same sample
    np.testing.assert_array_equal(a[:, :5], ids)  # prompt preserved
    c = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       temperature=0.8, top_k=50, seed=8).numpy()
    assert not np.array_equal(a, c)           # different seed varies


def test_generate_single_token(model):
    ids = np.array([[1, 2, 3]], np.int64)
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=1,
                         temperature=0).numpy()
    assert out.shape == (1, 4)


def test_eos_sticks(model):
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 1024, (2, 4)).astype(np.int64)
    # force eos to whatever greedy emits first: then ALL later tokens = eos
    first = model.generate(paddle.to_tensor(ids), max_new_tokens=1,
                           temperature=0).numpy()[:, -1]
    eos = int(first[0])
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=8,
                         temperature=0, eos_token_id=eos).numpy()
    row = out[0, 4:]
    after = np.where(row == eos)[0]
    assert len(after) > 0
    np.testing.assert_array_equal(row[after[0]:],
                                  np.full(len(row) - after[0], eos))


def test_top_p_filtering_valid(model):
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 1024, (2, 4)).astype(np.int64)
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=3,
                         temperature=1.0, top_p=0.5, seed=1).numpy()
    assert out.shape == (2, 7)
    assert (out >= 0).all() and (out < model.config.vocab_size).all()


def test_length_guard(model):
    ids = np.zeros((1, 4), np.int64)
    with pytest.raises(ValueError, match="max_seq_len"):
        model.generate(paddle.to_tensor(ids),
                       max_new_tokens=model.config.max_seq_len)


def test_generate_under_amp_caches_separately():
    """Tracing generate under paddle.amp.auto_cast bakes bf16 matmuls into
    the decode executable; the amp scope must be part of the registry key
    so f32 and bf16 programs never collide."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 100, (2, 8)).astype(np.int64))
    out_f32 = m.generate(ids, max_new_tokens=4, temperature=0)
    with paddle.amp.auto_cast(dtype="bfloat16"):
        out_bf16 = m.generate(ids, max_new_tokens=4, temperature=0)
    assert out_bf16.shape == out_f32.shape == [2, 12]
    # two distinct cached executables (amp state in the key)
    assert len(m.decode_exec_registry()) == 2
    # prompts are echoed verbatim either way
    np.testing.assert_array_equal(out_bf16.numpy()[:, :8], ids.numpy())


# ---- round 6 satellites: prompt bucketing, LRU jit cache, top-k clamp ------
def test_prompt_bucket_identical_tokens_and_shared_executable(model):
    """prompt_bucket right-pads to the rung but must emit IDENTICAL tokens
    to the unpadded run (greedy), and every prompt length in a bucket must
    share ONE executable (keyed on the rung, prompt length traced)."""
    rng = np.random.RandomState(11)
    model.decode_exec_registry().clear()
    for plen in (3, 5, 7, 8):                 # all land in the 8-rung
        ids = rng.randint(0, 1024, (2, plen)).astype(np.int64)
        plain = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                               temperature=0).numpy()
        bucketed = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                                  temperature=0,
                                  prompt_bucket=(8, 16, 32)).numpy()
        np.testing.assert_array_equal(plain, bucketed)
    # 4 exact-shape executables + ONE shared bucketed executable
    keys = list(model.decode_exec_registry().keys())
    assert len(keys) == 5
    # sampling under a bucket is deterministic per seed too
    ids = rng.randint(0, 1024, (1, 5)).astype(np.int64)
    a = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       temperature=0.8, top_k=20, seed=3,
                       prompt_bucket=16).numpy()
    b = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       temperature=0.8, top_k=20, seed=3,
                       prompt_bucket=16).numpy()
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a[:, :5], ids)  # unpadded prompt echoed
    assert a.shape == (1, 9)


def test_prompt_bucket_validation(model):
    ids = np.zeros((1, 20), np.int64)
    with pytest.raises(ValueError, match="exceeds"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                       prompt_bucket=16)          # prompt 20 > rung 16
    with pytest.raises(ValueError, match="max_seq_len"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=8,
                       prompt_bucket=128)         # 128 + 8 > max_seq_len
    with pytest.raises(ValueError, match="beam_search"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=4, num_beams=2,
                       prompt_bucket=32)


def test_generate_jit_cache_lru_bounded(model):
    """The per-model decode-executable dict is LRU-bounded by
    FLAGS_decode_jit_cache_size; evictions and compiles count in
    core.monitor (decode.cache_evictions / decode.jit_compiles)."""
    from paddle_tpu.core import monitor

    def counter(name):
        return monitor.registry().report().get(name, {}).get("value", 0)

    ids = paddle.to_tensor(np.random.RandomState(12).randint(
        0, 1024, (1, 4)).astype(np.int64))
    old = paddle.get_flags(
        ["decode_jit_cache_size"])["FLAGS_decode_jit_cache_size"]
    try:
        paddle.set_flags({"decode_jit_cache_size": 2})
        model.decode_exec_registry().clear()
        c0 = counter("decode.jit_compiles")
        e0 = counter("decode.cache_evictions")
        for t in (0.5, 0.6, 0.7, 0.8):        # 4 configs, bound 2
            model.generate(ids, max_new_tokens=2, temperature=t, seed=1)
        assert len(model.decode_exec_registry()) == 2
        assert counter("decode.jit_compiles") - c0 == 4
        assert counter("decode.cache_evictions") - e0 == 2
        # LRU: most recent configs survive — no recompile on re-use
        c1 = counter("decode.jit_compiles")
        model.generate(ids, max_new_tokens=2, temperature=0.8, seed=1)
        assert counter("decode.jit_compiles") == c1
        # beam executables share the same bounded cache
        model.generate(ids, max_new_tokens=2, num_beams=2)
        assert len(model.decode_exec_registry()) == 2
    finally:
        paddle.set_flags({"decode_jit_cache_size": old})
        model.decode_exec_registry().clear()


def test_top_k_clamped_to_vocab(model):
    """top_k >= vocab must mean 'keep everything' (identical to top_k ==
    vocab), not an out-of-range sort index."""
    ids = paddle.to_tensor(np.random.RandomState(13).randint(
        0, 1024, (2, 5)).astype(np.int64))
    v = model.config.vocab_size
    exact = model.generate(ids, max_new_tokens=4, temperature=0.9,
                           top_k=v, seed=5).numpy()
    huge = model.generate(ids, max_new_tokens=4, temperature=0.9,
                          top_k=10 * v, seed=5).numpy()
    np.testing.assert_array_equal(exact, huge)
    assert (huge >= 0).all() and (huge < v).all()


# ---- round 4: beam search (one-scan, beam dim in the KV cache) -------------
def test_beam_search_beats_or_matches_greedy_logprob():
    """Beam-K's selected sequence must score >= greedy's under the model's
    own sequence log-probability (the defining property of beam search),
    verified with an independent full-forward log-prob oracle."""
    import scipy.special as sp

    cfg = gpt_tiny()
    paddle.seed(0)
    m = GPTForPretraining(cfg)
    m.eval()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 8)).astype(np.int64)
    pt = paddle.to_tensor(ids)
    greedy = m.generate(pt, max_new_tokens=6, temperature=0).numpy()
    beam = m.generate(pt, max_new_tokens=6, decode_strategy="beam_search",
                      num_beams=4).numpy()
    assert beam.shape == greedy.shape == (2, 14)
    assert (beam[:, :8] == ids).all()

    def seq_logprob(full):
        logits = m.logits(paddle.to_tensor(full[None, :-1])).numpy()[0]
        lp = 0.0
        for t in range(7, full.shape[0] - 1):
            lp += (logits[t] - sp.logsumexp(logits[t]))[full[t + 1]]
        return lp

    for r in range(2):
        assert seq_logprob(beam[r]) >= seq_logprob(greedy[r]) - 1e-4


def test_beam_search_eos_freezes_and_pads():
    cfg = gpt_tiny()
    paddle.seed(0)
    m = GPTForPretraining(cfg)
    m.eval()
    ids = np.random.RandomState(1).randint(
        0, cfg.vocab_size, (2, 8)).astype(np.int64)
    pt = paddle.to_tensor(ids)
    probe = m.generate(pt, max_new_tokens=6, temperature=0).numpy()
    eos = int(probe[0, 9])
    out = m.generate(pt, max_new_tokens=6, num_beams=3,
                     eos_token_id=eos).numpy()
    for row in out[:, 8:]:
        lst = row.tolist()
        if eos in lst:
            i = lst.index(eos)
            assert all(x == eos for x in lst[i:]), lst


def test_generate_decode_strategy_routing():
    cfg = gpt_tiny()
    paddle.seed(0)
    m = GPTForPretraining(cfg)
    m.eval()
    ids = np.random.RandomState(2).randint(
        0, cfg.vocab_size, (1, 8)).astype(np.int64)
    pt = paddle.to_tensor(ids)
    g1 = m.generate(pt, max_new_tokens=4, temperature=0).numpy()
    g2 = m.generate(pt, max_new_tokens=4,
                    decode_strategy="greedy_search").numpy()
    np.testing.assert_array_equal(g1, g2)
    with pytest.raises(ValueError, match="decode_strategy"):
        m.generate(pt, max_new_tokens=4, decode_strategy="nope")


def test_generate_beam_routing_validation():
    # round-4 review: explicit non-beam strategy must not be silently
    # overridden by num_beams, and beam_search rejects num_beams < 2
    cfg = gpt_tiny()
    paddle.seed(0)
    m = GPTForPretraining(cfg)
    m.eval()
    pt = paddle.to_tensor(np.random.RandomState(3).randint(
        0, cfg.vocab_size, (1, 8)).astype(np.int64))
    with pytest.raises(ValueError, match="conflicts"):
        m.generate(pt, max_new_tokens=4, decode_strategy="greedy_search",
                   num_beams=4)
    with pytest.raises(ValueError, match="num_beams >= 2"):
        m.generate(pt, max_new_tokens=4, decode_strategy="beam_search",
                   num_beams=1)
    with pytest.raises(ValueError, match="decode_strategy"):
        m.generate(pt, max_new_tokens=4, decode_strategy="typo", num_beams=2)

"""Eager dispatch rule cache (FLAGS_eager_op_jit): correctness of the cache key.

The cached (fwd, bwd) pair must never alias two semantically different
kernels — closure scalars, attrs, shapes/dtypes, and trace-time flags are all
part of the key; anything unhashable (arrays in closures) must bypass caching.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import dispatch
from paddle_tpu.core.tensor import Tensor

import jax.numpy as jnp


def setup_function(_):
    dispatch._clear_rule_cache()


def test_cache_hit_same_kernel():
    a = Tensor(jnp.ones((4,)), stop_gradient=False)

    def call():
        return dispatch.apply("t_scale2", lambda x: x * 2.0, [a])

    out1 = call()
    n1 = len(dispatch._RULE_CACHE)
    out2 = call()
    assert len(dispatch._RULE_CACHE) == n1 == 1  # second call hit
    np.testing.assert_allclose(out2.numpy(), 2 * np.ones(4))


def test_closure_scalar_changes_key():
    a = Tensor(jnp.ones((4,)), stop_gradient=False)

    def make(scale):
        def kernel(x):
            return x * scale
        return kernel

    out2 = dispatch.apply("t_scale", make(2.0), [a])
    out3 = dispatch.apply("t_scale", make(3.0), [a])
    np.testing.assert_allclose(out2.numpy(), 2 * np.ones(4))
    np.testing.assert_allclose(out3.numpy(), 3 * np.ones(4))  # no stale hit
    assert len(dispatch._RULE_CACHE) == 2


def test_array_closure_bypasses_cache():
    a = Tensor(jnp.ones((4,)), stop_gradient=False)
    shift = jnp.arange(4.0)

    def kernel(x):
        return x + shift  # array closure: _freeze must refuse

    out = dispatch.apply("t_shift", kernel, [a])
    assert len(dispatch._RULE_CACHE) == 0
    np.testing.assert_allclose(out.numpy(), 1 + np.arange(4.0))


def test_attrs_and_shapes_in_key():
    a = Tensor(jnp.ones((2, 3)), stop_gradient=False)
    b = Tensor(jnp.ones((3, 2)), stop_gradient=False)

    def kernel(x, axis):
        return jnp.sum(x, axis=axis)

    o1 = dispatch.apply("t_sum", kernel, [a], {"axis": 0})
    o2 = dispatch.apply("t_sum", kernel, [b], {"axis": 0})
    o3 = dispatch.apply("t_sum", kernel, [a], {"axis": 1})
    assert list(o1.shape) == [3] and list(o2.shape) == [2] and list(o3.shape) == [2]
    assert len(dispatch._RULE_CACHE) == 3  # distinct shapes/attrs, distinct rules


def test_cached_backward_matches_uncached():
    rng = np.random.RandomState(0)
    an, bn = rng.randn(8, 8).astype(np.float32), rng.randn(8, 8).astype(np.float32)

    def run(flag_on):
        paddle.set_flags({"eager_op_jit": flag_on})
        try:
            a = paddle.to_tensor(an, stop_gradient=False)
            b = paddle.to_tensor(bn, stop_gradient=False)
            loss = (paddle.matmul(a, b) ** 2).mean()
            loss.backward()
            return loss.numpy(), a.grad.numpy(), b.grad.numpy()
        finally:
            paddle.set_flags({"eager_op_jit": True})

    l1, ga1, gb1 = run(True)
    l2, ga2, gb2 = run(False)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    np.testing.assert_allclose(ga1, ga2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gb1, gb2, rtol=1e-5, atol=1e-6)


def test_flag_toggle_invalidates():
    a = Tensor(jnp.ones((4, 4)), stop_gradient=False)
    out1 = dispatch.apply("t_mm", lambda x: jnp.matmul(x, x), [a])
    n1 = len(dispatch._RULE_CACHE)
    paddle.set_flags({"tpu_matmul_precision": "highest"})
    try:
        out2 = dispatch.apply("t_mm", lambda x: jnp.matmul(x, x), [a])
        assert len(dispatch._RULE_CACHE) == n1 + 1  # new key under new flag
    finally:
        paddle.set_flags({"tpu_matmul_precision": "default"})


def test_value_dependent_kernel_falls_back():
    """Kernels whose output shape depends on array VALUES can't be traced;
    the cache must mark them uncacheable and run them eagerly, forever."""
    ids = Tensor(jnp.asarray(np.array([0, 0, 1], np.int64)))

    def kernel(i):
        n = int(jnp.max(i)) + 1  # concretization: untraceable
        return jnp.zeros((n,))

    out = dispatch.apply("t_valdep", kernel, [ids], differentiable=False)
    assert list(out.shape) == [2]
    key = [k for k in dispatch._RULE_CACHE][0]
    assert dispatch._RULE_CACHE[key] is None  # marked uncacheable
    out2 = dispatch.apply("t_valdep", kernel, [ids], differentiable=False)
    assert list(out2.shape) == [2]


def test_multi_output_int_cotangent_topk():
    """topk returns (float, int64) — the int output's float0 cotangent can't
    enter the jitted cached backward; the wrapper must fall back cleanly."""
    x = paddle.to_tensor(np.array([3.0, 1.0, 2.0, 5.0], np.float32),
                         stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g, [1.0, 0.0, 0.0, 1.0])


def test_autotune_config_invalidates_rules():
    from paddle_tpu.core import autotune as at

    a = Tensor(jnp.ones((4, 4)), stop_gradient=False)
    dispatch.apply("t_at", lambda x: jnp.matmul(x, x), [a])
    assert len(dispatch._RULE_CACHE) == 1
    at.set_config({"kernel": {"enable": False}})  # bump -> on_change clears
    assert len(dispatch._RULE_CACHE) == 0  # stale traces dropped wholesale
    assert len(dispatch._FREEZE_MEMO) == 0  # the freeze memo goes with it
    dispatch.apply("t_at", lambda x: jnp.matmul(x, x), [a])
    assert len(dispatch._RULE_CACHE) == 1  # rebuilt fresh


def test_freeze_memo_short_circuits_steady_state(monkeypatch):
    """Cache hits must not re-freeze the kernel's closure/defaults: after the
    first call the frozen projection is memoized per code object and the hit
    path does zero _freeze walks (perf_opt PR 2 satellite)."""
    a = Tensor(jnp.ones((4,)), stop_gradient=False)
    scale = 2.5

    def kernel(x):
        return x * scale  # one closure cell

    dispatch.apply("t_memo", kernel, [a])
    assert id(kernel.__code__) in dispatch._FREEZE_MEMO
    calls = {"n": 0}
    real = dispatch._freeze

    def counting(v):
        calls["n"] += 1
        return real(v)

    monkeypatch.setattr(dispatch, "_freeze", counting)
    out = dispatch.apply("t_memo", kernel, [a])
    assert calls["n"] == 0  # memo hit: no re-freeze on the hot path
    np.testing.assert_allclose(out.numpy(), 2.5 * np.ones(4))


def test_freeze_memo_nonlocal_rebind_not_stale():
    """A nonlocal rebind changes the cell CONTENT object, which must miss the
    identity-checked memo — a stale frozen value would alias two different
    kernels under one rule."""
    a = Tensor(jnp.ones((4,)), stop_gradient=False)

    def make():
        s = 2.0

        def kernel(x):
            return x * s

        def rebind(v):
            nonlocal s
            s = v

        return kernel, rebind

    kernel, rebind = make()
    o1 = dispatch.apply("t_rebind", kernel, [a])
    rebind(3.0)
    o2 = dispatch.apply("t_rebind", kernel, [a])
    np.testing.assert_allclose(o1.numpy(), 2 * np.ones(4))
    np.testing.assert_allclose(o2.numpy(), 3 * np.ones(4))
    assert len(dispatch._RULE_CACHE) == 2  # two distinct keys, no aliasing

"""OpTest harness — the single most important test machinery to replicate from the reference
(python/paddle/fluid/tests/unittests/op_test.py:289): numpy-reference forward checks
(`check_output`) and numeric-vs-analytic gradient checks (`check_grad`) against the XLA
lowerings, on every available place."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


# Per-op tolerance white-list (reference op_test.py keeps per-op thresholds
# for ops whose numerics are legitimately looser — iterative/decomposition
# kernels, large reductions). Keys are op function names; entries override the
# check_output/check_grad defaults unless the caller passes explicit values.
OP_TOLERANCES = {
    "erfinv": dict(rtol=2e-5, atol=2e-5),       # rational-approx inverse
    "digamma": dict(rtol=2e-5, atol=2e-5),      # series expansion
    "matrix_power": dict(rtol=1e-4, atol=1e-5),  # repeated-squaring error
    "matrix_rank": dict(rtol=1e-4, atol=1e-5),   # svd threshold
    "lstsq": dict(rtol=1e-4, atol=1e-4),
    "svd": dict(rtol=1e-4, atol=1e-5),
    "eigh": dict(rtol=1e-4, atol=1e-4),
    "conv2d_transpose": dict(rtol=1e-4, atol=1e-5),  # large accumulations
    "conv3d_transpose": dict(rtol=1e-4, atol=1e-5),
    "logsumexp": dict(grad_rtol=1e-2),
    "cumprod": dict(grad_rtol=1e-2, grad_atol=1e-3),  # product chains
}

_SENTINEL = object()


def _tol(op_fn, kind, passed, default):
    if passed is not _SENTINEL:
        return passed
    name = getattr(op_fn, "__name__", "")
    return OP_TOLERANCES.get(name, {}).get(kind, default)


def check_output(op_fn, np_ref, inputs, attrs=None, rtol=_SENTINEL,
                 atol=_SENTINEL):
    """Run op_fn(*tensors, **attrs) and compare with np_ref(*numpy_inputs, **attrs)."""
    attrs = attrs or {}
    rtol = _tol(op_fn, "rtol", rtol, 1e-5)
    atol = _tol(op_fn, "atol", atol, 1e-6)
    tensors = [paddle.to_tensor(i) if isinstance(i, np.ndarray) else i for i in inputs]
    out = op_fn(*tensors, **attrs)
    expect = np_ref(*[np.asarray(i) for i in inputs], **attrs)
    _compare(out, expect, rtol, atol, name=getattr(op_fn, "__name__", str(op_fn)))
    return out


def _compare(out, expect, rtol, atol, name=""):
    if isinstance(out, (tuple, list)):
        assert isinstance(expect, (tuple, list)), f"{name}: output arity mismatch"
        for o, e in zip(out, expect):
            _compare(o, e, rtol, atol, name)
        return
    got = out.numpy() if isinstance(out, Tensor) else np.asarray(out)
    expect = np.asarray(expect)
    assert got.shape == expect.shape, f"{name}: shape {got.shape} vs {expect.shape}"
    if np.iscomplexobj(got) or np.iscomplexobj(expect):
        # keep complex: casting to float64 would silently drop the imaginary
        # part and make e.g. a conj check vacuous
        np.testing.assert_allclose(got.astype(np.complex128),
                                   expect.astype(np.complex128),
                                   rtol=rtol, atol=atol, err_msg=f"op {name}")
        return
    np.testing.assert_allclose(got.astype(np.float64), expect.astype(np.float64),
                               rtol=rtol, atol=atol, err_msg=f"op {name}")


def check_grad(op_fn, inputs, attrs=None, input_idx=0, eps=1e-3, rtol=_SENTINEL,
               atol=_SENTINEL, reduce_to_scalar=True):
    """Numeric (central difference) vs analytic (tape backward) gradient check."""
    attrs = attrs or {}
    rtol = _tol(op_fn, "grad_rtol", rtol, 5e-3)
    atol = _tol(op_fn, "grad_atol", atol, 5e-4)
    # integer inputs (indices) keep their dtype and never get differentiated
    np_inputs = [np.asarray(i) if np.issubdtype(np.asarray(i).dtype, np.integer)
                 else np.asarray(i, np.float64) for i in inputs]
    assert np.issubdtype(np_inputs[input_idx].dtype, np.floating), (
        "check_grad target input must be floating point")

    def run(np_vals):
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Tensor as _T

        tensors = []
        for k, v in enumerate(np_vals):
            # float64 on the CPU test mesh so central differences aren't drowned by
            # rounding (x64 is enabled by paddle_tpu; to_tensor would demote to f32).
            # jnp.array (not asarray): asarray can alias the numpy buffer zero-copy on
            # CPU, and this harness mutates the buffers in the numeric-diff loop.
            t = _T(jnp.array(v, None if np.issubdtype(v.dtype, np.integer)
                             else jnp.float64))
            t.stop_gradient = k != input_idx
            tensors.append(t)
        out = op_fn(*tensors, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        loss = out.sum() if reduce_to_scalar else out
        return loss, tensors[input_idx]

    loss, target = run(np_inputs)
    loss.backward()
    analytic = target.grad.numpy().astype(np.float64)

    numeric = np.zeros_like(np_inputs[input_idx])
    flat = numeric.reshape(-1)
    base = np_inputs[input_idx].reshape(-1)
    for i in range(flat.size):
        # sync (.item) BEFORE the next in-place mutation of `base`: jax may
        # defer the host-buffer copy of a jnp.array input under async dispatch,
        # so mutating before the previous evaluation completes races with it
        orig = base[i]
        base[i] = orig + eps
        lp = float(run(np_inputs)[0].item())
        base[i] = orig - eps
        lm = float(run(np_inputs)[0].item())
        base[i] = orig
        flat[i] = (lp - lm) / (2 * eps)

    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                               err_msg=f"grad check {getattr(op_fn, '__name__', op_fn)}")

"""TCPStore (C++ native + Python fallback): single- and multi-process semantics.

Mirrors reference tests for distributed/store (set/get/wait/add, cross-process
rendezvous on localhost ports; reference test_dist_base.py spawns subprocess
clusters the same way)."""
import multiprocessing as mp
import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.store import FileStore, TCPStore


@pytest.fixture(scope="module")
def store():
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=20.0)
    yield s


def test_native_library_builds():
    from paddle_tpu.core.native import load_library

    assert load_library("tcp_store") is not None, "C++ TCPStore must build here"


def test_set_get_roundtrip(store):
    store.set("k1", b"hello")
    assert store.get("k1") == b"hello"
    store.set("k1", "overwritten")  # str values are encoded
    assert store.get("k1") == b"overwritten"


def test_large_value_grows_buffer(store):
    big = os.urandom(300_000)
    store.set("big", big)
    assert store.get("big") == big


def test_add_counter(store):
    assert store.add("ctr", 1) == 1
    assert store.add("ctr", 5) == 6
    assert store.add("ctr", -2) == 4
    assert store.get("ctr") == b"4"


def test_get_nowait_missing_raises(store):
    with pytest.raises(KeyError):
        store.get("missing-key", wait=False)


def test_wait_timeout(store):
    with pytest.raises(TimeoutError):
        store.wait(["never-set"], timeout=0.3)


def test_num_keys_and_delete(store):
    before = store.num_keys()
    store.set("del-me", b"x")
    assert store.num_keys() == before + 1
    assert store.delete_key("del-me")
    assert not store.delete_key("del-me")
    assert store.num_keys() == before


def test_list_prefix(store):
    store.set("nodes/0", b"a")
    store.set("nodes/1", b"b")
    store.set("other", b"c")
    keys = store.list_keys("nodes/")
    assert sorted(keys) == ["nodes/0", "nodes/1"]


_WORKER = textwrap.dedent("""
    import sys, time
    from paddle_tpu.distributed.store import TCPStore

    rank, world, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    # generous timeout: 4 interpreters cold-start SERIALLY on a loaded 1-core
    # box (each pays the jax import), so the non-master clients can sit tens
    # of seconds ahead of rank 0's bind — 30 s flaked in full-suite runs
    store = TCPStore("127.0.0.1", port, is_master=(rank == 0), world_size=world,
                     timeout=150.0)
    store.set(f"rank/{rank}", str(rank))
    # everyone reads everyone (get blocks until the key appears)
    total = sum(int(store.get(f"rank/{r}")) for r in range(world))
    assert total == sum(range(world)), total
    n = store.add("joined", 1)
    store.barrier("end", world)
    print(f"rank{rank} OK total={total}")
""")


def test_multiprocess_rendezvous(tmp_path):
    """4 processes rendezvous through rank-0's server, cross-set keys, barrier."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    world = 4
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    # the worker script lives in tmp_path, so sys.path[0] won't contain the
    # repo — put it on PYTHONPATH explicitly instead of relying on the
    # invoking environment having done so
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pp = os.environ.get("PYTHONPATH")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + (os.pathsep + pp if pp else "")}
    procs = [subprocess.Popen([sys.executable, str(script), str(r), str(world),
                               str(port)], env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(world)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, f"worker failed:\n{out}"
    assert all("OK" in o for o in outs)


def test_python_fallback_parity(monkeypatch, tmp_path):
    """Force the fallback path and run the same semantics."""
    import paddle_tpu.distributed.store as store_mod

    monkeypatch.setattr(store_mod, "_lib", lambda: None)
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10.0)
    s.set("k", b"v")
    assert s.get("k") == b"v"
    assert s.add("c", 3) == 3
    s.wait(["k"], timeout=1.0)
    with pytest.raises(TimeoutError):
        s.wait(["nope"], timeout=0.3)
    assert sorted(s.list_keys("")) == ["c", "k"]
    assert s.delete_key("k")


def test_file_store(tmp_path):
    fs = FileStore(str(tmp_path / "fs"), world_size=2)
    fs.set("a", b"1")
    assert fs.get("a") == b"1"
    assert fs.add("cnt", 2) == 2
    assert fs.add("cnt", 1) == 3
    fs.wait(["a"], timeout=1.0)
    with pytest.raises(TimeoutError):
        fs.wait(["zz"], timeout=0.2)


def test_hostname_resolution():
    """Native client resolves hostnames (getaddrinfo), not just numeric IPv4."""
    s = TCPStore("localhost", 0, is_master=True, world_size=1, timeout=10.0)
    s.set("h", b"1")
    assert s.get("h") == b"1"


def test_server_stop_with_connected_clients_returns():
    """Stop() must unblock Serve threads parked in recv on live connections."""
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10.0)
    extra = TCPStore("127.0.0.1", s.port, is_master=False, world_size=1,
                     timeout=10.0)
    extra.set("x", b"y")
    t0 = time.time()
    s.__del__()  # server teardown with `extra`'s connection still open
    assert time.time() - t0 < 5.0, "server stop hung on live client connections"


def test_get_wait_honors_timeout():
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=0.5)
    with pytest.raises(TimeoutError):
        s.get("never-set-key")


def test_store_barrier_reusable():
    """Same barrier name synchronizes repeatedly (round-scoped done keys)."""
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=10.0)
    c = TCPStore("127.0.0.1", s.port, is_master=False, world_size=2, timeout=10.0)
    import threading

    for _ in range(3):
        t = threading.Thread(target=lambda: c.barrier("step", 2))
        t.start()
        s.barrier("step", 2)
        t.join(timeout=10)
        assert not t.is_alive()


def test_barrier_generation_namespaced():
    """The same barrier name under different generations uses disjoint keys
    — a re-formed world can't trip over a dead generation's counts."""
    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10.0)
    s.barrier("sync", 1, generation=1)
    s.barrier("sync", 1, generation=2)
    keys = s.list_keys("__barrier__/")
    assert any(k.startswith("__barrier__/gen1/sync/") for k in keys)
    assert any(k.startswith("__barrier__/gen2/sync/") for k in keys)


def test_gc_generation_tcp():
    """gc_generation sweeps one generation's elastic + barrier keys and
    counts them, leaving every other namespace alone."""
    from paddle_tpu.core import monitor

    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10.0)
    s.set("__elastic__/gen5/member/w0", b"{}")
    s.set("__elastic__/gen5/leave/w1", b"{}")
    s.set("__elastic__/gen6/member/w0", b"{}")
    s.barrier("sync", 1, generation=5)
    before = monitor.stat("store.gc_keys").get()
    removed = s.gc_generation(5)
    assert removed >= 3
    assert monitor.stat("store.gc_keys").get() == before + removed
    assert s.list_keys("__elastic__/gen5/") == []
    assert s.list_keys("__barrier__/gen5/") == []
    assert s.list_keys("__elastic__/gen6/") == ["__elastic__/gen6/member/w0"]


def test_file_store_backend_parity_for_coordinator(tmp_path):
    """The membership coordinator's whole store surface behaves the same on
    FileStore as on TCPStore: bounded get/wait, delete_key, list_keys,
    num_keys, generation barrier, gc."""
    for make in (lambda: TCPStore("127.0.0.1", 0, is_master=True,
                                  world_size=1, timeout=1.0),
                 lambda: FileStore(str(tmp_path / "fs"), world_size=1,
                                   timeout=1.0)):
        s = make()
        s.set("__elastic__/gen0/member/a", b"x")
        s.set("__elastic__/gen0/member/b", b"y")
        assert s.list_keys("__elastic__/gen0/member/") == [
            "__elastic__/gen0/member/a", "__elastic__/gen0/member/b"]
        assert s.delete_key("__elastic__/gen0/member/a") is True
        assert s.delete_key("__elastic__/gen0/member/a") is False
        with pytest.raises(KeyError):
            s.get("missing", wait=False)
        with pytest.raises(TimeoutError):
            s.wait(["missing"], timeout=0.2)
        assert s.num_keys() >= 1
        s.barrier("go", 1, generation=0)
        assert s.gc_generation(0) >= 1


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_connect_retry_backoff_unit():
    """_connect_with_retry keeps attempting through transient refusals,
    counting each retry in store.retries."""
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.store import _connect_with_retry

    calls = []

    def flaky(per_attempt_timeout):
        calls.append(per_attempt_timeout)
        if len(calls) < 3:
            raise ConnectionRefusedError("server not up yet")
        return "client"

    r0 = monitor.stat("store.retries").get()
    assert _connect_with_retry(flaky, "h", 1, timeout=10.0) == "client"
    assert len(calls) == 3
    assert monitor.stat("store.retries").get() == r0 + 2


def test_client_retries_until_master_binds():
    """The elastic-restart race: a client rank starts BEFORE its master has
    bound the port. Previously the first ECONNREFUSED failed the job; now
    the client backs off and wins once the server appears."""
    import threading

    port = _free_port()
    result = {}

    def connect():
        result["store"] = TCPStore("127.0.0.1", port, is_master=False,
                                   world_size=1, timeout=60.0)

    t = threading.Thread(target=connect)
    t.start()
    time.sleep(1.0)  # let the client eat refusals first
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=1,
                      timeout=60.0)
    t.join(timeout=60)
    # (the native client may absorb the wait inside one connect attempt, so
    # store.retries is asserted in the unit test above, not here)
    assert not t.is_alive() and "store" in result
    result["store"].set("late", b"1")
    assert master.get("late") == b"1"


def test_connect_attempts_bounded_by_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_STORE_CONNECT_ATTEMPTS", "2")
    port = _free_port()  # nothing listens here
    t0 = time.time()
    with pytest.raises(TimeoutError, match="after 2 attempt"):
        TCPStore("127.0.0.1", port, is_master=False, world_size=1,
                 timeout=60.0)
    assert time.time() - t0 < 30.0, "attempt bound did not cut the deadline"


def test_server_stop_unblocks_waiting_get():
    """Teardown must not hang on a Serve thread parked in a blocking wait."""
    import threading

    s = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=30.0)
    waiter = TCPStore("127.0.0.1", s.port, is_master=False, world_size=1,
                      timeout=30.0)
    t = threading.Thread(
        target=lambda: pytest.raises(Exception, waiter.wait, ["never"], 25.0))
    t.start()
    time.sleep(0.3)  # let the wait park server-side
    t0 = time.time()
    s.__del__()
    assert time.time() - t0 < 5.0, "Stop() hung on a parked waiter"
    t.join(timeout=10)

"""paddle.vision.ops: RoI ops, NMS, deformable conv, YOLO decode/loss,
and the transforms functional API."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
import paddle_tpu.vision.transforms as T


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestRoIOps:
    def test_roi_align_whole_image_avg(self):
        # aligned sampling of the whole box with 1x1 output == exact mean
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
        out = vops.roi_align(t(x), t(boxes), t(np.array([1])), output_size=1,
                             aligned=True)
        np.testing.assert_allclose(out.numpy().item(), x.mean(), rtol=1e-6)

    def test_roi_align_shapes_and_grad(self):
        rs = np.random.RandomState(0)
        x = t(rs.rand(2, 3, 8, 8).astype(np.float32))
        x.stop_gradient = False
        boxes = t(np.array([[0, 0, 4, 4], [2, 2, 6, 6], [0, 0, 8, 8]],
                           np.float32))
        bnum = t(np.array([2, 1]))
        out = vops.roi_align(x, boxes, bnum, output_size=2)
        assert out.shape == [3, 3, 2, 2]
        out.sum().backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 1, 1] = 9.0
        out = vops.roi_pool(t(x), t(np.array([[0, 0, 3, 3]], np.float32)),
                            t(np.array([1])), output_size=1)
        assert out.numpy().item() == 9.0

    def test_psroi_pool_shape(self):
        x = t(np.random.RandomState(0).rand(1, 8, 4, 4).astype(np.float32))
        out = vops.psroi_pool(x, t(np.array([[0, 0, 4, 4]], np.float32)),
                              t(np.array([1])), output_size=2)
        assert out.shape == [1, 2, 2, 2]  # 8 channels / (2*2) = 2 out channels

    def test_layers(self):
        x = t(np.random.RandomState(0).rand(1, 2, 4, 4).astype(np.float32))
        boxes = t(np.array([[0, 0, 4, 4]], np.float32))
        bnum = t(np.array([1]))
        assert vops.RoIAlign(2)(x, boxes, bnum).shape == [1, 2, 2, 2]
        assert vops.RoIPool(2)(x, boxes, bnum).shape == [1, 2, 2, 2]


class TestNMS:
    def test_suppression(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = vops.nms(t(boxes), iou_threshold=0.5, scores=t(scores)).numpy()
        np.testing.assert_array_equal(keep, [0, 2])  # box 1 overlaps box 0

    def test_category_aware(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int64)
        keep = vops.nms(t(boxes), 0.5, t(scores), category_idxs=t(cats),
                        categories=[0, 1]).numpy()
        assert len(keep) == 2  # different classes never suppress each other

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [9, 9, 10, 10]],
                         np.float32)
        scores = np.array([0.5, 0.9, 0.7], np.float32)
        keep = vops.nms(t(boxes), 0.5, t(scores), top_k=2).numpy()
        np.testing.assert_array_equal(keep, [1, 2])


class TestDeformConv:
    def test_zero_offset_matches_regular_conv(self):
        import paddle_tpu.nn.functional as F

        rs = np.random.RandomState(0)
        x = rs.rand(1, 2, 6, 6).astype(np.float32)
        w = rs.rand(4, 2, 3, 3).astype(np.float32)
        offset = np.zeros((1, 18, 4, 4), np.float32)  # kh*kw*2 channels
        out = vops.deform_conv2d(t(x), t(offset), t(w))
        ref = F.conv2d(t(x), t(w))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_layer_and_mask(self):
        paddle.seed(0)
        layer = vops.DeformConv2D(2, 4, 3, padding=1)
        x = t(np.random.RandomState(0).rand(1, 2, 5, 5).astype(np.float32))
        offset = t(np.zeros((1, 18, 5, 5), np.float32))
        mask = t(np.ones((1, 9, 5, 5), np.float32))
        out = layer(x, offset, mask)
        assert out.shape == [1, 4, 5, 5]


class TestYolo:
    def test_yolo_box_shapes(self):
        na, cls = 3, 4
        x = t(np.random.RandomState(0).randn(2, na * (5 + cls), 4, 4)
              .astype(np.float32))
        img = t(np.array([[64, 64], [64, 64]], np.int64))
        boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30, 33, 23],
                                      class_num=cls, conf_thresh=0.0,
                                      downsample_ratio=16)
        assert boxes.shape == [2, na * 16, 4]
        assert scores.shape == [2, na * 16, cls]

    def test_yolo_loss_decreases(self):
        paddle.seed(0)
        na, cls = 3, 4
        rs = np.random.RandomState(0)
        x = t(rs.randn(1, na * (5 + cls), 4, 4).astype(np.float32) * 0.1)
        x.stop_gradient = False
        gt_box = t(np.array([[[0.5, 0.5, 0.3, 0.4]]], np.float32))
        gt_label = t(np.array([[2]], np.int64))
        loss = vops.yolo_loss(x, gt_box, gt_label,
                              anchors=[10, 13, 16, 30, 33, 23],
                              anchor_mask=[0, 1, 2], class_num=cls,
                              ignore_thresh=0.7, downsample_ratio=16)
        assert loss.shape == [1]
        loss.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()


class TestTransformsFunctional:
    def test_to_tensor_and_flips(self):
        img = (np.random.RandomState(0).rand(5, 6, 3) * 255).astype(np.uint8)
        tt = T.to_tensor(img)
        assert tt.shape == [3, 5, 6] and float(tt.numpy().max()) <= 1.0
        np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(T.vflip(img), img[::-1])

    def test_crop_center_resize(self):
        img = np.arange(48, dtype=np.float32).reshape(6, 8)
        c = T.crop(img, 1, 2, 3, 4)
        np.testing.assert_array_equal(c, img[1:4, 2:6])
        cc = T.center_crop(np.zeros((3, 8, 8), np.float32), 4)
        assert cc.shape == (3, 4, 4)

    def test_adjust_and_normalize(self):
        img = np.full((3, 2, 2), 0.5, np.float32)
        np.testing.assert_allclose(T.adjust_brightness(img, 2.0), 1.0)
        out = T.normalize(img, [0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
        np.testing.assert_allclose(out, 0.0)
        hue = T.adjust_hue(img, 0.25)
        assert hue.shape == img.shape

    def test_rotate_identity(self):
        img = np.random.RandomState(0).rand(1, 5, 5).astype(np.float32)
        np.testing.assert_allclose(T.rotate(img, 0.0), img, atol=1e-6)

    def test_base_transform(self):
        class Double(T.BaseTransform):
            def _apply_image(self, image):
                return image * 2

        out = Double()(np.ones((2, 2), np.float32))
        np.testing.assert_allclose(out, 2.0)


class TestImageIO:
    def test_read_file(self, tmp_path):
        p = tmp_path / "blob.bin"
        p.write_bytes(bytes([1, 2, 3, 250]))
        data = vops.read_file(str(p))
        np.testing.assert_array_equal(data.numpy(), [1, 2, 3, 250])

"""Guard: the full reference api.yaml surface (235 forward APIs + 182 grads,
snapshot in tools/api_surface.json) stays implemented, stub-free, and
referenced by at least one test (VERDICT r1 item #3's done-condition)."""
import os
import re
import sys

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(TESTS_DIR), "tools"))

from op_coverage import audit  # noqa: E402


def test_api_yaml_surface_fully_covered():
    rep = audit()
    assert rep["missing"] == [], f"unimplemented APIs: {rep['missing']}"
    assert rep["stubs"] == [], f"stub APIs: {rep['stubs']}"
    assert rep["backward_missing"] == [], (
        f"grads without forward: {rep['backward_missing']}")
    assert rep["sparse_missing"] == [], (
        f"sparse_api.yaml gaps: {rep['sparse_missing']}")
    assert rep["strings_missing"] == [], (
        f"strings_api.yaml gaps: {rep['strings_missing']}")
    # every waiver must carry a reason
    for name, reason in rep["waived"].items():
        assert reason and len(reason) > 10, f"waiver for {name} has no reason"


def test_every_api_is_referenced_by_some_test():
    rep = audit()
    blob = ""
    for fn in os.listdir(TESTS_DIR):
        if fn.endswith(".py") and fn != os.path.basename(__file__):
            with open(os.path.join(TESTS_DIR, fn)) as f:
                blob += f.read()
    untested = []
    for name, path in rep["implemented"].items():
        covered = False
        for cand in {path.split(".")[-1], name}:
            esc = re.escape(cand)
            # call-site evidence only: `foo(` or `.foo` — a bare word in a
            # comment/docstring is not coverage
            if re.search(r"\b" + esc + r"\s*\(", blob) \
                    or re.search(r"\." + esc + r"\b", blob):
                covered = True
                break
        if not covered:
            untested.append(f"{name}->{path}")
    assert untested == [], (
        f"{len(untested)} APIs with no test call-site: {untested}")

"""Guard: the full reference api.yaml surface (235 forward APIs + 182 grads,
snapshot in tools/api_surface.json) stays implemented, stub-free, and
referenced by at least one test (VERDICT r1 item #3's done-condition)."""
import os
import re
import sys

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(TESTS_DIR), "tools"))

from op_coverage import audit  # noqa: E402


def test_api_yaml_surface_fully_covered():
    rep = audit()
    assert rep["missing"] == [], f"unimplemented APIs: {rep['missing']}"
    assert rep["stubs"] == [], f"stub APIs: {rep['stubs']}"
    assert rep["backward_missing"] == [], (
        f"grads without forward: {rep['backward_missing']}")
    assert rep["sparse_missing"] == [], (
        f"sparse_api.yaml gaps: {rep['sparse_missing']}")
    assert rep["strings_missing"] == [], (
        f"strings_api.yaml gaps: {rep['strings_missing']}")
    # every waiver must carry a reason
    for name, reason in rep["waived"].items():
        assert reason and len(reason) > 10, f"waiver for {name} has no reason"


def test_every_api_is_referenced_by_some_test():
    rep = audit()
    blob = ""
    for fn in os.listdir(TESTS_DIR):
        if fn.endswith(".py") and fn != os.path.basename(__file__):
            with open(os.path.join(TESTS_DIR, fn)) as f:
                blob += f.read()
    untested = []
    for name, path in rep["implemented"].items():
        covered = False
        for cand in {path.split(".")[-1], name}:
            esc = re.escape(cand)
            # call-site evidence only: `foo(` or `.foo` — a bare word in a
            # comment/docstring is not coverage
            if re.search(r"\b" + esc + r"\s*\(", blob) \
                    or re.search(r"\." + esc + r"\b", blob):
                covered = True
                break
        if not covered:
            untested.append(f"{name}->{path}")
    assert untested == [], (
        f"{len(untested)} APIs with no test call-site: {untested}")


def test_numeric_coverage_partition_is_total():
    """VERDICT r2 #5: every implemented forward API is either NUMERICALLY
    exercised (check_output/check_grad or statistical/structural check) by
    the test file the manifest points at, or carries an explicit waiver."""
    import numeric_coverage as nc

    rep = audit()
    impl = set(rep["implemented"])
    covered = set(nc.COVERED)
    waived = set(nc.NUMERIC_WAIVERS)
    assert not (covered & waived), sorted(covered & waived)
    # audit() computes the partition — assert its verdict, don't re-derive
    assert rep["numeric_untested"] == [], (
        f"{len(rep['numeric_untested'])} ops numerically untested and "
        f"unwaived: {rep['numeric_untested']}")
    stale = covered - impl
    assert stale == set(), f"manifest entries for unknown ops: {sorted(stale)}"
    for name, reason in nc.NUMERIC_WAIVERS.items():
        assert reason and len(reason) > 10, f"numeric waiver {name}: no reason"
    # pointers must be real: the file exists and names the op (by api name
    # or its public leaf) somewhere — keeps the manifest honest
    for name, fn in nc.COVERED.items():
        path = os.path.join(TESTS_DIR, fn)
        assert os.path.exists(path), f"{name}: {fn} does not exist"
        with open(path) as f:
            txt = f.read()
        leaf = rep["implemented"][name].split(".")[-1]
        assert any(re.search(r"\b" + re.escape(c) + r"\b", txt)
                   for c in {name, leaf}), (
            f"{name}: neither '{name}' nor '{leaf}' appears in {fn}")


def test_legacy_op_surface_fully_scoped():
    """VERDICT r3 missing #3: the NON-api.yaml operator surface must be
    explicitly delimited — every root-dir fluid operator is api-surface /
    equivalent (evidence verified) / waived (reasoned), and every family
    directory has a disposition. The 235/235 headline is about api.yaml;
    this keeps it from being mistaken for full-fluid parity."""
    import op_coverage as oc

    rep = oc.legacy_audit()
    assert rep["root"]["unscoped"] == [], rep["root"]["unscoped"]
    assert rep["root"]["broken_evidence"] == [], rep["root"]["broken_evidence"]
    c = rep["counts"]
    assert c["api_surface"] + c["equivalent"] + c["waived"] == c["root_ops"]
    # the audit is hermetic: the bundled snapshot must exist and parse
    ops, _ = oc.extract_legacy_root_ops("/nonexistent")
    assert len(ops) >= 400

"""Runtime cross-mesh resharding (VERDICT r1 missing #5: the reference's
reshard.py had no runtime analogue here beyond checkpoint conversion).

The flagship scenario: a LIVE training run switches parallel topology
mid-stream (dp8 -> mp2xdp4) — params + optimizer state + step counter move
onto the new mesh and training continues with loss continuity.
"""
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import (Resharder,
                                                  transfer_engine_state)
from paddle_tpu.distributed.mesh import set_hybrid_communicate_group


def _engine(confs, model, opt_lr=1e-2, sharding=False):
    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.sharding = sharding
    strategy.hybrid_configs = confs
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.Adam(learning_rate=opt_lr,
                                parameters=model.parameters())
    return fleet.distributed_engine(model, opt,
                                    loss_fn=lambda out, y: ((out - y) ** 2).mean())


def test_resharder_plan_and_apply():
    import jax

    devs = np.array(jax.devices())
    mesh_a = Mesh(devs.reshape(8), ("x",))
    mesh_b = Mesh(devs.reshape(2, 4), ("a", "b"))
    r = Resharder(mesh_b)

    x = jax.device_put(np.arange(32.0, dtype=np.float32).reshape(8, 4),
                       jax.sharding.NamedSharding(mesh_a, P("x", None)))
    assert r.plan(x, P("a", "b")) == "repartition"  # same devices, new layout
    y = r.apply(x, P("a", "b"))
    np.testing.assert_allclose(np.asarray(y),
                               np.arange(32.0).reshape(8, 4))
    assert r.stats["repartition"] == 1 and r.stats["bytes_moved"] == 128

    # already-matching sharding: noop — but donate=False must NOT alias
    z = r.apply(y, P("a", "b"))
    assert z is not y and r.stats["noop"] == 1
    np.testing.assert_allclose(np.asarray(z), np.asarray(y))
    assert r.apply(y, P("a", "b"), donate=True) is y  # surrendered: alias ok

    # subset mesh -> different device set: cross_mesh
    mesh_half = Mesh(devs[:4].reshape(4), ("h",))
    r2 = Resharder(mesh_half)
    assert r2.plan(y, P("h", None)) == "cross_mesh"
    w = r2.apply(y, P("h", None))
    np.testing.assert_allclose(np.asarray(w),
                               np.arange(32.0).reshape(8, 4))


def test_mid_training_topology_switch_dp_to_mp():
    paddle.seed(0)
    rs = np.random.RandomState(0)
    xs = rs.rand(12, 8, 16).astype(np.float32)
    ys = (xs.sum(-1, keepdims=True) * 0.1).astype(np.float32)

    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))
    eng_dp = _engine({"dp_degree": 8, "mp_degree": 1}, model)
    losses = []
    for i in range(3):
        losses.append(float(eng_dp.step(paddle.to_tensor(xs[i]),
                                        paddle.to_tensor(ys[i])).item()))

    # switch topology mid-run: dp8 (all replicated) -> ZeRO sharding8 (opt
    # state partitioned) — a REAL layout change, so bytes must move.
    # sync_to_model first: the dp engine DONATED the layer's original buffers
    # on its first step, and the new engine initializes from the layer.
    eng_dp.sync_to_model()
    eng_mp = _engine({"dp_degree": 1, "mp_degree": 1, "sharding_degree": 8},
                     model, sharding=True)
    eng_mp.step(paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0]))  # build
    stats = transfer_engine_state(eng_dp, eng_mp, donate=False)
    assert stats["bytes_moved"] > 0      # opt-state repartition over 'sharding'
    assert stats["repartition"] > 0

    for i in range(3, 6):
        losses.append(float(eng_mp.step(paddle.to_tensor(xs[i]),
                                        paddle.to_tensor(ys[i])).item()))
    assert all(np.isfinite(losses))
    # continuity: the post-switch trajectory keeps descending on average
    # (exact per-step parity with an unswitched run is asserted in
    # test_topology_switch_matches_unswitched_training)
    assert np.mean(losses[3:]) < np.mean(losses[:3])
    assert eng_mp._step_count == 6  # 3 dp steps (build step overwritten) + 3


def test_donate_false_keeps_source_engine_alive():
    """donate=False must guarantee the destination never aliases the source:
    the dst engine's donating step would otherwise delete the src's buffers
    (regression: noop-plan transfers aliased)."""
    paddle.seed(0)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.rand(8, 16).astype(np.float32))
    y = paddle.to_tensor(rs.rand(8, 1).astype(np.float32))
    model = nn.Linear(16, 1)
    eng_a = _engine({"dp_degree": 8, "mp_degree": 1}, model)
    eng_a.step(x, y)
    eng_a.sync_to_model()
    eng_b = _engine({"dp_degree": 8, "mp_degree": 1}, model)  # same topology
    eng_b.step(x, y)
    transfer_engine_state(eng_a, eng_b, donate=False)
    eng_b.step(x, y)          # donates eng_b's params — must not touch eng_a's
    loss_a = float(eng_a.step(x, y).item())  # source still fully usable
    assert np.isfinite(loss_a)


def test_topology_switch_matches_unswitched_training():
    """Switching layouts must not change the math: dp8->mp2 mid-run equals
    staying on dp8 the whole time (same data order, same seeds)."""
    def run(switch):
        paddle.seed(0)
        rs = np.random.RandomState(0)
        xs = rs.rand(6, 8, 16).astype(np.float32)
        ys = (xs.sum(-1, keepdims=True) * 0.1).astype(np.float32)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))
        eng = _engine({"dp_degree": 8, "mp_degree": 1}, model)
        out = []
        for i in range(2):
            out.append(float(eng.step(paddle.to_tensor(xs[i]),
                                      paddle.to_tensor(ys[i])).item()))
        if switch:
            eng.sync_to_model()
            eng2 = _engine({"dp_degree": 2, "mp_degree": 4}, model)
            eng2.step(paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0]))
            transfer_engine_state(eng, eng2, donate=False)
            eng = eng2
        for i in range(2, 5):
            out.append(float(eng.step(paddle.to_tensor(xs[i]),
                                      paddle.to_tensor(ys[i])).item()))
        return out

    base = run(switch=False)
    switched = run(switch=True)
    np.testing.assert_allclose(switched, base, rtol=2e-4)

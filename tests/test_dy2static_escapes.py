"""dy2static break/continue/early-return lowering (VERDICT r1 item #7).

Reference: dygraph_to_static/break_continue_transformer.py +
return_transformer.py. A traced `while` containing break must stay inside the
one-XLA-computation world (lowered to lax.while_loop with bool flag carries),
and early returns must lower to lax.cond with the continuation inlined.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import get_code


def t(v):
    x = paddle.to_tensor(np.asarray(v))
    return x


# ---- break ------------------------------------------------------------------
def fn_break(x, n):
    i = t(0)
    while i < n:          # traced condition
        x = x + 1.0
        if x.sum() > 6.0:
            break
        i = i + 1
    return x


def test_traced_while_break_matches_eager_and_stays_lowered():
    x = t(np.zeros((2,), np.float32))
    n = t(np.int64(10))
    st = to_static(fn_break)
    out = st(x, n)
    np.testing.assert_allclose(out.numpy(), fn_break(x, n).numpy())
    # x goes 1,2,3,4 -> sum 8 > 6 at x=4 -> break
    np.testing.assert_allclose(out.numpy(), [4.0, 4.0])
    code = get_code(fn_break)
    assert "convert_while_loop" in code          # loop IS lowered
    import re
    assert not re.search(r"^\s*break\s*$", code, re.M)  # escape eliminated
    assert "__esc_brk" in code


# ---- continue ---------------------------------------------------------------
def fn_continue(x, n):
    i = t(0)
    acc = t(np.zeros((), np.float32))
    while i < n:
        i = i + 1
        if (i % 2) == 0:
            continue
        acc = acc + x
    return acc


def test_traced_while_continue_matches_eager():
    x = t(np.float32(1.5))
    n = t(np.int64(6))
    st = to_static(fn_continue)
    out = st(x, n)
    # odd i in 1..6 -> 3 additions
    np.testing.assert_allclose(out.numpy(), 4.5)
    code = get_code(fn_continue)
    assert "convert_while_loop" in code
    import re
    assert not re.search(r"^\s*continue\s*$", code, re.M)


# ---- break in for-range -----------------------------------------------------
def fn_for_break(x):
    s = t(np.zeros((), np.float32))
    for i in range(10):
        if s > 5.0:
            break
        s = s + x
    return s


def test_for_range_break():
    st = to_static(fn_for_break)
    out = st(t(np.float32(2.0)))
    np.testing.assert_allclose(out.numpy(), 6.0)  # 2,4,6 then stop
    np.testing.assert_allclose(out.numpy(),
                               fn_for_break(t(np.float32(2.0))).numpy())
    assert "convert_while_loop" in get_code(fn_for_break)


def fn_for_continue(x):
    s = t(np.zeros((), np.float32))
    for i in range(6):
        if (s + x).sum() > 100.0:  # traced predicate keeps the loop lowered
            continue
        s = s + x
    return s


def test_for_range_continue_terminates_and_matches():
    # regression: the loop increment must stay OUTSIDE the continue-guard —
    # a guarded increment made this loop spin forever
    st = to_static(fn_for_continue)
    out = st(t(np.float32(2.0)))
    np.testing.assert_allclose(out.numpy(), 12.0)
    np.testing.assert_allclose(out.numpy(),
                               fn_for_continue(t(np.float32(2.0))).numpy())


def fn_for_continue_skips(x):
    s = t(np.zeros((), np.float32))
    for i in range(6):
        if s > 5.0:      # true from s=6 on -> skip further additions
            continue
        s = s + x
    return s


def test_for_range_continue_actually_skips():
    st = to_static(fn_for_continue_skips)
    out = st(t(np.float32(2.0)))  # 2,4,6 then every later iter skipped
    np.testing.assert_allclose(out.numpy(), 6.0)
    np.testing.assert_allclose(
        out.numpy(), fn_for_continue_skips(t(np.float32(2.0))).numpy())


def fn_break_leaves_loop_var(x):
    j = t(np.int64(0))
    for i in range(10):
        j = j + 1
        if j >= 3:
            break
    return j


def test_break_does_not_run_trailing_increment():
    # regression: the for-range increment must NOT run on the break iteration
    # (python leaves the loop variable at its break-time value)
    st = to_static(fn_break_leaves_loop_var)
    out = st(t(np.float32(0.0)))
    np.testing.assert_allclose(out.numpy(),
                               fn_break_leaves_loop_var(t(np.float32(0.0))).numpy())
    np.testing.assert_allclose(out.numpy(), 3)


def fn_continue_in_try(x):
    s = 0.0
    for i in range(4):
        try:
            if i == 1:
                continue
        finally:
            pass
        s = s + 1.0
    return t(np.float32(s))


def test_escape_inside_try_falls_back_with_warning():
    # _guard cannot rewrite a continue inside try/finally: loud python fallback
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_continue_in_try, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_continue_in_try)
        out = st(t(np.float32(0.0)))
    np.testing.assert_allclose(out.numpy(), 3.0)  # python semantics preserved
    assert any("try/with" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])


# ---- early return -----------------------------------------------------------
def fn_early_return(x):
    if x.sum() > 0.0:       # traced predicate
        return x * 2.0
    y = x - 1.0
    return y * 3.0


def test_traced_early_return_both_paths():
    st = to_static(fn_early_return)
    pos = t(np.ones((2,), np.float32))
    neg = t(np.full((2,), -1.0, np.float32))
    np.testing.assert_allclose(st(pos).numpy(), [2.0, 2.0])
    np.testing.assert_allclose(st(neg).numpy(), [-6.0, -6.0])
    code = get_code(fn_early_return)
    assert "convert_ifelse" in code              # lowered, not python if
    assert "__esc_rv" in code


def fn_nested_returns(x):
    if x.sum() > 10.0:
        return x
    if x.sum() > 0.0:
        x = x + 1.0
        return x * 2.0
    return x * -1.0


def test_chained_early_returns():
    st = to_static(fn_nested_returns)
    for v in ([20.0], [3.0], [-4.0]):
        arr = t(np.asarray(v, np.float32))
        np.testing.assert_allclose(st(arr).numpy(),
                                   fn_nested_returns(arr).numpy())


def fn_return_none_path(x):
    if x.sum() > 0.0:
        return x
    x = x * 2.0  # falls through -> implicit None


def test_fallthrough_function_is_not_lowered_and_warns():
    # implicit-None fall-through can't mix with tensor returns under lax.cond:
    # such functions keep the python fallback, loudly
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_none_path, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        code = get_code(fn_return_none_path)
    assert "__esc_rv" not in code
    assert any("fall through" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])


# ---- warnings on remaining fallbacks ---------------------------------------
def fn_return_in_loop(x):
    for i in range(3):
        if x.sum() > 0.0:
            return x
        x = x + 1.0
    return x


def test_return_in_loop_warns_not_silent():
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_in_loop, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_in_loop)
        out = st(t(np.asarray([1.0], np.float32)))  # python fallback still works
    np.testing.assert_allclose(out.numpy(), [1.0])
    assert any("return inside a loop" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])


# ---- undefined-variable diagnostics (ADVICE r1) -----------------------------
def test_one_sided_branch_var_raises_clear_error():
    # a variable assigned in only one branch of a TRACED if: the lax.cond
    # structure mismatch must surface as a clear UnboundLocalError, not an
    # obscure pytree error (ADVICE r1)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.jit.dy2static import convert_ifelse, undefined

    def f(flag, x):
        return convert_ifelse(
            flag,
            lambda z: (x * 2.0,),      # true: assigns z
            lambda z: (z,),            # false: z stays undefined
            (undefined("z"),))

    with pytest.raises(UnboundLocalError, match="branch"):
        jax.jit(f)(jnp.bool_(True), jnp.ones((2,), jnp.float32))

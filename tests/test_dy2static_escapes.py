"""dy2static break/continue/early-return lowering (VERDICT r1 item #7).

Reference: dygraph_to_static/break_continue_transformer.py +
return_transformer.py. A traced `while` containing break must stay inside the
one-XLA-computation world (lowered to lax.while_loop with bool flag carries),
and early returns must lower to lax.cond with the continuation inlined.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import get_code


def t(v):
    x = paddle.to_tensor(np.asarray(v))
    return x


# ---- break ------------------------------------------------------------------
def fn_break(x, n):
    i = t(0)
    while i < n:          # traced condition
        x = x + 1.0
        if x.sum() > 6.0:
            break
        i = i + 1
    return x


def test_traced_while_break_matches_eager_and_stays_lowered():
    x = t(np.zeros((2,), np.float32))
    n = t(np.int64(10))
    st = to_static(fn_break)
    out = st(x, n)
    np.testing.assert_allclose(out.numpy(), fn_break(x, n).numpy())
    # x goes 1,2,3,4 -> sum 8 > 6 at x=4 -> break
    np.testing.assert_allclose(out.numpy(), [4.0, 4.0])
    code = get_code(fn_break)
    assert "convert_while_loop" in code          # loop IS lowered
    import re
    assert not re.search(r"^\s*break\s*$", code, re.M)  # escape eliminated
    assert "__esc_brk" in code


# ---- continue ---------------------------------------------------------------
def fn_continue(x, n):
    i = t(0)
    acc = t(np.zeros((), np.float32))
    while i < n:
        i = i + 1
        if (i % 2) == 0:
            continue
        acc = acc + x
    return acc


def test_traced_while_continue_matches_eager():
    x = t(np.float32(1.5))
    n = t(np.int64(6))
    st = to_static(fn_continue)
    out = st(x, n)
    # odd i in 1..6 -> 3 additions
    np.testing.assert_allclose(out.numpy(), 4.5)
    code = get_code(fn_continue)
    assert "convert_while_loop" in code
    import re
    assert not re.search(r"^\s*continue\s*$", code, re.M)


# ---- break in for-range -----------------------------------------------------
def fn_for_break(x):
    s = t(np.zeros((), np.float32))
    for i in range(10):
        if s > 5.0:
            break
        s = s + x
    return s


def test_for_range_break():
    st = to_static(fn_for_break)
    out = st(t(np.float32(2.0)))
    np.testing.assert_allclose(out.numpy(), 6.0)  # 2,4,6 then stop
    np.testing.assert_allclose(out.numpy(),
                               fn_for_break(t(np.float32(2.0))).numpy())
    assert "convert_while_loop" in get_code(fn_for_break)


def fn_for_continue(x):
    s = t(np.zeros((), np.float32))
    for i in range(6):
        if (s + x).sum() > 100.0:  # traced predicate keeps the loop lowered
            continue
        s = s + x
    return s


def test_for_range_continue_terminates_and_matches():
    # regression: the loop increment must stay OUTSIDE the continue-guard —
    # a guarded increment made this loop spin forever
    st = to_static(fn_for_continue)
    out = st(t(np.float32(2.0)))
    np.testing.assert_allclose(out.numpy(), 12.0)
    np.testing.assert_allclose(out.numpy(),
                               fn_for_continue(t(np.float32(2.0))).numpy())


def fn_for_continue_skips(x):
    s = t(np.zeros((), np.float32))
    for i in range(6):
        if s > 5.0:      # true from s=6 on -> skip further additions
            continue
        s = s + x
    return s


def test_for_range_continue_actually_skips():
    st = to_static(fn_for_continue_skips)
    out = st(t(np.float32(2.0)))  # 2,4,6 then every later iter skipped
    np.testing.assert_allclose(out.numpy(), 6.0)
    np.testing.assert_allclose(
        out.numpy(), fn_for_continue_skips(t(np.float32(2.0))).numpy())


def fn_break_leaves_loop_var(x):
    j = t(np.int64(0))
    for i in range(10):
        j = j + 1
        if j >= 3:
            break
    return j


def test_break_does_not_run_trailing_increment():
    # regression: the for-range increment must NOT run on the break iteration
    # (python leaves the loop variable at its break-time value)
    st = to_static(fn_break_leaves_loop_var)
    out = st(t(np.float32(0.0)))
    np.testing.assert_allclose(out.numpy(),
                               fn_break_leaves_loop_var(t(np.float32(0.0))).numpy())
    np.testing.assert_allclose(out.numpy(), 3)


def fn_continue_in_try(x):
    s = 0.0
    for i in range(4):
        try:
            if i == 1:
                continue
        finally:
            pass
        s = s + 1.0
    return t(np.float32(s))


def test_escape_inside_try_is_lowered():
    # round 3 (VERDICT r2 #8): _guard rewrites THROUGH try/with — the
    # continue becomes a flag, no python fallback, no warning
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_continue_in_try, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_continue_in_try)
        out = st(t(np.float32(0.0)))
    np.testing.assert_allclose(out.numpy(), 3.0)
    assert not any("try/with" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])
    code = get_code(fn_continue_in_try)
    assert "__esc_cont" in code  # flag-lowered, not python continue


def fn_break_in_with(x):
    # traced predicate, break under a context manager
    import paddle_tpu as paddle

    s = x * 0.0
    for i in range(5):
        with paddle.no_grad():
            if (s.sum() >= 2.0):
                break
            s = s + 1.0
    return s


def test_break_under_with_traced_is_lowered():
    st = to_static(fn_break_in_with)
    out = st(t(np.asarray([0.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0])
    code = get_code(fn_break_in_with)
    assert "__esc_brk" in code and "convert_while_loop" in code


# ---- early return -----------------------------------------------------------
def fn_early_return(x):
    if x.sum() > 0.0:       # traced predicate
        return x * 2.0
    y = x - 1.0
    return y * 3.0


def test_traced_early_return_both_paths():
    st = to_static(fn_early_return)
    pos = t(np.ones((2,), np.float32))
    neg = t(np.full((2,), -1.0, np.float32))
    np.testing.assert_allclose(st(pos).numpy(), [2.0, 2.0])
    np.testing.assert_allclose(st(neg).numpy(), [-6.0, -6.0])
    code = get_code(fn_early_return)
    assert "convert_ifelse" in code              # lowered, not python if
    assert "__esc_rv" in code


def fn_nested_returns(x):
    if x.sum() > 10.0:
        return x
    if x.sum() > 0.0:
        x = x + 1.0
        return x * 2.0
    return x * -1.0


def test_chained_early_returns():
    st = to_static(fn_nested_returns)
    for v in ([20.0], [3.0], [-4.0]):
        arr = t(np.asarray(v, np.float32))
        np.testing.assert_allclose(st(arr).numpy(),
                                   fn_nested_returns(arr).numpy())


def fn_return_none_path(x):
    if x.sum() > 0.0:
        return x
    x = x * 2.0  # falls through -> implicit None


def test_fallthrough_function_is_not_lowered_and_warns():
    # implicit-None fall-through can't mix with tensor returns under lax.cond:
    # such functions keep the python fallback, loudly
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_none_path, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        code = get_code(fn_return_none_path)
    assert "__esc_rv" not in code
    assert any("fall through" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])


# ---- return inside loops (round 3: lowered, not warned) --------------------
def fn_return_in_loop(x):
    for i in range(3):
        if x.sum() > 0.0:
            return x
        x = x + 1.0
    return x


def test_return_in_for_loop_is_lowered():
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_in_loop, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_in_loop)
        # return path: fires on the first iteration
        out = st(t(np.asarray([1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [1.0])
        # no-return path: x climbs -2 -> 1 (sum > 0 at i=2) -> returns 1.0?
        # trace: i0: sum=-2<=0, x=-1; i1: sum=-1<=0, x=0; i2: sum=0<=0, x=1
        out2 = st(t(np.asarray([-2.0], np.float32)))
        np.testing.assert_allclose(out2.numpy(), [1.0])
    assert not any("return inside a loop" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])
    code = get_code(fn_return_in_loop)
    assert "__esc_rdone" in code and "convert_while_loop" in code


def fn_return_in_while(x, n):
    # the VERDICT headline case: return inside a TENSOR-condition while
    while n.sum() > 0.0:
        if x.sum() > 10.0:
            return x * 100.0
        x = x + 1.0
        n = n - 1.0
    return x


def test_return_in_tensor_while_is_lowered():
    st = to_static(fn_return_in_while)
    # return fires mid-loop: x starts 9, reaches 11 after 2 iterations
    out = st(t(np.asarray([9.0], np.float32)),
             t(np.asarray([5.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [1100.0])
    # loop drains without the return firing
    out2 = st(t(np.asarray([0.0], np.float32)),
              t(np.asarray([3.0], np.float32)))
    np.testing.assert_allclose(out2.numpy(), [3.0])
    code = get_code(fn_return_in_while)
    assert "__esc_rdone" in code and "convert_while_loop" in code
    # under TRACING this is one computation: lax.while_loop + lax.cond in
    # the jaxpr, and both paths produce correct values through jit
    import jax
    import jax.numpy as jnp

    def f(xd, nd):
        return st(t(np.asarray([0.0], np.float32)).__class__(xd),
                  t(np.asarray([0.0], np.float32)).__class__(nd))._data

    s = str(jax.make_jaxpr(f)(jnp.asarray([9.0], jnp.float32),
                              jnp.asarray([5.0], jnp.float32)))
    assert "while" in s and "cond" in s
    jf = jax.jit(f)
    np.testing.assert_allclose(
        np.asarray(jf(jnp.asarray([9.0], jnp.float32),
                      jnp.asarray([5.0], jnp.float32))), [1100.0])
    np.testing.assert_allclose(
        np.asarray(jf(jnp.asarray([0.0], jnp.float32),
                      jnp.asarray([3.0], jnp.float32))), [3.0])


def fn_two_returns_in_loop(x):
    for i in range(4):
        if x.sum() > 10.0:
            return x + 100.0
        if x.sum() < -10.0:
            return x - 100.0
        x = x * 2.0
    return x


def test_multiple_return_sites_in_loop():
    st = to_static(fn_two_returns_in_loop)
    for v, want in [([20.0], [120.0]), ([-20.0], [-120.0]),
                    ([1.0], [16.0])]:
        got = st(t(np.asarray(v, np.float32))).numpy()
        ref = fn_two_returns_in_loop(t(np.asarray(v, np.float32))).numpy()
        np.testing.assert_allclose(got, ref)
        np.testing.assert_allclose(got, want)


# ---- undefined-variable diagnostics (ADVICE r1) -----------------------------
def test_one_sided_branch_var_raises_clear_error():
    # a variable assigned in only one branch of a TRACED if: the lax.cond
    # structure mismatch must surface as a clear UnboundLocalError, not an
    # obscure pytree error (ADVICE r1)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.jit.dy2static import convert_ifelse, undefined

    def f(flag, x):
        return convert_ifelse(
            flag,
            lambda z: (x * 2.0,),      # true: assigns z
            lambda z: (z,),            # false: z stays undefined
            (undefined("z"),))

    with pytest.raises(UnboundLocalError, match="branch"):
        jax.jit(f)(jnp.bool_(True), jnp.ones((2,), jnp.float32))


# ---- round-3 review regressions --------------------------------------------
def fn_break_skips_try_else(x):
    out = x * 0.0
    i = 0
    while i < 5:
        try:
            if i == 2:
                break
        except ValueError:
            pass
        else:
            out = out + 1.0  # python: break SKIPS the try-else
        i += 1
    return out


def test_break_in_try_body_skips_else_clause():
    st = to_static(fn_break_skips_try_else)
    arr = t(np.asarray([0.0], np.float32))
    np.testing.assert_allclose(st(arr).numpy(),
                               fn_break_skips_try_else(arr).numpy())
    np.testing.assert_allclose(st(arr).numpy(), [2.0])


def fn_return_under_finally_that_assigns(x):
    for i in range(3):
        try:
            if i == 1:
                return x
        finally:
            x = x + 100.0  # runs AFTER the return value is computed
    return x


def test_return_under_mutating_finally_falls_back():
    # re-evaluating the return expression post-loop would see the finally's
    # write (200 instead of python's 100): such loops must NOT lower
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_under_finally_that_assigns, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_under_finally_that_assigns)
        out = st(t(np.asarray([0.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [100.0])  # python semantics
    assert any("return inside" in str(w.message) for w in rec)


def fn_return_in_match_loop(x, k):
    for i in range(3):
        match k:
            case 1:
                return x * 10.0
            case _:
                x = x + 1.0
    return x


def test_return_under_match_is_lowered():
    # round 4: _ReturnInLoopLowering descends ast.Match case bodies (they are
    # mutually exclusive, like If branches) — concrete-subject matches lower
    # instead of falling back (VERDICT r3 missing #2)
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_in_match_loop, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_in_match_loop)
        out = st(t(np.asarray([2.0], np.float32)), 1)
        np.testing.assert_allclose(out.numpy(), [20.0])
        out2 = st(t(np.asarray([2.0], np.float32)), 0)
    assert not any("falls back" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])
    assert "__esc_rdone" in get_code(fn_return_in_match_loop)
    np.testing.assert_allclose(out2.numpy(), [5.0])


# ---- loop-else (round 4: lowered via the broke-flag, VERDICT r3 missing #2) --
def fn_while_else_break(x, lim):
    i = 0
    while i < 5:
        if float(x.sum()) > lim:
            break
        x = x + 1.0
        i += 1
    else:
        x = x * 100.0  # runs only when the loop drains without break
    return x


def fn_for_else_break(x, lim):
    for i in range(4):
        if float(x.sum()) > lim:
            break
        x = x + 1.0
    else:
        x = x - 1000.0
    return x


def fn_for_else_continue_only(x):
    for i in range(3):
        if i == 1:
            continue
        x = x + 1.0
    else:
        x = x * 10.0  # continue never skips the else
    return x


def fn_while_else_break_tensor(x, n):
    # tensor condition: the whole loop must lower to lax.while_loop
    while n.sum() > 0.0:
        if x.sum() > 3.0:
            break
        x = x + 1.0
        n = n - 1.0
    else:
        x = x * 100.0
    return x


def fn_return_plus_loop_else(x, lim):
    for i in range(3):
        if float(x.sum()) > lim:
            return x * 7.0  # return skips the else (not normal completion)
        x = x + 1.0
    else:
        x = x - 500.0
    return x


def fn_return_else_break(x):
    for i in range(3):
        if float(x.sum()) > 100.0:
            return x
        if float(x.sum()) > 1.0:
            break
        x = x + 1.0
    else:
        x = x - 500.0
    return x


@pytest.mark.parametrize("fn,args_list", [
    (fn_while_else_break, [([0.0], 2.0), ([0.0], 99.0)]),
    (fn_for_else_break, [([0.0], 1.0), ([0.0], 99.0)]),
    (fn_for_else_continue_only, [([0.0],)]),
    (fn_return_plus_loop_else, [([0.0], 1.0), ([0.0], 99.0)]),
])
def test_loop_else_matches_python_and_does_not_warn(fn, args_list):
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn)
        for args in args_list:
            tensor_args = [t(np.asarray(a, np.float32))
                           if isinstance(a, list) else a for a in args]
            ref_args = [t(np.asarray(a, np.float32))
                        if isinstance(a, list) else a for a in args]
            np.testing.assert_allclose(st(*tensor_args).numpy(),
                                       fn(*ref_args).numpy(), err_msg=str(args))
    assert not any("falls back" in str(w.message) for w in rec), (
        fn.__name__, [str(w.message) for w in rec])


def test_tensor_while_else_break_is_one_computation():
    import jax
    import jax.numpy as jnp

    st = to_static(fn_while_else_break_tensor)
    # break fires: x 0->4 (sum>3 at 4), else skipped
    out = st(t(np.asarray([0.0], np.float32)), t(np.asarray([9.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [4.0])
    # loop drains: x 0->2, else runs -> 200
    out2 = st(t(np.asarray([0.0], np.float32)), t(np.asarray([2.0], np.float32)))
    np.testing.assert_allclose(out2.numpy(), [200.0])
    code = get_code(fn_while_else_break_tensor)
    assert "__esc_brk" in code and "convert_while_loop" in code

    def f(xd, nd):
        return st(t(np.asarray([0.0], np.float32)).__class__(xd),
                  t(np.asarray([0.0], np.float32)).__class__(nd))._data

    s = str(jax.make_jaxpr(f)(jnp.asarray([0.0], jnp.float32),
                              jnp.asarray([9.0], jnp.float32)))
    assert "while" in s
    jf = jax.jit(f)
    np.testing.assert_allclose(
        np.asarray(jf(jnp.asarray([0.0], jnp.float32),
                      jnp.asarray([9.0], jnp.float32))), [4.0])
    np.testing.assert_allclose(
        np.asarray(jf(jnp.asarray([0.0], jnp.float32),
                      jnp.asarray([2.0], jnp.float32))), [200.0])


def test_return_plus_else_plus_break_is_lowered():
    # VERDICT r4 item 8: the user break is tagged with its own flag
    # (`__esc_ubrk`) BEFORE return lowering, so the loop-else runs only
    # when neither the lowered return nor the user break fired — and the
    # whole combo lowers with no python fallback
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_else_break, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_else_break)
        # break path: x.sum()=2 > 1 -> break, else skipped
        out = st(t(np.asarray([2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [2.0])
        # return path: x.sum() > 100 -> early return x
        out_r = st(t(np.asarray([200.0], np.float32)))
        np.testing.assert_allclose(
            out_r.numpy(),
            fn_return_else_break(t(np.asarray([200.0], np.float32))).numpy())
        # drain path: loop completes, else runs (x - 500)
        out2 = st(t(np.asarray([-9.0], np.float32)))
        np.testing.assert_allclose(out2.numpy(),
                                   fn_return_else_break(
                                       t(np.asarray([-9.0], np.float32))).numpy())
    assert not any("falls back" in str(w.message) for w in rec), \
        [str(w.message) for w in rec]
    code = get_code(fn_return_else_break)
    assert "__esc_ubrk" in code and "__esc_rdone" in code
    import re
    assert not re.search(r"^\s*break\s*$", code, re.M)  # escapes eliminated


def fn_break_in_inner_loop_else(x):
    # python scoping: the inner while's ELSE is outside the inner loop, so
    # its break targets the OUTER while — and skips the outer else
    i = 0
    while i < 3:
        if float(x.sum()) > 100.0:
            break
        j = 0
        while j < 1:
            j += 1
        else:
            break  # breaks the OUTER loop
        x = x + 1.0
        i += 1
    else:
        x = x * 1000.0
    return x


def test_break_in_nested_loop_else_targets_outer_loop():
    # round-4 review regression: _EscapeScan must not swallow a break that
    # lives in a nested loop's orelse, and _guard must rewrite it
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_break_in_inner_loop_else, None)
    st = to_static(fn_break_in_inner_loop_else)
    arr = t(np.asarray([1.0], np.float32))
    got = st(arr).numpy()
    want = fn_break_in_inner_loop_else(arr).numpy()
    np.testing.assert_allclose(got, want)
    np.testing.assert_allclose(got, [1.0])  # outer else must NOT run


def fn_return_else_inner_break(x):
    for i in range(3):
        if float(x.sum()) > 100.0:
            return x * 7.0
        j = 0
        while j < 1:
            j += 1
        else:
            break  # targets the for loop -> skips its else
        x = x + 1.0
    else:
        x = x - 500.0
    return x


def test_return_plus_else_plus_nested_break_is_lowered():
    # the inner while's orelse-break targets the OUTER for loop (python
    # scoping) — the ubrk tag must land there too, skipping the outer else
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_else_inner_break, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_return_else_inner_break)
        arr = t(np.asarray([1.0], np.float32))
        np.testing.assert_allclose(st(arr).numpy(),
                                   fn_return_else_inner_break(arr).numpy())
        np.testing.assert_allclose(st(arr).numpy(), [1.0])
        # return path
        big = t(np.asarray([200.0], np.float32))
        np.testing.assert_allclose(st(big).numpy(),
                                   fn_return_else_inner_break(big).numpy())
    assert not any("falls back" in str(w.message) for w in rec), \
        [str(w.message) for w in rec]
    code = get_code(fn_return_else_inner_break)
    assert "__esc_ubrk" in code


def fn_return_else_break_tensor(x, lim):
    # fully tensor-predicated: every path must survive tracing
    for i in range(3):
        if x.sum() > 100.0:
            return x * 7.0
        if x.sum() > lim.sum():
            break
        x = x + 1.0
    else:
        x = x - 500.0
    return x


def test_return_else_break_tensor_is_one_computation():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_else_break_tensor, None)
    st = to_static(fn_return_else_break_tensor)
    ref = fn_return_else_break_tensor

    def f(xd, ld):
        from paddle_tpu.core.tensor import Tensor

        return st(Tensor(xd), Tensor(ld))._data

    # traces to ONE jaxpr (no python fallback would survive make_jaxpr on
    # all three control paths at once)
    jax.make_jaxpr(f)(jnp.asarray([0.0], jnp.float32),
                      jnp.asarray([9.0], jnp.float32))
    jf = jax.jit(f)
    cases = [
        ([200.0], [9.0]),   # early return: 200*7
        ([2.0], [1.0]),     # user break: else skipped
        ([0.0], [99.0]),    # drain: else runs (x+3-500)
    ]
    for xv, lv in cases:
        want = ref(t(np.asarray(xv, np.float32)),
                   t(np.asarray(lv, np.float32))).numpy()
        got = np.asarray(jf(jnp.asarray(xv, jnp.float32),
                            jnp.asarray(lv, jnp.float32)))
        np.testing.assert_allclose(got, want, rtol=1e-6,
                                   err_msg=f"case {(xv, lv)}")


def fn_inner_for_body_break_and_else_break(x):
    # the inner (non-range) for keeps an UNLOWERED body break, so its else is
    # conditional — hoisting it would run the outer-loop break unconditionally
    i = 0
    while i < 3:
        for it in [1, 2]:
            if it == 1:
                break  # inner break: skips the inner else
        else:
            break  # would break the OUTER loop — but never runs here
        x = x + 1.0
        i += 1
    return x


def test_inner_body_break_plus_else_break_falls_back_correctly():
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_inner_for_body_break_and_else_break, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_inner_for_body_break_and_else_break)
        arr = t(np.asarray([0.0], np.float32))
        got = st(arr).numpy()
    np.testing.assert_allclose(
        got, fn_inner_for_body_break_and_else_break(arr).numpy())
    np.testing.assert_allclose(got, [3.0])  # inner else never fires
    assert any("nested loop's else" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])


def fn_bounded_break_loop(x):
    # range-for + tensor-condition break: lowers to a FIXED-length scan with
    # frozen-state selects (round 4) — reverse-differentiable, which a
    # lax.while_loop lowering fundamentally is not
    for i in range(4):
        if x.sum() > 5.0:
            break
        x = x * 2.0
    return x


def test_bounded_break_loop_is_differentiable():
    import jax
    import jax.numpy as jnp

    st = to_static(fn_bounded_break_loop)
    # forward parity on both paths
    for v in ([1.0], [9.0]):
        np.testing.assert_allclose(
            st(t(np.asarray(v, np.float32))).numpy(),
            fn_bounded_break_loop(t(np.asarray(v, np.float32))).numpy(),
            err_msg=str(v))
    # the lowered loop must be a scan (differentiable), not a while
    def f(xd):
        return st(t(np.asarray([0.0], np.float32)).__class__(xd))._data.sum()

    s = str(jax.make_jaxpr(f)(jnp.asarray([1.0], jnp.float32)))
    assert "scan" in s and "while" not in s, s[:400]
    # grad == analytic: x*2 runs twice for x=[1.] (1->2->4, 4+... sum>5 stops
    # after the 3rd double? trace: sum=1<=5 -> 2; 2<=5 -> 4; 4<=5 -> 8;
    # 8>5 -> break at i=3. d(out)/dx = 8
    g = jax.grad(f)(jnp.asarray([1.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [8.0])
    # eager backward through the same to_static program
    xt = t(np.asarray([1.0], np.float32))
    xt.stop_gradient = False
    loss = st(xt).sum()
    loss.backward()
    np.testing.assert_allclose(xt.grad.numpy(), [8.0])


def test_static_string_args_pass_through():
    # non-tensorizable positional args are closed over, not force-wrapped
    # (they used to crash jnp.asarray); each value steers its own trace
    def fn(x, mode):
        if mode == "double":
            return x * 2.0
        return x + 1.0

    st = to_static(fn)
    np.testing.assert_allclose(
        st(t(np.asarray([3.0], np.float32)), "double").numpy(), [6.0])
    np.testing.assert_allclose(
        st(t(np.asarray([3.0], np.float32)), "other").numpy(), [4.0])
    # and back again: one mode's program must not leak into the other
    np.testing.assert_allclose(
        st(t(np.asarray([5.0], np.float32)), "double").numpy(), [10.0])


def fn_return_reads_pattern_bound_name(x, d):
    # `m` is bound by the match PATTERN (MatchMapping), not a Name store —
    # it must still be collected as a loop carry or the post-loop
    # re-evaluated return expression NameErrors (round-4 review regression)
    for i in range(3):
        match d:
            case {"m": m}:
                return x * m
        x = x + 1.0
    return x


def test_match_pattern_bound_name_is_a_loop_carry():
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_return_reads_pattern_bound_name, None)
    st = to_static(fn_return_reads_pattern_bound_name)
    np.testing.assert_allclose(
        st(t(np.asarray([2.0], np.float32)), {"m": 3.0}).numpy(), [6.0])
    np.testing.assert_allclose(
        st(t(np.asarray([2.0], np.float32)), {"z": 0.0}).numpy(), [5.0])


def fn_break_under_match(x, k):
    i = 0
    while i < 4:
        match k:
            case 1:
                break
            case _:
                x = x + 1.0
        i += 1
    return x


def test_break_under_match_is_lowered():
    from paddle_tpu.jit.dy2static import _CONVERTED_CACHE

    _CONVERTED_CACHE.pop(fn_break_under_match, None)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        st = to_static(fn_break_under_match)
        np.testing.assert_allclose(
            st(t(np.asarray([0.0], np.float32)), 1).numpy(), [0.0])
        np.testing.assert_allclose(
            st(t(np.asarray([0.0], np.float32)), 0).numpy(), [4.0])
    assert not any("falls back" in str(w.message) for w in rec), (
        [str(w.message) for w in rec])
    assert "__esc_brk" in get_code(fn_break_under_match)

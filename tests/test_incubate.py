"""incubate: ASP 2:4 sparsity, LookAhead, ModelAverage; core.monitor stats.

Mirrors reference tests under unittests/asp/ and incubate optimizer tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.incubate import LookAhead, ModelAverage, asp


def test_asp_mask_2of4():
    w = np.random.RandomState(0).randn(8, 16).astype("float32")
    mask = asp.create_mask(w, n=2, m=4)
    assert mask.shape == w.shape
    groups = mask.reshape(-1, 4)
    assert (groups.sum(1) == 2).all()
    # mask keeps the largest-magnitude entries
    pruned = w * mask
    assert asp.check_sparsity(pruned, 2, 4)
    assert asp.calculate_density(pruned) == pytest.approx(0.5)


def test_asp_prune_model_and_decorate():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    densities = asp.prune_model(net)
    assert len(densities) == 2
    assert all(d == pytest.approx(0.5) for d in densities.values())

    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=net.parameters()))
    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 16).astype("float32"))
    y = paddle.to_tensor(np.zeros((4,), "int64"))
    loss = paddle.nn.CrossEntropyLoss()(net(x), y)
    loss.backward()
    opt.step()
    # sparsity survives the dense gradient update
    assert asp.check_sparsity(net[0].weight, 2, 4)
    assert asp.check_sparsity(net[2].weight, 2, 4)
    asp.reset_excluded_layers()


def test_lookahead_interpolates_slow_weights():
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 2)
    w0 = lin.weight.numpy().copy()
    inner = paddle.optimizer.SGD(learning_rate=0.5, parameters=lin.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    for i in range(2):
        loss = lin(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # after k=2 steps: fast took 2 sgd steps, slow = w0 + 0.5*(fast - w0)
    fast_expected = w0 - 0.5 * 2 * np.ones_like(w0) * 2  # dL/dw = sum over batch = 2
    np.testing.assert_allclose(lin.weight.numpy(),
                               w0 + 0.5 * (fast_expected - w0), rtol=1e-5)


def test_model_average_apply_restore():
    paddle.seed(0)
    lin = paddle.nn.Linear(3, 1)
    ma = ModelAverage(parameters=lin.parameters())
    vals = []
    for v in (1.0, 3.0):
        lin.weight._data = lin.weight._data * 0 + v
        ma.step()
        vals.append(lin.weight.numpy().copy())
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(),
                                   (vals[0] + vals[1]) / 2, rtol=1e-6)
    np.testing.assert_allclose(lin.weight.numpy(), vals[1], rtol=1e-6)  # restored


def test_monitor_stats():
    s = monitor.stat("test_counter")
    s.set(0)
    s.increase(5)
    s.increase(3)
    s.decrease(2)
    assert s.get() == 6
    assert s.peak() == 8
    assert "test_counter" in monitor.registry().report()
    # CPU has no PJRT memory stats; must degrade to {} not raise
    assert isinstance(monitor.device_memory_stats(), dict)

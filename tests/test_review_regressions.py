"""Regression tests for code-review findings (round 1)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_setitem_on_nonleaf_backwardable():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    y[0] = 5.0
    y.sum().backward()  # must not raise "cycle detected"
    np.testing.assert_allclose(x.grad.numpy(), [0, 2, 2])


def test_inplace_on_leaf_requires_grad_raises():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with pytest.raises(RuntimeError, match="in-place"):
        x.add_(1.0)
    with paddle.no_grad():
        x.add_(1.0)  # fine under no_grad (optimizer pattern)
    np.testing.assert_allclose(x.numpy(), [2.0])


def test_adamw_explicit_zero_weight_decay():
    p = nn.Parameter(np.asarray([1.0], np.float32))
    opt = paddle.optimizer.AdamW(learning_rate=0.0, parameters=[p], weight_decay=0.0)
    assert opt._rule_kwargs(p)["weight_decay"] == 0.0
    opt2 = paddle.optimizer.AdamW(learning_rate=0.0, parameters=[p],
                                  apply_decay_param_fun=lambda n: False)
    assert opt2._rule_kwargs(p)["weight_decay"] == 0.0
    opt3 = paddle.optimizer.AdamW(learning_rate=0.0, parameters=[p])
    assert opt3._rule_kwargs(p)["weight_decay"] == 0.01  # default


def test_grad_api_does_not_pollute_other_leaves():
    w = paddle.to_tensor([3.0], stop_gradient=False)
    x = paddle.to_tensor([2.0], stop_gradient=False)
    (gx,) = paddle.grad((w * x).sum(), [x])
    np.testing.assert_allclose(gx.numpy(), [3.0])
    assert w.grad is None and x.grad is None


def test_bool_mask_getitem_differentiable():
    a = paddle.to_tensor(np.arange(4, dtype=np.float32), stop_gradient=False)
    b = a * 2
    mask = paddle.to_tensor([True, False, True, False])
    sel = b[mask]
    assert not sel.stop_gradient
    sel.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [2, 0, 2, 0])


def test_masked_select_differentiable():
    a = paddle.to_tensor(np.arange(4, dtype=np.float32), stop_gradient=False)
    sel = paddle.masked_select(a * 3, paddle.to_tensor([False, True, True, False]))
    np.testing.assert_allclose(sel.numpy(), [3, 6])
    sel.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [0, 3, 3, 0])


def test_split_indivisible_raises():
    with pytest.raises(ValueError, match="not divisible"):
        paddle.split(paddle.ones([5]), 2)


def test_cross_entropy_ignore_index_default_mean():
    logits = paddle.to_tensor(np.random.RandomState(0).rand(4, 3).astype(np.float32))
    labels_pad = paddle.to_tensor(np.array([0, 1, -100, -100], np.int64))
    labels_valid = paddle.to_tensor(np.array([0, 1], np.int64))
    loss_pad = F.cross_entropy(logits, labels_pad)
    loss_valid = F.cross_entropy(logits[paddle.to_tensor([0, 1])], labels_valid)
    np.testing.assert_allclose(loss_pad.numpy(), loss_valid.numpy(), rtol=1e-5)


def test_non_persistable_buffer_excluded_from_state_dict():
    layer = nn.Linear(2, 2)
    layer.register_buffer("scratch", paddle.ones([1]), persistable=False)
    layer.register_buffer("kept", paddle.ones([1]), persistable=True)
    sd = layer.state_dict()
    assert "kept" in sd and "scratch" not in sd


def test_reshape_inplace_on_nonleaf():
    x = paddle.to_tensor(np.ones((2, 3), np.float32), stop_gradient=False)
    y = x * 2
    y.reshape_([6])
    assert y.shape == [6]
    y.sum().backward()
    assert x.grad.shape == [2, 3]


def test_native_build_race_two_processes(tmp_path):
    """Two processes building the same native library concurrently must both
    end with a loadable .so (a shared .tmp target used to let one rank rename
    the other's half-written object — the corrupted cache then broke every
    later multi-process fleet-executor run)."""
    import subprocess
    import sys
    import textwrap

    script = tmp_path / "build_one.py"
    script.write_text(textwrap.dedent("""
        import ctypes
        from paddle_tpu.core.native import build_library
        ctypes.CDLL(build_library("tcp_store"))
        print("LOADED")
    """))
    import os

    env = {**os.environ, "PADDLE_TPU_NATIVE_CACHE": str(tmp_path / "cache")}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, str(script)], env=env,
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              text=True) for _ in range(2)]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert all("LOADED" in o for o in outs), outs


def test_native_corrupted_cache_recovers(tmp_path, monkeypatch):
    """A corrupted cached .so (e.g. from a pre-fix concurrent build) must heal:
    load_library recompiles to a temp, loads it, and swaps it into the cache
    without ever deleting an entry another process might hold open."""
    import os

    monkeypatch.setenv("PADDLE_TPU_NATIVE_CACHE", str(tmp_path))
    import importlib

    import paddle_tpu.core.native as native
    native = importlib.reload(native)
    src = [os.path.join(native._SRC_DIR, "tcp_store.cc")]
    out = native._out_path("tcp_store", src, ())
    with open(out, "wb") as f:
        f.write(b"garbage not an elf")
    lib = native.load_library("tcp_store")
    assert lib is not None
    assert os.path.getsize(out) > 1000  # cache healed in place


def test_native_env_load_failure_does_not_rebuild(tmp_path, monkeypatch):
    """A cache entry that IS a real ELF but still fails to dlopen signals an
    environment problem (missing runtime dep), not corruption — rebuilding
    would reproduce the failure at multi-second cost per process, so the
    loader must fall back to Python without recompiling."""
    import os

    monkeypatch.setenv("PADDLE_TPU_NATIVE_CACHE", str(tmp_path))
    import importlib

    import paddle_tpu.core.native as native
    native = importlib.reload(native)
    src = [os.path.join(native._SRC_DIR, "tcp_store.cc")]
    out = native._out_path("tcp_store", src, ())
    os.makedirs(os.path.dirname(out), exist_ok=True)
    # structurally-valid ELF header (magic, 64-bit LE, section table fits in
    # the file) whose body is garbage — dlopen fails, but the structure says
    # "not truncated", i.e. rebuild would reproduce the failure
    import struct
    hdr = bytearray(64)
    hdr[0:4] = b"\x7fELF"
    hdr[4], hdr[5] = 2, 1  # ELFCLASS64, little-endian
    struct.pack_into("<Q", hdr, 0x28, 64)   # e_shoff = end of header
    struct.pack_into("<HH", hdr, 0x3A, 0, 0)  # e_shentsize, e_shnum
    payload = bytes(hdr) + b"\0" * 64
    with open(out, "wb") as f:
        f.write(payload)
    calls = []
    real_compile = native._compile
    monkeypatch.setattr(native, "_compile",
                        lambda *a, **k: calls.append(a) or real_compile(*a, **k))
    lib = native.load_library("tcp_store")
    assert lib is None          # python fallback
    assert calls == []          # and NO rebuild churn
    with open(out, "rb") as f:
        assert f.read() == payload  # cache entry untouched


def test_native_truncated_cache_recovers(tmp_path, monkeypatch):
    """A HALF-written .so keeps the ELF magic (the header lands first) but
    its section table points past the truncation — that must still classify
    as corruption and heal, not as an environment failure."""
    import os

    monkeypatch.setenv("PADDLE_TPU_NATIVE_CACHE", str(tmp_path))
    import importlib

    import paddle_tpu.core.native as native
    native = importlib.reload(native)
    # build WITHOUT dlopen-ing here: truncating a file this process has
    # mapped poisons the live mapping (later symbol access SIGBUSes the
    # whole pytest process — exactly the hazard the loader guards against)
    out = native.build_library("tcp_store")
    with open(out, "rb") as f:
        real = f.read()
    tmp_trunc = out + ".trunc"
    with open(tmp_trunc, "wb") as f:
        f.write(real[:1024])  # truncate early (magic survives, segments don't)
    os.replace(tmp_trunc, out)  # swap, never write the cache file in place
    assert not native._elf_intact(out)
    # dlopen caches by path within a process (the intact pre-truncation
    # mapping would mask the damage) — a FRESH process must hit the heal path
    import subprocess
    import sys as _sys
    env = dict(os.environ, PADDLE_TPU_NATIVE_CACHE=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [_sys.executable, "-c",
         "import paddle_tpu.core.native as n; "
         "print('LOADED' if n.load_library('tcp_store') else 'NONE')"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "LOADED" in r.stdout, (r.stdout, r.stderr[-500:])
    assert os.path.getsize(out) > len(real) // 2  # cache healed in place

"""Static-graph Program IR + Executor tests (SURVEY.md §3.4 path).

Covers: op capture into OpDescs, shape inference, Executor forward lowering,
Optimizer.minimize training through the lowered step (loss parity with dygraph),
program_guard isolation, clone(for_test), and static.nn layers."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
import paddle_tpu.nn as nn


def test_capture_and_infer():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        y = x * 2.0 + 1.0
        z = paddle.matmul(y, paddle.to_tensor(np.ones((8, 3), np.float32)))
    assert isinstance(y, static.Variable)
    assert z.shape == [4, 3]
    assert len(main.global_block().ops) == 3
    assert main.global_block().ops[0].type in ("multiply", "scale")


def test_executor_forward():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 3], "float32")
        out = paddle.nn.functional.relu(x - 1.0)
    exe = static.Executor()
    xs = np.array([[0.5, 1.5, 2.0], [-1.0, 1.0, 3.0]], np.float32)
    (res,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(res, np.maximum(xs - 1.0, 0.0), rtol=1e-6)


def test_static_linear_regression_trains():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 4).astype(np.float32)
    w_true = np.array([[2.0], [-1.0], [0.5], [3.0]], np.float32)
    ys = xs @ w_true + 1.0

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [64, 4], "float32")
        y = static.data("y", [64, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - y) ** 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = []
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 0.01, losses[::10]
    assert losses[-1] < losses[0] / 100


def test_static_matches_dygraph_loss():
    """First-step loss of a static fc must equal the dygraph Linear with the same
    params — the dygraph_to_static parity contract (SURVEY.md §4)."""
    rng = np.random.RandomState(1)
    xs = rng.randn(8, 5).astype(np.float32)
    ys = rng.randn(8, 2).astype(np.float32)

    paddle.seed(42)
    lin = nn.Linear(5, 2)
    eager_loss = float(paddle.mean((lin(paddle.to_tensor(xs)) -
                                    paddle.to_tensor(ys)) ** 2).item())

    paddle.seed(42)
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [8, 5], "float32")
        y = static.data("y", [8, 2], "float32")
        pred = static.nn.fc(x, 2)
        loss = paddle.mean((pred - y) ** 2)
    exe = static.Executor()
    (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    np.testing.assert_allclose(float(lv), eager_loss, rtol=1e-5)


def test_clone_for_test_drops_train():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [4, 2], "float32")
        loss = paddle.mean(static.nn.fc(x, 1))
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    assert main._train is not None
    test_prog = main.clone(for_test=True)
    assert test_prog._train is None
    assert len(test_prog.global_block().ops) == len(main.global_block().ops)


def test_program_guard_isolation():
    p1, p2 = static.Program(), static.Program()
    with static.program_guard(p1, static.Program()):
        a = static.data("a", [2], "float32")
        _ = a + 1.0
        with static.program_guard(p2, static.Program()):
            b = static.data("b", [2], "float32")
            _ = b * 3.0
        _ = a - 1.0
    assert len(p1.global_block().ops) == 2
    assert len(p2.global_block().ops) == 1


def test_default_program_survives_guard():
    # regression: guard exit must not poison default_main_program (review r2)
    with static.program_guard(static.Program(), static.Program()):
        pass
    v = static.data("x_guard_regress", [2, 2])
    assert v.block.program is static.default_main_program()


def test_clone_is_isolated():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 2])
        _ = x + 1.0
    clone = main.clone(for_test=True)
    with static.program_guard(main, static.Program()):
        _ = x * 2.0
    assert len(main.global_block().ops) == 2
    assert len(clone.global_block().ops) == 1


def test_dynamic_batch_dim():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [None, 3], "float32")
        out = paddle.nn.functional.relu(x * 2.0)
        assert out.shape[0] == -1 and out.shape[1] == 3
    exe = static.Executor()
    for b in (2, 5):  # two batch sizes through the same program
        xs = np.ones((b, 3), np.float32)
        (res,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
        assert res.shape == (b, 3)


def test_dygraph_optimizer_without_params_raises():
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    with pytest.raises(ValueError):
        opt.step()


def test_param_updates_visible_in_dygraph():
    """Static training updates the SAME Parameter objects the layer owns."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [16, 3], "float32")
        y = static.data("y", [16, 1], "float32")
        lin = nn.Linear(3, 1)
        before = lin.weight.numpy().copy()
        loss = paddle.mean((lin(x) - y) ** 2)
        paddle.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = static.Executor()
    rng = np.random.RandomState(0)
    exe.run(main, feed={"x": rng.randn(16, 3).astype(np.float32),
                        "y": rng.randn(16, 1).astype(np.float32)},
            fetch_list=[loss])
    after = lin.weight.numpy()
    assert not np.allclose(before, after)

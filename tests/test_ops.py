"""Numpy-referenced op tests via the OpTest harness (reference op_test.py pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_grad, check_output

class _RNG:
    """Order-independent determinism: fresh stream per access."""

    def __getattr__(self, name):
        return getattr(np.random.RandomState(42), name)


rng = _RNG()


@pytest.mark.parametrize("op,ref", [
    (paddle.add, np.add), (paddle.subtract, np.subtract),
    (paddle.multiply, np.multiply), (paddle.divide, np.divide),
    (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
    (paddle.atan2, np.arctan2),
])
def test_binary_elementwise(op, ref):
    a = rng.rand(3, 4).astype(np.float32) + 0.5
    b = rng.rand(3, 4).astype(np.float32) + 0.5
    check_output(op, ref, [a, b])


def test_broadcasting():
    a = rng.rand(3, 1, 4).astype(np.float32)
    b = rng.rand(1, 5, 4).astype(np.float32)
    check_output(paddle.add, np.add, [a, b])


@pytest.mark.parametrize("op,ref", [
    (paddle.exp, np.exp), (paddle.log, np.log), (paddle.sqrt, np.sqrt),
    (paddle.abs, np.abs), (paddle.sin, np.sin), (paddle.cos, np.cos),
    (paddle.tanh, np.tanh), (paddle.floor, np.floor), (paddle.ceil, np.ceil),
    (paddle.square, np.square), (paddle.sign, np.sign),
])
def test_unary(op, ref):
    a = rng.rand(3, 4).astype(np.float32) + 0.5
    check_output(op, ref, [a])


def test_reductions():
    a = rng.rand(3, 4, 5).astype(np.float32)
    check_output(paddle.sum, lambda x: x.sum(), [a])
    check_output(lambda x: paddle.sum(x, axis=1), lambda x: x.sum(1), [a])
    check_output(lambda x: paddle.sum(x, axis=[0, 2], keepdim=True),
                 lambda x: x.sum((0, 2), keepdims=True), [a])
    check_output(paddle.mean, lambda x: x.mean(), [a])
    check_output(lambda x: paddle.max(x, axis=-1), lambda x: x.max(-1), [a])
    check_output(lambda x: paddle.min(x, axis=0), lambda x: x.min(0), [a])
    check_output(lambda x: paddle.prod(x, axis=1), lambda x: x.prod(1), [a], rtol=1e-4)
    check_output(lambda x: paddle.argmax(x, axis=1), lambda x: x.argmax(1), [a])
    check_output(lambda x: paddle.std(x, axis=1), lambda x: x.std(1, ddof=1), [a])
    check_output(lambda x: paddle.var(x, axis=1), lambda x: x.var(1, ddof=1), [a])
    check_output(paddle.logsumexp, lambda x: np.log(np.exp(x).sum()), [a])


def test_matmul_variants():
    a = rng.rand(3, 4).astype(np.float32)
    b = rng.rand(4, 5).astype(np.float32)
    check_output(paddle.matmul, np.matmul, [a, b])
    check_output(lambda x, y: paddle.matmul(x, y, transpose_y=True),
                 lambda x, y: x @ y.T, [a, rng.rand(5, 4).astype(np.float32)])
    batch_a = rng.rand(2, 3, 4).astype(np.float32)
    batch_b = rng.rand(2, 4, 5).astype(np.float32)
    check_output(paddle.bmm, np.matmul, [batch_a, batch_b])
    check_output(lambda x, y: paddle.einsum("ij,jk->ik", x, y),
                 lambda x, y: x @ y, [a, b])


def test_softmax_ops():
    x = rng.rand(4, 7).astype(np.float32)

    def np_softmax(v):
        e = np.exp(v - v.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    check_output(paddle.nn.functional.softmax, np_softmax, [x])
    check_output(paddle.nn.functional.log_softmax, lambda v: np.log(np_softmax(v)), [x])


def test_activations_numeric():
    x = (rng.rand(3, 4).astype(np.float32) - 0.5) * 4
    check_output(F.relu, lambda v: np.maximum(v, 0), [x])
    check_output(F.sigmoid, lambda v: 1 / (1 + np.exp(-v)), [x])
    check_output(F.silu, lambda v: v / (1 + np.exp(-v)), [x], rtol=1e-4)
    check_output(lambda t: F.leaky_relu(t, 0.1),
                 lambda v: np.where(v > 0, v, 0.1 * v), [x])
    import math

    check_output(lambda t: F.gelu(t),
                 lambda v: 0.5 * v * (1 + np.vectorize(math.erf)(v / np.sqrt(2))),
                 [x], rtol=1e-4)


# ---- gradient checks (numeric vs analytic through the tape) ----

@pytest.mark.parametrize("op", [
    paddle.exp, paddle.tanh, paddle.square,
    lambda x: paddle.nn.functional.softmax(x),
    lambda x: F.gelu(x),
])
def test_grad_unary(op):
    x = rng.rand(3, 4).astype(np.float64) + 0.3
    check_grad(op, [x])


def test_grad_matmul():
    a = rng.rand(3, 4).astype(np.float64)
    b = rng.rand(4, 2).astype(np.float64)
    check_grad(paddle.matmul, [a, b], input_idx=0)
    check_grad(paddle.matmul, [a, b], input_idx=1)


def test_grad_reduction():
    x = rng.rand(4, 5).astype(np.float64) * 10  # well-separated so max() is not tied
    check_grad(lambda t: paddle.mean(t, axis=1), [x])
    check_grad(lambda t: paddle.max(t, axis=1), [x], eps=1e-3, rtol=2e-2, atol=2e-3)


def test_grad_conv2d():
    x = rng.rand(2, 3, 8, 8).astype(np.float64)
    w = rng.rand(4, 3, 3, 3).astype(np.float64)
    check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w], input_idx=0,
               rtol=2e-2, atol=2e-3)
    check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w], input_idx=1,
               rtol=2e-2, atol=2e-3)


def test_grad_layer_norm():
    x = rng.rand(4, 6).astype(np.float64)
    check_grad(lambda t: F.layer_norm(t, 6), [x], rtol=2e-2, atol=2e-3)


def test_grad_cross_entropy():
    logits = rng.rand(4, 5).astype(np.float64)
    labels = np.array([0, 1, 2, 3])

    def op(lg):
        return F.cross_entropy(lg, paddle.to_tensor(labels))

    check_grad(op, [logits])


def test_cross_entropy_value():
    logits = rng.rand(4, 5).astype(np.float32)
    labels = np.array([0, 1, 2, 3])
    loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
    # numpy reference
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expect = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(loss.numpy(), expect, rtol=1e-5)


def test_conv2d_value_vs_scipy():
    try:
        from scipy import signal
    except ImportError:
        pytest.skip("scipy missing")
    x = rng.rand(1, 1, 6, 6).astype(np.float32)
    w = rng.rand(1, 1, 3, 3).astype(np.float32)
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)).numpy()
    expect = signal.correlate2d(x[0, 0], w[0, 0], mode="valid")[None, None]
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_pool_values():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = F.max_pool2d(paddle.to_tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])
    out = F.avg_pool2d(paddle.to_tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(out[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_batch_norm_train_eval():
    x = rng.rand(8, 3, 4, 4).astype(np.float32)
    bn = paddle.nn.BatchNorm2D(3)
    bn.train()
    out = bn(paddle.to_tensor(x))
    got = out.numpy()
    m = x.mean((0, 2, 3), keepdims=True)
    v = x.var((0, 2, 3), keepdims=True)
    np.testing.assert_allclose(got, (x - m) / np.sqrt(v + 1e-5), rtol=1e-4, atol=1e-4)
    # running stats moved toward batch stats
    assert abs(bn._mean.numpy().mean()) > 0
    bn.eval()
    out2 = bn(paddle.to_tensor(x))
    assert not np.allclose(out2.numpy(), got)


def test_dropout_train_eval():
    paddle.seed(0)
    x = paddle.ones([1000])
    y = F.dropout(x, 0.5, training=True)
    kept = (y.numpy() > 0).mean()
    assert 0.35 < kept < 0.65
    np.testing.assert_allclose(y.numpy()[y.numpy() > 0], 2.0)
    y_eval = F.dropout(x, 0.5, training=False)
    np.testing.assert_allclose(y_eval.numpy(), 1.0)


def test_embedding_and_one_hot():
    table = rng.rand(10, 4).astype(np.float32)
    ids = np.array([[1, 2], [3, 4]])
    out = F.embedding(paddle.to_tensor(ids), paddle.to_tensor(table))
    np.testing.assert_allclose(out.numpy(), table[ids])
    oh = F.one_hot(paddle.to_tensor([1, 3]), 5).numpy()
    np.testing.assert_allclose(oh, np.eye(5)[[1, 3]])


def test_attention_causal():
    q = rng.rand(2, 6, 2, 8).astype(np.float32)
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q), is_causal=True)
    assert out.shape == [2, 6, 2, 8]
    # first position output must equal v at first position (causal)
    np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], rtol=1e-5)

"""tools/plan_validate.py join logic: only CLEAN rows may match a predicted
variant — kernel-variant and full-recompute runs must not masquerade as the
plain measurement (round-4 review: the b32 history row is recompute=true)."""
import json
import os
import sys

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(TESTS_DIR), "tools"))


def _write(tmp_path, rows):
    p = tmp_path / "hist.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    return str(p)


def _row(value, **extra):
    base = {"seq": 1024, "devices": 1, "batch": 16}
    base.update(extra)
    return {"metric": "m", "value": value, "extra": base}


def test_measured_tokens_clean_join(tmp_path):
    import plan_validate as pv

    path = _write(tmp_path, [
        _row(100.0),                                   # clean b16
        _row(250.0, batch=32, recompute=True),         # full recompute: skip
        _row(130.0, recompute="selective"),            # b16_selective
        _row(999.0, pallas_ln="1"),                    # kernel variant: skip
        _row(888.0, scan="1"),                         # scan trainer: skip
        _row(777.0, seq=4096),                         # wrong seq: skip
        _row(666.0, devices=8),                        # multi-device: skip
        _row(120.0, ce_chunk="4096"),                  # ce4096_b16
        _row(110.0),                                   # best-per-tag max
    ])
    got = pv.measured_tokens(path, 1024)
    assert got == {"b16": 110.0, "b16_selective": 130.0,
                   "ce4096_b16": 120.0}, got


def test_measured_tokens_rejects_model_and_knob_mismatches(tmp_path):
    import plan_validate as pv

    path = _write(tmp_path, [
        _row(100.0, hidden=768, layers=12),            # clean base row
        _row(500.0, hidden=1024, layers=24),           # medium model: skip
        _row(400.0, pallas_ln="0"),                    # "0" is knob-ON: skip
        _row(300.0, ce_chunk="4096",
             recompute="selective"),                   # combined knobs: skip
    ])
    got = pv.measured_tokens(path, 1024)
    assert got == {"b16": 100.0}, got

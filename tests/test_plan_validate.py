"""tools/plan_validate.py join logic: only CLEAN rows may match a predicted
variant — kernel-variant and full-recompute runs must not masquerade as the
plain measurement (round-4 review: the b32 history row is recompute=true)."""
import json
import os
import sys

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(TESTS_DIR), "tools"))


def _write(tmp_path, rows):
    p = tmp_path / "hist.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    return str(p)


def _row(value, **extra):
    base = {"seq": 1024, "devices": 1, "batch": 16}
    base.update(extra)
    return {"metric": "m", "value": value, "extra": base}


def test_measured_tokens_clean_join(tmp_path):
    import plan_validate as pv

    path = _write(tmp_path, [
        _row(100.0),                                   # clean b16
        _row(250.0, batch=32, recompute=True),         # full recompute: skip
        _row(130.0, recompute="selective"),            # b16_selective
        _row(999.0, pallas_ln="1"),                    # kernel variant: skip
        _row(888.0, scan="1"),                         # scan trainer: skip
        _row(115.0, autotune="1"),                     # tuned flash blocks:
        _row(104.0, autotune_cache_loaded=True),       # ACCEPTED since r5 —
        # the committed cache makes tuned blocks the default program
        _row(777.0, seq=4096),                         # wrong seq: skip
        _row(666.0, devices=8),                        # multi-device: skip
        _row(120.0, ce_chunk="4096"),                  # ce4096_b16
        _row(110.0),                                   # best-per-tag max
    ])
    got = pv.measured_tokens(path, 1024)
    assert got == {"b16": 115.0, "b16_selective": 130.0,
                   "ce4096_b16": 120.0}, got


def test_measured_tokens_rejects_model_and_knob_mismatches(tmp_path):
    import plan_validate as pv

    path = _write(tmp_path, [
        _row(100.0, hidden=768, layers=12),            # clean base row
        _row(500.0, hidden=1024, layers=24),           # medium model: skip
        _row(400.0, pallas_ln="0"),                    # "0" is knob-ON: skip
        _row(300.0, ce_chunk="4096",
             recompute="selective"),                   # combined knobs: skip
    ])
    got = pv.measured_tokens(path, 1024)
    assert got == {"b16": 100.0}, got


def test_policy_peak_distinguishes_remat_variants():
    """VERDICT r4 weak #4: XLA's AOT memory analysis reports identical peaks
    with and without selective remat (the declared round-4 limitation); the
    policy-aware residual term must give the remat variant a STRICTLY
    smaller corrected peak while the blind-spotted XLA peaks stay equal."""
    import plan_validate as pv

    m_plain = pv.score_variant({"tag": "b16", "batch": 16}, 256, quick=True)
    m_sel = pv.score_variant(
        {"tag": "b16_selective", "batch": 16, "recompute": "selective"},
        256, quick=True)
    assert m_plain["peak_policy_bytes"] is not None
    assert m_sel["peak_policy_bytes"] is not None
    # the blind spot itself (documents WHY the corrected term exists); if
    # XLA's analysis ever learns to credit remat this guard goes stale
    # loudly and the correction can be retired. Tolerance 10%: CPU-target
    # scheduling wobbles the temp estimate a few percent between releases
    # (seen at 6.5% with zero repo changes) — full remat credit would show
    # as a several-10s-of-percent drop, nowhere near this band.
    assert abs(m_plain["peak_bytes"] - m_sel["peak_bytes"]) \
        < 0.10 * m_plain["peak_bytes"]
    assert m_sel["peak_policy_bytes"] < 0.9 * m_plain["peak_policy_bytes"], (
        m_sel["peak_policy_bytes"], m_plain["peak_policy_bytes"])


def test_planner_budget_gate_uses_corrected_peak():
    """A budget between the remat variant's corrected peak and the XLA
    number must keep the remat variant feasible (min-of-estimates gate)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import planner as P
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, use_recompute=True,
                    recompute_granularity="selective", dropout=0.0,
                    attention_dropout=0.0)

    def mk():
        paddle.seed(0)
        return GPTForPretraining(cfg)

    def mko(m):
        return paddle.optimizer.AdamW(learning_rate=1e-3,
                                      parameters=m.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (8, 256)).astype(np.int64)
    batch = [ids, np.roll(ids, -1, 1)]
    # no budget: the residual trace is skipped (it re-runs the forward, so
    # it only pays off when feasibility is actually in question)
    r0 = P.score_topology(mk, mko, batch, {"dp_degree": 1})
    assert r0.detail.get("peak_policy_bytes") is None
    # huge budget: policy peak computed and recorded
    r = P.score_topology(mk, mko, batch, {"dp_degree": 1},
                         memory_budget=1 << 50)
    pol = r.detail.get("peak_policy_bytes")
    assert pol is not None and pol < r.peak_bytes, (pol, r.peak_bytes)
    safety = int(P._POLICY_GATE_SAFETY * pol)
    assert safety < r.peak_bytes, "model too small to exercise the override"
    # budget between the SAFETY-padded policy peak and the XLA peak: the
    # remat variant stays feasible, flagged as speculatively admitted
    budget = (safety + r.peak_bytes) // 2
    r2 = P.score_topology(mk, mko, batch, {"dp_degree": 1},
                          memory_budget=budget)
    assert r2.feasible, (
        f"corrected-peak gate regressed: budget {budget} rejected a variant "
        f"whose padded policy peak is {safety}")
    assert r2.detail.get("feasibility_gate") == "policy_peak_with_safety"
    # budget UNDER the padded policy peak: still rejected — the safety
    # factor (unmodeled recompute working set) must not be bypassed
    r3 = P.score_topology(mk, mko, batch, {"dp_degree": 1},
                          memory_budget=safety // 2)
    assert not r3.feasible


def test_replay_correction_separates_remat_variants():
    """Round-5 correction: the raw AOT score under-prices selective remat
    (~1.5% apart vs ~15% measured on chip); the replay term — 2x the
    saved-residual delta vs the plain twin — must push the corrected score
    of the remat variant strictly above its twin's, while non-remat
    variants keep their raw score."""
    import plan_validate as pv

    m_plain = pv.score_variant({"tag": "b16", "batch": 16}, 256, quick=True)
    m_sel = pv.score_variant(
        {"tag": "b16_selective", "batch": 16, "recompute": "selective"},
        256, quick=True)
    rows = [
        {"tag": "b16", "score": m_plain["score"],
         "residual_bytes": m_plain["residual_bytes"]},
        {"tag": "b16_selective", "score": m_sel["score"],
         "residual_bytes": m_sel["residual_bytes"]},
    ]
    pv.apply_replay_correction(rows, 256)
    plain, sel = rows
    assert plain["score_corrected"] == plain["score"]
    expected = m_sel["score"] + 2 * (m_plain["residual_bytes"]
                                     - m_sel["residual_bytes"])
    assert sel["score_corrected"] == expected
    # the whole point: corrected, the remat variant prices its replay
    assert sel["score_corrected"] > plain["score_corrected"]
    # per-token prediction follows the corrected score
    assert sel["pred_tokens_per_s_rel_corrected"] < \
        plain["pred_tokens_per_s_rel_corrected"]


def test_replay_correction_survives_missing_residuals():
    import plan_validate as pv

    rows = [{"tag": "b32", "score": 100.0, "residual_bytes": None},
            {"tag": "b32_selective", "score": 101.0, "residual_bytes": None}]
    pv.apply_replay_correction(rows, 1024)
    assert [r["score_corrected"] for r in rows] == [100.0, 101.0]


def test_pair_verdict_abstains_batch_axis_inside_resolution():
    """VERDICT r5 next #5: batch-axis comparisons inside the model's stated
    resolution are 'not decidable', not ranked — the b16/b24 regime (the
    proxy's batch margins are sub-1% while the measured mis-rank margin was
    2.3%). Structurally different programs keep full-margin ranking."""
    from paddle_tpu.distributed.auto_parallel.planner import (
        PREDICTION_RESOLUTION, pair_verdict)

    # the known mis-rank shape: tiny predicted batch margin -> abstain
    v, margin = pair_verdict(1.013, 1.0097, batch_axis_only=True)
    assert v == "not_decidable" and margin < PREDICTION_RESOLUTION
    # same margin on a structurally different pair -> still ranked
    v, _ = pair_verdict(1.013, 1.0097, batch_axis_only=False)
    assert v == "a"
    # a batch pair OUTSIDE the resolution stays decidable
    v, _ = pair_verdict(1.10, 1.00, batch_axis_only=True)
    assert v == "a"
    v, _ = pair_verdict(1.00, 1.10, batch_axis_only=True)
    assert v == "b"
    # degenerate zero prediction never divides by zero
    v, margin = pair_verdict(1.0, 0.0, batch_axis_only=True)
    assert v == "a" and margin == float("inf")

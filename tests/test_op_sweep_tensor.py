"""Numeric sweep 2/2 — manipulation, indexing, linalg ops from the reference
api.yaml surface that had no per-op test (VERDICT r1 weak #5). Same op_test
pattern as test_op_sweep_math.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


def t(a):
    return paddle.to_tensor(a)


def _rand(shape, lo=-1.0, hi=1.0, seed=0):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype(np.float32)


# ---- indexing / rearrangement ----------------------------------------------
def test_argmin_argsort():
    x = _rand((3, 5))
    check_output(paddle.argmin, lambda a, axis: np.argmin(a, axis),
                 [x], {"axis": 1})
    check_output(paddle.argsort, lambda a, axis: np.argsort(a, axis),
                 [x], {"axis": 1})


def test_flip_diagonal_unbind():
    x = _rand((2, 3, 4))
    check_output(paddle.flip, lambda a, axis: np.flip(a, axis),
                 [x], {"axis": [0, 2]})
    check_output(paddle.diagonal,
                 lambda a, offset, axis1, axis2: np.diagonal(a, offset, axis1, axis2),
                 [x], {"offset": 1, "axis1": 1, "axis2": 2})
    outs = paddle.unbind(t(x), axis=1)
    assert len(outs) == 3
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o.numpy(), x[:, i])
    check_grad(paddle.diagonal, [x.astype(np.float64)[0]],
               {"offset": 0, "axis1": 0, "axis2": 1})


def test_expand_as_meshgrid():
    x = _rand((1, 3))
    y = np.zeros((4, 3), np.float32)
    np.testing.assert_allclose(paddle.expand_as(t(x), t(y)).numpy(),
                               np.broadcast_to(x, (4, 3)))
    a, b = np.arange(3, dtype=np.float32), np.arange(2, dtype=np.float32)
    ga, gb = paddle.meshgrid(t(a), t(b))
    ea, eb = np.meshgrid(a, b, indexing="ij")
    np.testing.assert_allclose(ga.numpy(), ea)
    np.testing.assert_allclose(gb.numpy(), eb)


def test_gather_nd_index_select_index_sample():
    x = _rand((3, 4, 5))
    idx = np.array([[0, 1], [2, 3]], np.int64)
    check_output(paddle.gather_nd,
                 lambda a, i: a[tuple(np.moveaxis(i, -1, 0))],
                 [x, idx])
    sel = np.array([2, 0], np.int64)
    check_output(paddle.index_select,
                 lambda a, i, axis: np.take(a, i, axis),
                 [x, sel], {"axis": 1})
    m = _rand((3, 6))
    samp = np.array([[0, 5], [2, 2], [1, 0]], np.int64)
    check_output(paddle.index_sample,
                 lambda a, i: np.take_along_axis(a, i, 1), [m, samp])
    check_grad(paddle.gather_nd, [x.astype(np.float64)[0], idx])


def test_put_along_axis_scatter_nd_add():
    x = _rand((3, 4))
    idx = np.array([[0, 2], [1, 3], [2, 0]], np.int64)
    val = _rand((3, 2), seed=3)

    def np_put(a, i, v, axis):
        out = a.copy()
        np.put_along_axis(out, i, v, axis)
        return out

    check_output(lambda a, i, v, axis: paddle.put_along_axis(a, i, v, axis),
                 np_put, [x, idx, val], {"axis": 1})

    base = _rand((4, 3))
    nd_idx = np.array([[1], [3], [1]], np.int64)
    upd = _rand((3, 3), seed=5)

    def np_scatter_nd_add(a, i, u):
        out = a.copy()
        for r in range(i.shape[0]):
            out[tuple(i[r])] += u[r]
        return out

    check_output(paddle.scatter_nd_add, np_scatter_nd_add,
                 [base, nd_idx, upd])
    check_grad(paddle.scatter_nd_add,
               [base.astype(np.float64), nd_idx, upd.astype(np.float64)],
               input_idx=2)


def test_searchsorted_strided_slice():
    edges = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
    q = np.array([0.0, 3.0, 8.0], np.float32)
    check_output(paddle.searchsorted,
                 lambda s, v: np.searchsorted(s, v, side="left"), [edges, q])
    check_output(lambda s, v: paddle.searchsorted(s, v, right=True),
                 lambda s, v: np.searchsorted(s, v, side="right"), [edges, q])
    x = _rand((6, 8))
    got = paddle.strided_slice(t(x), axes=[0, 1], starts=[1, 0],
                               ends=[5, 8], strides=[2, 3]).numpy()
    np.testing.assert_allclose(got, x[1:5:2, 0:8:3])


def test_unique_full():
    x = np.array([2, 3, 3, 1, 5, 3], np.int64)
    out, index, inverse, counts = paddle.unique(
        t(x), return_index=True, return_inverse=True, return_counts=True)
    e_out, e_idx, e_inv, e_cnt = np.unique(
        x, return_index=True, return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), e_out)
    np.testing.assert_array_equal(index.numpy(), e_idx)
    np.testing.assert_array_equal(inverse.numpy(), e_inv)
    np.testing.assert_array_equal(counts.numpy(), e_cnt)


def test_kthvalue_mode_histogram():
    x = _rand((3, 7))
    v, i = paddle.kthvalue(t(x), k=3, axis=1)
    expect = np.sort(x, 1)[:, 2]
    np.testing.assert_allclose(v.numpy(), expect, rtol=1e-6)
    np.testing.assert_allclose(np.take_along_axis(x, i.numpy()[:, None], 1)[:, 0],
                               expect, rtol=1e-6)

    m = np.array([[1, 2, 2, 3], [4, 4, 5, 5]], np.float32)
    mv, mi = paddle.mode(t(m), axis=1)
    np.testing.assert_allclose(mv.numpy(), [2.0, 4.0])  # ties -> smallest value
    np.testing.assert_allclose(
        np.take_along_axis(m, mi.numpy()[:, None], 1)[:, 0], mv.numpy())
    mk, _ = paddle.mode(t(m), axis=0, keepdim=True)
    assert tuple(mk.shape) == (1, 4)

    # grads flow through the selected slots (reference kthvalue_grad/mode_grad)
    check_grad(lambda a: paddle.kthvalue(a, k=2, axis=1)[0],
               [_rand((2, 4), seed=9).astype(np.float64)])
    # mode's numeric diff is ill-posed (perturbing a tied element changes the
    # selection discontinuously): assert the analytic grad is the one-hot
    # scatter into the selected slot instead
    xm = t(np.array([[1.0, 3.0, 3.0, 2.0], [5.0, 4.0, 4.0, 6.0]], np.float32))
    xm.stop_gradient = False
    mv2, mi2 = paddle.mode(xm, axis=1)
    mv2.sum().backward()
    expect_g = np.zeros((2, 4), np.float32)
    expect_g[np.arange(2), mi2.numpy()] = 1.0
    np.testing.assert_allclose(xm.grad.numpy(), expect_g)

    h = np.array([1.0, 2.0, 1.0, 2.9], np.float32)
    check_output(lambda a, bins, min, max: paddle.histogram(a, bins=bins, min=min, max=max),
                 lambda a, bins, min, max: np.histogram(a, bins, (min, max))[0],
                 [h], {"bins": 3, "min": 0.0, "max": 3.0})


def test_multiplex_shard_index():
    a, b = _rand((4, 3)), _rand((4, 3), seed=1)
    idx = np.array([0, 1, 1, 0], np.int64)

    def np_multiplex(x1, x2, i):
        stacked = np.stack([x1, x2])
        return stacked[i, np.arange(len(i))]

    check_output(lambda x1, x2, i: paddle.multiplex([x1, x2], i),
                 np_multiplex, [a, b, idx])

    ids = np.array([[1], [7], [15]], np.int64)

    def np_shard(i, index_num, nshards, shard_id, ignore_value=-1):
        size = (index_num + nshards - 1) // nshards
        out = np.where(i // size == shard_id, i % size, ignore_value)
        return out

    check_output(
        lambda i, **kw: paddle.shard_index(i, **kw), np_shard, [ids],
        {"index_num": 16, "nshards": 2, "shard_id": 1})


# ---- linalg ----------------------------------------------------------------
def test_kron_dot_addmm():
    a, b = _rand((2, 3)), _rand((3, 2), seed=1)
    check_output(paddle.kron, np.kron, [a, b])
    v1, v2 = _rand((5,)), _rand((5,), seed=2)
    check_output(paddle.dot, np.dot, [v1, v2])
    inp, x, y = _rand((2, 4)), _rand((2, 3), seed=3), _rand((3, 4), seed=4)
    check_output(
        lambda i, m1, m2, beta, alpha: paddle.addmm(i, m1, m2, beta=beta, alpha=alpha),
        lambda i, m1, m2, beta, alpha: beta * i + alpha * (m1 @ m2),
        [inp, x, y], {"beta": 0.5, "alpha": 2.0}, rtol=1e-5)
    check_grad(paddle.kron, [a.astype(np.float64), b.astype(np.float64)])


def test_matrix_power():
    x = _rand((3, 3), 0.1, 1.0) + 2 * np.eye(3, dtype=np.float32)
    for n in (0, 1, 3, -1):
        check_output(lambda a, n: paddle.linalg.matrix_power(a, n),
                     lambda a, n: np.linalg.matrix_power(a, n),
                     [x], {"n": n}, rtol=1e-4, atol=1e-5)


def test_triangular_solve():
    A = np.triu(_rand((3, 3), 0.5, 2.0)) + np.eye(3, dtype=np.float32)
    b = _rand((3, 2), seed=1)
    got = paddle.linalg.triangular_solve(t(A), t(b), upper=True).numpy()
    np.testing.assert_allclose(A @ got, b, rtol=1e-4, atol=1e-5)
    L = np.tril(_rand((3, 3), 0.5, 2.0)) + np.eye(3, dtype=np.float32)
    got = paddle.linalg.triangular_solve(t(L), t(b), upper=False).numpy()
    np.testing.assert_allclose(L @ got, b, rtol=1e-4, atol=1e-5)


def test_eigh_properties():
    rng = np.random.RandomState(0)
    A = rng.randn(4, 4).astype(np.float32)
    A = (A + A.T) / 2
    w, v = paddle.linalg.eigh(t(A))
    w, v = w.numpy(), v.numpy()
    np.testing.assert_allclose(np.sort(w), w, rtol=1e-5)  # ascending
    np.testing.assert_allclose(A @ v, v * w[None, :], atol=1e-4)
    np.testing.assert_allclose(v.T @ v, np.eye(4), atol=1e-5)
    np.testing.assert_allclose(w, np.linalg.eigvalsh(A), rtol=1e-4, atol=1e-5)


def test_matrix_rank_with_tol():
    A = np.diag([5.0, 1.0, 1e-7, 0.0]).astype(np.float32)
    assert int(paddle.linalg.matrix_rank(t(A))) == 2
    assert int(paddle.linalg.matrix_rank(t(A), tol=0.5)) == 2
    assert int(paddle.linalg.matrix_rank(t(A), tol=1e-8)) == 3
    B = _rand((3, 5))
    assert int(paddle.linalg.matrix_rank(t(B))) == 3

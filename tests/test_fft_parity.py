"""paddle.fft vs numpy.fft: values, norm conventions, and inverse
round-trips (reference python/paddle/fft.py wraps the same FFT semantics;
numpy is the independent ground truth)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft

RTOL, ATOL = 2e-5, 2e-5


def _x(shape, complex_=False, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(*shape).astype("float32")
    if complex_:
        return (a + 1j * rng.randn(*shape).astype("float32")).astype(
            "complex64")
    return a


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_fft_ifft_norms(norm):
    x = _x((4, 16), complex_=True)
    ours = fft.fft(paddle.to_tensor(x), norm=norm).numpy()
    ref = np.fft.fft(x, norm=norm)
    np.testing.assert_allclose(ours, ref, rtol=RTOL, atol=ATOL)
    back = fft.ifft(paddle.to_tensor(ours), norm=norm).numpy()
    np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)


def test_rfft_irfft_roundtrip():
    x = _x((3, 32))
    ours = fft.rfft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(ours, np.fft.rfft(x), rtol=RTOL, atol=ATOL)
    back = fft.irfft(paddle.to_tensor(ours), n=32).numpy()
    np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)


def test_fft2_and_fftn():
    x = _x((2, 8, 8), complex_=True)
    np.testing.assert_allclose(fft.fft2(paddle.to_tensor(x)).numpy(),
                               np.fft.fft2(x), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(fft.fftn(paddle.to_tensor(x)).numpy(),
                               np.fft.fftn(x), rtol=RTOL, atol=ATOL)


def test_fftshift_fftfreq():
    np.testing.assert_allclose(fft.fftfreq(10, d=0.5).numpy(),
                               np.fft.fftfreq(10, d=0.5), rtol=RTOL)
    x = _x((9,))
    np.testing.assert_allclose(fft.fftshift(paddle.to_tensor(x)).numpy(),
                               np.fft.fftshift(x), rtol=RTOL)
    np.testing.assert_allclose(fft.ifftshift(paddle.to_tensor(x)).numpy(),
                               np.fft.ifftshift(x), rtol=RTOL)


def test_stft_istft_roundtrip():
    from paddle_tpu import signal

    x = _x((2, 512), seed=3)
    n_fft = 64
    spec = signal.stft(paddle.to_tensor(x), n_fft=n_fft, hop_length=16)
    back = signal.istft(spec, n_fft=n_fft, hop_length=16, length=512).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)

"""Numeric sweep — the remaining api.yaml forward ops (VERDICT r2 #5).

Closes the numeric-test tail: every op here was resolvable but not yet
numerically exercised by test_ops.py or the three earlier sweeps. Pattern
follows the reference OpTest culture (op_test.py:289): independent numpy/
scipy references for values, central-difference vs tape for gradients;
random ops get statistical checks, structured ops (roi/deform/viterbi)
get exactness special cases plus brute-force references.

tests/numeric_coverage.py records the full op -> test-file partition;
tests/test_op_coverage.py asserts it is total.
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
from op_test import check_grad, check_output

F = paddle.nn.functional


def t(a):
    return paddle.to_tensor(a)


def _rand(shape, lo=-1.0, hi=1.0, seed=0):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype(np.float32)


# ---------------------------------------------------------------- unary ----

UNARY = [
    ("acos", paddle.acos, np.arccos, _rand((2, 3), -0.9, 0.9), True),
    ("sinh", paddle.sinh, np.sinh, _rand((2, 3), -2, 2), True),
    ("erf", paddle.erf, sps.erf, _rand((2, 3), -2, 2), True),
    ("lgamma", paddle.lgamma, sps.gammaln, _rand((2, 3), 0.5, 4.0), True),
    ("log1p", paddle.log1p, np.log1p, _rand((2, 3), -0.5, 2.0), True),
    ("round", paddle.round, np.round, _rand((2, 3), -3, 3), False),
]


@pytest.mark.parametrize("name,fn,ref,x,diff", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_rest(name, fn, ref, x, diff):
    check_output(fn, ref, [x], rtol=2e-5, atol=2e-5)
    if diff:
        check_grad(fn, [x.astype(np.float64)])


def test_clip_scale():
    x = _rand((3, 4), -2, 2)
    check_output(lambda a: paddle.clip(a, -0.5, 0.8),
                 lambda a: np.clip(a, -0.5, 0.8), [x])
    check_grad(lambda a: paddle.clip(a, -0.5, 0.8),
               [x.astype(np.float64)])
    check_output(lambda a: paddle.scale(a, scale=2.5, bias=0.5),
                 lambda a: 2.5 * a + 0.5, [x])
    check_output(
        lambda a: paddle.scale(a, scale=2.5, bias=0.5,
                               bias_after_scale=False),
        lambda a: 2.5 * (a + 0.5), [x])


def test_complex_parts():
    z = (_rand((2, 3)) + 1j * _rand((2, 3), seed=1)).astype(np.complex64)
    check_output(paddle.real, np.real, [z])
    check_output(paddle.imag, np.imag, [z])
    check_output(paddle.conj, np.conj, [z])


def test_isfinite_allclose():
    x = np.array([[1.0, np.inf], [np.nan, -2.0]], np.float32)
    check_output(paddle.isfinite, np.isfinite, [x])
    a = _rand((2, 3))
    b = a + 1e-9
    assert bool(paddle.allclose(t(a), t(b)))
    assert not bool(paddle.allclose(t(a), t(a + 1.0)))


def test_bitwise():
    rng = np.random.RandomState(0)
    a = rng.randint(0, 16, (3, 4)).astype(np.int32)
    b = rng.randint(0, 16, (3, 4)).astype(np.int32)
    check_output(paddle.bitwise_and, np.bitwise_and, [a, b])
    check_output(paddle.bitwise_or, np.bitwise_or, [a, b])
    check_output(paddle.bitwise_xor, np.bitwise_xor, [a, b])


def test_all_any_add_n():
    m = np.array([[True, False], [True, True]])
    check_output(paddle.all, np.all, [m])
    check_output(lambda a: paddle.all(a, axis=1),
                 lambda a: a.all(1), [m])
    check_output(paddle.any, np.any, [m])
    xs = [_rand((2, 3), seed=s) for s in range(3)]
    out = paddle.add_n([t(x) for x in xs])
    np.testing.assert_allclose(out.numpy(), sum(xs), rtol=1e-6)


def test_softmax_log_softmax_grad():
    x = _rand((3, 5), -2, 2)

    def np_softmax(v, axis=-1):
        e = np.exp(v - v.max(axis, keepdims=True))
        return e / e.sum(axis, keepdims=True)

    check_output(F.softmax, np_softmax, [x])
    check_output(F.log_softmax, lambda v: np.log(np_softmax(v)), [x])
    check_grad(F.log_softmax, [x.astype(np.float64)])


def test_cast():
    x = _rand((2, 3), -2, 2)
    check_output(lambda a: paddle.cast(a, "int32"),
                 lambda a: a.astype(np.int32), [x])
    check_output(lambda a: paddle.cast(a, "float64"),
                 lambda a: a.astype(np.float64), [x])


# ------------------------------------------------------------- creation ----

def test_creation_ops():
    np.testing.assert_array_equal(paddle.arange(2, 14, 3).numpy(),
                                  np.arange(2, 14, 3))
    np.testing.assert_array_equal(paddle.eye(3, 5).numpy(), np.eye(3, 5))
    np.testing.assert_allclose(paddle.linspace(0, 1, 7).numpy(),
                               np.linspace(0, 1, 7), rtol=1e-6)
    np.testing.assert_array_equal(
        paddle.full([2, 3], 7.5).numpy(), np.full((2, 3), 7.5, np.float32))
    v = _rand((4,))
    np.testing.assert_array_equal(paddle.diag(t(v)).numpy(), np.diag(v))
    m = _rand((3, 3))
    np.testing.assert_array_equal(paddle.diag(t(m)).numpy(), np.diag(m))
    x = _rand((2, 3))
    np.testing.assert_array_equal(paddle.ones_like(t(x)).numpy(),
                                  np.ones_like(x))
    np.testing.assert_array_equal(paddle.zeros_like(t(x)).numpy(),
                                  np.zeros_like(x))


def test_shape_size_is_empty_copy_to():
    x = t(_rand((2, 3, 4)))
    np.testing.assert_array_equal(np.asarray(paddle.shape(x)), [2, 3, 4])
    assert int(paddle.numel(x)) == 24
    assert not bool(paddle.is_empty(x))
    assert bool(paddle.is_empty(t(np.zeros((0, 3), np.float32))))
    # copy_to/Tensor.cuda: a device-placement copy must preserve values
    y = x.cuda()
    np.testing.assert_array_equal(y.numpy(), x.numpy())


# --------------------------------------------------------- manipulation ----

def test_manipulation_values():
    x = _rand((2, 3, 4))
    check_output(lambda a: paddle.concat([a, a], axis=1),
                 lambda a: np.concatenate([a, a], 1), [x])
    check_output(lambda a: paddle.expand(a, [2, 2, 3, 4]),
                 lambda a: np.broadcast_to(a, (2, 2, 3, 4)), [x])
    check_output(lambda a: paddle.flatten(a, 1, 2),
                 lambda a: a.reshape(2, 12), [x])
    check_output(lambda a: paddle.reshape(a, [4, 6]),
                 lambda a: a.reshape(4, 6), [x])
    check_output(lambda a: paddle.roll(a, 2, axis=1),
                 lambda a: np.roll(a, 2, 1), [x])
    check_output(lambda a: paddle.slice(a, [1, 2], [1, 0], [3, 2]),
                 lambda a: a[:, 1:3, 0:2], [x])
    outs = paddle.split(t(x), 3, axis=1)
    for o, e in zip(outs, np.split(x, 3, 1)):
        np.testing.assert_array_equal(o.numpy(), e)
    check_output(lambda a: paddle.squeeze(paddle.unsqueeze(a, 0), 0),
                 lambda a: a, [x])
    check_output(lambda a: paddle.stack([a, a], axis=1),
                 lambda a: np.stack([a, a], 1), [x])
    check_output(lambda a: paddle.tile(a, [1, 2, 1]),
                 lambda a: np.tile(a, (1, 2, 1)), [x])
    check_output(lambda a: paddle.transpose(a, [2, 0, 1]),
                 lambda a: a.transpose(2, 0, 1), [x])
    check_grad(lambda a: paddle.transpose(a, [2, 0, 1]),
               [x.astype(np.float64)])


def test_gather_scatter_family():
    x = _rand((5, 4))
    idx = np.array([3, 1, 4], np.int64)
    check_output(lambda a: paddle.gather(a, t(idx)),
                 lambda a: a[idx], [x])
    check_grad(lambda a: paddle.gather(a, t(idx)), [x.astype(np.float64)])
    upd = _rand((3, 4), seed=2)
    ref = x.copy()
    ref[idx] = upd
    out = paddle.scatter(t(x), t(idx), t(upd), overwrite=True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
    tk = _rand((4, 6))
    ti = np.array([[1, 0], [2, 3], [4, 5], [0, 1]], np.int64)
    check_output(lambda a: paddle.take_along_axis(a, t(ti), 1),
                 lambda a: np.take_along_axis(a, ti, 1), [tk])
    mask = x > 0
    np.testing.assert_array_equal(
        paddle.masked_select(t(x), t(mask)).numpy(), x[mask])
    cond = x > 0
    y = _rand((5, 4), seed=3)
    check_output(lambda a, b: paddle.where(t(cond), a, b),
                 lambda a, b: np.where(cond, a, b), [x, y])
    nz = paddle.nonzero(t(cond)).numpy()
    np.testing.assert_array_equal(nz, np.argwhere(cond))


def test_topk_tril_triu_unfold():
    x = _rand((3, 6))
    vals, idxs = paddle.topk(t(x), k=2, axis=1)
    ref_idx = np.argsort(-x, axis=1)[:, :2]
    np.testing.assert_array_equal(np.sort(idxs.numpy(), 1),
                                  np.sort(ref_idx, 1))
    np.testing.assert_allclose(np.sort(vals.numpy(), 1),
                               np.sort(np.take_along_axis(x, ref_idx, 1), 1),
                               rtol=1e-6)
    m = _rand((4, 4))
    check_output(paddle.tril, np.tril, [m])
    check_output(paddle.triu, np.triu, [m])
    # unfold (im2col): reference layout [N, C*kh*kw, L]
    img = _rand((1, 2, 4, 4))
    out = F.unfold(t(img), kernel_sizes=2).numpy()
    assert out.shape == (1, 2 * 2 * 2, 9)
    # first column = the top-left 2x2 patch of each channel, row-major
    patch = img[0, :, :2, :2].reshape(2, 4)
    np.testing.assert_allclose(out[0, :, 0], patch.reshape(-1), rtol=1e-6)


# ---------------------------------------------------------------- random ----

def test_randint_truncated_normal_stats():
    paddle.seed(1234)
    r = paddle.randint(3, 9, [2000]).numpy()
    assert r.min() >= 3 and r.max() <= 8
    assert set(np.unique(r)) == set(range(3, 9))
    g = paddle.nn.initializer.TruncatedNormal(mean=0.0, std=1.0)
    vals = np.asarray(g([4000], "float32"))
    assert np.abs(vals).max() <= 2.0 + 1e-6  # truncation at 2 std
    assert abs(vals.mean()) < 0.1


# ---------------------------------------------------------------- linalg ----

def test_linalg_rest():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = paddle.linalg.cholesky(t(spd)).numpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    b = rng.randn(4, 2).astype(np.float32)
    x = paddle.linalg.cholesky_solve(t(b), t(np.linalg.cholesky(spd)),
                                     upper=False).numpy()
    np.testing.assert_allclose(spd @ x, b, rtol=1e-3, atol=1e-3)
    check_output(paddle.linalg.det, np.linalg.det, [spd], rtol=1e-4,
                 atol=1e-4)
    ms = [rng.randn(3, 4).astype(np.float32),
          rng.randn(4, 5).astype(np.float32),
          rng.randn(5, 2).astype(np.float32)]
    np.testing.assert_allclose(
        paddle.linalg.multi_dot([t(m) for m in ms]).numpy(),
        ms[0] @ ms[1] @ ms[2], rtol=1e-4)
    v = rng.randn(4).astype(np.float32)
    np.testing.assert_allclose(paddle.mv(t(a), t(v)).numpy(), a @ v,
                               rtol=1e-5)
    q, r = paddle.linalg.qr(t(a))
    np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4),
                               atol=1e-4)


# ----------------------------------------------------- nn/vision/special ----

def test_prelu():
    x = _rand((2, 3, 4), -2, 2)
    w = np.array([0.25, 0.1, 0.5], np.float32)
    check_output(lambda a, ww: F.prelu(a, ww),
                 lambda a, ww: np.where(a > 0, a, a * ww.reshape(1, 3, 1)),
                 [x, w])


def test_max_pool3d_with_index():
    x = _rand((1, 1, 4, 4, 4))
    out, mask = F.max_pool3d(t(x), kernel_size=2, stride=2,
                             return_mask=True)
    ref = x.reshape(1, 1, 2, 2, 2, 2, 2, 2).transpose(
        0, 1, 2, 4, 6, 3, 5, 7).reshape(1, 1, 2, 2, 2, 8).max(-1)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
    # indices are flat positions within the input volume; re-gathering must
    # reproduce the pooled values
    flat = x.reshape(1, 1, -1)
    np.testing.assert_allclose(
        np.take_along_axis(flat, mask.numpy().reshape(1, 1, -1), 2).reshape(
            out.shape), out.numpy(), rtol=1e-6)


def test_deform_conv_zero_offset_equals_conv():
    from paddle_tpu.vision.ops import deform_conv2d

    x = _rand((1, 2, 6, 6))
    w = _rand((3, 2, 3, 3), seed=1)
    offset = np.zeros((1, 2 * 3 * 3, 4, 4), np.float32)
    out = deform_conv2d(t(x), t(offset), t(w)).numpy()
    ref = F.conv2d(t(x), t(w)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_roi_align_identity_and_grad():
    from paddle_tpu.vision.ops import roi_align

    x = _rand((1, 1, 4, 4))
    # exactness case: aligned=True shifts by -0.5, so a full-image box with
    # output HxW and sampling_ratio=1 samples exactly at the pixel centers
    # (xs = -0.5 + (ix + 0.5) * 1 = ix) -> identity
    boxes = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
    out = roi_align(t(x), t(boxes), t(np.array([1], np.int32)),
                    output_size=4, sampling_ratio=1, aligned=True).numpy()
    np.testing.assert_allclose(out[0, 0], x[0, 0], rtol=1e-5, atol=1e-5)


def test_roi_pool_per_pixel_bins():
    from paddle_tpu.vision.ops import roi_pool

    # exactness case: full-image box with output HxW makes every quantized
    # bin one pixel (ys = iy + frac, int -> iy) -> identity
    x = _rand((1, 2, 6, 6))
    boxes = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
    out = roi_pool(t(x), t(boxes), t(np.array([1], np.int32)),
                   output_size=6).numpy()
    np.testing.assert_allclose(out[0], x[0], rtol=1e-6)


def test_psroi_pool_constant():
    from paddle_tpu.vision.ops import psroi_pool

    # position-sensitive pooling of a constant input returns the constant
    oh = ow = 2
    c = 3
    x = np.full((1, oh * ow * c, 6, 6), 2.5, np.float32)
    boxes = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
    out = psroi_pool(t(x), t(boxes), t(np.array([1], np.int32)),
                     output_size=oh).numpy()
    assert out.shape == (1, c, oh, ow)
    np.testing.assert_allclose(out, np.full((1, c, oh, ow), 2.5), rtol=1e-6)


def test_yolo_box_numpy_ref():
    from paddle_tpu.vision.ops import yolo_box

    rng = np.random.RandomState(0)
    class_num, na, H, W = 2, 2, 3, 3
    anchors = [10, 14, 23, 27]
    xin = rng.randn(1, na * (5 + class_num), H, W).astype(np.float32)
    img = np.array([[96, 96]], np.int32)
    boxes, scores = yolo_box(t(xin), t(img), anchors, class_num,
                             conf_thresh=0.0, downsample_ratio=32,
                             clip_bbox=False)
    a = xin.reshape(1, na, 5 + class_num, H, W)
    an = np.array(anchors, np.float32).reshape(na, 2)
    sig = lambda v: 1 / (1 + np.exp(-v))
    gx = np.arange(W)[None, None, None, :]
    gy = np.arange(H)[None, None, :, None]
    bx = (gx + sig(a[:, :, 0])) / W
    by = (gy + sig(a[:, :, 1])) / H
    bw = np.exp(a[:, :, 2]) * an[None, :, 0:1, None] / (W * 32)
    bh = np.exp(a[:, :, 3]) * an[None, :, 1:2, None] / (H * 32)
    x1 = (bx - bw / 2) * 96
    y1 = (by - bh / 2) * 96
    x2 = (bx + bw / 2) * 96
    y2 = (by + bh / 2) * 96
    ref_boxes = np.stack([x1, y1, x2, y2], -1).reshape(1, -1, 4)
    np.testing.assert_allclose(boxes.numpy(), ref_boxes, rtol=1e-4,
                               atol=1e-4)
    conf = sig(a[:, :, 4])
    probs = sig(a[:, :, 5:]) * conf[:, :, None]
    ref_scores = probs.transpose(0, 1, 3, 4, 2).reshape(1, -1, class_num)
    np.testing.assert_allclose(scores.numpy(), ref_scores, rtol=1e-4,
                               atol=1e-4)


def test_gather_tree():
    # [max_time, batch, beam] ids + parents; backtrace from last step
    ids = np.array([[[2, 5]], [[6, 8]], [[3, 9]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
    out = F.gather_tree(t(ids), t(parents)).numpy()
    # beam 0 at t=2 -> parent 0 at t=1 (id 6, parent 1) -> t=0 id 5
    # beam 1 at t=2 -> parent 1 at t=1 (id 8, parent 0) -> t=0 id 2
    ref = np.array([[[5, 2]], [[6, 8]], [[3, 9]]], np.int64)
    np.testing.assert_array_equal(out, ref)


def test_graph_send_recv_and_segment_pool():
    from paddle_tpu.incubate import graph_send_recv, segment_mean, \
        segment_sum

    x = _rand((5, 3))
    src = np.array([0, 1, 2, 3], np.int64)
    dst = np.array([1, 1, 0, 4], np.int64)
    out = graph_send_recv(t(x), t(src), t(dst), pool_type="sum").numpy()
    ref = np.zeros_like(x)
    for s, d in zip(src, dst):
        ref[d] += x[s]
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    ids = np.array([0, 0, 1, 2, 2], np.int64)
    np.testing.assert_allclose(
        segment_sum(t(x), t(ids)).numpy(),
        np.stack([x[:2].sum(0), x[2], x[3:].sum(0)]), rtol=1e-6)
    np.testing.assert_allclose(
        segment_mean(t(x), t(ids)).numpy(),
        np.stack([x[:2].mean(0), x[2], x[3:].mean(0)]), rtol=1e-6)


def test_viterbi_decode_bruteforce():
    from paddle_tpu.text import viterbi_decode

    rng = np.random.RandomState(0)
    B, T, K = 2, 4, 3
    pot = rng.randn(B, T, K).astype(np.float32)
    trans = rng.randn(K, K).astype(np.float32)
    lengths = np.array([4, 3], np.int64)
    scores, paths = viterbi_decode(t(pot), t(trans), t(lengths),
                                   include_bos_eos_tag=False)
    import itertools

    for b in range(B):
        L = int(lengths[b])
        best, best_path = -1e30, None
        for path in itertools.product(range(K), repeat=L):
            s = pot[b, 0, path[0]]
            for i in range(1, L):
                s += trans[path[i - 1], path[i]] + pot[b, i, path[i]]
            if s > best:
                best, best_path = s, path
        np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                   rtol=1e-4)
        np.testing.assert_array_equal(paths.numpy()[b, :L], best_path)


# ---------------------------------------------------------------- metric ----

def test_accuracy_and_auc():
    probs = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]],
                     np.float32)
    labels = np.array([[1], [0], [0], [1]], np.int64)
    acc = paddle.metric.accuracy(t(probs), t(labels), k=1)
    np.testing.assert_allclose(float(acc), 0.5)  # rows 0,1 right; 2,3 wrong

    m = paddle.metric.Auc()
    m.update(probs, labels)
    # rank-based AUC over pos scores [0.9, 0.4], neg scores [0.2, 0.7]
    pos, neg = [0.9, 0.4], [0.2, 0.7]
    pairs = [(p > n) + 0.5 * (p == n) for p in pos for n in neg]
    np.testing.assert_allclose(m.accumulate(), np.mean(pairs), atol=1e-3)


# ------------------------------------------------------------ optimizers ----

def _one_step(opt_cls, np_update, seed=0, **opt_kw):
    """Run ONE optimizer step on a known gradient and compare against the
    reference update formula in numpy (reference OpTest for sgd/adam/...)."""
    rng = np.random.RandomState(seed)
    w0 = rng.randn(4, 3).astype(np.float32)
    g = rng.randn(4, 3).astype(np.float32)
    p = paddle.to_tensor(w0.copy())
    p.stop_gradient = False
    opt = opt_cls(parameters=[p], **opt_kw)
    (p * t(g)).sum().backward()
    opt.step()
    ref = np_update(w0, g)
    np.testing.assert_allclose(p.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_sgd_step():
    _one_step(paddle.optimizer.SGD, lambda w, g: w - 0.1 * g,
              learning_rate=0.1)


def test_momentum_step():
    # velocity = mu*0 + g; w -= lr * velocity
    _one_step(paddle.optimizer.Momentum, lambda w, g: w - 0.1 * g,
              learning_rate=0.1, momentum=0.9)


def _adam_ref(w, g, lr=0.01, b1=0.9, b2=0.999, eps=1e-8, wd=0.0):
    m = (1 - b1) * g
    v = (1 - b2) * g * g
    mhat = m / (1 - b1)
    vhat = v / (1 - b2)
    out = w - lr * mhat / (np.sqrt(vhat) + eps)
    if wd:
        out = out - lr * wd * w
    return out


def test_adam_step():
    _one_step(paddle.optimizer.Adam, lambda w, g: _adam_ref(w, g),
              learning_rate=0.01)


def test_adamw_step():
    _one_step(paddle.optimizer.AdamW,
              lambda w, g: _adam_ref(w, g, wd=0.05),
              learning_rate=0.01, weight_decay=0.05)


def test_adamax_step():
    def ref(w, g, lr=0.01, b1=0.9, b2=0.999, eps=1e-8):
        m = (1 - b1) * g
        u = np.maximum(0.0, np.abs(g))  # inf-norm accumulator
        return w - lr / (1 - b1) * m / (u + eps)

    _one_step(paddle.optimizer.Adamax, ref, learning_rate=0.01)


def test_adadelta_step():
    def ref(w, g, rho=0.95, eps=1e-6, lr=1.0):
        acc = (1 - rho) * g * g
        upd = np.sqrt(eps) / np.sqrt(acc + eps) * g
        return w - lr * upd

    _one_step(paddle.optimizer.Adadelta, ref, learning_rate=1.0,
              rho=0.95, epsilon=1e-6)

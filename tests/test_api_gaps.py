"""Coverage for the API-parity gap fill: attribute ops, new math/linalg ops,
Tensor-method wiring, and top-level utilities (reference surfaces:
python/paddle/__init__.py __all__ and python/paddle/tensor/__init__.py method table)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestAttributeOps:
    def test_is_tensor(self):
        assert paddle.is_tensor(t([1.0]))
        assert not paddle.is_tensor([1.0])

    def test_rank_shape(self):
        x = t(np.zeros((2, 3, 4), np.float32))
        assert int(paddle.rank(x)) == 3
        np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3, 4])

    def test_is_empty(self):
        assert bool(paddle.is_empty(t(np.zeros((0, 3)))))
        assert not bool(paddle.is_empty(t(np.zeros((1,)))))

    def test_dtype_predicates(self):
        assert paddle.is_floating_point(t(np.float32(1)))
        assert not paddle.is_floating_point(t(np.int64(1)))
        assert paddle.is_integer(t(np.int32(1)))
        assert paddle.is_complex(t(np.complex64(1)))
        x = paddle.to_tensor(np.ones((2,), np.float32), dtype="bfloat16")
        assert paddle.is_floating_point(x)

    def test_check_shape(self):
        paddle.check_shape([2, 3])
        with pytest.raises(ValueError):
            paddle.check_shape([-2, 3])


class TestNewMathOps:
    def test_add_n(self):
        xs = [np.random.RandomState(i).rand(3, 4).astype(np.float32) for i in range(3)]
        out = paddle.add_n([t(x) for x in xs])
        np.testing.assert_allclose(out.numpy(), sum(xs), rtol=1e-6)

    def test_add_n_grad(self):
        a, b = t(np.ones((2, 2), np.float32)), t(np.ones((2, 2), np.float32))
        a.stop_gradient = False
        b.stop_gradient = False
        paddle.add_n([a, b]).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), np.ones((2, 2)))
        np.testing.assert_allclose(b.grad.numpy(), np.ones((2, 2)))

    def test_renorm(self):
        x = np.random.RandomState(0).randn(2, 3, 4).astype(np.float32)
        out = paddle.renorm(t(x), p=2.0, axis=1, max_norm=1.0).numpy()
        for j in range(3):
            n = np.linalg.norm(out[:, j, :])
            assert n <= 1.0 + 1e-4
        # slices already under the budget are untouched
        small = np.full((2, 2), 0.01, np.float32)
        np.testing.assert_allclose(
            paddle.renorm(t(small), 2.0, 0, 5.0).numpy(), small, rtol=1e-5)

    def test_complex(self):
        re = np.array([1.0, 2.0], np.float32)
        im = np.array([3.0, -1.0], np.float32)
        out = paddle.complex(t(re), t(im))
        np.testing.assert_allclose(out.numpy(), re + 1j * im)
        assert paddle.is_complex(out)

    def test_real_imag_conj_angle(self):
        z = np.array([1 + 2j, 3 - 4j], np.complex64)
        np.testing.assert_allclose(paddle.real(t(z)).numpy(), z.real)
        np.testing.assert_allclose(paddle.imag(t(z)).numpy(), z.imag)
        np.testing.assert_allclose(paddle.conj(t(z)).numpy(), z.conj())
        np.testing.assert_allclose(paddle.angle(t(z)).numpy(), np.angle(z), rtol=1e-6)


class TestNewLinalg:
    def test_multi_dot(self):
        rs = np.random.RandomState(0)
        a, b, c = (rs.rand(4, 5).astype(np.float32), rs.rand(5, 3).astype(np.float32),
                   rs.rand(3, 2).astype(np.float32))
        out = paddle.linalg.multi_dot([t(a), t(b), t(c)])
        np.testing.assert_allclose(out.numpy(), a @ b @ c, rtol=1e-5)

    def test_cholesky_solve(self):
        rs = np.random.RandomState(1)
        a = rs.rand(4, 4).astype(np.float64)
        a = a @ a.T + 4 * np.eye(4)
        b = rs.rand(4, 2).astype(np.float64)
        L = np.linalg.cholesky(a)
        out = paddle.linalg.cholesky_solve(t(b), t(L), upper=False)
        np.testing.assert_allclose(out.numpy(), np.linalg.solve(a, b), rtol=1e-6)
        out_u = paddle.linalg.cholesky_solve(t(b), t(L.T.copy()), upper=True)
        np.testing.assert_allclose(out_u.numpy(), np.linalg.solve(a, b), rtol=1e-6)

    def test_lu_unpack(self):
        rs = np.random.RandomState(2)
        a = rs.rand(5, 5).astype(np.float64)
        lu_t, piv_t = paddle.linalg.lu(t(a))
        P, L, U = paddle.linalg.lu_unpack(lu_t, piv_t)
        np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), a, rtol=1e-6,
                                   atol=1e-8)

    def test_cond(self):
        a = np.diag([1.0, 10.0]).astype(np.float64)
        np.testing.assert_allclose(float(paddle.linalg.cond(t(a))), 10.0, rtol=1e-6)

    def test_lu_unpack_batched(self):
        rs = np.random.RandomState(3)
        a = rs.rand(3, 4, 4).astype(np.float64)
        lu_t, piv_t = paddle.linalg.lu(t(a))
        P, L, U = paddle.linalg.lu_unpack(lu_t, piv_t)
        np.testing.assert_allclose(
            np.einsum("bij,bjk,bkl->bil", P.numpy(), L.numpy(), U.numpy()), a,
            rtol=1e-6, atol=1e-8)


class TestManipGaps:
    def test_unstack(self):
        x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
        outs = paddle.unstack(t(x), axis=1)
        assert len(outs) == 3
        for j, o in enumerate(outs):
            np.testing.assert_array_equal(o.numpy(), x[:, j, :])

    def test_reverse(self):
        x = np.arange(6).reshape(2, 3).astype(np.float32)
        np.testing.assert_array_equal(paddle.reverse(t(x), [0]).numpy(), x[::-1])


class TestTensorMethodWiring:
    def test_trig_methods(self):
        x = t(np.array([0.1, 0.5], np.float32))
        np.testing.assert_allclose(x.acos().numpy(), np.arccos(x.numpy()), rtol=1e-6)
        np.testing.assert_allclose(x.sinh().numpy(), np.sinh(x.numpy()), rtol=1e-6)
        np.testing.assert_allclose(x.log1p().numpy(), np.log1p(x.numpy()), rtol=1e-6)
        import math
        np.testing.assert_allclose(x.lgamma().numpy(),
                                   np.vectorize(math.lgamma)(x.numpy()), rtol=1e-5)

    def test_linalg_methods(self):
        a = np.random.RandomState(0).rand(3, 3).astype(np.float64) + 3 * np.eye(3)
        x = t(a)
        q, r = x.qr()
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-6)
        assert x.det().numpy().shape == ()
        v = t(np.ones(3, np.float64))
        np.testing.assert_allclose(x.mv(v).numpy(), a @ np.ones(3), rtol=1e-6)

    def test_bitwise_methods(self):
        a = t(np.array([0b1100], np.int32))
        b = t(np.array([0b1010], np.int32))
        assert int(a.bitwise_and(b)) == 0b1000
        assert int(a.bitwise_or(b)) == 0b1110
        assert int(a.bitwise_xor(b)) == 0b0110

    def test_inplace_methods(self):
        x = t(np.array([1.4, 2.6], np.float32))
        y = x.floor_()
        assert y is x
        np.testing.assert_array_equal(x.numpy(), [1.0, 2.0])
        z = t(np.zeros((100,), np.float32))
        z.uniform_(0.0, 1.0)
        assert 0.0 <= float(z.numpy().min()) and float(z.numpy().max()) <= 1.0
        assert z.numpy().std() > 0.1
        e = t(np.zeros((200,), np.float32))
        e.exponential_(2.0)
        assert e.numpy().min() >= 0 and 0.2 < e.numpy().mean() < 1.0

    def test_misc_methods(self):
        x = t(np.arange(4, dtype=np.float32))
        assert x.numel() == 4
        assert int(x.rank()) == 1
        assert x.tolist() == [0.0, 1.0, 2.0, 3.0]
        np.testing.assert_array_equal(
            x.unstack(0)[2].numpy(), np.float32(2.0))


class TestTopLevelUtilities:
    def test_param_attr_create_parameter(self):
        attr = paddle.ParamAttr(name="w", learning_rate=0.5)
        p = paddle.create_parameter([3, 4], "float32", attr=attr)
        assert p.shape == [3, 4]
        assert not p.stop_gradient
        assert p.optimize_attr["learning_rate"] == 0.5

    def test_create_parameter_attr_false(self):
        assert paddle.create_parameter([3], "float32", attr=False) is None

    def test_add_n_single_tensor_not_aliased(self):
        x = t(np.ones((2,), np.float32))
        y = paddle.add_n(x)
        assert y is not x
        y.set_value(np.zeros((2,), np.float32))
        np.testing.assert_array_equal(x.numpy(), [1, 1])

    def test_custom_place_identity(self):
        a, b = paddle.CustomPlace("npu", 0), paddle.CustomPlace("fpga", 0)
        assert a != b and a != paddle.TPUPlace(0) and paddle.TPUPlace(0) != a
        assert a == paddle.CustomPlace("npu", 0)
        assert "npu" in repr(a)
        assert paddle.is_compiled_with_distribute()

    def test_sequence_mask_empty(self):
        import paddle_tpu.nn.functional as F

        m = F.sequence_mask(t(np.zeros((0,), np.int64)))
        assert m.shape[0] == 0

    def test_batch(self):
        def reader():
            return iter(range(7))

        batches = list(paddle.batch(reader, 3)())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches = list(paddle.batch(reader, 3, drop_last=True)())
        assert batches == [[0, 1, 2], [3, 4, 5]]

    def test_set_printoptions(self):
        paddle.set_printoptions(precision=2)
        s = repr(t(np.array([1.23456], np.float32)))
        assert "1.23" in s and "1.2345" not in s
        paddle.set_printoptions(precision=8)

    def test_flops(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(10, 20)

            def forward(self, x):
                return self.fc(x)

        n = paddle.flops(M(), input_size=[1, 10])
        assert n == 10 * 20 + 20

    def test_places(self):
        for cls in [paddle.NPUPlace, paddle.XPUPlace, paddle.MLUPlace,
                    paddle.IPUPlace]:
            assert cls(0).device_id == 0
        assert not paddle.is_compiled_with_npu()
        assert not paddle.is_compiled_with_rocm()

    def test_cuda_rng_state_aliases(self):
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)

    def test_scatter_inplace_toplevel(self):
        x = t(np.zeros((3, 2), np.float32))
        idx = t(np.array([1], np.int64))
        upd = t(np.ones((1, 2), np.float32))
        y = paddle.scatter_(x, idx, upd)
        assert y is x
        np.testing.assert_array_equal(x.numpy(), [[0, 0], [1, 1], [0, 0]])

    def test_disable_signal_handler(self):
        paddle.disable_signal_handler()

    def test_dtype_alias(self):
        assert paddle.dtype("float32") == paddle.float32

"""Persistent compilation cache (FLAGS_compile_cache_dir /
PADDLE_TPU_COMPILE_CACHE): a second process must NOT pay XLA compile cost
for a step program the first process already compiled.

The cross-process claim is the whole point, so the core test runs two real
subprocesses against one cache dir and compares the engine's measured
compile wall time: process 2's step compile must be classified WARM (served
from the store) and take a small fraction of process 1's COLD compile.
Off-by-default is asserted in-process: no env/flag -> nothing configured,
no directory, and jax.config untouched.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROG = r"""
import json, os
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, monitor
from paddle_tpu.distributed.engine import TrainStepEngine

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(32, 64), paddle.nn.ReLU(),
                           paddle.nn.Linear(64, 8))
opt = paddle.optimizer.AdamW(learning_rate=0.01,
                             parameters=net.parameters())
eng = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss())
rng = np.random.RandomState(0)
x = paddle.to_tensor(rng.randn(16, 32).astype(np.float32))
y = paddle.to_tensor(rng.randint(0, 8, (16,)).astype(np.int64))
loss = eng.step(x, y)
rep = monitor.registry().report()
print(json.dumps({
    "enabled": compile_cache.enabled(),
    "entries": compile_cache.entries(),
    "loss": repr(float(loss.item())),
    "compile_ms": rep["engine.jit_compile_ms"]["value"],
    "cold": rep.get("engine.compile_cold", {}).get("value", 0),
    "cold_ms": rep.get("engine.compile_cold_ms", {}).get("value", 0),
    "warm": rep.get("engine.compile_warm", {}).get("value", 0),
    "warm_ms": rep.get("engine.compile_warm_ms", {}).get("value", 0),
}))
"""


def _run(extra_env):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("PADDLE_TPU_COMPILE_CACHE", None)
    env.pop("FLAGS_compile_cache_dir", None)
    env.update(extra_env)
    res = subprocess.run([sys.executable, "-c", _PROG], capture_output=True,
                         text=True, timeout=300, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    return json.loads(res.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_second_process_compiles_warm_and_fast(tmp_path):
    cache = str(tmp_path / "xla_cache")
    first = _run({"PADDLE_TPU_COMPILE_CACHE": cache})
    assert first["enabled"] and first["entries"] > 0
    assert first["cold"] >= 1 and first["warm_ms"] == 0
    assert first["compile_ms"] > 0

    second = _run({"PADDLE_TPU_COMPILE_CACHE": cache})
    assert second["warm"] >= 1 and second["cold"] == 0, second
    assert second["entries"] == first["entries"]  # nothing recompiled
    # "~0 ms": deserialization only. Generous bound for CI noise — the
    # real ratio is ~10x even for this toy program.
    assert second["compile_ms"] <= max(50, 0.5 * first["compile_ms"]), (
        f"second-process compile not served from the persistent cache: "
        f"{second['compile_ms']}ms vs cold {first['compile_ms']}ms")

    # cache on vs off is bit-identical
    plain = _run({})
    assert plain["loss"] == first["loss"] == second["loss"]
    assert not plain["enabled"] and plain["entries"] == -1
    assert plain["cold"] == 0 and plain["warm"] == 0  # unclassified when off


def test_off_by_default_touches_nothing(tmp_path, monkeypatch):
    import paddle_tpu  # noqa: F401  (import-time configure already ran)
    from paddle_tpu.core import compile_cache

    if compile_cache.enabled():
        pytest.skip("suite launched with a compile cache configured")
    import jax

    assert jax.config.jax_compilation_cache_dir in (None, "")
    assert compile_cache.entries() == -1
    assert compile_cache.note_compile(5, -1, -1) is None


def test_set_flags_configures_cache_in_process(tmp_path):
    """paddle.set_flags({'compile_cache_dir': d}) wires jax.config without a
    restart (the flag is also env-bootstrapped for new processes)."""
    import paddle_tpu as paddle
    from paddle_tpu.core import compile_cache

    if compile_cache.enabled():
        pytest.skip("suite launched with a compile cache configured")
    d = str(tmp_path / "cc")
    import jax

    try:
        paddle.set_flags({"compile_cache_dir": d})
        assert compile_cache.enabled()
        assert compile_cache.cache_dir() == d
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
        f = jax.jit(lambda a: a * 2 + 1)
        f(jax.numpy.ones((8, 8))).block_until_ready()
        assert compile_cache.entries() >= 1
    finally:
        # disable through the real path: configure() unsets jax.config AND
        # drops jax's latched cache singleton (reset_cache). Anything less
        # leaks the cache into every later compile — cache-served
        # multi-device CPU executables are nondeterministic on this jax,
        # which is how this test once made test_dist_checkpoint flaky.
        paddle.set_flags({"compile_cache_dir": ""})
        assert not compile_cache.enabled()
        assert jax.config.jax_compilation_cache_dir in (None, "")

"""SLO engine (ISSUE 15 tentpole): declarative objectives, multi-window
burn-rate alerting, and self-healing hooks.

Pinned contracts:
- snapshot subtraction: counter/histogram window deltas are exact in
  count/sum/buckets, window percentiles within one bucket width of a
  pooled numpy recompute, and going backwards raises;
- burn-rate math against closed-form values (bad-fraction / budget) for
  both SLI forms, latency thresholds snapping down to bucket granularity;
- multi-window evaluation: an alert needs burn >= factor in BOTH the long
  and the short window; the severity is the worst firing pair's;
- AlertManager: pending -> firing (after for_s) -> resolved with
  duration, dedup while firing, severity escalation, silent pending drop;
- dark by default: with no active registry, tick() is a no-op — no ring
  growth, no gauges, no alerts file;
- exporter: /healthz keeps the legacy plain-200 contract with no engine,
  flips 200 -> 503 -> 200 around a page-severity fire; /alerts 404s with
  no engine and serves the full doc with one;
- self-healing: ReplicaRouter.attach_slo sheds the firing replica's
  placements and unsheds on resolve; FleetCollector evaluates attached
  SLOs over the merged fleet snapshot;
- serving outcome: every finished request carries a terminal outcome
  threaded through handles, sink records and the serving counters.
"""
import bisect
import collections
import json
import os
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.store import FileStore
from paddle_tpu.models import GPTForPretraining, gpt_tiny
from paddle_tpu.observability import exporter, fleet, metrics, slo
from paddle_tpu.serving.engine import ServingEngine
from paddle_tpu.serving.router import ReplicaRouter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

approx = pytest.approx


@pytest.fixture(autouse=True)
def _clean_observability():
    """Metrics/exporter/SLO engine are process-globals the shared conftest
    doesn't know about: start every test dark, leave it dark."""
    exporter.stop_exporter()
    metrics.reset()
    slo.uninstall_engine()
    yield
    exporter.stop_exporter()
    metrics.reset()
    slo.uninstall_engine()


def _reg_snap(counters=None, histograms=None):
    return {"counters": dict(counters or {}), "gauges": {},
            "histograms": dict(histograms or {})}


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode("utf-8")


# ------------------------------------------------- snapshot subtraction

def test_subtract_histogram_exact_vs_pooled_recompute():
    reg = metrics.enable()
    h = reg.histogram("lat")
    rnd = np.random.RandomState(3)
    first = rnd.lognormal(1.0, 0.5, 300).tolist()
    for v in first:
        h.observe(v)
    prev = reg.snapshot()["histograms"]["lat"]
    second = rnd.lognormal(2.0, 0.7, 500).tolist()
    for v in second:
        h.observe(v)
    curr = reg.snapshot()["histograms"]["lat"]

    d = metrics.subtract_histogram_snapshots(curr, prev)
    assert d["count"] == 500
    assert sum(d["counts"]) == 500
    assert d["sum"] == approx(sum(second))
    # window min/max bracket the true window extremes
    assert d["min"] <= min(second) and d["max"] >= max(second)
    # percentiles within one bucket width of the pooled numpy recompute
    bs = d["boundaries"]
    for q in (50, 90, 99):
        exact = float(np.percentile(second, q))
        i = bisect.bisect_left(bs, exact)
        lo = bs[i - 1] if i > 0 else d["min"]
        hi = bs[i] if i < len(bs) else d["max"]
        assert abs(d["p%g" % q] - exact) <= (hi - lo) + 1e-9

    # prev=None: window-from-empty equals the full current view
    full = metrics.subtract_histogram_snapshots(curr, None)
    assert full["count"] == 800 and full["counts"] == list(curr["counts"])


def test_subtract_histogram_rejects_bad_pairs():
    reg = metrics.enable()
    h = reg.histogram("lat")
    h.observe(3.0)
    prev = reg.snapshot()["histograms"]["lat"]
    h.observe(5.0)
    curr = reg.snapshot()["histograms"]["lat"]
    with pytest.raises(ValueError, match="went backwards"):
        metrics.subtract_histogram_snapshots(prev, curr)
    mangled = dict(prev)
    mangled["boundaries"] = [1.0, 2.0]
    mangled["counts"] = [0, 0]
    with pytest.raises(ValueError, match="boundaries"):
        metrics.subtract_histogram_snapshots(curr, mangled)
    assert metrics.subtract_histogram_snapshots(None, prev) is None


def test_subtract_registry_snapshots_semantics():
    curr = {"counters": {"a": 10.0, "born": 3.0}, "gauges": {"g": 7.0},
            "histograms": {},
            "monitor": {"m": {"value": 5.0, "peak": 9.0}}}
    prev = {"counters": {"a": 4.0}, "gauges": {"g": 2.0}, "histograms": {},
            "monitor": {"m": {"value": 2.0, "peak": 4.0}}}
    d = metrics.subtract_registry_snapshots(curr, prev)
    assert d["counters"] == {"a": 6.0, "born": 3.0}
    assert d["gauges"] == {"g": 7.0}          # level, not event, valued
    assert d["monitor"]["m"] == {"value": 3.0, "peak": 9.0}
    full = metrics.subtract_registry_snapshots(curr, None)
    assert full["counters"] == curr["counters"]
    with pytest.raises(ValueError, match="backwards"):
        metrics.subtract_registry_snapshots(prev, curr)


# ------------------------------------------------------- burn-rate math

def test_ratio_burn_rate_closed_form():
    spec = slo.ratio_slo("avail", "err", "req", 0.999)
    assert spec.budget == approx(0.001)
    delta = _reg_snap(counters={"err": 3.0, "req": 1000.0})
    # burn = (3/1000) / 0.001 = 3.0
    assert slo.burn_rate(spec, delta) == approx(3.0)
    # idle window spends nothing
    assert slo.burn_rate(spec, _reg_snap()) == 0.0
    # all-bad window burns the full 1/budget
    worst = _reg_snap(counters={"err": 10.0, "req": 10.0})
    assert slo.burn_rate(spec, worst) == approx(1000.0)


def test_latency_burn_rate_threshold_snaps_to_bucket():
    h = {"boundaries": [1.0, 2.0, 4.0, 8.0], "counts": [5, 3, 2, 0],
         "count": 10, "sum": 20.0, "min": 0.5, "max": 3.9}
    spec = slo.latency_slo("lat", "m", 2.0, 0.9)
    delta = _reg_snap(histograms={"m": h})
    # threshold on a boundary: buckets <= 2.0 are good -> 8 good, 2 bad
    assert slo.burn_rate(spec, delta) == approx((2 / 10) / 0.1)
    # threshold inside (2, 4]: snaps DOWN, the straddling bucket is bad
    spec3 = slo.latency_slo("lat", "m", 3.0, 0.9)
    assert slo.burn_rate(spec3, delta) == approx((2 / 10) / 0.1)
    # threshold at the top boundary: everything is good
    spec8 = slo.latency_slo("lat", "m", 8.0, 0.9)
    assert slo.burn_rate(spec8, delta) == 0.0
    # missing metric / empty histogram: no traffic, no burn
    assert slo.burn_rate(spec, _reg_snap()) == 0.0


def test_events_resolution_order_counters_monitor_histogram():
    snap = {"counters": {"x": 7.0},
            "monitor": {"y": {"value": 3.0, "peak": 5.0}},
            "histograms": {"z": {"count": 11}}}
    assert slo._events(snap, "x") == 7.0
    assert slo._events(snap, "y") == 3.0
    assert slo._events(snap, "z") == 11.0
    assert slo._events(snap, "absent") == 0.0


# -------------------------------------------------------- snapshot ring

def test_snapshot_ring_window_semantics():
    ring = slo.SnapshotRing(retention_s=10.0)
    assert ring.delta(5.0) is None and ring.at(0.0) is None
    ring.push(0.0, _reg_snap(counters={"c": 5.0}))
    # single entry: the window predates the ring -> delta from empty
    d = ring.delta(5.0, now=0.0)
    assert d["counters"]["c"] == 5.0 and d["_window_s"] == 0.0
    ring.push(4.0, _reg_snap(counters={"c": 9.0}))
    d = ring.delta(2.0, now=4.0)  # baseline at(2.0) -> the t=0 entry
    assert d["counters"]["c"] == 4.0 and d["_window_s"] == 4.0
    # window longer than history: oldest entry serves as baseline
    d = ring.delta(100.0, now=4.0)
    assert d["counters"]["c"] == 4.0
    # retention trim keeps at least two entries, drops expired ones
    ring.push(20.0, _reg_snap(counters={"c": 9.0}))
    assert len(ring) == 2 and ring.at(1.0) is None


def test_snapshot_ring_max_entries():
    ring = slo.SnapshotRing(retention_s=1e9, max_entries=3)
    for i in range(6):
        ring.push(float(i), _reg_snap(counters={"c": float(i)}))
    assert len(ring) == 3
    assert ring.latest()[0] == 5.0


# ------------------------------------------------ multi-window evaluate

def test_evaluate_requires_both_windows_and_ranks_severity():
    spec = slo.ratio_slo(
        "avail", "err", "req", 0.99,
        windows=[slo.BurnWindow(50.0, 2.0, 5.0, "page"),
                 slo.BurnWindow(100.0, 2.0, 0.5, "warn")])
    ring = slo.SnapshotRing(retention_s=200.0)
    ring.push(0.0, _reg_snap(counters={"err": 0.0, "req": 0.0}))
    ring.push(98.0, _reg_snap(counters={"err": 0.0, "req": 900.0}))
    ring.push(100.0, _reg_snap(counters={"err": 10.0, "req": 1000.0}))
    res = slo.evaluate(spec, ring, now=100.0)
    # long-50 burn: 10 bad / 1000 total / 0.01 budget = 1.0
    # short-2 burn: 10 bad / 100 total / 0.01 budget = 10.0
    fast, slow = res["windows"]
    assert fast["burn_long"] == approx(1.0)
    assert fast["burn_short"] == approx(10.0)
    # the page pair does NOT fire: long burn 1.0 < factor 5 even though
    # the short window is way over — BOTH windows must exceed
    assert not fast["firing"]
    assert slow["firing"]  # 1.0 >= 0.5 and 10.0 >= 0.5
    assert res["breach"] and res["severity"] == "warn"
    assert res["burn"] == approx(1.0)  # the fast pair's long burn
    assert res["budget_remaining"] == approx(0.0)


def test_burn_window_validation():
    with pytest.raises(ValueError, match="severity"):
        slo.BurnWindow(10.0, 1.0, 2.0, "sev1")
    with pytest.raises(ValueError, match="short_s"):
        slo.BurnWindow(1.0, 10.0, 2.0)
    w = slo.default_windows(scale=1.0 / 3600.0)
    assert w[0].long_s == approx(1.0) and w[0].short_s == approx(300 / 3600)
    assert (w[0].factor, w[0].severity) == (14.4, "page")
    assert (w[1].factor, w[1].severity) == (1.0, "warn")


# --------------------------------------------------- alert state machine

def _result(breach, burn=5.0, sev="page", name="s"):
    return {"slo": name, "labels": {}, "burn": burn,
            "budget_remaining": 0.5, "breach": breach,
            "severity": sev if breach else None, "windows": []}


def test_alert_manager_pending_firing_resolved():
    am = slo.AlertManager(for_s=1.0)
    ev = am.update([_result(True)], now=0.0)
    assert [e["state"] for e in ev] == ["pending"]
    assert am.update([_result(True, burn=9.0)], now=0.5) == []  # not yet
    ev = am.update([_result(True)], now=1.5)
    assert [e["state"] for e in ev] == ["firing"]
    assert am.update([_result(True)], now=2.0) == []  # dedup while firing
    assert am.firing()[0]["peak_burn"] == approx(9.0)
    ev = am.update([_result(False)], now=3.0)
    assert ev[0]["state"] == "resolved"
    assert ev[0]["duration_s"] == approx(1.5)
    assert am.firing() == [] and am.resolved_count == 1


def test_alert_manager_for_s_zero_and_silent_pending_drop():
    am = slo.AlertManager(for_s=0.0)
    ev = am.update([_result(True)], now=0.0)
    assert [e["state"] for e in ev] == ["pending", "firing"]
    am2 = slo.AlertManager(for_s=10.0)
    am2.update([_result(True)], now=0.0)
    # a pending alert that clears before for_s elapses drops silently
    assert am2.update([_result(False)], now=1.0) == []
    assert am2.active == {}


def test_alert_manager_severity_escalation():
    am = slo.AlertManager(for_s=0.0)
    am.update([_result(True, sev="warn")], now=0.0)
    assert am.firing()[0]["severity"] == "warn"
    assert am.update([_result(True, sev="page")], now=1.0) == []
    assert am.firing()[0]["severity"] == "page"


# ------------------------------------------------------------ SloEngine

def test_engine_dark_by_default(tmp_path):
    alerts = tmp_path / "alerts.jsonl"
    eng = slo.SloEngine(specs=slo.default_slos(),
                        alerts_path=str(alerts))
    assert metrics.active_registry() is None
    assert eng.tick() == []
    assert len(eng.ring) == 0 and eng.ticks == 0
    assert not alerts.exists()
    assert eng.status()["status"] == "ok"


def test_engine_tick_fires_gauges_jsonl_and_hooks(tmp_path):
    reg = metrics.enable()
    alerts = tmp_path / "alerts.jsonl"
    spec = slo.ratio_slo("avail", "err", "req", 0.999,
                         windows=[slo.BurnWindow(60.0, 10.0, 1.0, "page")])
    eng = slo.SloEngine(specs=[spec], alerts_path=str(alerts))
    seen = []
    eng.add_hook(lambda ev: (_ for _ in ()).throw(RuntimeError("boom")))
    eng.add_hook(seen.append)  # a broken hook must not starve the next

    reg.counter("req").inc(1000)
    assert eng.tick(now=0.0) == []
    reg.counter("err").inc(10)
    reg.counter("req").inc(1000)
    ev = eng.tick(now=1.0)
    assert [e["state"] for e in ev] == ["pending", "firing"]
    # window delta: 10 bad / 1000 total -> burn (0.01)/0.001 = 10
    assert ev[0]["burn"] == approx(10.0)
    snap = reg.snapshot()
    assert snap["gauges"]["slo.avail.burn_rate"] == approx(10.0)
    assert snap["gauges"]["slo.avail.firing"] == 2.0  # page rank
    assert snap["gauges"]["slo.avail.error_budget_remaining"] == 0.0
    assert eng.status()["status"] == "degraded"
    assert [e["state"] for e in seen] == ["pending", "firing"]

    # no new traffic: the window drains empty and the alert resolves
    ev = eng.tick(now=100.0)
    assert [e["state"] for e in ev] == ["resolved"]
    assert ev[0]["duration_s"] == approx(99.0)
    assert eng.status()["status"] == "ok"
    assert reg.snapshot()["gauges"]["slo.avail.firing"] == 0.0
    lines = [json.loads(ln) for ln in alerts.read_text().splitlines()]
    assert [ln["state"] for ln in lines] == ["pending", "firing",
                                             "resolved"]
    doc = eng.doc()
    assert doc["specs"][0]["name"] == "avail"
    assert doc["results"][0]["slo"] == "avail"


def test_install_uninstall_engine_globals():
    assert slo.active_engine() is None
    eng = slo.install_engine(specs=[slo.ratio_slo("a", "e", "t", 0.9)])
    assert slo.active_engine() is eng
    slo.uninstall_engine()
    assert slo.active_engine() is None


def test_default_packs_shapes():
    serving = slo.default_serving_slos()
    assert [s.name for s in serving] == [
        "serve.availability", "serve.ttft", "serve.tpot",
        "serve.queue_wait"]
    per = slo.default_serving_slos(replica="r0")
    assert [s.name for s in per] == ["serve.availability.r0",
                                     "serve.ttft.r0"]
    assert per[0].bad == "serve.replica.r0.errors"
    assert per[1].metric == "serve.replica.r0.ttft_ms"
    assert all(s.labels == {"replica": "r0"} for s in per)
    train = slo.default_train_slos()
    assert [s.name for s in train] == ["train.step_time",
                                       "train.finite_loss"]
    assert len(slo.default_slos()) == 6


# ----------------------------------------------------- exporter routes

def test_healthz_flips_and_alerts_route(tmp_path):
    ex = exporter.start_exporter(0)
    # legacy contract with no engine installed
    code, body = _get(ex.url + "/healthz")
    assert (code, body) == (200, "ok\n")
    code, _ = _get(ex.url + "/alerts")
    assert code == 404

    reg = metrics.default_registry()
    spec = slo.ratio_slo("avail", "err", "req", 0.99,
                         windows=[slo.BurnWindow(0.5, 0.1, 1.0, "page")])
    slo.install_engine(specs=[spec],
                       alerts_path=str(tmp_path / "alerts.jsonl"))
    reg.counter("req").inc(100)
    code, body = _get(ex.url + "/healthz")
    assert code == 200 and json.loads(body)["status"] == "ok"

    reg.counter("err").inc(5)
    code, body = _get(ex.url + "/healthz")
    assert code == 503 and json.loads(body)["status"] == "degraded"
    assert json.loads(body)["firing"][0]["slo"] == "avail"
    code, body = _get(ex.url + "/alerts")
    assert code == 200
    doc = json.loads(body)
    assert doc["status"] == "degraded" and doc["specs"]

    time.sleep(0.6)  # both windows slide past the burst
    code, body = _get(ex.url + "/healthz")
    assert code == 200 and json.loads(body)["status"] == "ok"


# ------------------------------------------------- router self-healing

class _FakeEngine:
    """The ServingEngine surface ReplicaRouter actually touches."""

    def __init__(self):
        self.replica_name = None
        self._draining = False
        self._queue = collections.deque()
        self._active = np.zeros(1, bool)
        self._lock = threading.Lock()
        self._completed = []
        self.slot_count = 1
        self.submitted = []

    def queue_depth(self):
        return len(self._queue)

    def occupancy(self):
        return 0.0

    def prefix_match_len(self, prompt_ids):
        return 0

    def submit(self, prompt_ids, trace_ctx=None, **kw):
        self.submitted.append(list(prompt_ids))
        return types.SimpleNamespace(id=len(self.submitted))

    def step(self):
        return 0

    def begin_drain(self, reason="drain"):
        self._draining = True


def test_router_shed_unshed_moves_placement():
    a, b = _FakeEngine(), _FakeEngine()
    router = ReplicaRouter({"a": a, "b": b})
    assert (a.replica_name, b.replica_name) == ("a", "b")
    with pytest.raises(KeyError):
        router.shed("nope")
    router.shed("a", penalty=50.0)
    assert router.shedding() == ["a"]
    assert router.stats()["shedding"] == ["a"]
    for _ in range(4):
        router.submit([1, 2, 3])
    assert router.routed == {"a": 0, "b": 4}
    router.unshed("a")
    router.unshed("a")  # idempotent
    assert router.shedding() == []


def test_router_attach_slo_sheds_on_fire_unsheds_on_resolve():
    a, b = _FakeEngine(), _FakeEngine()
    router = ReplicaRouter({"a": a, "b": b})
    spec = slo.ratio_slo("avail.b", "r.b.err", "r.b.req", 0.99,
                         windows=[slo.BurnWindow(10.0, 10.0, 2.0, "page")],
                         labels={"replica": "b"})
    eng = slo.SloEngine(specs=[spec])
    router.attach_slo(eng, penalty=9.0, drain=True)
    eng.tick(now=0.0,
             snapshot=_reg_snap(counters={"r.b.err": 0.0, "r.b.req": 0.0}))
    bad = _reg_snap(counters={"r.b.err": 5.0, "r.b.req": 10.0})
    ev = eng.tick(now=1.0, snapshot=bad)
    assert [e["state"] for e in ev] == ["pending", "firing"]
    assert router.shedding() == ["b"]
    assert b._draining  # page fire + drain=True + another live replica
    ev = eng.tick(now=20.0, snapshot=bad)  # window slid past the burst
    assert [e["state"] for e in ev] == ["resolved"]
    assert router.shedding() == []


# --------------------------------------------- fleet-level evaluation

def test_fleet_collector_evaluates_slos_over_merged(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    reg = metrics.enable()
    reg.counter("serve.requests").inc(100)
    reg.counter("serve.errors").inc(50)
    fleet.FleetPublisher(store, "w0", interval_s=0.1).publish_once()
    coll = fleet.FleetCollector(store)
    eng = slo.SloEngine(specs=[slo.ratio_slo(
        "fleet.avail", "serve.errors", "serve.requests", 0.99,
        windows=[slo.BurnWindow(5.0, 1.0, 1.0, "page")])])
    coll.attach_slo(eng)
    snap = coll.collect()
    # first collect: window-from-empty already holds the bad counters
    assert snap["slo"]["status"] == "degraded"
    assert snap["slo"]["firing"][0]["slo"] == "fleet.avail"
    assert [e["state"] for e in snap["slo"]["events"]] == ["pending",
                                                           "firing"]


# --------------------------------------------------- serving outcomes

class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    return m


def test_serve_request_outcome_and_replica_metrics(model):
    reg = metrics.enable()
    sink = _ListSink()
    eng = ServingEngine(model, slot_count=2, ladder=(8,), max_new_cap=4,
                        max_seq_len=32, steps_per_dispatch=2, sink=sink)
    eng.replica_name = "r0"
    h = eng.submit([1, 2, 3], max_new_tokens=3)
    eng.run()
    assert h.done and h.outcome in ("ok", "eos", "length")
    recs = [r for r in sink.records if r["event"] == "serve_request"]
    assert recs and recs[-1]["outcome"] == h.outcome
    snap = reg.snapshot()
    assert snap["counters"]["serve.requests"] == 1.0
    assert "serve.errors" not in snap["counters"]
    assert snap["counters"]["serve.replica.r0.requests"] == 1.0
    assert snap["histograms"]["serve.replica.r0.ttft_ms"]["count"] == 1


# ------------------------------------------------- trace_summary render

def test_trace_summary_renders_alert_timeline(tmp_path):
    base = {"event": "alert", "slo": "serve.ttft", "severity": "page",
            "labels": {"replica": "r1"}, "budget_remaining": 0.4}
    rows = [
        dict(base, ts=100.0, state="pending", burn=20.0, peak_burn=20.0),
        dict(base, ts=100.0, state="firing", burn=20.0, peak_burn=20.0),
        dict(base, ts=103.0, state="resolved", burn=0.0, peak_burn=25.0,
             duration_s=3.0),
    ]
    p = tmp_path / "alerts.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    env = {**os.environ, "PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_summary.py"),
         str(p)], env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])["summary"]
    assert summary["kind"] == "alert_timeline"
    assert summary["events"] == 3 and summary["span_s"] == 3.0
    s = summary["slos"]["serve.ttft"]
    assert (s["fires"], s["resolves"]) == (1, 1)
    assert s["peak_burn"] == 25.0 and s["total_firing_s"] == 3.0
    assert summary["still_firing"] == []

"""Closed-loop capacity controller (ISSUE 16 tentpole b).

Pinned contracts:
- scale out on a firing alert (target = ceil(cur * factor) clamped to
  max_replicas, spawned replicas named past the existing index) and on
  occupancy/queue sustained above the high-water marks;
- scale in only when nothing fires, every SLO keeps >= budget_min error
  budget, the fleet idles for idle_sustain_s, and nothing is retiring —
  newest replicas drain first, reaped only once their drain completes;
- hysteresis/flap damping: cooldown_s dead time after every action, the
  sustain clocks reset on action;
- every decision is one capacity.jsonl record carrying the full signal
  snapshot (holds elidable via log_holds=False) and doc() serves the
  policy + decision tail;
- counter audit (the begin_drain double-count regression): a drain
  re-placement moves the routed credit and counts under route.replaced —
  route.requests counts each logical request exactly once;
- dark by default: nothing installed at import, /capacity 404s until
  install_controller, polls run fine with no metrics registry;
- the pinned spike episode (the drill's autoscale leg, run in-process):
  spike -> page alert -> 2->4 -> resolve -> 4->2, zero requests lost.
"""
import collections
import json
import os
import subprocess
import sys
import threading
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.observability import capacity, exporter, metrics, slo
from paddle_tpu.serving.router import ReplicaRouter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_observability():
    """Controller/exporter/registry are process-globals the shared
    conftest doesn't know about: start dark, leave dark."""
    capacity.uninstall_controller()
    exporter.stop_exporter()
    metrics.reset()
    slo.uninstall_engine()
    yield
    capacity.uninstall_controller()
    exporter.stop_exporter()
    metrics.reset()
    slo.uninstall_engine()


class _Engine:
    """The ServingEngine surface ReplicaRouter + CapacityController touch.

    Queued requests carry the full re-placement field set so the real
    begin_drain path can re-submit them; drain() semantics are modeled by
    the _draining flag + step() admitting one queued request per call."""

    def __init__(self, occupancy=0.0):
        self.replica_name = None
        self.slot_count = 1
        self._draining = False
        self._queue = collections.deque()
        self._active = np.zeros(1, bool)
        self._lock = threading.Lock()
        self._completed = []
        self._occ = occupancy
        self.retired = False

    def queue_depth(self):
        return len(self._queue)

    def occupancy(self):
        return self._occ

    def prefix_match_len(self, prompt_ids):
        return 0

    def submit(self, prompt_ids, trace_ctx=None, max_new_tokens=None,
               temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
               seed=None, tenant=None):
        if self._draining:
            raise RuntimeError("draining")
        req = types.SimpleNamespace(
            id=f"q{id(self)}-{len(self._completed) + len(self._queue)}",
            prompt_ids=list(prompt_ids), trace_ctx=trace_ctx,
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            seed=seed, tenant=tenant, done=False, outcome=None)
        self._queue.append(req)
        return req

    def step(self):
        if self._queue:
            req = self._queue.popleft()
            req.done, req.outcome = True, "length"
            self._completed.append(req)
        return 0

    def begin_drain(self, reason="drain"):
        self._draining = True

    def retire(self):
        self.retired = True

    def register_replica(self, store, replica_id, lease_s=None):
        raise AssertionError("no store attached in these tests")


class _FakeSlo:
    """The SloEngine surface the controller reads."""

    def __init__(self):
        self._firing = []
        self.last_results = []

    def firing(self, severity=None):
        return list(self._firing)

    def fire(self, name="serve.ttft", severity="page"):
        self._firing = [{"slo": name, "severity": severity, "labels": {}}]

    def calm(self, budget_remaining=1.0):
        self._firing = []
        self.last_results = [{"budget_remaining": budget_remaining}]


def _fleet(n=2, occupancy=0.0):
    router = ReplicaRouter({f"r{i}": _Engine(occupancy=occupancy)
                            for i in range(n)})
    return router, (lambda name: _Engine(occupancy=occupancy))


def _controller(router, spawn, slo_engine=None, **pol):
    defaults = dict(min_replicas=1, max_replicas=4, cooldown_s=5.0,
                    idle_sustain_s=1.0, occupancy_low=0.2, queue_low=0.5)
    defaults.update(pol)
    return capacity.CapacityController(
        router, spawn, policy=capacity.CapacityPolicy(**defaults),
        slo_engine=slo_engine)


# ----------------------------------------------------------------- policy

def test_policy_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        capacity.CapacityPolicy(min_replicas=0)
    with pytest.raises(ValueError, match="min_replicas"):
        capacity.CapacityPolicy(min_replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="factors"):
        capacity.CapacityPolicy(scale_out_factor=1.0)
    d = capacity.CapacityPolicy().as_dict()
    assert d["max_replicas"] == 8 and "cooldown_s" in d


# ------------------------------------------------------------- scale out

def test_scale_out_on_firing_alert_names_past_existing():
    router, spawn = _fleet(2)
    eng = _FakeSlo()
    eng.fire()
    ctl = _controller(router, spawn, slo_engine=eng)
    rec = ctl.poll(now=100.0)
    assert rec["action"] == "scale_out" and rec["reason"] == "slo_burn"
    assert (rec["replicas"], rec["target"]) == (2, 4)
    assert rec["added"] == ["r2", "r3"]          # index seeded past r0/r1
    assert sorted(router.replicas) == ["r0", "r1", "r2", "r3"]
    assert rec["signals"]["firing"][0]["slo"] == "serve.ttft"
    assert ctl.scale_outs == 1
    # max_replicas clamps: still firing, but the fleet is at the ceiling
    rec = ctl.poll(now=200.0)
    assert rec["action"] == "hold"


def test_scale_out_on_sustained_occupancy_only():
    router, spawn = _fleet(2, occupancy=0.95)
    ctl = _controller(router, spawn, occupancy_high=0.9,
                      high_sustain_s=1.0)
    assert ctl.poll(now=10.0)["action"] == "hold"   # hot, not yet sustained
    assert ctl.poll(now=10.5)["action"] == "hold"
    rec = ctl.poll(now=11.1)
    assert rec["action"] == "scale_out" and rec["reason"] == "occupancy"
    assert len(router.replicas) == 4


# -------------------------------------------------------------- scale in

def test_scale_in_waits_for_idle_sustain_budget_and_cooldown():
    router, spawn = _fleet(4)
    eng = _FakeSlo()
    eng.calm(budget_remaining=0.1)
    ctl = _controller(router, spawn, slo_engine=eng, budget_min=0.25,
                      cooldown_s=5.0, idle_sustain_s=1.0)
    # idle but budget-starved: no shrink (a recent burn ate the budget)
    ctl.poll(now=0.0)
    assert ctl.poll(now=2.0)["action"] == "hold"
    # budget back: idle clock already satisfied -> shrink 4 -> 2
    eng.calm(budget_remaining=0.9)
    rec = ctl.poll(now=3.0)
    assert rec["action"] == "scale_in" and rec["reason"] == "idle_budget"
    assert (rec["replicas"], rec["target"]) == (4, 2)
    assert rec["draining"] == ["r3", "r2"]       # newest drain first
    assert router.replicas["r3"]._draining
    assert ctl.scale_ins == 1
    # the action reset the idle clock; this poll also reaps the drained
    # pair and restarts the clock at 4.0
    assert ctl.poll(now=4.0)["action"] == "hold"
    # idle sustained again, but the cooldown dead time blocks the flap
    rec = ctl.poll(now=5.5)
    assert rec["action"] == "hold" and rec["reason"] == "cooldown"


def test_retiring_replicas_reaped_after_drain_completes():
    router, spawn = _fleet(2)
    ctl = _controller(router, spawn, min_replicas=1, cooldown_s=0.5,
                      idle_sustain_s=0.5)
    router.submit([1, 2])  # lands on r0 (deterministic tie-break)
    ctl.poll(now=0.0)
    rec = ctl.poll(now=1.0)
    assert rec["action"] == "scale_in" and rec["draining"] == ["r1"]
    # r1 is drained (no queue, no active) -> the next poll reaps it
    assert "r1" in router.replicas
    rec = ctl.poll(now=2.0)
    assert "r1" not in router.replicas
    assert rec["signals"]["retiring"] == []
    assert ctl.doc()["retiring"] == []


def test_scale_in_blocked_while_firing_or_retiring():
    router, spawn = _fleet(4)
    eng = _FakeSlo()
    eng.fire()
    ctl = _controller(router, spawn, slo_engine=eng, max_replicas=4,
                      cooldown_s=0.0, idle_sustain_s=0.5)
    assert ctl.poll(now=0.0)["action"] == "hold"  # firing + at ceiling
    assert ctl.poll(now=5.0)["action"] == "hold"  # firing blocks shrink
    eng.calm()
    rec = ctl.poll(now=6.0)                       # idle sustained since 0.0
    assert rec["action"] == "scale_in" and rec["draining"] == ["r3", "r2"]
    # an unfinished drain blocks further shrink: r3 keeps an active slot
    router.replicas["r3"]._active[0] = True
    rec = ctl.poll(now=7.0)                       # reaps r2, r3 lingers
    assert rec["action"] == "hold"
    assert rec["signals"]["retiring"] == ["r3"]
    assert "r2" not in router.replicas
    router.replicas["r3"]._active[0] = False      # slot finishes
    ctl.poll(now=8.0)
    assert "r3" not in router.replicas
    assert router.replicas["r0"].retired is False  # survivors untouched


# ------------------------------------------------------ evidence surfaces

def test_jsonl_records_and_log_holds(tmp_path):
    path = str(tmp_path / "capacity.jsonl")
    router, spawn = _fleet(1)
    eng = _FakeSlo()
    ctl = capacity.CapacityController(
        router, spawn, policy=capacity.CapacityPolicy(max_replicas=2),
        slo_engine=eng, jsonl_path=path, log_holds=False)
    ctl.poll(now=0.0)                 # hold: not logged
    eng.fire()
    ctl.poll(now=1.0)                 # scale_out: logged
    with open(path) as f:
        recs = [json.loads(ln) for ln in f]
    assert [r["action"] for r in recs] == ["scale_out"]
    assert recs[0]["event"] == "capacity"
    assert set(recs[0]["signals"]) >= {"replicas", "occupancy", "queued",
                                       "queue_per_slot", "firing",
                                       "budget_remaining"}
    doc = ctl.doc()
    assert doc["policy"]["max_replicas"] == 2
    assert doc["scale_outs"] == 1 and doc["polls"] == 2
    assert doc["last"]["action"] == "scale_out"
    assert doc["decisions"][-1] == doc["last"]


def test_metrics_gauges_and_counters():
    metrics.enable()
    router, spawn = _fleet(1)
    eng = _FakeSlo()
    eng.fire()
    ctl = _controller(router, spawn, slo_engine=eng)
    ctl.poll(now=0.0)
    snap = metrics.default_registry().snapshot()
    assert snap["counters"]["capacity.scale_outs"] == 1
    assert snap["gauges"]["capacity.target_replicas"] == 2.0
    assert snap["gauges"]["capacity.replicas"] == 1.0


def test_capacity_route_dark_until_installed():
    ex = exporter.start_exporter(0)

    def get(path):
        try:
            with urllib.request.urlopen(ex.url + path, timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as err:
            return err.code, err.read().decode()

    code, body = get("/capacity")
    assert code == 404 and "no capacity controller" in body
    router, spawn = _fleet(2)
    ctl = capacity.install_controller(_controller(router, spawn))
    assert capacity.active_controller() is ctl
    ctl.poll(now=0.0)
    code, body = get("/capacity")
    assert code == 200
    doc = json.loads(body)
    assert doc["replicas"] == ["r0", "r1"] and doc["polls"] == 1
    capacity.uninstall_controller()
    assert capacity.active_controller() is None
    assert get("/capacity")[0] == 404


def test_poll_runs_dark_with_no_registry_tracer_or_jsonl():
    assert metrics.active_registry() is None
    router, spawn = _fleet(1)
    ctl = _controller(router, spawn)
    rec = ctl.poll(now=0.0)
    assert rec["action"] == "hold" and ctl.last_decision is rec


# ------------------------------------------- counter audit (satellite 5)

def test_begin_drain_replacement_counts_each_request_once():
    """The regression the drill's autoscale leg relies on: re-placing a
    drained replica's queued work must not double-count route.requests
    (the controller's scale-in signal) nor credit the drained replica's
    routed tally for work it never served."""
    metrics.enable()
    router = ReplicaRouter({"a": _Engine(), "b": _Engine()})
    reqs = [router.submit([i, i + 1]) for i in range(6)]
    placed_a = router.routed["a"]
    assert placed_a > 0 and router.routed["b"] > 0  # queue-balanced spread
    replaced = router.begin_drain("a")
    assert len(replaced) == placed_a  # nothing was admitted yet
    snap = metrics.default_registry().snapshot()["counters"]
    assert snap["route.requests"] == 6          # once per logical request
    assert snap["route.replaced"] == len(replaced)
    assert router.routed["a"] == 0              # credit moved with the work
    assert router.routed["b"] == 6
    router.run()
    assert router.drained("a")
    done = [r for r in reqs if r.done] + replaced
    assert {tuple(r.prompt_ids) for r in done} == \
        {(i, i + 1) for i in range(6)}
    # the sink-visible flag: replaced records are distinguishable
    assert all(r.outcome == "length" for r in replaced)


# ------------------------------------------ trace_summary scaling story

def test_trace_summary_renders_capacity_timeline(tmp_path):
    caps = [
        {"event": "capacity", "ts": 100.0, "action": "hold",
         "reason": "steady", "replicas": 2, "target": 2,
         "signals": {"occupancy": 0.1, "queued": 0, "firing": []}},
        {"event": "capacity", "ts": 101.5, "action": "scale_out",
         "reason": "slo_burn", "replicas": 2, "target": 4,
         "signals": {"occupancy": 0.9, "queued": 6,
                     "firing": [{"slo": "serve.ttft"}]},
         "added": ["r2", "r3"]},
        {"event": "capacity", "ts": 106.0, "action": "scale_in",
         "reason": "idle_budget", "replicas": 4, "target": 2,
         "signals": {"occupancy": 0.0, "queued": 0, "firing": []},
         "draining": ["r3", "r2"]},
    ]
    alerts = [
        {"event": "alert", "ts": 101.0, "slo": "serve.ttft",
         "state": "firing", "severity": "page", "burn": 4.0},
        {"event": "alert", "ts": 103.0, "slo": "serve.ttft",
         "state": "resolved", "severity": "page", "burn": 0.2,
         "duration_s": 2.0, "peak_burn": 4.0},
    ]
    p = tmp_path / "merged.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in caps + alerts))
    env = {**os.environ, "PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_summary.py"),
         str(p)], env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "scaling timeline:" in out.stdout
    assert "steady holds elided" in out.stdout
    summary = json.loads(out.stdout.strip().splitlines()[-1])["summary"]
    assert summary["kind"] == "capacity_timeline"
    assert summary["scale_outs"] == 1 and summary["scale_ins"] == 1
    assert (summary["replicas_initial"], summary["replicas_peak"],
            summary["replicas_final"]) == (2, 4, 2)
    assert summary["reaction_s"] == 0.5    # firing -> scale_out
    assert summary["recovery_s"] == 2.0    # firing -> last resolve
    assert summary["alerts"]["kind"] == "alert_timeline"


# ----------------------------------- the pinned spike episode (dryrun)

def test_autoscale_spike_episode_dryrun(tmp_path):
    """The drill's autoscale leg, in-process: the SAME code path
    __graft_entry__'s dryrun asserts on, so tier-1 catches a broken loop
    without the 8-worker drill. spike -> page alert -> 2->4 -> resolve
    -> 4->2 after cooldown, zero lost, route.requests counted once."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "elastic_drill_for_test",
            os.path.join(_REPO, "tools", "elastic_drill.py"))
        drill = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(drill)
    finally:
        sys.path.pop(0)
    verdicts = []

    def verdict(check, ok, **extra):
        verdicts.append({"check": check, "ok": bool(ok), **extra})

    recovery_s, schedule_ms, n = drill._autoscale_leg(
        verdict, str(tmp_path))
    failed = [v for v in verdicts if not v["ok"]]
    assert not failed, failed
    names = {v["check"] for v in verdicts}
    assert {"autoscale_scenario_replayable", "autoscale_alert_fires",
            "autoscale_scales_out", "autoscale_alert_resolves",
            "autoscale_scales_back", "autoscale_membership_follows",
            "autoscale_zero_lost", "autoscale_route_counts_once",
            "autoscale_decisions_logged",
            "autoscale_recovery_timed"} <= names
    assert recovery_s > 0 and schedule_ms > 0 and n > 0
    assert os.path.exists(os.path.join(str(tmp_path), "capacity.jsonl"))

"""fused_linear_cross_entropy vs the dense matmul+softmax_with_cross_entropy path.

Mirrors the reference's OpTest pattern (numpy/dense reference + gradient check)
for the fused classifier op of ops/fused.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.fused import fused_linear_cross_entropy
from paddle_tpu.ops import linalg as L
from paddle_tpu.nn import functional as F


def _dense_loss(h, w, labels):
    logits = L.matmul(h, w, transpose_y=True)
    loss = F.softmax_with_cross_entropy(logits, labels.unsqueeze(-1))
    return loss.squeeze(-1)


@pytest.mark.parametrize("shape", [(2, 16, 32, 64), (1, 7, 32, 64)])  # odd rows pad
def test_fused_matches_dense(shape):
    b, s, v, hdim = shape
    rng = np.random.RandomState(0)
    h = paddle.to_tensor(rng.randn(b, s, hdim).astype(np.float32))
    w = paddle.to_tensor(rng.randn(v, hdim).astype(np.float32) * 0.1)
    labels = paddle.to_tensor(rng.randint(0, v, (b, s)).astype(np.int64))

    fused = fused_linear_cross_entropy(h, w, labels)
    dense = _dense_loss(h, w, labels)
    np.testing.assert_allclose(fused.numpy(), dense.numpy(), rtol=2e-5, atol=2e-5)


def test_fused_grads_match_dense():
    b, s, v, hdim = 2, 16, 48, 32
    rng = np.random.RandomState(1)
    hn = rng.randn(b, s, hdim).astype(np.float32)
    wn = (rng.randn(v, hdim) * 0.1).astype(np.float32)
    ln = rng.randint(0, v, (b, s)).astype(np.int64)

    def run(loss_path):
        h = paddle.to_tensor(hn, stop_gradient=False)
        w = paddle.to_tensor(wn, stop_gradient=False)
        labels = paddle.to_tensor(ln)
        loss = loss_path(h, w, labels).mean()
        loss.backward()
        return loss.numpy(), h.grad.numpy(), w.grad.numpy()

    lf, dhf, dwf = run(fused_linear_cross_entropy)
    ld, dhd, dwd = run(_dense_loss)
    np.testing.assert_allclose(lf, ld, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dhf, dhd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(dwf, dwd, rtol=1e-4, atol=1e-5)


def test_fused_ignore_index():
    b, s, v, hdim = 1, 8, 16, 8
    rng = np.random.RandomState(2)
    h = paddle.to_tensor(rng.randn(b, s, hdim).astype(np.float32), stop_gradient=False)
    w = paddle.to_tensor((rng.randn(v, hdim) * 0.1).astype(np.float32),
                         stop_gradient=False)
    ln = rng.randint(0, v, (b, s)).astype(np.int64)
    ln[0, :4] = -100
    labels = paddle.to_tensor(ln)

    loss = fused_linear_cross_entropy(h, w, labels)
    out = loss.numpy()
    assert (out[0, :4] == 0).all()
    assert (out[0, 4:] > 0).all()

    loss.sum().backward()
    dh = h.grad.numpy()
    assert np.abs(dh[0, :4]).max() == 0.0  # ignored rows get no gradient
    assert np.abs(dh[0, 4:]).max() > 0.0


def test_gpt_uses_fused_path_same_loss():
    """GPTForPretraining forward (fused head) vs explicit logits+CE."""
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    paddle.seed(0)
    cfg = gpt_tiny()
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(3)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 64)).astype(np.int64))
    labels = paddle.to_tensor(np.roll(ids.numpy(), -1, 1))

    assert model._can_fuse_loss()
    fused_loss = float(model(ids, labels).numpy())
    logits = model.logits(ids)
    dense_loss = float(F.softmax_with_cross_entropy(
        logits, labels.unsqueeze(-1)).mean().numpy())
    np.testing.assert_allclose(fused_loss, dense_loss, rtol=1e-5, atol=1e-6)

"""Auto-parallel planner: topology search on the XLA cost model.

Reference parity: auto_parallel/planner.py (dist-attr search) +
cost_model.py (op cost simulation) — here the compiler is the cost model
(VERDICT r2 #4 acceptance: the planner must pick a non-trivial topology
that beats naive dp for a TP-friendly model).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.auto_parallel.planner import (
    collective_bytes, enumerate_topologies, plan, score_topology)
from paddle_tpu.distributed.meta_parallel import (ColumnParallelLinear,
                                                  RowParallelLinear)


class TPNet(nn.Layer):
    """Megatron MLP block: big weights, small activations — TP-friendly."""

    def __init__(self, hidden=256, mult=8):
        super().__init__()
        self.up = ColumnParallelLinear(hidden, mult * hidden,
                                       gather_output=False)
        self.down = RowParallelLinear(mult * hidden, hidden,
                                      input_is_parallel=True)

    def forward(self, x):
        return self.down(self.up(x))


def _mf():
    paddle.seed(0)
    return TPNet()


def _of(m):
    return paddle.optimizer.AdamW(learning_rate=1e-3,
                                  parameters=m.parameters())


def _batch():
    rng = np.random.RandomState(0)
    return [paddle.to_tensor(rng.randn(8, 256).astype("float32")),
            paddle.to_tensor(rng.randn(8, 256).astype("float32"))]


def test_enumerate_topologies_covers_factorizations():
    cands = enumerate_topologies(8)
    keys = [tuple(sorted(c.items())) for c in cands]
    assert len(keys) == len(set(keys))
    assert {"dp_degree": 8} in cands
    # dp_degree is always EXPLICIT (even at 1): an omitted dp would let the
    # HCG auto-fill consume every host device, scoring a different topology
    # than the candidate's label
    assert {"dp_degree": 1, "mp_degree": 8} in cands
    assert {"dp_degree": 2, "mp_degree": 2, "sharding_degree": 2} in cands
    for c in cands:
        total = 1
        for v in c.values():
            total *= v
        assert total in (8, 1) or total == 8  # dp_degree:1 sentinel allowed


def test_collective_bytes_parses_hlo():
    hlo = """
  %all-reduce.5 = (f32[], f32[64]{0}, f32[64,64]{1,0}) all-reduce(%a, %b, %c)
  %get-tuple-element = f32[] get-tuple-element(%all-reduce.5), index=0
  %all-gather.1 = bf16[16,32]{1,0} all-gather(%x)
"""
    out = collective_bytes(hlo)
    assert out["all-reduce"] == 4 + 64 * 4 + 64 * 64 * 4
    assert out["all-gather"] == 16 * 32 * 2


def test_planner_prefers_tp_for_megatron_block():
    """dp replicates the big weights to every device; mp shards them — the
    cost model must rank mp above naive dp (and the score gap should be
    decisive, not noise)."""
    best, results = plan(_mf, _of, _batch(), n_devices=8,
                         loss_fn=paddle.nn.MSELoss())
    assert best.get("mp_degree", 1) > 1, (best, results[:3])
    by_cfg = {tuple(sorted(r.config.items())): r for r in results}
    naive_dp = by_cfg[(("dp_degree", 8),)]
    assert results[0].score < 0.5 * naive_dp.score, (
        results[0], naive_dp)


def test_score_topology_rejects_indivisible_batch():
    r = score_topology(_mf, _of, _batch(), {"dp_degree": 8, "mp_degree": 1},
                       loss_fn=paddle.nn.MSELoss())
    assert r.feasible  # 8 % 8 == 0
    r2 = score_topology(_mf, _of,
                        [paddle.to_tensor(np.zeros((6, 256), "float32")),
                         paddle.to_tensor(np.zeros((6, 256), "float32"))],
                        {"dp_degree": 8}, loss_fn=paddle.nn.MSELoss())
    assert not r2.feasible


def test_memory_budget_rejects_replication():
    """A budget below the replicated footprint forces a sharded winner."""
    _, results = plan(_mf, _of, _batch(), n_devices=8,
                      loss_fn=paddle.nn.MSELoss())
    by_cfg = {tuple(sorted(r.config.items())): r for r in results}
    dp_peak = by_cfg[(("dp_degree", 8),)].peak_bytes
    best, results2 = plan(_mf, _of, _batch(), n_devices=8,
                          loss_fn=paddle.nn.MSELoss(),
                          memory_budget=int(dp_peak * 0.6))
    assert best.get("mp_degree", 1) > 1 or best.get("sharding_degree", 1) > 1
    by_cfg2 = {tuple(sorted(r.config.items())): r for r in results2}
    assert not by_cfg2[(("dp_degree", 8),)].feasible


def test_fleet_engine_auto_plans_and_trains():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}  # planner should override
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = TPNet()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = fleet.distributed_engine(model, opt, loss_fn=paddle.nn.MSELoss(),
                                   auto=True, sample_batch=_batch())
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.degrees["mp"] > 1, hcg.topology()
    x, y = _batch()
    loss = eng.step(x, y)
    assert np.isfinite(float(loss.item()))


def test_annotation_engine_fit_auto_picks_mesh():
    """Engine.fit(auto=True): mesh SHAPE chosen by compiling candidates."""
    from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh, \
        shard_tensor
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __init__(self, n=32):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 256).astype("float32")
            self.y = rng.randn(n, 256).astype("float32")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    paddle.seed(0)
    pm = ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                     dim_names=["dp", "mp"])
    net = nn.Sequential(nn.Linear(256, 2048), nn.ReLU(),
                        nn.Linear(2048, 256))
    shard_tensor(net[0].weight, pm, [None, "mp"])
    shard_tensor(net[0].bias, pm, ["mp"])
    shard_tensor(net[2].weight, pm, ["mp", None])
    eng = Engine(model=net, loss=paddle.nn.MSELoss(),
                 optimizer=paddle.optimizer.Adam(
                     learning_rate=0.01, parameters=net.parameters()),
                 process_mesh=pm)
    history = eng.fit(DS(), epochs=2, batch_size=8, auto=True)
    assert len(eng.plan_table) >= 2  # several shapes actually compiled
    assert np.isfinite(history).all()
    # the chosen mesh keeps the annotation dim names
    assert eng._process_mesh.dim_names == ["dp", "mp"]


def test_planner_picks_sequence_parallel_at_long_context():
    """VERDICT r3 #5: SP's raison d'etre — the regime where the global
    batch is SMALLER than the device count (one/few very long sequences),
    so dp cannot shard further and sequence parallelism is the only way to
    spread one sequence's activations. batch 2 on 4 devices: dp4 is
    infeasible outright (indivisible batch) and the planner must rank an
    sp config first. Also pins the 'sp' axis -> sep_degree spelling and
    that candidates carry dp_degree EXPLICITLY (an omitted dp would
    auto-fill to consume all host devices, mislabeling the score)."""
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def _gpt():
        paddle.seed(0)
        return GPTForPretraining(GPTConfig(
            vocab_size=256, hidden_size=64, num_layers=1, num_heads=4,
            max_seq_len=4096))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (2, 4096)).astype(np.int64)
    batch = [paddle.to_tensor(ids),
             paddle.to_tensor(np.roll(ids, -1, 1))]
    best, results = plan(_gpt, _of, batch, n_devices=4, axes=("dp", "sp"))
    assert best.get("sep_degree", 1) > 1, (best, [
        (r.config, r.feasible, r.peak_bytes) for r in results])
    by_cfg = {tuple(sorted(r.config.items())): r for r in results}
    sp4 = by_cfg[(("dp_degree", 1), ("sep_degree", 4))]
    dp4 = by_cfg[(("dp_degree", 4),)]
    assert sp4.feasible and not dp4.feasible, (sp4, dp4)
    # sp4 spreads the one-per-device-sequence activations 4 ways: its peak
    # must come in well under the dense dp2 x sp1-equivalent... there is no
    # feasible sp-free config at this batch, which is exactly the point
    assert all(r.config.get("sep_degree", 1) > 1 for r in results
               if r.feasible)

"""Compile-only gate for the EXACT flagship-bench configuration.

VERDICT r4 weak #8: four consecutive rounds ran bench.py in CPU-degraded mode,
which means the real bench path (hidden 768, 12 layers, vocab 50304, seq 1024,
bf16 autocast, flash attention) was never even COMPILED between on-chip
windows — a trace-level regression would surface only at the next live run.
These tests AOT-lower that exact config every suite run, chip or no chip:

- the full fused train step (fwd + bwd + AdamW) exports for the TPU target
  (``jax.export platforms=["tpu"]``) with the real Mosaic flash kernel
  embedded — the same mechanism that caught three on-chip compile bugs in
  round 3 (test_hlo_perf_gates.py);
- the K-step scan program compiles (CPU backend) to the expected shape: the
  steps stay inside while-loops (no unrolling — the loop count is K-
  independent) and the carried params/opt state stay donation-aliased.

The config comes from ``bench.bench_config()`` — the same function main()
runs — so the gate and the benchmark cannot drift apart.
"""
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root for `import bench`
import bench  # noqa: E402

import paddle_tpu.ops.pallas.flash_attention  # noqa: F401,E402

_FA = sys.modules["paddle_tpu.ops.pallas.flash_attention"]


def _bench_engine(batch=8):
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForPretraining

    cfg, _, seq, _, _ = bench.bench_config("base")
    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    eng = fleet.distributed_engine(model, opt)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (batch, seq)).astype(np.int64))
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1))
    return eng, ids, labels


@pytest.mark.slow
def test_bench_config_step_exports_for_tpu_target(monkeypatch):
    """The exact bench train step lowers for a TPU target from the CPU host
    (no execution), flash kernel Mosaic-compiled and embedded."""
    from jax import export as jexport

    monkeypatch.setattr(_FA, "_interpret", lambda: False)
    paddle.set_flags({"use_flash_attention": True, "pallas_interpret_ok": True})
    eng, ids, labels = _bench_engine(batch=8)
    step = eng._raw_step()
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        mod = jexport.export(jax.jit(step), platforms=["tpu"])(
            eng.params, eng.opt_state, jnp.float32(1e-4), jnp.int32(1),
            jax.random.key(0), ids, labels).mlir_module()
    assert "tpu_custom_call" in mod, (
        "bench-config attention no longer routes to the Mosaic flash kernel "
        "on the TPU target")


@pytest.mark.slow
def test_bench_config_scan_compiles_one_program_no_unroll():
    """The K-step scan program at the exact bench config compiles (CPU
    backend) with a K-independent while-loop count and donation-aliased
    state — K unrolled bodies or per-step double buffering fail here."""
    eng, ids, labels = _bench_engine(batch=8)
    arrays = [ids, labels]
    jf = eng._build_scan(arrays, True)

    def lower(k):
        keys = jnp.stack([jax.random.key(i) for i in range(k)])
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            return jf.lower(eng.params, eng.opt_state,
                            jnp.full((k,), 1e-4, jnp.float32), jnp.int32(1),
                            keys, *arrays)

    comp = lower(3).compile()
    txt = comp.as_text()
    n_while = len(re.findall(r"\) while\(", txt))
    # outer K-scan + the fused-CE chunk scans (fwd + bwd); anything beyond
    # that bound means a loop got unrolled or duplicated
    assert 1 <= n_while <= 6, (
        f"{n_while} while-loops in the bench-config scan program — expected "
        f"the K-step scan plus the chunked-CE loops only")
    ma = comp.memory_analysis()
    state_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in eng.params.values())
    assert ma.alias_size_in_bytes >= 0.9 * state_bytes, (
        "bench-config scan donation regressed: params would double-buffer "
        "in HBM every step")
    # K-independence: the jaxpr for a longer K must not grow new scans
    # (compiling twice would double the gate's cost; the jaxpr check is
    # trace-level and cheap)
    k5 = lower(5).as_text("stablehlo")
    n_while5 = len(re.findall(r"stablehlo.while", k5))
    k3 = lower(3).as_text("stablehlo")
    n_while3 = len(re.findall(r"stablehlo.while", k3))
    assert n_while5 == n_while3, (
        f"while-op count scales with K ({n_while3} -> {n_while5}): the "
        f"K-step trainer is unrolling instead of scanning")

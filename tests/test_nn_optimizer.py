import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_linear_layer():
    paddle.seed(0)
    l = nn.Linear(4, 3)
    assert l.weight.shape == [4, 3]
    assert l.bias.shape == [3]
    out = l(paddle.ones([2, 4]))
    assert out.shape == [2, 3]
    np.testing.assert_allclose(out.numpy(),
                               np.ones((2, 4)) @ l.weight.numpy() + l.bias.numpy(),
                               rtol=1e-5)


def test_parameters_traversal():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    params = model.parameters()
    assert len(params) == 4
    names = [n for n, _ in model.named_parameters()]
    assert "0.weight" in names and "2.bias" in names


def test_state_dict_roundtrip(tmp_path):
    model = nn.Linear(3, 3)
    sd = model.state_dict()
    path = str(tmp_path / "model.pdparams")
    paddle.save(sd, path)
    model2 = nn.Linear(3, 3)
    model2.set_state_dict(paddle.load(path))
    np.testing.assert_allclose(model.weight.numpy(), model2.weight.numpy())


def test_train_eval_mode():
    model = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    model.eval()
    assert not model[1].training
    model.train()
    assert model[1].training


def test_sublayer_buffers():
    bn = nn.BatchNorm2D(4)
    buf_names = [n for n, _ in bn.named_buffers()]
    assert "_mean" in buf_names and "_variance" in buf_names
    sd = bn.state_dict()
    assert "_mean" in sd


def test_sgd_step():
    p = nn.Parameter(np.asarray([1.0, 2.0], np.float32))
    import jax.numpy as jnp

    p._data = jnp.asarray([1.0, 2.0], jnp.float32)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    (p * 3.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.7, 1.7], rtol=1e-6)
    opt.clear_grad()
    assert p.grad is None


def test_adam_converges_quadratic():
    paddle.seed(0)
    x = nn.Parameter(np.asarray([5.0], np.float32))
    opt = paddle.optimizer.Adam(learning_rate=0.3, parameters=[x])
    for _ in range(200):
        loss = (x * x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert abs(x.numpy()[0]) < 0.1


@pytest.mark.parametrize("cls,kwargs", [
    (paddle.optimizer.SGD, {}),
    (paddle.optimizer.Momentum, {"momentum": 0.9}),
    (paddle.optimizer.Adam, {}),
    (paddle.optimizer.AdamW, {"weight_decay": 0.01}),
    (paddle.optimizer.Adamax, {}),
    (paddle.optimizer.Adagrad, {}),
    (paddle.optimizer.Adadelta, {}),
    (paddle.optimizer.RMSProp, {}),
    (paddle.optimizer.Lamb, {}),
])
def test_all_optimizers_decrease_loss(cls, kwargs):
    paddle.seed(1)
    model = nn.Linear(4, 1)
    opt = cls(learning_rate=0.05, parameters=model.parameters(), **kwargs)
    xs = paddle.to_tensor(np.random.RandomState(0).rand(16, 4).astype(np.float32))
    ys = paddle.to_tensor(np.random.RandomState(1).rand(16, 1).astype(np.float32))
    losses = []
    for _ in range(20):
        loss = nn.functional.mse_loss(model(xs), ys)
        losses.append(float(loss.item()))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0]


def test_grad_clip_global_norm():
    p1 = nn.Parameter(np.asarray([3.0], np.float32))
    p2 = nn.Parameter(np.asarray([4.0], np.float32))
    clip = nn.ClipGradByGlobalNorm(1.0)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p1, p2], grad_clip=clip)
    (p1 * 3.0 + p2 * 4.0).sum().backward()  # grads 3, 4 -> global norm 5
    opt.step()
    np.testing.assert_allclose(p1.numpy(), [3.0 - 3.0 / 5], rtol=1e-5)
    np.testing.assert_allclose(p2.numpy(), [4.0 - 4.0 / 5], rtol=1e-5)


def test_lr_scheduler():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    model = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=model.parameters())
    lrs = []
    for _ in range(6):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025, 0.025])


def test_linear_warmup():
    sched = paddle.optimizer.lr.LinearWarmup(learning_rate=0.1, warmup_steps=4,
                                             start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(6):
        vals.append(sched())
        sched.step()
    np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075])
    np.testing.assert_allclose(vals[4:], [0.1, 0.1])


def test_optimizer_state_dict():
    model = nn.Linear(2, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=model.parameters())
    (model(paddle.ones([1, 2]))).sum().backward()
    opt.step()
    sd = opt.state_dict()
    assert sd["_step_count"] == 1
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=model.parameters())
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1


def test_amp_autocast_bf16():
    with paddle.amp.auto_cast(dtype="bfloat16"):
        a = paddle.ones([4, 4])
        b = paddle.ones([4, 4])
        c = paddle.matmul(a, b)
    assert c.dtype == paddle.bfloat16
    # black-listed op stays f32
    with paddle.amp.auto_cast(dtype="bfloat16"):
        s = paddle.nn.functional.softmax(paddle.ones([4, 4]))
    assert s.dtype == paddle.float32


def test_grad_scaler():
    model = nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    loss = model(paddle.ones([4, 2])).mean()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()
    assert scaler.get_loss_scaling().item() == 1024.0


def test_grad_scaler_inf_skips_step():
    import jax.numpy as jnp

    model = nn.Linear(2, 1)
    w_before = model.weight.numpy().copy()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
    loss = model(paddle.ones([4, 2])).mean()
    scaler.scale(loss).backward()
    model.weight.grad = paddle.to_tensor(np.full((2, 1), np.inf, np.float32))
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(model.weight.numpy(), w_before)
    assert scaler._scale == 4.0  # decreased

"""Kernel autotune cache (core/autotune.py + incubate.autotune surface).

Reference analogue: phi AlgorithmsCache / switch_autotune step-window tests.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core import autotune


def setup_function(_):
    # isolate: fresh cache + disabled config per test
    autotune._cache = autotune.AlgorithmsCache()
    autotune._config["kernel"] = {"enable": False, "tuning_range": [1, 10]}
    autotune._config["cache_path"] = None
    autotune._step = 0


def test_cache_hit_miss_stats():
    c = autotune.AlgorithmsCache()
    assert c.get("k", (1, 2)) is None
    c.put("k", (1, 2), (512, 256))
    assert c.get("k", (1, 2)) == (512, 256)
    assert c.hits == 1 and c.misses == 1
    assert 0.0 < c.cache_hit_rate() < 1.0
    assert c.size() == 1


def test_pick_measures_and_caches():
    autotune.set_config({"kernel": {"enable": True}})
    calls = []

    def run(c):
        calls.append(c)
        if c == "slow":
            import time
            time.sleep(0.02)

    best = autotune.pick("dummy", ("shape",), ["slow", "fast"], run)
    assert best == "fast"
    assert calls.count("slow") == 2 and calls.count("fast") == 2  # warmup+timed
    # second call: cache hit, no re-measurement
    calls.clear()
    assert autotune.pick("dummy", ("shape",), ["slow", "fast"], run) == "fast"
    assert not calls


def test_pick_disabled_returns_default():
    out = autotune.pick("dummy", ("k",), [1, 2, 3], lambda c: None, default=2)
    assert out == 2
    assert autotune.cache().size() == 0  # nothing cached when off


def test_tuning_window_closes():
    autotune.set_config({"kernel": {"enable": True, "tuning_range": [1, 3]}})
    autotune.set_step(5)  # outside [1, 3)
    out = autotune.pick("dummy", ("k",), [1, 2], lambda c: None, default=2)
    assert out == 2 and autotune.cache().size() == 0
    autotune.set_step(2)  # inside window
    out = autotune.pick("dummy", ("k",), [1, 2], lambda c: None)
    assert autotune.cache().size() == 1


def test_failing_candidate_skipped():
    autotune.set_config({"kernel": {"enable": True}})

    def run(c):
        if c == "broken":
            raise RuntimeError("compile failed")

    assert autotune.pick("dummy", ("k",), ["broken", "ok"], run) == "ok"


def test_persistence_roundtrip(tmp_path):
    path = str(tmp_path / "autotune.json")
    c = autotune.AlgorithmsCache()
    c.put("flash_attention", (96, 1024, 1024), (512, 512))
    c.save(path)
    c2 = autotune.AlgorithmsCache()
    c2.load(path)
    assert c2.get("flash_attention", (96, 1024, 1024)) == (512, 512)


def test_flash_attention_uses_tuned_blocks():
    """End-to-end: tuning picks a block pair and the kernel still matches the
    dense reference (CPU interpret mode; timing is meaningless there but the
    mechanism must produce a valid, cached choice)."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    autotune.set_config({"kernel": {"enable": True}})
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(1, 256, 2, 32).astype(np.float32))
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=True)
    assert autotune.cache().size() == 1
    (choice,) = [vv for sub in autotune.cache()._map.values() for vv in sub.values()]
    assert tuple(choice)[0] in (128, 256) and tuple(choice)[1] in (128, 256)

    # dense reference
    import jax
    qt, kt, vt = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(32)
    m = jnp.tril(jnp.ones(s.shape[-2:], bool))
    p = jax.nn.softmax(jnp.where(m, s, -1e30), axis=-1)
    ref = jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_incubate_surface():
    paddle.incubate.autotune.set_config({"kernel": {"enable": True}})
    assert autotune.enabled()
    stats = paddle.incubate.autotune.kernel_cache()
    assert hasattr(stats, "cache_hit_rate")

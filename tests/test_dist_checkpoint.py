"""Distributed checkpoint: shard-wise save + cross-layout restore
(reference auto_parallel dist_saver.py + converter.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import (
    Converter, load_distributed_checkpoint, load_distributed_state,
    save_distributed_checkpoint)
from paddle_tpu.models import GPTForPretraining, gpt_tiny


def _engine(degrees):
    paddle.seed(123)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=strategy)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    return fleet.distributed_engine(model, opt)


def _batch():
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int64)
    return paddle.to_tensor(ids), paddle.to_tensor(np.roll(ids, -1, 1))


def test_save_load_same_layout(tmp_path):
    eng = _engine({"dp_degree": 2, "mp_degree": 4})
    ids, labels = _batch()
    for _ in range(2):
        eng.step(ids, labels)
    save_distributed_checkpoint(eng, str(tmp_path))

    eng2 = _engine({"dp_degree": 2, "mp_degree": 4})
    load_distributed_checkpoint(eng2, str(tmp_path))
    for n in eng.params:
        np.testing.assert_allclose(np.asarray(eng.params[n]),
                                   np.asarray(eng2.params[n]), rtol=1e-6)
    assert eng2._step_count == eng._step_count
    # optimizer state restored too: next steps match exactly
    l1 = float(eng.step(ids, labels).item())
    l2 = float(eng2.step(ids, labels).item())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_reshard_across_layouts(tmp_path):
    """Save under dp2 x mp4, restore into dp4 x mp2: training continues
    identically (the converter merge+reslice path)."""
    eng = _engine({"dp_degree": 2, "mp_degree": 4})
    ids, labels = _batch()
    losses_a = [float(eng.step(ids, labels).item()) for _ in range(2)]
    save_distributed_checkpoint(eng, str(tmp_path))

    eng2 = _engine({"dp_degree": 4, "mp_degree": 2})
    load_distributed_checkpoint(eng2, str(tmp_path))
    for n in eng.params:
        np.testing.assert_allclose(np.asarray(eng.params[n]),
                                   np.asarray(eng2.params[n]), rtol=1e-6)
    l_a = float(eng.step(ids, labels).item())
    l_b = float(eng2.step(ids, labels).item())
    np.testing.assert_allclose(l_a, l_b, rtol=2e-3)


def test_manifest_merge_utils(tmp_path):
    eng = _engine({"dp_degree": 2, "mp_degree": 2, "sharding_degree": 2})
    save_distributed_checkpoint(eng, str(tmp_path))
    state = load_distributed_state(str(tmp_path))
    assert state["params"]
    name = next(iter(eng.params))
    np.testing.assert_allclose(state["params"][name],
                               np.asarray(eng.params[name]), rtol=1e-6)
    # every opt state component serialized
    comp0 = f"{name}.0"
    assert comp0 in state["opt"]


def test_converter_merge_slice():
    full_ref = np.arange(16, dtype=np.float32).reshape(4, 4)
    slices = [(full_ref[:2], [[0, 2], [0, 4]]), (full_ref[2:], [[2, 4], [0, 4]])]
    merged = Converter.merge_with_dist_attr(slices, [4, 4])
    np.testing.assert_array_equal(merged, full_ref)
    part = Converter.slice_with_dist_attr(merged, [[1, 3], [0, 2]])
    np.testing.assert_array_equal(part, full_ref[1:3, :2])

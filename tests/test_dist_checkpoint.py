"""Distributed checkpoint: shard-wise save + cross-layout restore
(reference auto_parallel dist_saver.py + converter.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import (
    Converter, load_distributed_checkpoint, load_distributed_state,
    save_distributed_checkpoint)
from paddle_tpu.models import GPTForPretraining, gpt_tiny


def _engine(degrees):
    paddle.seed(123)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=strategy)
    model = GPTForPretraining(gpt_tiny())
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    return fleet.distributed_engine(model, opt)


def _batch():
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int64)
    return paddle.to_tensor(ids), paddle.to_tensor(np.roll(ids, -1, 1))


def test_save_load_same_layout(tmp_path):
    eng = _engine({"dp_degree": 2, "mp_degree": 4})
    ids, labels = _batch()
    for _ in range(2):
        eng.step(ids, labels)
    save_distributed_checkpoint(eng, str(tmp_path))

    eng2 = _engine({"dp_degree": 2, "mp_degree": 4})
    load_distributed_checkpoint(eng2, str(tmp_path))
    for n in eng.params:
        np.testing.assert_allclose(np.asarray(eng.params[n]),
                                   np.asarray(eng2.params[n]), rtol=1e-6)
    assert eng2._step_count == eng._step_count
    # optimizer state restored too: next steps match exactly
    l1 = float(eng.step(ids, labels).item())
    l2 = float(eng2.step(ids, labels).item())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_reshard_across_layouts(tmp_path):
    """Save under dp2 x mp4, restore into dp4 x mp2: training continues
    identically (the converter merge+reslice path)."""
    eng = _engine({"dp_degree": 2, "mp_degree": 4})
    ids, labels = _batch()
    losses_a = [float(eng.step(ids, labels).item()) for _ in range(2)]
    save_distributed_checkpoint(eng, str(tmp_path))

    eng2 = _engine({"dp_degree": 4, "mp_degree": 2})
    load_distributed_checkpoint(eng2, str(tmp_path))
    for n in eng.params:
        np.testing.assert_allclose(np.asarray(eng.params[n]),
                                   np.asarray(eng2.params[n]), rtol=1e-6)
    l_a = float(eng.step(ids, labels).item())
    l_b = float(eng2.step(ids, labels).item())
    np.testing.assert_allclose(l_a, l_b, rtol=2e-3)


def test_manifest_merge_utils(tmp_path):
    eng = _engine({"dp_degree": 2, "mp_degree": 2, "sharding_degree": 2})
    save_distributed_checkpoint(eng, str(tmp_path))
    state = load_distributed_state(str(tmp_path))
    assert state["params"]
    name = next(iter(eng.params))
    np.testing.assert_allclose(state["params"][name],
                               np.asarray(eng.params[name]), rtol=1e-6)
    # every opt state component serialized
    comp0 = f"{name}.0"
    assert comp0 in state["opt"]


def _tiny_engine(dp=2, zero=False):
    import jax

    from paddle_tpu.distributed.engine import TrainStepEngine
    from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                             set_hybrid_communicate_group)

    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=dp, devices=jax.devices()[:dp])
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    # same microbatch count either way: k changes the gradient summation
    # order, and the zero-vs-replicated comparisons below are bit-exact
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           hcg=hcg, microbatches=2, zero_update=zero)


def _tiny_batch():
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(32, 8).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (32,)).astype(np.int64)))


def test_save_is_atomic_and_checksummed(tmp_path):
    """Every shard commits via temp-file + rename with a sha256 recorded in
    the manifest: no .tmp leftovers, and the digests verify."""
    import json
    import os

    from paddle_tpu.distributed.elastic import file_sha256

    eng = _tiny_engine()
    x, y = _tiny_batch()
    eng.step(x, y)
    save_distributed_checkpoint(eng, str(tmp_path))
    names = os.listdir(tmp_path)
    assert not [n for n in names if ".tmp." in n]
    with open(tmp_path / "manifest.rank0.json") as f:
        manifest = json.load(f)
    shards = [sh for kind in ("params", "opt")
              for ent in manifest[kind].values() for sh in ent["shards"]]
    assert shards and all(sh.get("checksum") for sh in shards)
    sh = shards[0]
    assert file_sha256(str(tmp_path / sh["file"])) == sh["checksum"]


def test_corrupted_shard_raises_on_load(tmp_path):
    from paddle_tpu.distributed.elastic import CheckpointCorrupt

    eng = _tiny_engine()
    eng.step(*_tiny_batch())
    save_distributed_checkpoint(eng, str(tmp_path))
    npy = sorted(p.name for p in tmp_path.glob("params__*.npy"))[0]
    with open(tmp_path / npy, "r+b") as f:
        f.seek(96)
        raw = f.read(4)
        f.seek(96)
        f.write(bytes(b ^ 0xFF for b in raw))
    with pytest.raises(CheckpointCorrupt, match="checksum mismatch"):
        load_distributed_state(str(tmp_path))


def test_zero_engine_roundtrips_via_dist_saver(tmp_path):
    """A ZeRO engine (opt_state=None, flat shards) saves through the legacy
    dict-form saver by gathering, and a ZeRO engine restores a dict
    checkpoint by lazy re-engagement — continuation matches a replicated
    engine restored from the same files bit for bit."""
    src = _tiny_engine(dp=4, zero=True)
    x, y = _tiny_batch()
    for _ in range(2):
        src.step(x, y)
    assert src.opt_state is None and src._zero_opt is not None
    save_distributed_checkpoint(src, str(tmp_path))

    ez = _tiny_engine(dp=4, zero=True)
    ez.step(x, y)  # engage, then restore must displace the flat state
    load_distributed_checkpoint(ez, str(tmp_path))
    assert ez.opt_state is not None and ez._zero_opt is None
    er = _tiny_engine(dp=4, zero=False)
    load_distributed_checkpoint(er, str(tmp_path))
    lz = [float(ez.step(x, y).item()) for _ in range(3)]
    lr = [float(er.step(x, y).item()) for _ in range(3)]
    assert lz == lr


def test_converter_merge_slice():
    full_ref = np.arange(16, dtype=np.float32).reshape(4, 4)
    slices = [(full_ref[:2], [[0, 2], [0, 4]]), (full_ref[2:], [[2, 4], [0, 4]])]
    merged = Converter.merge_with_dist_attr(slices, [4, 4])
    np.testing.assert_array_equal(merged, full_ref)
    part = Converter.slice_with_dist_attr(merged, [[1, 3], [0, 2]])
    np.testing.assert_array_equal(part, full_ref[1:3, :2])

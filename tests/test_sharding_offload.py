"""Sharding stage-2/3 offload + segment_size fidelity (VERDICT r1 item #5).

Reference: group_sharded_optimizer_stage2.py:48 (offload), and
group_sharded_stage3.py:80/:314 (segment_size keeps small params unsliced).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import set_hybrid_communicate_group
from paddle_tpu.distributed.meta_parallel.sharding import (
    GroupShardedOptimizerStage2, GroupShardedStage3, group_sharded_parallel)


def _fleet(confs, sharding=False):
    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.sharding = sharding
    strategy.hybrid_configs = confs
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _train(offload, steps=3):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    opt2 = GroupShardedOptimizerStage2(net.parameters(), opt, offload=offload)
    rs = np.random.RandomState(0)
    for _ in range(steps):
        x = paddle.to_tensor(rs.rand(4, 8).astype(np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
    return net, opt


def test_eager_offload_state_is_host_resident_and_numerically_identical():
    import jax

    net_off, opt_off = _train(offload=True)
    net_on, opt_on = _train(offload=False)
    # identical numerics
    for (n1, p1), (n2, p2) in zip(net_off.named_parameters(),
                                  net_on.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-6,
                                   err_msg=n1)
    # offloaded states are numpy (host RAM), non-offloaded are device arrays
    for _, st in opt_off._states.values():
        assert all(isinstance(s, np.ndarray) for s in st), type(st[0])
    for _, st in opt_on._states.values():
        assert all(isinstance(s, jax.Array) for s in st), type(st[0])
    # state_dict still round-trips from host state
    sd = opt_off.state_dict()
    assert any(k.startswith("param0_state") for k in sd)


def test_engine_offload_places_opt_state_in_host_memory():
    hcg = _fleet({"dp_degree": 4, "mp_degree": 2}, sharding=True)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    opt._offload = True
    engine = fleet.distributed_engine(net, opt,
                                      loss_fn=lambda out: (out ** 2).mean())
    rs = np.random.RandomState(0)
    losses = [float(engine.step(
        paddle.to_tensor(rs.rand(8, 8).astype(np.float32))).item())
        for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # host kind is backend-dependent: pinned_host on TPU/GPU and newer CPU
    # clients, unpinned_host on older CPU clients (core.jax_compat); the
    # offload-vs-resident distinction below is sharp wherever they differ
    from paddle_tpu.core.jax_compat import host_memory_kind

    host_kind = host_memory_kind()
    for n, st in engine.opt_state.items():
        for leaf in st:
            assert leaf.sharding.memory_kind == host_kind, (
                n, leaf.sharding)

    # parity vs the non-offloaded engine
    set_hybrid_communicate_group(None)
    hcg = _fleet({"dp_degree": 4, "mp_degree": 2}, sharding=True)
    paddle.seed(0)
    net2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt2 = paddle.optimizer.Adam(learning_rate=1e-2,
                                 parameters=net2.parameters())
    engine2 = fleet.distributed_engine(net2, opt2,
                                       loss_fn=lambda out: (out ** 2).mean())
    rs = np.random.RandomState(0)
    losses2 = [float(engine2.step(
        paddle.to_tensor(rs.rand(8, 8).astype(np.float32))).item())
        for _ in range(3)]
    np.testing.assert_allclose(losses, losses2, rtol=1e-5)
    import jax

    default_kind = jax.devices()[0].default_memory().kind
    for n, st in engine2.opt_state.items():
        for leaf in st:
            assert leaf.sharding.memory_kind in (None, default_kind)


def test_stage3_segment_size_keeps_small_params_whole():
    _fleet({"dp_degree": 1, "mp_degree": 1, "sharding_degree": 8},
           sharding=True)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 64),   # 4096 elems: sharded
                        nn.Linear(4, 4))     # 16 elems: stays whole
    GroupShardedStage3(net, segment_size=256)
    big = net[0].weight
    small = net[1].weight
    assert getattr(big, "dist_attr", None) is not None
    assert "sharding" in str(big.dist_attr)
    assert getattr(small, "dist_attr", None) is None
    # biases (64 and 4 elems) both under the 256 segment floor
    assert getattr(net[0].bias, "dist_attr", None) is None


def test_group_sharded_parallel_offload_plumbs_through():
    _fleet({"dp_degree": 1, "mp_degree": 1, "sharding_degree": 8},
           sharding=True)
    paddle.seed(0)
    net = nn.Linear(16, 16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    model, out_opt = group_sharded_parallel(net, opt, "p_g_os", offload=True,
                                            segment_size=8)
    assert opt._offload is True and opt._zero_stage == 3
    model2, out2 = group_sharded_parallel(nn.Linear(4, 4),
                                          paddle.optimizer.SGD(
                                              learning_rate=0.1,
                                              parameters=net.parameters()),
                                          "os_g", offload=True)
    assert out2._optim._offload is True

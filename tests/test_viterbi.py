"""viterbi_decode vs a brute-force all-paths numpy oracle.

Reference semantics: python/paddle/text/viterbi_decode.py + the op test's
decoder (python/paddle/fluid/tests/unittests/test_viterbi_decode_op.py:20).
Instead of mirroring that recurrence, the oracle enumerates every tag sequence,
which independently pins down the scoring convention:
  score(path) = sum_t emit[t, y_t] + sum_t trans[y_{t-1}, y_t]
                (+ trans[BOS, y_0] and + trans[EOS_row, y_last] with tags on).
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle


def brute_force(pot, trans, lengths, use_tag):
    bz, _, n = pot.shape
    scores, paths = [], []
    max_len = int(lengths.max())
    for b in range(bz):
        L = int(lengths[b])
        best, best_path = -np.inf, None
        for path in itertools.product(range(n), repeat=L):
            s = pot[b, 0, path[0]]
            if use_tag:
                s += trans[-1, path[0]]  # forced BOS start
            for t in range(1, L):
                s += pot[b, t, path[t]] + trans[path[t - 1], path[t]]
            if use_tag:
                s += trans[-2, path[-1]]  # EOS row added at the final step
            if s > best:
                best, best_path = s, path
        scores.append(best)
        paths.append(list(best_path) + [0] * (max_len - L))
    return np.array(scores), np.array(paths, np.int64)


@pytest.mark.parametrize("use_tag", [True, False])
def test_viterbi_matches_brute_force(use_tag):
    rng = np.random.RandomState(7)
    bz, T, n = 4, 5, 3
    pot = rng.randn(bz, T, n).astype(np.float32)
    trans = rng.randn(n, n).astype(np.float32)
    lengths = np.array([5, 3, 1, 4], np.int64)
    scores, paths = paddle.text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lengths), include_bos_eos_tag=use_tag)
    exp_scores, exp_paths = brute_force(pot, trans, lengths, use_tag)
    np.testing.assert_allclose(scores.numpy(), exp_scores, rtol=1e-5)
    np.testing.assert_array_equal(paths.numpy(), exp_paths)


def test_viterbi_forbidden_transitions_respect_forced_bos():
    # CRF constraint masking: trans[BOS, j] = -10000 forbids starting at j.
    # A soft BOS init (-1e4 penalty) would leak a non-BOS start here; the
    # exact init (reference phi viterbi_decode_kernel.cc:244) must not.
    n = 4
    pot = np.zeros((1, 3, n), np.float32)
    trans = np.full((n, n), 5.0, np.float32)
    trans[-1, :] = -10000.0  # BOS row: every start forbidden...
    trans[-1, 0] = 0.0       # ...except tag 0
    lengths = np.array([3], np.int64)
    scores, paths = paddle.text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lengths), include_bos_eos_tag=True)
    exp_scores, exp_paths = brute_force(pot, trans, lengths, True)
    np.testing.assert_allclose(scores.numpy(), exp_scores, rtol=1e-5)
    np.testing.assert_array_equal(paths.numpy(), exp_paths)
    assert paths.numpy()[0, 0] == 0  # must start at the only allowed tag


def test_viterbi_decoder_layer_and_jit():
    import jax

    rng = np.random.RandomState(0)
    pot = rng.randn(2, 4, 3).astype(np.float32)
    trans = rng.randn(3, 3).astype(np.float32)
    lengths = np.array([4, 2], np.int64)
    dec = paddle.text.ViterbiDecoder(paddle.to_tensor(trans))
    s_eager, p_eager = dec(paddle.to_tensor(pot), paddle.to_tensor(lengths))

    def fn(p, t, l):
        s, pa = paddle.text.viterbi_decode(p, t, l)
        return s._data, pa._data

    s_jit, p_jit = jax.jit(fn)(pot, trans, lengths)
    np.testing.assert_allclose(np.asarray(s_jit), s_eager.numpy(), rtol=1e-6)
    # traced path is padded to T; eager is trimmed to max(lengths)
    np.testing.assert_array_equal(
        np.asarray(p_jit)[:, :p_eager.shape[1]], p_eager.numpy())

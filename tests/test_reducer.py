"""Reducer: bucketed fused gradient allreduce (VERDICT r1 item #4).

Reference: paddle/fluid/imperative/reducer.cc / reducer.h:126 — collective
count must scale with total grad bytes / comm_buffer_size, not with the number
of parameters; find_unused_parameters keeps ranks in lockstep when a branch is
skipped.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.meta_parallel.data_parallel import Reducer



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

class _FakeGroup:
    nranks = 2


def _params(sizes, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    ps = []
    for i, s in enumerate(sizes):
        p = paddle.to_tensor(rng.rand(*s).astype(dtype))
        p.stop_gradient = False
        ps.append(p)
    return ps


def test_bucket_build_respects_caps_and_dtype():
    # 6 x 1MB f32 params with a 2MB cap -> 3 buckets before the last-cap split
    ps = _params([(256, 1024)] * 6)  # 1 MiB each
    ps_half = paddle.to_tensor(np.zeros((4,), np.float16))
    ps_half.stop_gradient = False
    red = Reducer(ps + [ps_half], group=_FakeGroup(), comm_buffer_size=2,
                  last_comm_buffer_size=1)
    sizes = [len(b) for b in red._buckets]
    # reverse order: f16 param (registered last) leads its own dtype bucket
    assert any(len(b) == 1 and str(b[0]._data.dtype) == "float16"
               for b in red._buckets)
    total = sum(sizes)
    assert total == 7
    # last bucket (front-of-model params) re-split to the 1MB last-cap
    assert all(len(b) <= 2 for b in red._buckets)


def test_reducer_fuses_on_virtual_mesh():
    """On the 8-device mesh the dp-group sync must produce the same result as
    per-param allreduce while issuing one collective per bucket."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    ps = _params([(4, 4), (16,), (2, 3)])
    for i, p in enumerate(ps):
        p.grad = paddle.to_tensor(np.full(p.shape, float(i + 1), np.float32))
    red = Reducer(ps, group=hcg.get_data_parallel_group())
    calls = red.sync()
    assert calls == 1  # tiny grads, one fused bucket
    # replicated grads: AVG over the dp axis is the identity
    for i, p in enumerate(ps):
        np.testing.assert_allclose(p.grad.numpy(), np.full(p.shape, i + 1.0),
                                   rtol=1e-6)


def test_find_unused_parameters_fills_zero_grads():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    ps = _params([(2, 2), (3,)])
    ps[0].grad = paddle.to_tensor(np.ones((2, 2), np.float32))
    # ps[1] unused: grad None
    red = Reducer(ps, group=hcg.get_data_parallel_group(),
                  find_unused_parameters=True)
    assert red.sync() == 1
    np.testing.assert_allclose(ps[0].grad.numpy(), np.ones((2, 2)))
    np.testing.assert_allclose(ps[1].grad.numpy(), np.zeros((3,)))

    # without the flag, the unused param is skipped and stays grad-less
    ps2 = _params([(2, 2), (3,)])
    ps2[0].grad = paddle.to_tensor(np.ones((2, 2), np.float32))
    red2 = Reducer(ps2, group=hcg.get_data_parallel_group())
    assert red2.sync() == 1
    assert ps2[1].grad is None


_TRAIN = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet, collective

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    rank = dist.get_rank()

    calls = [0]
    _real = collective.all_reduce
    def counting_all_reduce(*a, **k):
        calls[0] += 1
        return _real(*a, **k)
    collective.all_reduce = counting_all_reduce

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    dp = paddle.DataParallel(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())

    rs = np.random.RandomState(0)
    losses = []
    for step in range(3):
        xg = rs.rand(8, 8).astype(np.float32)          # same global batch
        xl = xg[rank * 4:(rank + 1) * 4]               # my dp shard
        loss = (dp(paddle.to_tensor(xl)) ** 2).mean()
        loss.backward()
        dp.sync_gradients()                            # fused bucketed sync
        opt.step(); opt.clear_grad()
        g = (dp(paddle.to_tensor(xg)) ** 2).mean()     # global-batch eval loss
        losses.append(float(g.item()))
    n_params = len(list(net.parameters()))
    assert calls[0] == 3, f"expected 1 fused collective/step, got {calls[0]}"
    assert calls[0] < 3 * n_params
    print("RANK", rank, "CALLS", calls[0], "LOSSES",
          ",".join(f"{v:.6f}" for v in losses), flush=True)
"""


def test_two_process_bucketed_dp_matches_single(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(_TRAIN))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])

    out = res.stdout
    for f in (tmp_path / "log").glob("*.log"):
        out += f.read_text()
    lines = {}
    for ln in out.splitlines():
        if ln.startswith("RANK"):
            parts = ln.split()
            lines[parts[1]] = parts[5]
    assert set(lines) == {"0", "1"}, out[-2000:]
    assert lines["0"] == lines["1"]  # both ranks converge identically

    # single-process oracle: full batch, no dp — same losses
    code = textwrap.dedent("""
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        rs = np.random.RandomState(0)
        losses = []
        for step in range(3):
            xg = rs.rand(8, 8).astype(np.float32)
            loss = (net(paddle.to_tensor(xg)) ** 2).mean()
            loss.backward(); opt.step(); opt.clear_grad()
            g = (net(paddle.to_tensor(xg)) ** 2).mean()
            losses.append(float(g.item()))
        print("SINGLE", ",".join(f"{v:.6f}" for v in losses))
    """)
    res1 = subprocess.run([sys.executable, "-c", code],
                          env={**os.environ,
                               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
                          capture_output=True, text=True, timeout=300)
    assert res1.returncode == 0, res1.stderr[-2000:]
    single = [ln for ln in res1.stdout.splitlines()
              if ln.startswith("SINGLE")][0].split()[1]
    dp_losses = [float(v) for v in lines["0"].split(",")]
    sp_losses = [float(v) for v in single.split(",")]
    np.testing.assert_allclose(dp_losses, sp_losses, rtol=2e-4)


_BCAST = """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.utils import broadcast_dp_parameters

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    rank = dist.get_rank()

    paddle.seed(100 + rank)               # DIVERGENT init per rank
    net = nn.Linear(4, 4)
    pre = float(np.abs(net.weight.numpy()).sum())
    broadcast_dp_parameters(net, hcg)     # multi-controller: really broadcasts
    post = float(np.abs(net.weight.numpy()).sum())
    print(f"RANK {rank} PRE {pre:.6f} POST {post:.6f}", flush=True)
"""


def test_two_process_broadcast_dp_parameters(tmp_path):
    """broadcast_dp_parameters must make divergent ranks agree (rank 0 wins)
    in multi-controller mode — it was a silent `pass` in round 1."""
    script = tmp_path / "bcast.py"
    script.write_text(textwrap.dedent(_BCAST))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    out = res.stdout
    for f in (tmp_path / "log").glob("*.log"):
        out += f.read_text()
    rows = {}
    for ln in out.splitlines():
        if ln.startswith("RANK"):
            parts = ln.split()
            rows[parts[1]] = (parts[3], parts[5])
    assert set(rows) == {"0", "1"}, out[-1500:]
    assert rows["0"][0] != rows["1"][0]      # inits diverged
    assert rows["0"][1] == rows["1"][1]      # broadcast converged them
    assert rows["0"][0] == rows["0"][1]      # rank 0 is the source

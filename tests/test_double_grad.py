"""Double backward: paddle.grad(create_graph=True) on the eager tape.

Reference: egr::RunBackward's create_graph path (eager/backward.cc) powering
gradient-penalty training (WGAN-GP style). Here the backward replays through
the dispatcher using each node's pure recompute-backward (dispatch rule
cache), so first-order grads carry a tape of grad::<op> nodes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import grad


def test_second_derivative_of_cubic():
    x = paddle.to_tensor(np.array([1.0, 2.0, -3.0], np.float32),
                         stop_gradient=False)
    y = (x ** 3).sum()
    (g,) = grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g.numpy(), 3 * x.numpy() ** 2, rtol=1e-6)
    assert not g.stop_gradient  # carries the tape

    (gg,) = grad(g.sum(), [x])
    np.testing.assert_allclose(gg.numpy(), 6 * x.numpy(), rtol=1e-6)


def test_mixed_partials_matmul():
    rng = np.random.RandomState(0)
    xn = rng.randn(3, 4).astype(np.float32)
    wn = rng.randn(4, 2).astype(np.float32)
    x = paddle.to_tensor(xn, stop_gradient=False)
    w = paddle.to_tensor(wn, stop_gradient=False)

    y = (paddle.matmul(x, w) ** 2).sum()
    (gx,) = grad(y, [x], create_graph=True)
    # d/dw of sum(gx) — mixed second-order partial
    (gw,) = grad(gx.sum(), [w])

    def jax_ref(xn, wn):
        f = lambda x, w: ((x @ w) ** 2).sum()
        gx_fn = jax.grad(f, argnums=0)
        return jax.grad(lambda w: gx_fn(jnp.asarray(xn), w).sum())(jnp.asarray(wn))

    np.testing.assert_allclose(gw.numpy(), np.asarray(jax_ref(xn, wn)),
                               rtol=1e-5, atol=1e-5)


def test_gradient_penalty_through_backward():
    """WGAN-GP shape: penalty on the input-grad norm, optimized via .backward()."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 1))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(5, 4).astype(np.float32), stop_gradient=False)

    out = net(x).sum()
    (gx,) = grad(out, [x], create_graph=True)
    gp = ((gx.square().sum(axis=1).sqrt() - 1.0) ** 2).mean()
    gp.backward()  # second-order: reaches the net's weights

    w0 = net[0].weight
    assert w0.grad is not None
    assert np.isfinite(w0.grad.numpy()).all()
    assert np.abs(w0.grad.numpy()).max() > 0

    # numeric check of d(gp)/d(w0[0,0])
    eps = 1e-3

    def gp_value():
        x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
        out = net(x2).sum()
        (g2,) = grad(out, [x2], create_graph=True)
        return float(((g2.square().sum(axis=1).sqrt() - 1.0) ** 2).mean().item())

    base = w0.numpy().copy()
    w0._data = jnp.asarray(base).at[0, 0].add(eps)
    hi = gp_value()
    w0._data = jnp.asarray(base).at[0, 0].add(-eps)
    lo = gp_value()
    w0._data = jnp.asarray(base)
    numeric = (hi - lo) / (2 * eps)
    np.testing.assert_allclose(w0.grad.numpy()[0, 0], numeric, rtol=5e-2,
                               atol=5e-4)


def test_create_graph_needs_rule_cache():
    paddle.set_flags({"eager_op_jit": False})
    try:
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        y = (x ** 2).sum()
        with pytest.raises(NotImplementedError, match="pure backward rule"):
            grad(y, [x], create_graph=True)
    finally:
        paddle.set_flags({"eager_op_jit": True})


def test_plain_grad_unchanged():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    (g,) = grad((x ** 2).sum(), [x])
    assert g.stop_gradient
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0])


def test_freed_graph_raises_in_create_graph_mode():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = (x ** 2).sum()
    grad(y, [x])  # frees the graph (retain_graph defaults False)
    with pytest.raises(RuntimeError, match="second time"):
        grad(y, [x], create_graph=True)


def test_amp_does_not_recast_grad_ops():
    """Black-listed ops' second-order backward must stay f32 under amp O2."""
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32),
                         stop_gradient=False)
    with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
        y = (paddle.nn.functional.softmax(x, axis=-1) ** 2).sum()
        (g,) = grad(y, [x], create_graph=True)
        (gg,) = grad(g.sum(), [x])
    assert gg.numpy().dtype == np.float32
    assert np.isfinite(gg.numpy()).all()

"""Pipeline parallelism tests on the 8-device virtual CPU mesh.

Covers: spmd_pipeline parity vs sequential execution (fwd + grads), the pipelined GPT
through the pjit engine (pp x dp), and the eager PipelineParallel facade's grad
accumulation equivalence (the 1F1B numerics contract)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import (
    HybridCommunicateGroup, set_hybrid_communicate_group,
)
from paddle_tpu.distributed.pipeline_schedule import (
    microbatch_merge, microbatch_split, spmd_pipeline,
)


@pytest.fixture(autouse=True)
def reset_hcg():
    yield
    set_hybrid_communicate_group(None)


def _body(lp, x):
    # one "stage": y = tanh(x @ w + b), params stacked [Lp, ...] -> scan
    def one(h, layer):
        return jnp.tanh(h @ layer["w"] + layer["b"]), None

    y, _ = jax.lax.scan(one, x, lp)
    return y


def _sequential(params, x_mb):
    merged = jax.tree.map(
        lambda l: l.reshape((l.shape[0] * l.shape[1],) + l.shape[2:]), params)
    return jax.vmap(lambda x: _body(merged, x))(x_mb)


def test_spmd_pipeline_matches_sequential():
    S, Lp, M, mb, d = 4, 2, 8, 2, 16
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(S, Lp, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, Lp, d).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    hcg = HybridCommunicateGroup(dp_degree=2, pp_degree=4)
    out = jax.jit(lambda p, x: spmd_pipeline(_body, p, x, hcg.mesh, "pp"))(params, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_grads_match_sequential():
    S, Lp, M, mb, d = 2, 1, 4, 2, 8
    rng = np.random.RandomState(1)
    params = {
        "w": jnp.asarray(rng.randn(S, Lp, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, Lp, d).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    hcg = HybridCommunicateGroup(dp_degree=1, pp_degree=2)

    def loss_pipe(p):
        return jnp.sum(spmd_pipeline(_body, p, x, hcg.mesh, "pp") ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_gpt_pipe_engine_step():
    from paddle_tpu.models import GPTForPretrainingPipe, gpt_tiny

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.degrees["pp"] == 2

    cfg = gpt_tiny()
    model = GPTForPretrainingPipe(cfg, num_microbatches=4)
    # eager (sequential-fallback) reference loss with the same params
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64))
    labels = paddle.to_tensor(np.roll(np.asarray(ids.numpy()), -1, 1))
    eager_loss = float(model(ids, labels).item())

    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)
    loss = engine.step(ids, labels)
    v = float(loss.item())
    assert np.isfinite(v)
    # engine step computes the loss with the initial params -> must match eager
    np.testing.assert_allclose(v, eager_loss, rtol=2e-4, atol=2e-4)
    # second step must decrease the loss on this overfit-able batch
    v2 = float(engine.step(ids, labels).item())
    assert np.isfinite(v2) and v2 < v


def test_pipeline_parallel_facade_grad_accum():
    from paddle_tpu.distributed.meta_parallel import (
        LayerDesc, PipelineLayer, PipelineParallel,
    )

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.pipeline_configs.accumulate_steps = 4

    def make_model():
        paddle.seed(7)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.ReLU),
                    LayerDesc(nn.Linear, 16, 4)],
            num_stages=1,
            loss_fn=nn.CrossEntropyLoss(),
        )

    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (8, 1)).astype(np.int64))

    # accumulated micro-batch path
    m1 = make_model()
    pp = PipelineParallel(m1, strategy=strategy)
    opt1 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    loss_pp = pp.train_batch((x, y), opt1)

    # single big-batch reference
    m2 = make_model()
    opt2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
    out = m2(x)
    loss_ref = m2.loss(out, y)
    loss_ref.backward()
    opt2.step()
    opt2.clear_grad()

    np.testing.assert_allclose(float(loss_pp.item()), float(loss_ref.item()),
                               rtol=1e-5, atol=1e-6)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-5, atol=1e-6)


# ---- round 4: interleaved (virtual-stage) scheduler (VERDICT r3 missing #4)
def test_interleaved_schedule_beats_stacking():
    """The static circular schedule must realize the bubble win: total
    ticks < the sequential-stacking baseline V*(M+P-1), and every
    microbatch emitted exactly once. At even V it hits the streaming
    optimum M*V + P - 1."""
    from paddle_tpu.distributed.pipeline_schedule import _interleaved_schedule

    for P_, V, M in [(2, 2, 4), (4, 2, 8), (2, 4, 4), (4, 4, 8)]:
        sched, T, slots = _interleaved_schedule(P_, V, M)
        assert T == M * V + P_ - 1, (P_, V, M, T)
        assert T < V * (M + P_ - 1)
        emitted = sorted(x for x in sched["out_write"].flatten() if x >= 0)
        assert emitted == list(range(M))
        assert slots <= P_  # bounded activation buffering


@pytest.mark.parametrize("P_,V", [(4, 2), (2, 4)])
def test_interleaved_pipeline_matches_logical_stage_composition(P_, V):
    """spmd_pipeline_interleaved == running the V*P logical stages in
    sequence — forward AND gradients (AD replays the mirrored schedule).
    (2, 4) is the deep-interleave shape the driver dryrun certifies at
    8 devices: backward-through-the-buffered-schedule at V > 2."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.distributed.pipeline_schedule import \
        spmd_pipeline_interleaved

    M, D = 8, 16
    mesh = Mesh(np.array(jax.devices()[:P_]), ("pp",))
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(V, P_, D, D).astype("float32")) * 0.3,
              "b": jnp.asarray(rng.randn(V, P_, D).astype("float32")) * 0.1}
    x = jnp.asarray(rng.randn(M, 4, D).astype("float32"))

    def body(p, xb):
        return jnp.tanh(xb @ p["w"] + p["b"])

    def ref_fwd(params, x):
        h = x
        for s in range(V * P_):
            v, r = s // P_, s % P_
            h = jax.vmap(lambda xb, v=v, r=r: body(
                {"w": params["w"][v, r], "b": params["b"][v, r]}, xb))(h)
        return h

    got = spmd_pipeline_interleaved(body, params, x, mesh, "pp", V)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_fwd(params, x)),
                               rtol=2e-6, atol=1e-6)

    g1 = jax.grad(lambda p: (spmd_pipeline_interleaved(
        body, p, x, mesh, "pp", V) ** 2).sum())(params)
    g2 = jax.grad(lambda p: (ref_fwd(p, x) ** 2).sum())(params)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g1["b"]), np.asarray(g2["b"]),
                               rtol=1e-4, atol=1e-5)


def test_gpt_pipe_interleaved_trains_identically():
    """GPTForPretrainingPipe(num_virtual_stages=2) under dp x pp x mp must
    produce the same losses as the single-chunk pipeline (identical init
    and math, only the schedule differs)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group
    from paddle_tpu.models import GPTConfig, GPTForPretrainingPipe

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attention_dropout=0.0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 64)).astype(np.int64)
    lab = np.roll(ids, -1, 1)

    def train(virtual):
        set_hybrid_communicate_group(None)
        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "pp_degree": 2,
                                   "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForPretrainingPipe(cfg, num_microbatches=4,
                                  num_virtual_stages=virtual)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        eng = fleet.distributed_engine(m, opt)
        return [float(eng.step(paddle.to_tensor(ids),
                               paddle.to_tensor(lab)).item())
                for _ in range(3)]

    plain, inter = train(1), train(2)
    np.testing.assert_allclose(inter, plain, rtol=1e-5)
    assert inter[-1] < inter[0]


def test_interleaved_pipe_untied_head_and_pp1_degenerate():
    """round-4 review regressions: (a) the V-prepend must not malform the
    non-stage lm_head_w under tie_word_embeddings=False; (b) pp degree 1
    with virtual stages degrades to a sequential chunk scan, not a squeeze
    crash."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group
    from paddle_tpu.models import GPTConfig, GPTForPretrainingPipe

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attention_dropout=0.0, tie_word_embeddings=False)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (4, 64)).astype(np.int64)
    lab = np.roll(ids, -1, 1)

    # (a) untied head under pp2 x interleave
    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    m = GPTForPretrainingPipe(cfg, num_microbatches=4, num_virtual_stages=2)
    assert tuple(m.lm_head_w.shape) == (64, 256), m.lm_head_w.shape
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    eng = fleet.distributed_engine(m, opt)
    v = float(eng.step(paddle.to_tensor(ids), paddle.to_tensor(lab)).item())
    assert np.isfinite(v)

    # (b) pp degree 1 + virtual stages: sequential chunk scan
    set_hybrid_communicate_group(None)
    strategy2 = dist.DistributedStrategy()
    strategy2.hybrid_configs = {"dp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy2)
    paddle.seed(0)
    m2 = GPTForPretrainingPipe(cfg, num_stages=1, num_microbatches=2,
                               num_virtual_stages=2)
    out = m2(paddle.to_tensor(ids), paddle.to_tensor(lab))
    assert np.isfinite(float(out.item()))

"""Pipeline parallelism tests on the 8-device virtual CPU mesh.

Covers: spmd_pipeline parity vs sequential execution (fwd + grads), the pipelined GPT
through the pjit engine (pp x dp), and the eager PipelineParallel facade's grad
accumulation equivalence (the 1F1B numerics contract)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import (
    HybridCommunicateGroup, set_hybrid_communicate_group,
)
from paddle_tpu.distributed.pipeline_schedule import (
    microbatch_merge, microbatch_split, spmd_pipeline,
)


@pytest.fixture(autouse=True)
def reset_hcg():
    yield
    set_hybrid_communicate_group(None)


def _body(lp, x):
    # one "stage": y = tanh(x @ w + b), params stacked [Lp, ...] -> scan
    def one(h, layer):
        return jnp.tanh(h @ layer["w"] + layer["b"]), None

    y, _ = jax.lax.scan(one, x, lp)
    return y


def _sequential(params, x_mb):
    merged = jax.tree.map(
        lambda l: l.reshape((l.shape[0] * l.shape[1],) + l.shape[2:]), params)
    return jax.vmap(lambda x: _body(merged, x))(x_mb)


def test_spmd_pipeline_matches_sequential():
    S, Lp, M, mb, d = 4, 2, 8, 2, 16
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(S, Lp, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, Lp, d).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    hcg = HybridCommunicateGroup(dp_degree=2, pp_degree=4)
    out = jax.jit(lambda p, x: spmd_pipeline(_body, p, x, hcg.mesh, "pp"))(params, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_grads_match_sequential():
    S, Lp, M, mb, d = 2, 1, 4, 2, 8
    rng = np.random.RandomState(1)
    params = {
        "w": jnp.asarray(rng.randn(S, Lp, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, Lp, d).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    hcg = HybridCommunicateGroup(dp_degree=1, pp_degree=2)

    def loss_pipe(p):
        return jnp.sum(spmd_pipeline(_body, p, x, hcg.mesh, "pp") ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_gpt_pipe_engine_step():
    from paddle_tpu.models import GPTForPretrainingPipe, gpt_tiny

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.degrees["pp"] == 2

    cfg = gpt_tiny()
    model = GPTForPretrainingPipe(cfg, num_microbatches=4)
    # eager (sequential-fallback) reference loss with the same params
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64))
    labels = paddle.to_tensor(np.roll(np.asarray(ids.numpy()), -1, 1))
    eager_loss = float(model(ids, labels).item())

    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)
    loss = engine.step(ids, labels)
    v = float(loss.item())
    assert np.isfinite(v)
    # engine step computes the loss with the initial params -> must match eager
    np.testing.assert_allclose(v, eager_loss, rtol=2e-4, atol=2e-4)
    # second step must decrease the loss on this overfit-able batch
    v2 = float(engine.step(ids, labels).item())
    assert np.isfinite(v2) and v2 < v


def test_pipeline_parallel_facade_grad_accum():
    from paddle_tpu.distributed.meta_parallel import (
        LayerDesc, PipelineLayer, PipelineParallel,
    )

    paddle.seed(0)
    strategy = dist.DistributedStrategy()
    strategy.pipeline_configs.accumulate_steps = 4

    def make_model():
        paddle.seed(7)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.ReLU),
                    LayerDesc(nn.Linear, 16, 4)],
            num_stages=1,
            loss_fn=nn.CrossEntropyLoss(),
        )

    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (8, 1)).astype(np.int64))

    # accumulated micro-batch path
    m1 = make_model()
    pp = PipelineParallel(m1, strategy=strategy)
    opt1 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    loss_pp = pp.train_batch((x, y), opt1)

    # single big-batch reference
    m2 = make_model()
    opt2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
    out = m2(x)
    loss_ref = m2.loss(out, y)
    loss_ref.backward()
    opt2.step()
    opt2.clear_grad()

    np.testing.assert_allclose(float(loss_pp.item()), float(loss_ref.item()),
                               rtol=1e-5, atol=1e-6)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-5, atol=1e-6)

"""RNN family tests: cells vs numpy references, scan vs eager-loop parity,
sequence-length masking semantics (reference fluid/layers/rnn.py:517 _maybe_copy),
multi-layer/bidirectional stacks, and gradient flow through the fused scan."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = np.split(z, 4, axis=-1)
    i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
    nc = f * c + i * np.tanh(g)
    nh = o * np.tanh(nc)
    return nh, nc


def _np_gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xg = x @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    x_r, x_z, x_c = np.split(xg, 3, axis=-1)
    h_r, h_z, h_c = np.split(hg, 3, axis=-1)
    r = _sigmoid(x_r + h_r)
    z = _sigmoid(x_z + h_z)
    c = np.tanh(x_c + r * h_c)
    return (h - c) * z + c


class TestCells:
    def test_simple_rnn_cell(self):
        paddle.seed(0)
        cell = nn.SimpleRNNCell(4, 8)
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        h0 = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        out, h = cell(paddle.to_tensor(x), paddle.to_tensor(h0))
        ref = np.tanh(x @ cell.weight_ih.numpy().T + cell.bias_ih.numpy()
                      + h0 @ cell.weight_hh.numpy().T + cell.bias_hh.numpy())
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h.numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_lstm_cell(self):
        paddle.seed(0)
        cell = nn.LSTMCell(4, 8)
        rs = np.random.RandomState(0)
        x, h0, c0 = (rs.randn(2, 4).astype(np.float32),
                     rs.randn(2, 8).astype(np.float32),
                     rs.randn(2, 8).astype(np.float32))
        out, (h, c) = cell(paddle.to_tensor(x),
                           (paddle.to_tensor(h0), paddle.to_tensor(c0)))
        rh, rc = _np_lstm_step(x, h0, c0, cell.weight_ih.numpy(),
                               cell.weight_hh.numpy(), cell.bias_ih.numpy(),
                               cell.bias_hh.numpy())
        np.testing.assert_allclose(out.numpy(), rh, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(c.numpy(), rc, rtol=1e-5, atol=1e-6)

    def test_gru_cell(self):
        paddle.seed(0)
        cell = nn.GRUCell(4, 8)
        rs = np.random.RandomState(0)
        x, h0 = rs.randn(2, 4).astype(np.float32), rs.randn(2, 8).astype(np.float32)
        out, h = cell(paddle.to_tensor(x), paddle.to_tensor(h0))
        ref = _np_gru_step(x, h0, cell.weight_ih.numpy(), cell.weight_hh.numpy(),
                           cell.bias_ih.numpy(), cell.bias_hh.numpy())
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_cell_default_states(self):
        cell = nn.LSTMCell(4, 8)
        x = paddle.to_tensor(np.zeros((3, 4), np.float32))
        out, (h, c) = cell(x)
        assert out.shape == [3, 8] and c.shape == [3, 8]

    def test_no_bias(self):
        cell = nn.GRUCell(4, 8, bias_ih_attr=False, bias_hh_attr=False)
        assert cell.bias_ih is None
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        out, _ = cell(x)
        assert out.shape == [2, 8]


class TestRNNWrapper:
    def test_rnn_matches_manual_loop(self):
        paddle.seed(0)
        cell = nn.LSTMCell(4, 8)
        rnn = nn.RNN(cell)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 5, 4).astype(np.float32)
        out, (h, c) = rnn(paddle.to_tensor(x))
        # manual numpy loop
        nh = np.zeros((2, 8), np.float32)
        nc = np.zeros((2, 8), np.float32)
        w_ih, w_hh = cell.weight_ih.numpy(), cell.weight_hh.numpy()
        b_ih, b_hh = cell.bias_ih.numpy(), cell.bias_hh.numpy()
        refs = []
        for t in range(5):
            nh, nc = _np_lstm_step(x[:, t], nh, nc, w_ih, w_hh, b_ih, b_hh)
            refs.append(nh)
        np.testing.assert_allclose(out.numpy(), np.stack(refs, 1), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(h.numpy(), nh, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), nc, rtol=1e-4, atol=1e-5)

    def test_reverse(self):
        paddle.seed(0)
        cell = nn.GRUCell(4, 8)
        rnn_rev = nn.RNN(cell, is_reverse=True)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 5, 4).astype(np.float32)
        out, h = rnn_rev(paddle.to_tensor(x))
        # reverse == forward RNN on time-flipped input, output flipped back
        rnn_fwd = nn.RNN(cell)
        out2, h2 = rnn_fwd(paddle.to_tensor(x[:, ::-1].copy()))
        np.testing.assert_allclose(out.numpy(), out2.numpy()[:, ::-1], rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(h.numpy(), h2.numpy(), rtol=1e-5, atol=1e-6)

    def test_time_major(self):
        paddle.seed(0)
        cell = nn.SimpleRNNCell(4, 8)
        rs = np.random.RandomState(0)
        x = rs.randn(5, 2, 4).astype(np.float32)  # [T, N, I]
        out_tm, h_tm = nn.RNN(cell, time_major=True)(paddle.to_tensor(x))
        out_bm, h_bm = nn.RNN(cell)(paddle.to_tensor(x.transpose(1, 0, 2).copy()))
        assert out_tm.shape == [5, 2, 8]
        np.testing.assert_allclose(out_tm.numpy(),
                                   out_bm.numpy().transpose(1, 0, 2), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(h_tm.numpy(), h_bm.numpy(), rtol=1e-5, atol=1e-6)

    def test_sequence_length_masking(self):
        """States freeze past each row's length (reference _maybe_copy semantics)."""
        paddle.seed(0)
        cell = nn.GRUCell(3, 6)
        rnn = nn.RNN(cell)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 5, 3).astype(np.float32)
        lens = np.array([3, 5], np.int64)
        out, h = rnn(paddle.to_tensor(x), sequence_length=paddle.to_tensor(lens))
        # row 0's final state equals running only 3 steps
        out3, h3 = rnn(paddle.to_tensor(x[:1, :3]))
        np.testing.assert_allclose(h.numpy()[0], h3.numpy()[0], rtol=1e-5, atol=1e-6)
        # row 1 runs the full 5 steps
        out5, h5 = rnn(paddle.to_tensor(x[1:2]))
        np.testing.assert_allclose(h.numpy()[1], h5.numpy()[0], rtol=1e-5, atol=1e-6)

    def test_custom_cell_eager_path(self):
        """A user-defined cell exercises the generic per-step loop."""

        class Decay(nn.RNNCellBase):
            def __init__(self):
                super().__init__()
                self.alpha = self.create_parameter((1,), default_initializer=None)

            @property
            def state_shape(self):
                return (2,)

            def forward(self, inputs, states=None):
                if states is None:
                    states = self.get_initial_states(inputs)
                h = states * 0.5 + inputs
                return h, h

        cell = Decay()
        cell.alpha.set_value(np.ones((1,), np.float32))
        x = np.ones((1, 3, 2), np.float32)
        out, h = nn.RNN(cell)(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy()[0, :, 0], [1.0, 1.5, 1.75], rtol=1e-6)

    def test_grad_flows_through_scan(self):
        paddle.seed(0)
        cell = nn.LSTMCell(4, 8)
        rnn = nn.RNN(cell)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 5, 4).astype(np.float32))
        x.stop_gradient = False
        out, _ = rnn(x)
        out.sum().backward()
        assert cell.weight_ih.grad is not None
        assert float(np.abs(cell.weight_ih.grad.numpy()).sum()) > 0
        assert x.grad is not None and x.grad.shape == [2, 5, 4]


class TestBiRNN:
    def test_birnn_shapes_and_parity(self):
        paddle.seed(0)
        cf, cb = nn.GRUCell(4, 8), nn.GRUCell(4, 8)
        bi = nn.BiRNN(cf, cb)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 5, 4).astype(np.float32)
        out, (hf, hb) = bi(paddle.to_tensor(x))
        assert out.shape == [2, 5, 16]
        of, hf2 = nn.RNN(cf)(paddle.to_tensor(x))
        ob, hb2 = nn.RNN(cb, is_reverse=True)(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy()[..., :8], of.numpy(), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(out.numpy()[..., 8:], ob.numpy(), rtol=1e-5,
                                   atol=1e-6)


class TestStacks:
    @pytest.mark.parametrize("cls,comp", [(nn.SimpleRNN, 1), (nn.LSTM, 2),
                                          (nn.GRU, 1)])
    def test_shapes_forward(self, cls, comp):
        paddle.seed(0)
        m = cls(10, 16, num_layers=2)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 7, 10).astype(np.float32))
        out, st = m(x)
        assert out.shape == [4, 7, 16]
        if comp == 2:
            h, c = st
            assert h.shape == [2, 4, 16] and c.shape == [2, 4, 16]
        else:
            assert st.shape == [2, 4, 16]

    @pytest.mark.parametrize("cls,comp", [(nn.LSTM, 2), (nn.GRU, 1)])
    def test_shapes_bidirectional(self, cls, comp):
        paddle.seed(0)
        m = cls(10, 16, num_layers=2, direction="bidirect")
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 7, 10).astype(np.float32))
        out, st = m(x)
        assert out.shape == [4, 7, 32]
        h = st[0] if comp == 2 else st
        assert h.shape == [4, 4, 16]  # L*D = 4

    def test_initial_state_roundtrip(self):
        """Final states of a run feed back in as initial states consistently."""
        paddle.seed(0)
        m = nn.LSTM(4, 8, num_layers=2)
        rs = np.random.RandomState(0)
        x1 = paddle.to_tensor(rs.randn(2, 3, 4).astype(np.float32))
        x2 = paddle.to_tensor(rs.randn(2, 3, 4).astype(np.float32))
        _, st1 = m(x1)
        out_chained, _ = m(x2, st1)
        # same as running 6 steps at once
        x12 = paddle.to_tensor(np.concatenate([x1.numpy(), x2.numpy()], axis=1))
        out_full, _ = m(x12)
        np.testing.assert_allclose(out_chained.numpy(), out_full.numpy()[:, 3:],
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_matches_torch(self):
        """Cross-check the full stacked bidirectional LSTM against torch CPU."""
        torch = pytest.importorskip("torch")
        paddle.seed(0)
        m = nn.LSTM(5, 7, num_layers=2, direction="bidirect")
        tm = torch.nn.LSTM(5, 7, num_layers=2, bidirectional=True, batch_first=True)
        # copy paddle params into torch (same gate order i,f,g,o)
        with torch.no_grad():
            for layer in range(2):
                pl = m._all_layers[layer]
                for d, cell in enumerate([pl.cell_fw, pl.cell_bw]):
                    sfx = "_reverse" if d else ""
                    getattr(tm, f"weight_ih_l{layer}{sfx}").copy_(
                        torch.tensor(cell.weight_ih.numpy()))
                    getattr(tm, f"weight_hh_l{layer}{sfx}").copy_(
                        torch.tensor(cell.weight_hh.numpy()))
                    getattr(tm, f"bias_ih_l{layer}{sfx}").copy_(
                        torch.tensor(cell.bias_ih.numpy()))
                    getattr(tm, f"bias_hh_l{layer}{sfx}").copy_(
                        torch.tensor(cell.bias_hh.numpy()))
        x = np.random.RandomState(0).randn(3, 6, 5).astype(np.float32)
        out, (h, c) = m(paddle.to_tensor(x))
        tout, (th, tc) = tm(torch.tensor(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_dropout_between_layers(self):
        paddle.seed(0)
        m = nn.GRU(4, 8, num_layers=2, dropout=0.5)
        x = paddle.to_tensor(np.ones((2, 5, 4), np.float32))
        m.train()
        o1, _ = m(x)
        o2, _ = m(x)
        assert not np.allclose(o1.numpy(), o2.numpy())  # dropout active
        m.eval()
        o3, _ = m(x)
        o4, _ = m(x)
        np.testing.assert_allclose(o3.numpy(), o4.numpy())

    def test_train_copy_task(self):
        """A 1-layer GRU learns to output the first input token (sanity e2e)."""
        paddle.seed(0)
        m = nn.GRU(2, 16)
        head = nn.Linear(16, 2)
        params = list(m.parameters()) + list(head.parameters())
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=params)
        rs = np.random.RandomState(0)
        losses = []
        for step in range(60):
            x = rs.randn(8, 4, 2).astype(np.float32)
            xt = paddle.to_tensor(x)
            out, h = m(xt)
            pred = head(out[:, -1])
            loss = ((pred - xt[:, 0]) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


class TestSequenceMaskFunctional:
    def test_sequence_mask(self):
        import paddle_tpu.nn.functional as F

        m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], np.int64)), maxlen=4)
        np.testing.assert_array_equal(m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_diag_embed(self):
        import paddle_tpu.nn.functional as F

        x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        out = F.diag_embed(x)
        assert out.shape == [2, 2, 2]
        np.testing.assert_allclose(out.numpy()[0], np.diag([1.0, 2.0]))
        out_off = F.diag_embed(x, offset=1)
        assert out_off.shape == [2, 3, 3]
        np.testing.assert_allclose(out_off.numpy()[1],
                                   np.diag([3.0, 4.0], k=1))

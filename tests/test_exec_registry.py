"""Unified executable registry (core/exec_registry.py) + AOT warm start.

Four claims (ISSUE 18):
1. Keys are honest: any shape/dtype/mesh/flag variation is a distinct
   entry; the same key is a hit that rebuilds nothing.
2. LRU eviction never touches pinned entries — the serving engine pins
   every active executable, so FLAGS_decode_jit_cache_size=1 yields
   eviction REFUSALS, not a recompile storm (the latent hazard the
   registry migration fixed).
3. A precompiled engine serves token-identical output with ZERO dispatch
   compiles — the AOT fast path is the same executable the lazy path
   would have built.
4. The AOT bundle round-trips across processes: a fresh replica loading
   the bundle joins with engine.compile_cold == 0 while compile_warm
   grew (both-flat would just mean the cache was off) and serves
   bit-identical tokens. Multi-device CPU is probe-gated, not trusted.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.core.exec_registry import ExecutableRegistry  # noqa: E402


def _mk(tag, log=None):
    def build():
        if log is not None:
            log.append(tag)
        return lambda *a: tag
    return build


# ---- claim 1: key uniqueness -------------------------------------------


def test_key_uniqueness_across_shape_mesh_flag_variants():
    reg = ExecutableRegistry(name="t")
    built = []
    keys = [
        ("prog", (4, 8), "f32", ("dp", 2), False),
        ("prog", (4, 16), "f32", ("dp", 2), False),   # shape
        ("prog", (4, 8), "bf16", ("dp", 2), False),   # dtype
        ("prog", (4, 8), "f32", ("dp", 4), False),    # mesh degree
        ("prog", (4, 8), "f32", ("tp", 2), False),    # mesh axis
        ("prog", (4, 8), "f32", ("dp", 2), True),     # flag
        ("prog2", (4, 8), "f32", ("dp", 2), False),   # program id
    ]
    entries = [reg.get_or_build(k, _mk(i, built)) for i, k in enumerate(keys)]
    assert len(reg) == len(keys)
    assert len({id(e) for e in entries}) == len(keys)
    assert built == list(range(len(keys)))
    assert reg.misses == len(keys) and reg.hits == 0

    again = reg.get_or_build(keys[0], _mk("never", built))
    assert again is entries[0]
    assert reg.hits == 1 and built == list(range(len(keys)))  # no rebuild


def test_prefix_count_and_discard():
    reg = ExecutableRegistry(name="t")
    reg.get_or_build(("serve.prefill", 8), _mk(1))
    reg.get_or_build(("serve.prefill", 16), _mk(2))
    reg.get_or_build(("serve.decode", "greedy"), _mk(3))
    assert reg.count("serve.prefill") == 2
    assert reg.count("serve.decode") == 1
    reg.discard("serve.prefill")
    assert reg.count("serve.prefill") == 0 and len(reg) == 1
    assert reg.evictions == 0  # discard is invalidation, not LRU pressure


# ---- claim 2: LRU + pinned-entry semantics ------------------------------


def test_lru_evicts_oldest_unpinned_only():
    reg = ExecutableRegistry(name="t", capacity=2)
    reg.get_or_build(("a",), _mk(1), pin=True)
    reg.get_or_build(("b",), _mk(2))
    reg.get_or_build(("c",), _mk(3))   # over capacity: b goes, a is pinned
    assert ("a",) in reg and ("c",) in reg and ("b",) not in reg
    assert reg.evictions == 1

    reg.unpin(("a",))
    reg.get_or_build(("d",), _mk(4))   # now a is the oldest AND unpinned
    assert ("a",) not in reg and ("c",) in reg and ("d",) in reg
    assert reg.evictions == 2


def test_all_pinned_registry_refuses_eviction():
    reg = ExecutableRegistry(name="t", capacity=1)
    reg.get_or_build(("a",), _mk(1), pin=True)
    reg.get_or_build(("b",), _mk(2), pin=True)
    # over capacity but nothing evictable: refuse, never drop a pinned
    # executable out from under an active slot
    assert len(reg) == 2
    assert ("a",) in reg and ("b",) in reg
    assert reg.evictions == 0 and reg.evict_refusals >= 1


def test_pin_is_refcounted():
    reg = ExecutableRegistry(name="t", capacity=1)
    reg.get_or_build(("a",), _mk(1), pin=True)
    reg.pin(("a",))                    # second holder
    reg.unpin(("a",))                  # first releases: still pinned
    reg.get_or_build(("b",), _mk(2))
    assert ("a",) in reg
    reg.unpin(("a",))                  # last holder releases
    reg.get_or_build(("c",), _mk(3))
    assert ("a",) not in reg


def test_serving_cache_size_1_refuses_not_thrashes():
    """The eviction-hazard regression (ISSUE 18 satellite): with
    FLAGS_decode_jit_cache_size=1 the serving engine's 3+ pinned
    executables exceed capacity on every insert — the registry must
    refuse eviction (counters prove it) and the engine must keep serving
    correct tokens on the executables it already built."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    model.eval()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, model.config.vocab_size, (n,)).astype(np.int64)
               for n in (5, 12, 6)]

    def serve(eng):
        reqs = [eng.submit(p, max_new_tokens=3, temperature=0.0)
                for p in prompts]
        eng.run()
        return [list(r.tokens) for r in reqs]

    reference = serve(ServingEngine(model, slot_count=2, ladder=(8, 16),
                                    max_new_cap=4, max_seq_len=32,
                                    steps_per_dispatch=1))

    old = paddle.get_flags(["decode_jit_cache_size"])[
        "FLAGS_decode_jit_cache_size"]
    paddle.set_flags({"decode_jit_cache_size": 1})
    try:
        eng = ServingEngine(model, slot_count=2, ladder=(8, 16),
                            max_new_cap=4, max_seq_len=32,
                            steps_per_dispatch=1)
        tokens = serve(eng)
        reg = eng.exec_registry()
        # both prefill rungs + greedy decode live despite capacity 1
        assert len(reg) >= 3
        assert reg.evictions == 0, "evicted a pinned serving executable"
        assert reg.evict_refusals > 0
        assert tokens == reference
    finally:
        paddle.set_flags({"decode_jit_cache_size": old})


# ---- claim 3: precompile == lazy, token-identical, zero dispatch compiles


def _counter(name):
    from paddle_tpu.core import monitor

    return monitor.registry().report().get(name, {}).get("value", 0)


def _dispatch_compiles():
    return sum(_counter(f"serving.{k}_compiles")
               for k in ("prefill", "decode", "verify", "draft_prefill"))


def test_precompiled_engine_token_identical_zero_dispatch_compiles():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    model.eval()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, model.config.vocab_size, (n,)).astype(np.int64)
               for n in (4, 7)]

    def serve(eng):
        reqs = [eng.submit(p, max_new_tokens=3, temperature=0.0)
                for p in prompts]
        eng.run()
        return [list(r.tokens) for r in reqs]

    kw = dict(slot_count=2, ladder=(8,), max_new_cap=4, max_seq_len=16,
              steps_per_dispatch=1)
    lazy_tokens = serve(ServingEngine(model, **kw))

    eng = ServingEngine(model, **kw)
    rep = eng.precompile(families=("greedy",))
    assert rep["skipped"] is None and rep["precompiled"] >= 2
    before = _dispatch_compiles()
    aot_tokens = serve(eng)
    assert _dispatch_compiles() == before, "precompiled dispatch compiled"
    assert aot_tokens == lazy_tokens
    assert eng.exec_registry().rollup()["aot_fallbacks"] == 0


def test_precompile_skips_on_probe_refusal(monkeypatch):
    import paddle_tpu as paddle
    from paddle_tpu.analysis import backend as _backend
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny())
    model.eval()
    eng = ServingEngine(model, slot_count=1, ladder=(8,), max_new_cap=2,
                        max_seq_len=16, steps_per_dispatch=1)
    monkeypatch.setattr(_backend, "aot_serving_reason",
                        lambda device_count=None, platform=None:
                        "probe says no")
    rep = eng.precompile()
    assert rep == {"precompiled": 0, "skipped": "probe says no",
                   "cold": 0, "warm": 0, "wall_ms": 0.0}
    assert eng.aot_skip_reason == "probe says no"
    assert len(eng.exec_registry()) == 0  # nothing half-built

    rep2 = eng.precompile(families=("greedy",), force=True)
    assert rep2["skipped"] is None and rep2["precompiled"] >= 2
    assert eng.aot_skip_reason is None


# ---- claim 4: multi-device probe + cross-process bundle round trip ------


def test_aot_probe_gates_multi_device_cpu_only():
    from paddle_tpu.analysis.backend import (aot_serving_reason,
                                             backend_supports_aot_serving)

    assert aot_serving_reason(device_count=1, platform="cpu") is None
    assert aot_serving_reason(device_count=1, platform="tpu") is None
    assert aot_serving_reason(device_count=4, platform="tpu") is None
    reason = aot_serving_reason(device_count=4, platform="cpu")
    assert reason is not None and "multi-device" in reason
    assert not backend_supports_aot_serving(device_count=4, platform="cpu")
    assert backend_supports_aot_serving(device_count=1, platform="cpu")


_SERVE_PROG = r"""
import json, sys
import numpy as np
sys.path.insert(0, "__TOOLS__")
import aot_bundle
from paddle_tpu.core import monitor

mode, bundle = sys.argv[1], sys.argv[2]
if mode == "build":
    manifest = aot_bundle.build_bundle(
        bundle, slots=1, ladder=(8,), max_new_cap=3, max_seq_len=16,
        steps_per_dispatch=1, seed=0, families=("greedy",))
    assert manifest["report"]["skipped"] is None, manifest
eng, rep = aot_bundle.load_engine(bundle)

def counter(name):
    return monitor.registry().report().get(name, {}).get("value", 0)

before = sum(counter(f"serving.{k}_compiles")
             for k in ("prefill", "decode", "verify", "draft_prefill"))
rng = np.random.RandomState(7)
reqs = [eng.submit(rng.randint(0, 50304, (n,)).astype(np.int64),
                   max_new_tokens=3, temperature=0.0) for n in (4, 6)]
eng.run()
after = sum(counter(f"serving.{k}_compiles")
            for k in ("prefill", "decode", "verify", "draft_prefill"))
print(json.dumps({
    "tokens": [list(map(int, r.tokens)) for r in reqs],
    "cold": rep["cold"], "warm": rep["warm"], "skipped": rep["skipped"],
    "dispatch_compiles": after - before,
    "monitor_cold": counter("engine.compile_cold"),
}))
"""


@pytest.mark.slow
def test_aot_bundle_round_trip_fresh_process(tmp_path):
    """Process 1 builds the bundle and serves; process 2 is the joining
    replica — same bundle, fresh interpreter. It must precompile all-warm
    (compile_cold == 0 AND compile_warm > 0: both-flat would just mean
    the cache never engaged), dispatch with zero compiles, and emit
    bit-identical tokens."""
    bundle = str(tmp_path / "bundle")
    prog = _SERVE_PROG.replace("__TOOLS__",
                               os.path.join(REPO, "tools"))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    env.pop("PADDLE_TPU_COMPILE_CACHE", None)
    env.pop("FLAGS_compile_cache_dir", None)

    def run(mode):
        res = subprocess.run([sys.executable, "-c", prog, mode, bundle],
                             capture_output=True, text=True, timeout=600,
                             env=env, cwd=REPO)
        assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
        return json.loads(res.stdout.strip().splitlines()[-1])

    first = run("build")
    assert first["skipped"] is None
    assert first["cold"] == 0 and first["warm"] > 0  # build_bundle compiled
    assert first["dispatch_compiles"] == 0

    second = run("join")
    assert second["skipped"] is None
    assert second["cold"] == 0 and second["monitor_cold"] == 0
    assert second["warm"] > 0
    assert second["dispatch_compiles"] == 0
    assert second["tokens"] == first["tokens"]  # bit-identical replica

"""Numeric sweep 3/3 — nn.functional ops from the reference api.yaml surface
that had no per-op test (VERDICT r1 weak #5): activations, losses, transposed
convs, pooling. Same op_test pattern as the other sweep files."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output

F = paddle.nn.functional


def t(a):
    return paddle.to_tensor(a)


def _rand(shape, lo=-1.0, hi=1.0, seed=0):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype(np.float32)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# ---- activations: (name, fn, np_ref, input, attrs) --------------------------
ACTS = [
    ("elu", F.elu, lambda x, alpha=1.0: np.where(x > 0, x, alpha * np.expm1(x)),
     _rand((2, 5), -3, 3), {}),
    ("selu", F.selu,
     lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
         scale * np.where(x > 0, x, alpha * np.expm1(x)),
     _rand((2, 5), -3, 3), {}),
    ("mish", F.mish,
     lambda x: x * np.tanh(np.log1p(np.exp(x))),
     _rand((2, 5), -3, 3), {}),
    ("swish", F.swish, lambda x: x * _sigmoid(x), _rand((2, 5), -3, 3), {}),
    ("hardshrink", F.hardshrink,
     lambda x, threshold=0.5: np.where(np.abs(x) > threshold, x, 0.0),
     _rand((2, 5), -2, 2), {}),
    ("hardsigmoid", F.hardsigmoid,
     lambda x: np.clip(x / 6.0 + 0.5, 0.0, 1.0),
     _rand((2, 5), -8, 8), {}),
    ("hardswish", F.hardswish,
     lambda x: x * np.clip(x + 3.0, 0.0, 6.0) / 6.0,
     _rand((2, 5), -8, 8), {}),
    ("softshrink", F.softshrink,
     lambda x, threshold=0.5: np.where(x > threshold, x - threshold,
                                       np.where(x < -threshold, x + threshold, 0.0)),
     _rand((2, 5), -2, 2), {}),
    ("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x),
     _rand((2, 5), -3, 3), {}),
    ("thresholded_relu", F.thresholded_relu,
     lambda x, threshold=1.0: np.where(x > threshold, x, 0.0),
     _rand((2, 5), -3, 3), {}),
    ("log_sigmoid", F.log_sigmoid, lambda x: np.log(_sigmoid(x)),
     _rand((2, 5), -4, 4), {}),
    ("hardtanh", F.hardtanh, lambda x: np.clip(x, -1.0, 1.0),
     _rand((2, 5), -3, 3), {}),
]


@pytest.mark.parametrize("name,fn,ref,x,attrs", ACTS, ids=[a[0] for a in ACTS])
def test_activation(name, fn, ref, x, attrs):
    check_output(fn, ref, [x], attrs, rtol=2e-5, atol=2e-6)
    # keep clear of the kink points so the central difference is valid
    safe = x.astype(np.float64) + 0.017
    check_grad(fn, [safe], attrs)


def test_maxout():
    x = _rand((2, 4, 3, 3))

    def ref(a, groups):
        n, c, h, w = a.shape
        return a.reshape(n, c // groups, groups, h, w).max(2)

    check_output(F.maxout, ref, [x], {"groups": 2})
    check_grad(F.maxout, [x.astype(np.float64)], {"groups": 2})


def test_pixel_shuffle():
    x = _rand((1, 8, 2, 3))

    def ref(a, upscale_factor):
        n, c, h, w = a.shape
        r = upscale_factor
        out = a.reshape(n, c // (r * r), r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, c // (r * r), h * r, w * r)

    check_output(F.pixel_shuffle, ref, [x], {"upscale_factor": 2})


def test_gumbel_softmax():
    paddle.seed(42)
    logits = _rand((64, 10), -2, 2)
    soft = F.gumbel_softmax(t(logits), temperature=0.5).numpy()
    np.testing.assert_allclose(soft.sum(-1), np.ones(64), rtol=1e-5)
    assert (soft >= 0).all()
    hard = F.gumbel_softmax(t(logits), temperature=0.5, hard=True).numpy()
    np.testing.assert_allclose(np.sort(hard, -1)[:, -1], np.ones(64))
    np.testing.assert_allclose(hard.sum(-1), np.ones(64))


# ---- losses -----------------------------------------------------------------
def test_binary_cross_entropy_pair():
    p = _rand((4, 3), 0.05, 0.95)
    y = (np.arange(12).reshape(4, 3) % 2).astype(np.float32)

    def bce_ref(pred, label):
        return -(label * np.log(pred) + (1 - label) * np.log(1 - pred)).mean()

    check_output(F.binary_cross_entropy, bce_ref, [p, y], rtol=1e-5)
    logits = _rand((4, 3), -3, 3)

    def bcel_ref(z, label):
        pred = _sigmoid(z)
        return -(label * np.log(pred) + (1 - label) * np.log(1 - pred)).mean()

    check_output(F.binary_cross_entropy_with_logits, bcel_ref, [logits, y],
                 rtol=1e-5)
    check_grad(F.binary_cross_entropy_with_logits,
               [logits.astype(np.float64), y.astype(np.float64)])


def test_kl_div_smooth_l1_log_loss():
    p = _rand((3, 4), 0.1, 1.0)
    p /= p.sum(-1, keepdims=True)
    q = _rand((3, 4), 0.1, 1.0, seed=1)
    q /= q.sum(-1, keepdims=True)
    check_output(F.kl_div, lambda x, target: (target * (np.log(target) - x)).mean(),
                 [np.log(p), q], rtol=1e-5)

    x, y = _rand((3, 4), -2, 2), _rand((3, 4), -2, 2, seed=2)

    def smooth_l1(input, label, delta=1.0):
        d = np.abs(input - label)
        return np.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta)).mean()

    check_output(F.smooth_l1_loss, smooth_l1, [x, y], rtol=1e-5)

    prob = _rand((5, 1), 0.05, 0.95)
    lab = (np.arange(5)[:, None] % 2).astype(np.float32)
    check_output(F.log_loss,
                 lambda i, l, epsilon=1e-4: -(l * np.log(i + epsilon) +
                                              (1 - l) * np.log(1 - i + epsilon)),
                 [prob, lab], rtol=1e-5)


def test_nll_loss_label_smooth():
    logp = np.log(_rand((4, 5), 0.05, 1.0))
    lab = np.array([0, 2, 4, 1], np.int64)
    check_output(F.nll_loss, lambda lp, l: -lp[np.arange(len(l)), l].mean(),
                 [logp, lab], rtol=1e-5)
    onehot = np.eye(5, dtype=np.float32)[lab]
    check_output(F.label_smooth,
                 lambda l, epsilon=0.1: (1 - epsilon) * l + epsilon / l.shape[-1],
                 [onehot], {"epsilon": 0.1})


# ---- transposed convs / pooling --------------------------------------------
def _conv_transpose2d_ref(x, w, stride):
    n, cin, h, wd = x.shape
    _, cout, kh, kw = w.shape
    oh, ow = (h - 1) * stride + kh, (wd - 1) * stride + kw
    out = np.zeros((n, cout, oh, ow), x.dtype)
    for b in range(n):
        for ci in range(cin):
            for i in range(h):
                for j in range(wd):
                    out[b, :, i * stride:i * stride + kh,
                        j * stride:j * stride + kw] += x[b, ci, i, j] * w[ci]
    return out


def test_conv2d_transpose():
    x, w = _rand((2, 3, 4, 4)), _rand((3, 2, 3, 3), seed=1)
    for stride in (1, 2):
        got = F.conv2d_transpose(t(x), t(w), stride=stride).numpy()
        np.testing.assert_allclose(got, _conv_transpose2d_ref(x, w, stride),
                                   rtol=1e-4, atol=1e-5)
    check_grad(lambda a, b: F.conv2d_transpose(a, b, stride=2),
               [x.astype(np.float64)[:1, :, :2, :2], w.astype(np.float64)],
               input_idx=1, rtol=1e-2, atol=1e-3)


def test_conv3d_transpose():
    x, w = _rand((1, 2, 3, 3, 3)), _rand((2, 2, 2, 2, 2), seed=1)
    got = F.conv3d_transpose(t(x), t(w), stride=2).numpy()
    n, cin, d, h, wd = x.shape
    _, cout, kd, kh, kw = w.shape
    out = np.zeros((n, cout, (d - 1) * 2 + kd, (h - 1) * 2 + kh,
                    (wd - 1) * 2 + kw), x.dtype)
    for ci in range(cin):
        for i in range(d):
            for j in range(h):
                for k in range(wd):
                    out[0, :, i * 2:i * 2 + kd, j * 2:j * 2 + kh,
                        k * 2:k * 2 + kw] += x[0, ci, i, j, k] * w[ci]
    np.testing.assert_allclose(got, out, rtol=1e-4, atol=1e-5)


def test_avg_pool3d():
    x = _rand((1, 2, 4, 4, 4))
    got = F.avg_pool3d(t(x), kernel_size=2, stride=2).numpy()
    ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean((3, 5, 7))
    np.testing.assert_allclose(got, ref, rtol=1e-5)

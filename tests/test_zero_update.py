"""ZeRO-style cross-replica weight-update sharding (ISSUE 9 tentpole).

The composition matrix under test, layer by layer:

- **bit-exactness**: the f32 sharded update (reduce-scatter -> shard-local
  clip+update -> all-gather, arXiv:2004.13336) reproduces the replicated
  fused-all-reduce trajectory bit for bit — loss AND params. The loss
  scalar rides the reduce-scatter in the flat buffer's guaranteed pad slot
  so it takes the identical reduction path as the gradients.
- **HLO gate**: exactly ONE reduce-scatter + ONE all-gather per optimizer
  step independent of microbatch count K, ZERO full-buffer all-reduces,
  and the K-microbatch scan while-loop survives — with health stats on.
- **layout pin**: each replica owns the contiguous [r*shard, (r+1)*shard)
  slice of the flat vector in grad_comm's segment order (sorted param
  names == ravel_pytree dict flatten order == health.segment_layout);
  the gathered flat opt state is bit-equal to the replicated dict.
- **low precision**: bf16 reduce-scatter with error feedback donates the
  residual buffer and tracks the f32 trajectory.
- **health attribution**: a NaN injected into one parameter still gets
  named even though that parameter's shard lives on ANOTHER replica —
  shard-local partials ride the all-gather slab and are re-assembled.
- **fallbacks**: mp/sp meshes and non-uniform optimizer rules warn ONCE
  and run the GSPMD/replicated path; run_steps (the fused K-step scan
  lane) refuses an active zero_update instead of silently diverging.
- **memory**: exec_introspect argument bytes show optimizer state at
  ~1/N per device vs the replicated accumulation executable, matching
  engine.zero_memory_model().
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.distributed import grad_comm
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)
from paddle_tpu.observability import (exec_introspect, flight_recorder,
                                      health, metrics)



@pytest.fixture(autouse=True)
def _observability_cleanup():
    yield
    metrics.reset()
    flight_recorder.disable()
    health.reset()
    exec_introspect.reset()


def _dp8():
    set_hybrid_communicate_group(None)
    return HybridCommunicateGroup(dp_degree=8)


def _make(k=2, zero=False, hcg=None, seed=0, width=32, optimizer="adamw",
          in_dim=16):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(in_dim, width),
                               paddle.nn.ReLU(),
                               paddle.nn.Linear(width, 4))
    if optimizer == "adamw":
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     parameters=net.parameters())
    elif optimizer == "momentum":
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=net.parameters())
    else:
        opt = paddle.optimizer.Lars(learning_rate=0.01,
                                    parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           hcg=hcg if hcg is not None else _dp8(),
                           microbatches=k, zero_update=zero)


def _batch(n=32, in_dim=16):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, in_dim).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def _losses(engine, x, y, steps=3):
    return [float(engine.step(x, y).item()) for _ in range(steps)]


def _zero_compiled(eng):
    (label, (fn, avals)), = [kv for kv in eng._exec_stash.items()
                             if kv[0].startswith("train.zero")]
    return label, fn.lower(*avals).compile()


# ----------------------------------------------------------- bit-exactness

def test_f32_sharded_update_bit_equal_to_replicated():
    """The whole point of the decomposition: all-reduce == reduce-scatter +
    shard-local update + all-gather, BIT FOR BIT at f32 — the final loss
    and every trained parameter match the replicated engine exactly, for
    five steps at dp8 with K=2 microbatches."""
    hcg = _dp8()
    x, y = _batch()
    er = _make(k=2, hcg=hcg)
    ez = _make(k=2, zero=True, hcg=hcg)
    lr, lz = _losses(er, x, y, steps=5), _losses(ez, x, y, steps=5)
    assert lz == lr  # exact float equality, not allclose
    for n in er.params:
        np.testing.assert_array_equal(np.asarray(ez.params[n]),
                                      np.asarray(er.params[n]))
    # ZeRO engaged: flat shards own the state, the dict is gone
    assert ez._zero_opt is not None and ez.opt_state is None
    assert er._zero_opt is None and er.opt_state is not None


# ---------------------------------------------------------------- HLO gate

@pytest.mark.parametrize("k", [2, 4])
def test_hlo_one_reduce_scatter_one_all_gather_no_all_reduce(k):
    """The compiled sharded step holds exactly ONE reduce-scatter and ONE
    all-gather independent of K, zero full-buffer all-reduces and zero
    all-to-alls (f32 path), and keeps the single microbatch scan
    while-loop — with health partials riding the same program."""
    ez = _make(k=k, zero=True)
    ez.enable_health(interval=1)
    x, y = _batch()
    ez.step(x, y)
    from paddle_tpu import analysis as an

    label, comp = _zero_compiled(ez)
    assert label == f"train.zero_k{k}_f32"
    # counts are op DEFINITIONS, not operand references; the microbatch scan
    # must survive (CPU collective emulation adds its own while loops, so a
    # lower bound rather than ==)
    rep = an.check_compiled(label, comp, an.ProgramContract(
        collectives={"reduce-scatter": 1, "all-gather": 1,
                     "all-reduce": 0, "all-to-all": 0},
        while_loops=(1, None),
        allow_host_calls=True, max_constant_bytes=None))
    assert rep.ok, f"ZeRO decomposition contract broken:\n{rep.format()}"
    ez.disable_health()


# -------------------------------------------------------------- layout pin

def test_shard_ownership_pins_flat_buffer_segment_order():
    """Replica r owns the contiguous [r*shard, (r+1)*shard) slice of the
    flat vector laid out in grad_comm segment order — which must be
    health.segment_layout's order (sorted names == ravel_pytree dict
    flatten order). Pinned two ways: the layout arithmetic itself, and the
    gathered flat opt state being bit-equal to the replicated dict."""
    hcg = _dp8()
    x, y = _batch()
    er = _make(k=2, hcg=hcg)
    ez = _make(k=2, zero=True, hcg=hcg)
    for _ in range(2):
        er.step(x, y)
        ez.step(x, y)

    n, n_pad, shard, nrep = ez._zero_layout()
    assert nrep == 8 and shard * nrep == n_pad
    # zero_pad_elems always leaves >= 1 spare pad slot: the f32/bf16 loss
    # scalar rides the reduce-scatter in flat slot n
    assert n_pad > n
    assert n_pad % (nrep * grad_comm.chunk_size()) == 0
    # segment order: health.segment_layout offsets ARE the flat offsets
    shapes = {nm: tuple(ez._state_refs[nm].shape) for nm in ez._param_names}
    layout = health.segment_layout(shapes)
    assert [nm for nm, _, _ in layout] == sorted(ez._param_names)
    assert layout[-1][1] + layout[-1][2] == n

    # the gathered flat shards reconstruct the replicated opt-state dict
    # bit for bit, per parameter, per slot (adamw: m and v)
    gathered = ez._gather_zero_opt()
    assert set(gathered) == set(er.opt_state)
    for nm in er.opt_state:
        assert len(gathered[nm]) == len(er.opt_state[nm]) == 2
        for j, slot in enumerate(er.opt_state[nm]):
            np.testing.assert_array_equal(gathered[nm][j],
                                          np.asarray(slot, np.float32))
    # pad tail stays exactly zero through the whitelisted rules
    for f in ez._zero_opt:
        tail = np.asarray(f)[n + 1:]  # slot n carries the loss ride
        np.testing.assert_array_equal(tail, np.zeros_like(tail))


# ----------------------------------------------- bf16 + error feedback

def test_bf16_error_feedback_residual_donated_and_tracks_f32():
    """bf16 reduce-scatter payload with error feedback: the residual is
    carried state (donated each step, scattered shard layout) and the
    quantized trajectory tracks f32; the flat opt shards are donated
    too."""
    hcg = _dp8()
    x, y = _batch()
    lf = _losses(_make(k=2, hcg=hcg), x, y, steps=4)
    paddle.set_flags({"grad_comm_dtype": "bf16",
                      "grad_comm_error_feedback": True})
    ez = _make(k=2, zero=True, hcg=hcg)
    ez.step(x, y)
    res0, opt0 = ez._grad_residual, ez._zero_opt[0]
    assert res0 is not None and float(jnp.abs(res0).max()) > 0
    lz = [float(ez.step(x, y).item()) for _ in range(3)]
    # donation: last step consumed the previous residual and opt shards
    assert res0.is_deleted() and opt0.is_deleted()
    assert not ez._grad_residual.is_deleted()
    np.testing.assert_allclose([lz[-1]], [lf[-1]], rtol=2e-2)
    (label,) = [kv for kv in ez._accum_fns]
    assert label == (2, "bf16", True, grad_comm.chunk_size(), False, True)


# ----------------------------------------------------- health attribution

class _Probe(paddle.nn.Layer):
    """Loss = mse + sum((tail.weight * s.mean())**2): the `s` batch column
    drives tail.weight's gradient to inf without touching any other
    parameter — data-driven injection into the compiled step."""

    def __init__(self):
        super().__init__()
        self.body = paddle.nn.Linear(8, 8)
        self.tail = paddle.nn.Linear(8, 8)

    def forward(self, x, y, s):
        h = self.tail(self.body(x))
        mse = ((h - y) ** 2).mean()
        canary = ((self.tail.weight * s.mean()) ** 2).sum()
        return mse + canary


def test_health_attribution_names_param_on_another_replicas_shard():
    """With FLAGS_grad_comm_chunk=16 the _Probe flat vector (n=144) pads
    to 256 -> shard=32, so tail.weight's segment [80,144) is owned by
    replicas 2..4 — NOT replica 0. The shard-local health partials riding
    the all-gather slab must still attribute the injected inf to
    tail.weight by name, and to no other parameter."""
    paddle.set_flags({"grad_comm_chunk": 16})
    hcg = _dp8()
    paddle.seed(0)
    net = _Probe()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    ez = TrainStepEngine(net, opt, loss_fn=None, hcg=hcg, microbatches=2,
                         zero_update=True)
    ez.enable_health(interval=1)

    n, n_pad, shard, nrep = ez._zero_layout()
    shapes = {nm: tuple(ez._state_refs[nm].shape) for nm in ez._param_names}
    (off, size), = [(o, s) for nm, o, s in health.segment_layout(shapes)
                    if nm == "tail.weight"]
    assert off // shard != 0, "scenario broken: shard owner is replica 0"

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8).astype("float32"))
    y = jnp.asarray(rng.randn(16, 8).astype("float32"))
    healthy = jnp.zeros((16,), jnp.float32)
    poisoned = jnp.full((16,), 1e25, jnp.float32)
    ez.step(x, y, healthy)
    ez.step(x, y, healthy)
    ez.step(x, y, poisoned)

    recs = ez._health.recent()
    assert [r["step"] for r in recs] == [1, 2, 3]
    assert recs[1]["nonfinite_count"] == 0
    bad = recs[2]
    assert bad["nonfinite_count"] > 0
    assert bad["first_nonfinite_param"] == "tail.weight"
    for name, pp in bad["per_param"].items():
        if name != "tail.weight":
            assert pp["nonfinite"] == 0, f"{name} wrongly flagged"
    ez.disable_health()


# --------------------------------------------------------------- fallbacks

def test_mp_mesh_falls_back_to_gspmd_with_single_warning():
    """A non-pure-dp topology can't own contiguous flat shards per dp
    replica; the engine warns ONCE and runs the GSPMD accumulation path —
    same losses as the pure-dp replicated engine."""
    hcg = HybridCommunicateGroup(dp_degree=4, mp_degree=2)
    x, y = _batch()
    with pytest.warns(UserWarning, match="not pure data-parallel"):
        em = _make(k=2, zero=True, hcg=hcg)
        lm = _losses(em, x, y, steps=3)
    assert em._zero_opt is None and em.opt_state is not None
    assert all(not key[-1] for key in em._accum_fns)  # zero never engaged
    assert em._zero_warned  # and won't warn again
    lr = _losses(_make(k=2), x, y, steps=3)
    np.testing.assert_allclose(lm, lr, rtol=1e-5)


def test_non_uniform_optimizer_rule_falls_back_bit_identical():
    """lars needs per-parameter trust ratios — not expressible as one
    uniform elementwise rule over a flat slice. zero_update warns and the
    trajectory is bit-identical to the plain replicated lars engine."""
    hcg = _dp8()
    x, y = _batch()
    lr = _losses(_make(k=2, hcg=hcg, optimizer="lars"), x, y, steps=3)
    with pytest.warns(UserWarning, match="uniform"):
        ez = _make(k=2, zero=True, hcg=hcg, optimizer="lars")
        lz = _losses(ez, x, y, steps=3)
    assert lz == lr
    assert ez._zero_opt is None


def test_run_steps_rejects_active_zero_update():
    """run_steps is the fused K-OPTIMIZER-step scan lane and carries the
    replicated opt-state dict; silently running it under zero_update would
    diverge from step() semantics, so it raises — but an engine whose
    zero_update FELL BACK (replicated path anyway) keeps run_steps."""
    x, y = _batch()
    ez = _make(k=1, zero=True)
    with pytest.raises(ValueError, match="zero_update"):
        ez.run_steps(x, y, steps=2)
    # fallback engine: zero never engages, run_steps still works
    with pytest.warns(UserWarning, match="uniform"):
        ef = _make(k=1, zero=True, optimizer="lars")
        losses = ef.run_steps(x, y, steps=2)
    assert tuple(losses.shape) == (2,)
    assert np.isfinite(np.asarray(losses)).all()


# --------------------------------------------------- memory + byte counters

def test_opt_state_bytes_scale_one_over_n():
    """exec_introspect: the sharded executable's per-device argument bytes
    drop by ~the replicated-vs-sharded opt-state delta that
    zero_memory_model() predicts (adamw: 2 f32 slots, 8 replicas)."""
    paddle.set_flags({"grad_comm_chunk": 64})
    hcg = _dp8()
    x, y = _batch(n=32, in_dim=128)
    er = _make(k=2, hcg=hcg, width=128, in_dim=128)
    ez = _make(k=2, zero=True, hcg=hcg, width=128, in_dim=128)
    er.step(x, y)
    ez.step(x, y)

    mm = ez.zero_memory_model()
    assert mm["opt_slots"] == 2 and mm["replicas"] == 8
    # big model + small chunk: padding is noise, sharded ~= replicated/8
    assert mm["sharded_opt_bytes_per_device"] < mm["replicated_opt_bytes"] / 6

    rep = er.introspect_executables()["train.accum_k2_f32"]
    zer = ez.introspect_executables()["train.zero_k2_f32"]
    measured = (rep["argument_size_in_bytes"] - zer["argument_size_in_bytes"])
    predicted = (mm["replicated_opt_bytes"]
                 - mm["sharded_opt_bytes_per_device"])
    assert measured == pytest.approx(predicted, rel=0.15)


def test_rs_ag_byte_counters_and_telemetry():
    """grad_comm.rs_bytes / ag_bytes count the collective payloads (K-
    independent per step) and surface as counter deltas in step telemetry
    records, which also carry the zero_update marker."""
    from paddle_tpu.observability.step_telemetry import StepTelemetry

    ez = _make(k=4, zero=True)
    ez.telemetry = StepTelemetry(collect_memory=False)
    rs0 = monitor.stat("grad_comm.rs_bytes").get()
    ag0 = monitor.stat("grad_comm.ag_bytes").get()
    x, y = _batch()
    ez.step(x, y)
    ez.step(x, y)
    n = ez._n_grad_elems()
    rs_b, ag_b = grad_comm.zero_payload_bytes(n, 8, "f32",
                                              grad_comm.chunk_size())
    assert monitor.stat("grad_comm.rs_bytes").get() - rs0 == 2 * rs_b
    assert monitor.stat("grad_comm.ag_bytes").get() - ag0 == 2 * ag_b
    rec = ez.telemetry.sink.records[-1]
    assert rec["zero_update"] is True
    assert rec["microbatches"] == 4
    assert rec["grad_comm_rs_bytes"] == rs0 + 2 * rs_b
    assert rec["grad_comm_ag_bytes"] == ag0 + 2 * ag_b
    assert rec["grad_comm_bytes"] == rs_b + ag_b

"""Fused Pallas LayerNorm vs the XLA lowering (values + grads), interpret
mode on the CPU mesh. Reference parity: phi layer_norm_kernel fused path."""
import numpy as np
import pytest

import paddle_tpu as paddle

F = paddle.nn.functional


@pytest.fixture
def flag():
    # interpret mode on CPU needs the explicit opt-in (same gate as the
    # other Pallas routes)
    paddle.set_flags({"use_pallas_layernorm": True, "pallas_interpret_ok": True})
    yield
    paddle.set_flags({"use_pallas_layernorm": False, "pallas_interpret_ok": False})


def _data(shape, hidden, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(*shape, hidden).astype(np.float32)
    g = rng.rand(hidden).astype(np.float32) + 0.5
    b = rng.randn(hidden).astype(np.float32)
    return x, g, b


@pytest.mark.parametrize("shape,hidden", [((16,), 128), ((4, 8), 256),
                                          ((2, 3, 8), 128)])
def test_values_match_xla_path(flag, shape, hidden):
    x, g, b = _data(shape, hidden)
    got = F.layer_norm(paddle.to_tensor(x), hidden,
                       weight=paddle.to_tensor(g),
                       bias=paddle.to_tensor(b)).numpy()
    paddle.set_flags({"use_pallas_layernorm": False})
    ref = F.layer_norm(paddle.to_tensor(x), hidden,
                       weight=paddle.to_tensor(g),
                       bias=paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_grads_match_xla_path(flag):
    x, g, b = _data((8,), 128, seed=3)
    w = np.random.RandomState(4).randn(8, 128).astype(np.float32)

    def run():
        xt = paddle.to_tensor(x.copy())
        gt = paddle.to_tensor(g.copy())
        bt = paddle.to_tensor(b.copy())
        for t in (xt, gt, bt):
            t.stop_gradient = False
        out = F.layer_norm(xt, 128, weight=gt, bias=bt)
        (out * paddle.to_tensor(w)).sum().backward()
        return xt.grad.numpy(), gt.grad.numpy(), bt.grad.numpy()

    dx, dg, db = run()
    paddle.set_flags({"use_pallas_layernorm": False})
    rdx, rdg, rdb = run()
    np.testing.assert_allclose(dx, rdx, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(dg, rdg, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(db, rdb, rtol=2e-4, atol=2e-4)


def test_unsupported_hidden_falls_back(flag):
    # hidden not a multiple of 128: silently uses the XLA path, still correct
    x, g, b = _data((4,), 96, seed=5)
    got = F.layer_norm(paddle.to_tensor(x), 96, weight=paddle.to_tensor(g),
                       bias=paddle.to_tensor(b)).numpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_bf16_io_f32_stats(flag):
    import jax.numpy as jnp

    x, g, b = _data((16,), 128, seed=6)
    xb = paddle.to_tensor(x, dtype="bfloat16")
    got = F.layer_norm(xb, 128,
                       weight=paddle.to_tensor(g, dtype="bfloat16"),
                       bias=paddle.to_tensor(b, dtype="bfloat16"))
    assert got._data.dtype == jnp.bfloat16
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(np.asarray(got._data, np.float32), ref,
                               rtol=0.05, atol=0.05)  # bf16 storage error

"""Fused Pallas LayerNorm vs the XLA lowering (values + grads), interpret
mode on CPU. Reference parity: phi layer_norm_kernel fused path.

Round 5: the kernel is RETIRED from the nn.functional.layer_norm route
(BASELINE.md retirement note) — these tests call it DIRECTLY
(ops/pallas/layer_norm.py), keeping its math pinned as a library kernel.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (x64 mode + platform init)
from paddle_tpu.ops.pallas.layer_norm import layer_norm as pln
from paddle_tpu.ops.pallas.layer_norm import supported


def _data(shape, hidden, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(*shape, hidden).astype(np.float32)
    g = rng.rand(hidden).astype(np.float32) + 0.5
    b = rng.randn(hidden).astype(np.float32)
    return x, g, b


def _ref(x, g, b, eps=1e-5):
    xf = x.astype(np.float32)
    m = xf.mean(-1, keepdims=True)
    v = xf.var(-1, keepdims=True)
    return (xf - m) / np.sqrt(v + eps) * g + b


@pytest.mark.parametrize("shape,hidden", [((16,), 128), ((4, 8), 256),
                                          ((2, 3, 8), 128)])
def test_values_match_reference(shape, hidden):
    x, g, b = _data(shape, hidden)
    got = np.asarray(pln(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b)))
    np.testing.assert_allclose(got, _ref(x, g, b), rtol=2e-5, atol=2e-5)


def test_grads_match_xla_lowering():
    x, g, b = _data((8,), 128, seed=3)
    w = np.random.RandomState(4).randn(8, 128).astype(np.float32)
    xj, gj, bj, wj = (jnp.asarray(a) for a in (x, g, b, w))

    def loss_pallas(xx, gg, bb):
        return (pln(xx, gg, bb) * wj).sum()

    def loss_xla(xx, gg, bb):
        xf = xx.astype(jnp.float32)
        m = xf.mean(-1, keepdims=True)
        v = ((xf - m) ** 2).mean(-1, keepdims=True)
        return (((xf - m) * jax.lax.rsqrt(v + 1e-5) * gg + bb) * wj).sum()

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(xj, gj, bj)
    gr = jax.grad(loss_xla, argnums=(0, 1, 2))(xj, gj, bj)
    for a, r in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)


def test_bf16_io_f32_stats():
    """bf16 in/out with f32 statistics inside the kernel: output dtype
    follows the input, values match the f32 reference at bf16 tolerance
    (pins the .astype chains in _fwd_kernel and the o_ref.dtype cast for
    the retained library kernel)."""
    x, g, b = _data((4, 8), 256, seed=7)
    out = pln(jnp.asarray(x, jnp.bfloat16), jnp.asarray(g), jnp.asarray(b))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref(x, g, b), rtol=2e-2, atol=2e-2)


def test_supported_predicate():
    assert supported(16384, 768)      # bench shape
    assert not supported(16, 100)     # hidden not lane-aligned

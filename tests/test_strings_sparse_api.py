"""strings_api.yaml + sparse conversion surface (reference
python/paddle/utils/code_gen/{strings,sparse}_api.yaml)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import strings


def test_strings_empty_and_like():
    e = strings.empty([2, 3])
    assert e.shape == [2, 3] and e.tolist() == [[""] * 3] * 2
    el = strings.empty_like(strings.StringTensor([["x", "y"]]))
    assert el.shape == [1, 2] and el.tolist() == [["", ""]]


def test_strings_lower_upper_ascii_vs_utf8():
    x = strings.StringTensor(["Hello World", "CAF\xc9 \xdcber", "mixed123!"])
    # ascii fast path: accented codepoints untouched (reference default)
    lo = strings.lower(x)
    assert lo.tolist() == ["hello world", "caf\xc9 \xdcber", "mixed123!"]
    up = strings.upper(x)
    assert up.tolist() == ["HELLO WORLD", "CAF\xc9 \xdcBER", "MIXED123!"]
    # utf8 path: full unicode case mapping
    lo8 = strings.lower(x, use_utf8_encoding=True)
    assert lo8.tolist() == ["hello world", "caf\xe9 \xfcber", "mixed123!"]
    up8 = strings.upper(x, use_utf8_encoding=True)
    assert up8.tolist() == ["HELLO WORLD", "CAF\xc9 \xdcBER", "MIXED123!"]


def test_dense_to_sparse_roundtrips():
    x = paddle.to_tensor(np.array([[0.0, 1.5], [2.5, 0.0], [0.0, 3.5]],
                                  np.float32))
    coo = x.to_sparse_coo(2)
    np.testing.assert_allclose(coo.to_dense().numpy(), x.numpy())
    np.testing.assert_allclose(np.sort(coo.values().numpy()), [1.5, 2.5, 3.5])

    csr = x.to_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), x.numpy())
    np.testing.assert_array_equal(csr.crows().numpy(), [0, 1, 2, 3])
    np.testing.assert_array_equal(csr.cols().numpy(), [1, 0, 1])
    # csr round-trips through the csr constructor too
    rebuilt = paddle.sparse.sparse_csr_tensor(
        csr.crows(), csr.cols(), csr.values(), x.shape)
    np.testing.assert_allclose(rebuilt.to_dense().numpy(), x.numpy())


def test_partial_sparse_dim():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 2, 3))
    sp = x.to_sparse_coo(2)  # last dim stays dense
    np.testing.assert_allclose(sp.to_dense().numpy(), x.numpy())

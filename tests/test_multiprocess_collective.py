"""True multi-PROCESS distributed training: launcher -> init_parallel_env
(jax.distributed + gloo CPU collectives) -> fleet engine over a mesh spanning
both processes. The SURVEY §4 test-pyramid level 2 — subprocess clusters on
one host, loss parity across ranks (reference test_dist_base.py:782)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest


pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

_TRAIN = """
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    assert jax.device_count() == 2 and jax.local_device_count() == 1

    paddle.seed(0)  # same init on every rank
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    engine = fleet.distributed_engine(net, opt,
                                      loss_fn=lambda out: (out ** 2).mean())

    rank = dist.get_rank()
    rs = np.random.RandomState(0)            # SAME global batch everywhere;
    xg = rs.rand(8, 8).astype(np.float32)    # engine shards it over dp
    losses = []
    for _ in range(3):
        losses.append(float(engine.step(paddle.to_tensor(xg)).item()))
    print("RANK", rank, "LOSSES", ",".join(f"{v:.6f}" for v in losses),
          flush=True)
    assert losses[-1] < losses[0]
"""


@pytest.mark.parametrize("nproc", [2])
def test_two_process_dp_training(tmp_path, nproc):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(_TRAIN))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # one CPU device per process: the mesh must span PROCESSES
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(nproc), "--log_dir", str(tmp_path / "log"),
         str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, (res.stdout, res.stderr)
    losses = {}
    for r in range(nproc):
        log = (tmp_path / "log" / f"workerlog.{r}.log").read_text()
        assert "LOSSES" in log, log
        for line in log.splitlines():
            if line.startswith("RANK"):
                parts = line.split()
                losses[int(parts[1])] = [float(v) for v in
                                         parts[3].split(",")]
    assert set(losses) == set(range(nproc))
    # every rank computed the SAME global loss (dp allreduce agreement)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)

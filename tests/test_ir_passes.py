"""IR pass system + per-op debug interpreter tests (reference
framework/ir pass registry; classic Executor walk as debug mode)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import passes


def _build_program():
    """x -> relu -> exp (fetched), plus a dead branch and a duplicate relu."""
    paddle.enable_static()
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        a = paddle.nn.functional.relu(x)
        b = paddle.exp(a)
        dead = paddle.tanh(x) * 3.0       # nothing fetches this
        dup = paddle.nn.functional.relu(x)  # identical to `a`
        c = b + dup
    paddle.disable_static()
    return main, x, c


class TestPasses:
    def test_dce_removes_dead_ops(self):
        main, x, c = _build_program()
        view = passes.ProgramView(main)
        n_before = len(view.global_block().ops)
        removed = passes.dead_code_elimination(view, [c.name])
        assert removed >= 2  # tanh + mul of the dead branch
        assert len(view.global_block().ops) == n_before - removed
        # the original program keeps every op (view isolation)
        assert len(main.global_block().ops) == n_before

    def test_cse_merges_duplicates(self):
        main, x, c = _build_program()
        view = passes.ProgramView(main)
        merged = passes.common_subexpression_elimination(view, [c.name])
        assert merged >= 1  # the duplicate relu folds into the first
        relus = [op for op in view.global_block().ops if op.type == "relu"]
        assert len(relus) == 1

    def test_fuse_elementwise_chains(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [4], "float32")
            y = paddle.exp(paddle.tanh(paddle.nn.functional.relu(x)))
        paddle.disable_static()
        view = passes.ProgramView(main)
        fused = passes.fuse_elementwise(view, [y.name])
        assert fused >= 1
        assert any(op.type.startswith("fused_") for op in view.global_block().ops)

    def test_executor_results_unchanged_by_passes(self):
        main, x, c = _build_program()
        exe = static.Executor()
        feed = {"x": np.array([-1.0, 0.5, 2.0, -3.0], np.float32)}
        paddle.set_flags({"apply_ir_passes": True})
        with_passes = exe.run(main, feed=feed, fetch_list=[c])
        exe2 = static.Executor()
        paddle.set_flags({"apply_ir_passes": False})
        try:
            without = exe2.run(main, feed=feed, fetch_list=[c])
        finally:
            paddle.set_flags({"apply_ir_passes": True})
        np.testing.assert_allclose(with_passes[0], without[0], rtol=1e-6)
        ref = np.exp(np.maximum(feed["x"], 0)) + np.maximum(feed["x"], 0)
        np.testing.assert_allclose(with_passes[0], ref, rtol=1e-5)

    def test_pass_registry(self):
        assert "dead_code_elimination" in passes.PASS_REGISTRY
        assert "common_subexpression_elimination" in passes.PASS_REGISTRY
        assert "fuse_elementwise" in passes.PASS_REGISTRY
        main, x, c = _build_program()
        passes.apply_pass(passes.ProgramView(main), "dead_code_elimination",
                          [c.name])


class TestDebugInterpreter:
    def test_matches_compiled_run(self):
        main, x, c = _build_program()
        exe = static.Executor()
        feed = {"x": np.array([1.0, -2.0, 3.0, 0.0], np.float32)}
        compiled = exe.run(main, feed=feed, fetch_list=[c])
        debug = exe.run_debug(main, feed=feed, fetch_list=[c])
        np.testing.assert_allclose(compiled[0], debug[0], rtol=1e-6)
        # per-op stats recorded
        assert len(exe.last_run_stats) == len(main.global_block().ops)
        assert all(t >= 0 for _, t in exe.last_run_stats)

    def test_nan_pinpointing(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [2], "float32")
            y = paddle.log(x)       # NaN for negative input
            z = paddle.exp(y)
        paddle.disable_static()
        exe = static.Executor()
        with pytest.raises(FloatingPointError, match="log"):
            exe.run_debug(main, feed={"x": np.array([-1.0, 1.0], np.float32)},
                          fetch_list=[z], check_nan_inf=True)

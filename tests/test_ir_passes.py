"""IR pass system + per-op debug interpreter tests (reference
framework/ir pass registry; classic Executor walk as debug mode)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import passes


def _build_program():
    """x -> relu -> exp (fetched), plus a dead branch and a duplicate relu."""
    paddle.enable_static()
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        a = paddle.nn.functional.relu(x)
        b = paddle.exp(a)
        dead = paddle.tanh(x) * 3.0       # nothing fetches this
        dup = paddle.nn.functional.relu(x)  # identical to `a`
        c = b + dup
    paddle.disable_static()
    return main, x, c


class TestPasses:
    def test_dce_removes_dead_ops(self):
        main, x, c = _build_program()
        view = passes.ProgramView(main)
        n_before = len(view.global_block().ops)
        removed = passes.dead_code_elimination(view, [c.name])
        assert removed >= 2  # tanh + mul of the dead branch
        assert len(view.global_block().ops) == n_before - removed
        # the original program keeps every op (view isolation)
        assert len(main.global_block().ops) == n_before

    def test_cse_merges_duplicates(self):
        main, x, c = _build_program()
        view = passes.ProgramView(main)
        merged = passes.common_subexpression_elimination(view, [c.name])
        assert merged >= 1  # the duplicate relu folds into the first
        relus = [op for op in view.global_block().ops if op.type == "relu"]
        assert len(relus) == 1

    def test_fuse_elementwise_chains(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [4], "float32")
            y = paddle.exp(paddle.tanh(paddle.nn.functional.relu(x)))
        paddle.disable_static()
        view = passes.ProgramView(main)
        fused = passes.fuse_elementwise(view, [y.name])
        assert fused >= 1
        assert any(op.type.startswith("fused_") for op in view.global_block().ops)

    def test_executor_results_unchanged_by_passes(self):
        main, x, c = _build_program()
        exe = static.Executor()
        feed = {"x": np.array([-1.0, 0.5, 2.0, -3.0], np.float32)}
        paddle.set_flags({"apply_ir_passes": True})
        with_passes = exe.run(main, feed=feed, fetch_list=[c])
        exe2 = static.Executor()
        paddle.set_flags({"apply_ir_passes": False})
        try:
            without = exe2.run(main, feed=feed, fetch_list=[c])
        finally:
            paddle.set_flags({"apply_ir_passes": True})
        np.testing.assert_allclose(with_passes[0], without[0], rtol=1e-6)
        ref = np.exp(np.maximum(feed["x"], 0)) + np.maximum(feed["x"], 0)
        np.testing.assert_allclose(with_passes[0], ref, rtol=1e-5)

    def test_pass_registry(self):
        assert "dead_code_elimination" in passes.PASS_REGISTRY
        assert "common_subexpression_elimination" in passes.PASS_REGISTRY
        assert "fuse_elementwise" in passes.PASS_REGISTRY
        main, x, c = _build_program()
        passes.apply_pass(passes.ProgramView(main), "dead_code_elimination",
                          [c.name])


class TestDebugInterpreter:
    def test_matches_compiled_run(self):
        main, x, c = _build_program()
        exe = static.Executor()
        feed = {"x": np.array([1.0, -2.0, 3.0, 0.0], np.float32)}
        compiled = exe.run(main, feed=feed, fetch_list=[c])
        debug = exe.run_debug(main, feed=feed, fetch_list=[c])
        np.testing.assert_allclose(compiled[0], debug[0], rtol=1e-6)
        # per-op stats recorded
        assert len(exe.last_run_stats) == len(main.global_block().ops)
        assert all(t >= 0 for _, t in exe.last_run_stats)

    def test_nan_pinpointing(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [2], "float32")
            y = paddle.log(x)       # NaN for negative input
            z = paddle.exp(y)
        paddle.disable_static()
        exe = static.Executor()
        with pytest.raises(FloatingPointError, match="log"):
            exe.run_debug(main, feed={"x": np.array([-1.0, 1.0], np.float32)},
                          fetch_list=[z], check_nan_inf=True)


def test_int8_fake_quantize_pass():
    """The static-graph quant pass (reference QuantizationTransformPass)
    inserts fake_quantize_dequantize ops ahead of quantizable ops' inputs;
    the rewritten program still executes and stays close to the f32 result."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu.distributed.passes import PassManager, new_pass

    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main_program=main,
                                  startup_program=startup):
            x = static.data(name="X", shape=[4, 8], dtype="float32")
            h = static.nn.fc(x, 16)
            y = paddle.mean(h)
        exe = static.Executor()
        exe.run(startup)
        feed = {"X": np.random.RandomState(0).randn(4, 8).astype("float32")}
        ref = exe.run(main, feed=feed, fetch_list=[y])[0]

        p = new_pass("int8_fake_quantize")
        pm = PassManager([p])
        pm.apply(main)
        n = pm.context.results["int8_fake_quantize"]["inserted"]
        assert n >= 2  # at least activation + weight of the fc matmul
        types = [op.type for op in main.global_block().ops]
        assert "fake_quantize_dequantize" in types
        out = exe.run(main, feed=feed, fetch_list=[y])[0]
        assert abs(float(out) - float(ref)) / (abs(float(ref)) + 1e-9) < 0.05
    finally:
        paddle.disable_static()


def test_int8_fake_quantize_pass_idempotent_and_clone_safe():
    """Double application must not stack fake-quant ops; a clone taken
    BEFORE the pass keeps its own un-quantized wiring (ops are never
    mutated in place); two quantization-type passes conflict."""
    import numpy as np
    import pytest as _pytest

    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu.distributed.passes import PassManager, new_pass

    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main_program=main,
                                  startup_program=startup):
            x = static.data(name="X", shape=[2, 4], dtype="float32")
            y = paddle.mean(static.nn.fc(x, 8))
        exe = static.Executor()
        exe.run(startup)
        feed = {"X": np.ones((2, 4), "float32")}
        clone = main.clone(for_test=True)

        p = new_pass("int8_fake_quantize")
        p.apply(main)
        n1 = sum(op.type == "fake_quantize_dequantize"
                 for op in main.global_block().ops)
        p.apply(main)  # second application: no stacking
        n2 = sum(op.type == "fake_quantize_dequantize"
                 for op in main.global_block().ops)
        assert n1 == n2 and n1 >= 2
        assert not any("@fake_quant@fake_quant" in v
                       for op in main.global_block().ops
                       for v in op.input_names + op.output_names)

        # the pre-pass clone still executes with its original wiring
        out = exe.run(clone, feed=feed, fetch_list=[clone.global_block()
                                                    .vars[y.name]])
        assert np.isfinite(float(out[0]))

        with _pytest.raises(ValueError):
            PassManager([new_pass("int8_fake_quantize"),
                         new_pass("int8_fake_quantize")])
    finally:
        paddle.disable_static()

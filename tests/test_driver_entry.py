"""Driver-contract tests: bench.py and __graft_entry__ must produce their
artifacts even when the accelerator tunnel is wedged (VERDICT r1 item #1).

The wedge is simulated by probe timeouts — a hung backend init and a
0-second-timeout probe are indistinguishable to the caller (both return None).
"""
import pytest
import json
import os
import subprocess
import sys


pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_probe_timeout_reads_as_dead():
    from paddle_tpu.device.probe import accelerator_backend, tpu_alive

    assert accelerator_backend(timeout=0.05) is None
    assert not tpu_alive(timeout=0.05)


def test_probe_never_hangs_the_caller():
    from paddle_tpu.device.probe import tpu_alive

    # Whatever state the machine's accelerator is in (absent, healthy-CPU-only,
    # or a wedged tunnel that ignores JAX_PLATFORMS env), the caller gets an
    # answer within the timeout instead of hanging.
    assert tpu_alive(timeout=15) in (True, False)


def test_bench_emits_json_when_tpu_dead(tmp_path):
    """No committed on-chip history -> honest CPU fallback, tagged."""
    env = {**os.environ,
           "PADDLE_TPU_BENCH_PROBE_TIMEOUT": "0.05",  # wedged-tunnel stand-in
           "PADDLE_TPU_BENCH_HISTORY": str(tmp_path / "none.jsonl"),
           "PADDLE_TPU_BENCH_STEPS": "2",
           "PADDLE_TPU_BENCH_BATCH": "2"}
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    line = p.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["value"] > 0
    assert payload["unit"] == "tokens/s/chip"
    assert payload["extra"]["degraded"] == "tpu_unavailable"
    assert payload["extra"]["platform"] == "cpu"


def test_bench_attaches_cached_tpu_result_when_tpu_dead(tmp_path):
    """With a committed on-chip history, a dead tunnel keeps the HONEST
    current (CPU fallback) headline value — replaying history as the
    top-level value would mask regressions — and attaches the best recorded
    on-chip measurement under extra.last_tpu_result with its own config and
    timestamp. Corrupt history lines must be skipped, not fatal."""
    hist = tmp_path / "hist.jsonl"
    hist.write_text(
        "not json\n" +
        json.dumps({"metric": "gpt_pretrain_tokens_per_sec_per_chip",
                    "extra": {"platform": "tpu"}}) + "\n" +  # no value
        json.dumps({"metric": "gpt_pretrain_tokens_per_sec_per_chip",
                    "value": None,
                    "extra": {"platform": "tpu"}}) + "\n" +  # null value
        json.dumps({"metric": "gpt_pretrain_tokens_per_sec_per_chip",
                    "value": 90000.0, "unit": "tokens/s/chip",
                    "extra": {"platform": "tpu", "ts": "2026-07-31T05:00:00",
                              "batch": 8}}) + "\n" +
        json.dumps({"metric": "gpt_pretrain_tokens_per_sec_per_chip",
                    "value": 93224.0, "unit": "tokens/s/chip",
                    "extra": {"platform": "tpu", "ts": "2026-07-31T05:10:00",
                              "batch": 16}}) + "\n")
    env = {**os.environ,
           "PADDLE_TPU_BENCH_PROBE_TIMEOUT": "0.05",
           "PADDLE_TPU_BENCH_STEPS": "2",
           "PADDLE_TPU_BENCH_BATCH": "2",
           "PADDLE_TPU_BENCH_HISTORY": str(hist)}
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    payload = json.loads(p.stdout.strip().splitlines()[-1])
    assert payload["extra"]["platform"] == "cpu"  # honest headline
    assert payload["extra"]["degraded"] == "tpu_unavailable"
    cached = payload["extra"]["last_tpu_result"]
    assert cached["value"] == 93224.0  # best valid entry, not latest
    assert cached["extra"]["platform"] == "tpu"
    assert cached["extra"]["ts"] == "2026-07-31T05:10:00"


def test_bench_sweep_picks_best_and_logs(tmp_path):
    """The self-sweeping orchestrator (BASELINE.md configs inside one driver
    invocation) must run every config within the generous budget and report
    the best attempt with a per-config sweep log."""
    env = {**os.environ,
           "PADDLE_TPU_BENCH_FORCE_SWEEP_CPU": "1",
           "PADDLE_TPU_BENCH_STEPS": "1",
           "PADDLE_TPU_BENCH_SWEEP_BUDGET": "3600"}
    env.pop("PADDLE_TPU_BENCH_BATCH", None)  # user-tuned env disables the sweep
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=900, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    payload = json.loads(p.stdout.strip().splitlines()[-1])
    sweep = payload["extra"]["sweep"]
    names = [s["config"] for s in sweep]
    assert names[0] == "default" and "batch16" in names, sweep
    ran = [s for s in sweep if isinstance(s["result"], (int, float))]
    assert ran, sweep
    assert payload["value"] == max(s["result"] for s in ran)


def test_dryrun_multichip_forces_virtual_cpu_mesh():
    # Fresh interpreter WITHOUT the conftest forcing: simulates the driver
    # process where a sitecustomize may freeze a dead accelerator platform.
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    code = ("import __graft_entry__ as g\n"
            "g.dryrun_multichip(4)\n"
            "print('DRYRUN_DONE')\n")
    p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "DRYRUN_DONE" in p.stdout

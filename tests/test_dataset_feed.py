"""C++ data feed + InMemoryDataset/QueueDataset tests (reference
data_feed.h:966 InMemoryDataFeed, fleet/dataset/dataset.py)."""
import numpy as np
import pytest

import paddle_tpu.distributed as dist


def _write_slot_file(path, rows, seed):
    """MultiSlot format: per line, for each slot '<n> v1 ... vn'.
    Slots: ids (sparse uint64), dense 3-float, label (1 float)."""
    rs = np.random.RandomState(seed)
    lines = []
    expect = []
    for _ in range(rows):
        nids = rs.randint(1, 5)
        ids = rs.randint(0, 10000, nids)
        dense = rs.rand(3).round(4)
        label = float(rs.randint(0, 2))
        lines.append(" ".join(
            [str(nids)] + [str(int(i)) for i in ids]
            + ["3"] + [f"{v:.4f}" for v in dense]
            + ["1", f"{label:.1f}"]))
        expect.append((ids, dense, label))
    path.write_text("\n".join(lines) + "\n")
    return expect


@pytest.fixture()
def slot_files(tmp_path):
    e1 = _write_slot_file(tmp_path / "part-0", 13, 0)
    e2 = _write_slot_file(tmp_path / "part-1", 9, 1)
    return [str(tmp_path / "part-0"), str(tmp_path / "part-1")], e1 + e2


def _make(batch_size=4):
    ds = dist.InMemoryDataset()
    ds.init(batch_size=batch_size, thread_num=2,
            use_var=[("ids", "sparse"), ("dense", "f"), ("label", "f")])
    return ds


class TestInMemoryDataset:
    def test_load_and_size(self, slot_files):
        files, expect = slot_files
        ds = _make()
        ds.set_filelist(files)
        n = ds.load_into_memory()
        assert n == 22
        assert ds.get_memory_data_size() == 22

    def test_batches_roundtrip(self, slot_files):
        files, expect = slot_files
        ds = _make(batch_size=5)
        ds.set_filelist(files)
        ds.load_into_memory()
        seen_rows = 0
        all_ids = []
        all_dense = []
        for batch in ds:
            vals, offs = batch["ids"]
            rows = len(offs) - 1
            assert batch["dense"].shape == (rows, 3)
            for r in range(rows):
                all_ids.append(vals[offs[r]:offs[r + 1]])
            all_dense.append(batch["dense"])
            seen_rows += rows
        assert seen_rows == 22
        # unshuffled: same order as files
        for got, (ids, dense, label) in zip(all_ids, expect):
            np.testing.assert_array_equal(got, ids.astype(np.uint64))
        np.testing.assert_allclose(np.concatenate(all_dense),
                                   np.stack([e[1] for e in expect]), rtol=1e-5)

    def test_global_shuffle_permutes(self, slot_files):
        files, expect = slot_files
        ds = _make(batch_size=22)
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(seed=7)
        batch = next(iter(ds))
        shuffled = batch["label"]
        if isinstance(shuffled, tuple):
            shuffled = shuffled[0].reshape(-1, 1)
        orig = np.array([e[2] for e in expect]).reshape(-1, 1)
        assert shuffled.shape == orig.shape
        # same multiset, (almost surely) different order
        np.testing.assert_allclose(np.sort(shuffled, 0), np.sort(orig, 0))
        assert not np.allclose(shuffled, orig)

    def test_release_memory(self, slot_files):
        files, _ = slot_files
        ds = _make()
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.release_memory()
        assert ds._feed is None


class TestQueueDataset:
    def test_streaming_matches_inmemory(self, slot_files):
        files, expect = slot_files
        qd = dist.QueueDataset()
        qd.init(batch_size=4, thread_num=1,
                use_var=[("ids", "sparse"), ("dense", "f"), ("label", "f")])
        qd.set_filelist(files)
        rows = 0
        denses = []
        for batch in qd:
            d = batch["dense"]
            rows += d.shape[0]
            denses.append(d)
        assert rows == 22
        np.testing.assert_allclose(np.concatenate(denses),
                                   np.stack([e[1] for e in expect]), rtol=1e-5)


def test_feeds_ps_model(slot_files, tmp_path):
    """End-to-end: the feed drives a DeepFM batch through a training step."""
    import paddle_tpu as paddle
    from paddle_tpu.models import DeepFM, ctr_loss

    files, _ = slot_files
    ds = _make(batch_size=8)
    ds.set_filelist(files)
    ds.load_into_memory()
    paddle.seed(0)
    net = DeepFM(sparse_feature_dim=10000, embedding_dim=4, num_fields=4,
                 dense_dim=3, hidden_sizes=(16,))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    for batch in ds:
        vals, offs = batch["ids"]
        # pad/truncate ragged ids to the model's fixed field count
        rows = len(offs) - 1
        ids = np.zeros((rows, 4), np.int64)
        for r in range(rows):
            row = vals[offs[r]:offs[r + 1]][:4]
            ids[r, :len(row)] = row.astype(np.int64)
        label = batch["label"]
        if isinstance(label, tuple):
            label = label[0].reshape(-1, 1)
        loss = ctr_loss(net(paddle.to_tensor(ids),
                            paddle.to_tensor(batch["dense"])),
                        paddle.to_tensor(label.astype(np.int64)))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert np.isfinite(float(loss))

"""tools/northstar_bench.py must stay runnable: the watcher queues it on
chip revival, and a bitrotted bench discovered at measurement time wastes
the tunnel window (VERDICT r3 #6)."""
import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_northstar_bench_smoke_all_configs():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "northstar_bench.py"),
         "--device", "cpu", "--smoke"],
        capture_output=True, text=True, timeout=540, cwd=repo)
    rows = [json.loads(ln) for ln in out.stdout.splitlines() if ln.strip()]
    assert len(rows) == 3, (out.stdout, out.stderr[-800:])
    by = {r["config"]: r for r in rows}
    for name in ("mnist_dygraph", "resnet50", "widedeep"):
        assert "error" not in by[name], by[name]
        assert by[name]["value"] > 0
    # the eager path must actually train (loss finite and sane)
    assert by["mnist_dygraph"]["final_loss"] < 3.0

"""Distributed pass plug-in surface (VERDICT §2 #8 partial: registry was
minimal) + FSStore (VERDICT §2 #26: HDFS-style store for PS barriers).

Reference: python/paddle/distributed/passes/pass_base.py (PassBase /
PassManager / new_pass) and paddle/fluid/framework/fleet/gloo_wrapper.h:134
(HdfsStore barrier files).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed.passes import (PassBase, PassContext, PassManager,
                                           new_pass, register_pass)
from paddle_tpu.distributed.fleet.fs import FSStore, LocalFS


def _program_with_gemm_dropout():
    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8], "float32")
            w = static.create_parameter([8, 16], "float32", name="w0")
            h = paddle.matmul(x, w)
            b = static.create_parameter([16], "float32", name="b0")
            h = h + b
            h = paddle.nn.functional.dropout(h, p=0.5)
            out = paddle.nn.functional.relu(h)
        return main, startup, out
    finally:
        paddle.disable_static()


def test_new_pass_factory_and_registry():
    p = new_pass("dead_code_elimination")
    assert isinstance(p, PassBase) and p.name == "dead_code_elimination"
    with pytest.raises(KeyError, match="unknown pass"):
        new_pass("nonexistent_pass")


def test_pass_manager_pipeline_rewrites_program():
    main, startup, out = _program_with_gemm_dropout()
    types_before = [op.type for op in main.global_block().ops]
    assert "dropout" in types_before

    pm = PassManager([new_pass("delete_dropout"),
                      new_pass("fuse_gemm_epilogue")])
    pm.apply(main)
    types_after = [op.type for op in main.global_block().ops]
    assert "dropout" not in types_after
    assert "fused_gemm_epilogue" in types_after
    assert pm.context.results["delete_dropout"] == 1
    assert pm.context.results["fuse_gemm_epilogue"] == 1

    # the rewritten program still executes and matches eval-mode eager math
    paddle.enable_static()
    try:
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        res = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
    finally:
        paddle.disable_static()
    assert res.shape == (4, 16)
    assert np.isfinite(res).all()
    assert (res >= 0).all()  # relu output


def test_custom_pass_plugs_in():
    @register_pass("count_ops_test")
    class CountOps(PassBase):
        def _apply_impl(self, program, context):
            return len(program.global_block().ops)

    main, _, _ = _program_with_gemm_dropout()
    ctx = PassContext()
    new_pass("count_ops_test").apply(main, ctx)
    assert ctx.results["count_ops_test"] == len(main.global_block().ops)


def test_pass_manager_conflict_detection():
    class A(PassBase):
        name = "a_test"

        def _check_conflict(self, other):
            return other.name != "b_test"

        def _apply_impl(self, program, context):
            return 0

    class B(PassBase):
        name = "b_test"

        def _apply_impl(self, program, context):
            return 0

    with pytest.raises(ValueError, match="conflicts"):
        PassManager([B(), A()])


# ---- FSStore ----------------------------------------------------------------

def test_fsstore_set_get_wait_delete(tmp_path):
    store = FSStore(LocalFS(), str(tmp_path / "store"), world_size=1)
    store.set("alpha/key", b"value1")
    assert store.get("alpha/key") == b"value1"
    assert store.list_keys("alpha") == ["alpha/key"]
    with pytest.raises(KeyError):
        store.get("missing", wait=False)
    assert store.delete_key("alpha/key") is True
    assert store.delete_key("alpha/key") is False
    with pytest.raises(TimeoutError):
        store.get("missing", wait=True, timeout=0.3)


def test_fsstore_barrier_across_workers(tmp_path):
    """Two 'nodes' rendezvous through per-rank marker files — the HdfsStore
    PS-barrier pattern, here over a shared local mount."""
    root = str(tmp_path / "store")
    s0 = FSStore(LocalFS(), root, world_size=2, rank=0, poll_interval=0.05)
    s1 = FSStore(LocalFS(), root, world_size=2, rank=1, poll_interval=0.05)

    reached = []

    def worker(store, rid):
        store.barrier("step0", timeout=10.0)
        reached.append(rid)

    t = threading.Thread(target=worker, args=(s1, 1))
    t.start()
    assert not reached  # rank 1 blocked until rank 0 arrives
    worker(s0, 0)
    t.join(timeout=10.0)
    assert sorted(reached) == [0, 1]

    with pytest.raises(TimeoutError, match="barrier"):
        s0.barrier("lonely", timeout=0.3)


def test_fsstore_barrier_reuse_does_not_leak_markers(tmp_path):
    """Reusing a barrier name must synchronize AGAIN — stale round-1 markers
    must not satisfy round 2 (regression: markers were never generational)."""
    root = str(tmp_path / "store")
    s0 = FSStore(LocalFS(), root, world_size=2, rank=0, poll_interval=0.05)
    s1 = FSStore(LocalFS(), root, world_size=2, rank=1, poll_interval=0.05)
    for _ in range(2):  # round 1 fills markers; round 2 must still block
        t = threading.Thread(target=s1.barrier, args=("loop",),
                             kwargs={"timeout": 10.0})
        t.start()
        s0.barrier("loop", timeout=10.0)
        t.join(timeout=10.0)
        assert not t.is_alive()
    # rank 0 alone on a 3rd round: must time out, not sail through
    with pytest.raises(TimeoutError):
        s0.barrier("loop", timeout=0.4)

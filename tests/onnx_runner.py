"""Independent ONNX validator for tests: a generic protobuf wire-format
decoder plus a tiny numpy evaluator for the op set paddle_tpu.onnx emits.
Deliberately separate from the exporter's encoder — round-tripping through
this reader catches wire-format mistakes, and executing the graph catches
semantic mis-mappings."""
from __future__ import annotations

import struct

import numpy as np


def decode_message(buf: bytes):
    """protobuf wire -> {field: [raw values]} (varint ints, bytes, f32)."""
    out = {}
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def _read_varint(buf, i):
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


_NP_DTYPE = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
             7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def decode_tensor(buf: bytes):
    m = decode_message(buf)
    dims = [int(d) for d in m.get(1, [])]
    dt = _NP_DTYPE[m[2][0]]
    name = m[8][0].decode() if 8 in m else ""
    if 9 in m:
        arr = np.frombuffer(m[9][0], dtype=dt).reshape(dims)
    else:
        raise ValueError("tensor without raw_data")
    return name, arr


def _decode_packed_int64(buf: bytes):
    vals, i = [], 0
    while i < len(buf):
        v, i = _read_varint(buf, i)
        if v >= 1 << 63:
            v -= 1 << 64
        vals.append(v)
    return vals


def decode_attr(buf: bytes):
    m = decode_message(buf)
    name = m[1][0].decode()
    atype = m.get(20, [0])[0]
    if atype == 1:
        return name, m[2][0]
    if atype == 2:
        v = m[3][0]
        if v >= 1 << 63:
            v -= 1 << 64
        return name, v
    if atype == 3:
        return name, m[4][0].decode()
    if atype == 4:
        return name, decode_tensor(m[5][0])[1]
    if atype == 6:
        raw = m[7][0]
        return name, [struct.unpack("<f", raw[i:i + 4])[0]
                      for i in range(0, len(raw), 4)]
    if atype == 7:
        return name, _decode_packed_int64(m[8][0])
    raise ValueError(f"attr type {atype}")


def load_model(path):
    with open(path, "rb") as f:
        m = decode_message(f.read())
    graph = decode_message(m[7][0])
    nodes = []
    for nb in graph.get(1, []):
        nm = decode_message(nb)
        attrs = dict(decode_attr(a) for a in nm.get(5, []))
        nodes.append({
            "inputs": [s.decode() for s in nm.get(1, [])],
            "outputs": [s.decode() for s in nm.get(2, [])],
            "op": nm[4][0].decode(), "attrs": attrs})
    inits = dict(decode_tensor(t) for t in graph.get(5, []))
    def vi_name(b):
        return decode_message(b)[1][0].decode()
    return {"nodes": nodes,
            "inputs": [vi_name(b) for b in graph.get(11, [])],
            "outputs": [vi_name(b) for b in graph.get(12, [])],
            "initializers": inits,
            "opset": decode_message(m[8][0])[2][0]}


# ------------------------------------------------------------------ evaluate


def _conv(x, w, strides, pads, dilations, group):
    n, cin, h, wd = x.shape
    cout, cpg, kh, kw = w.shape
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)])
    oh = (xp.shape[2] - (dilations[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (xp.shape[3] - (dilations[1] * (kw - 1) + 1)) // strides[1] + 1
    out = np.zeros((n, cout, oh, ow), x.dtype)
    cin_per_g = cin // group
    cout_per_g = cout // group
    for oc in range(cout):
        gidx = oc // cout_per_g
        for i in range(oh):
            for j in range(ow):
                hs, ws = i * strides[0], j * strides[1]
                patch = xp[:, gidx * cin_per_g:(gidx + 1) * cin_per_g,
                           hs:hs + dilations[0] * (kh - 1) + 1:dilations[0],
                           ws:ws + dilations[1] * (kw - 1) + 1:dilations[1]]
                out[:, oc, i, j] = np.einsum("nchw,chw->n", patch, w[oc])
    return out


def _pool(x, kernel, strides, pads, mode, dilations=(1, 1)):
    n, c, h, w = x.shape
    kh, kw = kernel
    dh, dw = dilations
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if mode == "max" else 0.0
    xp = np.pad(x, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)],
                constant_values=fill)
    eh, ew = dh * (kh - 1) + 1, dw * (kw - 1) + 1  # effective window extent
    oh = (xp.shape[2] - eh) // strides[0] + 1
    ow = (xp.shape[3] - ew) // strides[1] + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * strides[0]:i * strides[0] + eh:dh,
                     j * strides[1]:j * strides[1] + ew:dw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


_ONNX2NP = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
            11: np.float64}


def run_model(path, feeds):
    g = load_model(path)
    env = dict(g["initializers"])
    env.update(feeds)
    for nd in g["nodes"]:
        ins = [env[i] for i in nd["inputs"]]
        a = nd["attrs"]
        op = nd["op"]
        if op == "MatMul":
            r = ins[0] @ ins[1]
        elif op == "Add":
            r = ins[0] + ins[1]
        elif op == "Sub":
            r = ins[0] - ins[1]
        elif op == "Mul":
            r = ins[0] * ins[1]
        elif op == "Div":
            r = ins[0] / ins[1]
        elif op == "Max":
            r = np.maximum(ins[0], ins[1])
        elif op == "Min":
            r = np.minimum(ins[0], ins[1])
        elif op == "Pow":
            r = np.power(ins[0], ins[1])
        elif op == "Neg":
            r = -ins[0]
        elif op == "Exp":
            r = np.exp(ins[0])
        elif op == "Log":
            r = np.log(ins[0])
        elif op == "Tanh":
            r = np.tanh(ins[0])
        elif op == "Sigmoid":
            r = 1 / (1 + np.exp(-ins[0]))
        elif op == "Sqrt":
            r = np.sqrt(ins[0])
        elif op == "Erf":
            import scipy.special as sps
            r = sps.erf(ins[0])
        elif op == "Reciprocal":
            r = 1 / ins[0]
        elif op == "Mod":
            r = np.fmod(ins[0], ins[1]) if a.get("fmod") else np.mod(ins[0], ins[1])
        elif op == "IsInf":
            r = np.isinf(ins[0])
        elif op == "IsNaN":
            r = np.isnan(ins[0])
        elif op == "Not":
            r = np.logical_not(ins[0])
        elif op == "Or":
            r = np.logical_or(ins[0], ins[1])
        elif op == "And":
            r = np.logical_and(ins[0], ins[1])
        elif op == "Xor":
            r = np.logical_xor(ins[0], ins[1])
        elif op == "Equal":
            r = np.equal(ins[0], ins[1])
        elif op == "Less":
            r = np.less(ins[0], ins[1])
        elif op == "LessOrEqual":
            r = np.less_equal(ins[0], ins[1])
        elif op == "Greater":
            r = np.greater(ins[0], ins[1])
        elif op == "GreaterOrEqual":
            r = np.greater_equal(ins[0], ins[1])
        elif op == "Identity":
            r = ins[0]
        elif op == "Cast":
            r = ins[0].astype(_ONNX2NP[a["to"]])
        elif op == "Where":
            r = np.where(ins[0], ins[1], ins[2])
        elif op == "Reshape":
            r = ins[0].reshape([int(d) for d in ins[1]])
        elif op == "Expand":
            r = np.broadcast_to(ins[0], [int(d) for d in ins[1]]).copy()
        elif op == "Squeeze":
            r = np.squeeze(ins[0], tuple(int(d) for d in ins[1]))
        elif op == "Transpose":
            r = np.transpose(ins[0], a["perm"])
        elif op == "Concat":
            r = np.concatenate(ins, axis=a["axis"])
        elif op == "Slice":
            x, starts, ends, axes, steps = ins
            idx = [slice(None)] * x.ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                idx[int(ax)] = slice(int(s), int(e), int(st))
            r = x[tuple(idx)]
        elif op == "ReduceSum":
            r = ins[0].sum(tuple(int(d) for d in ins[1]),
                           keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMax":
            r = ins[0].max(tuple(a["axes"]),
                           keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMin":
            r = ins[0].min(tuple(a["axes"]),
                           keepdims=bool(a.get("keepdims", 1)))
        elif op == "ArgMax":
            r = np.argmax(ins[0], axis=a["axis"])
        elif op == "ArgMin":
            r = np.argmin(ins[0], axis=a["axis"])
        elif op == "Conv":
            r = _conv(ins[0], ins[1], a["strides"], a["pads"],
                      a["dilations"], a.get("group", 1))
            if len(ins) == 3:
                r = r + ins[2].reshape(1, -1, 1, 1)
        elif op == "MaxPool":
            r = _pool(ins[0], a["kernel_shape"], a["strides"], a["pads"],
                      "max", tuple(a.get("dilations", (1, 1))))
        elif op == "AveragePool":
            r = _pool(ins[0], a["kernel_shape"], a["strides"], a["pads"],
                      "avg")
        else:
            raise NotImplementedError(f"runner: op {op}")
        env[nd["outputs"][0]] = r
    return [env[o] for o in g["outputs"]]

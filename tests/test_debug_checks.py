"""Numeric/debug sentinels: FLAGS_enable_unused_var_check + op_bench harness.

Reference: framework/unused_var_check.cc (ops that declare-but-don't-read
inputs) and operators/benchmark/op_tester.cc (config-driven op latency).
"""
import json
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import dispatch
from paddle_tpu.core.tensor import Tensor


def test_unused_var_check_warns():
    paddle.set_flags({"enable_unused_var_check": True})
    dispatch._unused_var_warned.discard("bad_op")
    try:
        import jax.numpy as jnp

        a = Tensor(jnp.ones((4,)), stop_gradient=False)
        b = Tensor(jnp.ones((4,)), stop_gradient=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            dispatch.apply("bad_op", lambda x, y: x * 2.0, [a, b])
        assert any("never reads input(s) [1]" in str(x.message) for x in w), \
            [str(x.message) for x in w]

        # a well-formed op stays silent
        dispatch._unused_var_warned.discard("good_op")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            dispatch.apply("good_op", lambda x, y: x + y, [a, b])
        assert not [x for x in w if "never reads" in str(x.message)]
    finally:
        paddle.set_flags({"enable_unused_var_check": False})


def test_unused_var_check_warns_once():
    paddle.set_flags({"enable_unused_var_check": True})
    dispatch._unused_var_warned.discard("bad_once")
    try:
        import jax.numpy as jnp

        a = Tensor(jnp.ones((2,)), stop_gradient=False)
        b = Tensor(jnp.ones((2,)), stop_gradient=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            dispatch.apply("bad_once", lambda x, y: x, [a, b])
            dispatch.apply("bad_once", lambda x, y: x, [a, b])
        assert len([x for x in w if "never reads" in str(x.message)]) == 1
    finally:
        paddle.set_flags({"enable_unused_var_check": False})


def test_op_bench_harness(tmp_path):
    cfgs = [{"op": "matmul", "args": [[64, 64], [64, 64]], "dtype": "float32",
             "repeat": 3},
            {"op": "relu", "args": [[128, 128]], "dtype": "float32", "repeat": 3}]
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(json.dumps(cfgs))
    out = subprocess.run(
        [sys.executable, "tools/op_bench.py", "--config", str(cfg_file),
         "--device", "cpu"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert lines[0]["backend"] == "cpu"
    by_op = {l.get("op"): l for l in lines[1:]}
    assert "error" not in by_op["matmul"], by_op["matmul"]
    assert by_op["matmul"]["mean_us"] > 0
    assert by_op["relu"]["p50_us"] > 0

"""Launcher CLI + elastic manager.

Mirrors reference launcher tests (spawn local pods with env contract, watch,
restart) and elastic manager tests (membership, lease expiry, watch callbacks —
reference mocks etcd; we use the real C++ TCPStore)."""
import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.store import TCPStore


pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, extra_args=(), returncode=0):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(script_body))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log"), *extra_args, str(script)]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=180, cwd=REPO)
    assert res.returncode == returncode, (res.stdout, res.stderr)
    return res, tmp_path / "log"


def test_launch_single_proc_env_contract(tmp_path):
    res, log = _run_launch(tmp_path, """
        import os
        assert os.environ["PADDLE_TRAINER_ID"] == "0"
        assert os.environ["PADDLE_TRAINERS_NUM"] == "1"
        assert os.environ["PADDLE_CURRENT_ENDPOINT"]
        assert os.environ["TRAINING_ROLE"] == "TRAINER"
        print("env ok")
    """)
    assert "all 1 processes finished" in res.stdout
    assert "env ok" in (log / "workerlog.0.log").read_text()


def test_launch_multi_proc_ranks(tmp_path):
    res, log = _run_launch(tmp_path, """
        import os
        rank = os.environ["PADDLE_TRAINER_ID"]
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
        assert len(eps) == 4
        assert os.environ["PADDLE_CURRENT_ENDPOINT"] == eps[int(rank)]
        print(f"rank {rank} ok")
    """, extra_args=["--nproc_per_node", "4"])
    seen = set()
    for i in range(4):
        text = (log / f"workerlog.{i}.log").read_text()
        for r in range(4):
            if f"rank {r} ok" in text:
                seen.add(r)
    assert seen == {0, 1, 2, 3}


def test_launch_failure_terminates_pod(tmp_path):
    res, log = _run_launch(tmp_path, """
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(7)
        time.sleep(60)
    """, extra_args=["--nproc_per_node", "2"], returncode=7)
    assert "failed rc=7" in res.stderr


def test_launch_elastic_restart(tmp_path):
    marker = tmp_path / "attempts"
    res, log = _run_launch(tmp_path, f"""
        import os, sys
        p = {str(marker)!r}
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        sys.exit(0 if n >= 1 else 3)  # fail on first attempt, succeed on retry
    """, extra_args=["--elastic_level", "1", "--max_restarts", "2"])
    assert int(marker.read_text()) == 2
    assert "restart 1/2" in res.stdout


def test_launch_ps_mode_roles(tmp_path):
    res, log = _run_launch(tmp_path, """
        import os
        role = os.environ["TRAINING_ROLE"]
        if role == "PSERVER":
            assert os.environ["PADDLE_PORT"]
            assert os.environ["PADDLE_PSERVER_ID"] in ("0", "1")
        else:
            assert len(os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")) == 2
        print(role, "ok")
    """, extra_args=["--run_mode", "ps", "--server_num", "2",
                     "--trainer_num", "2"])
    texts = [(log / n).read_text() for n in
             ["server.0.log", "server.1.log", "trainer.0.log", "trainer.1.log"]]
    assert sum("PSERVER ok" in t for t in texts) == 2
    assert sum("TRAINER ok" in t for t in texts) == 2


# ---- elastic manager ----

@pytest.fixture()
def store():
    return TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10.0)


def test_elastic_membership_and_lease_expiry(store):
    m1 = ElasticManager(store, "job1", np=2, host="node-a",
                        heartbeat_interval=0.1, ttl=0.5)
    m2 = ElasticManager(store, "job1", np=2, host="node-b",
                        heartbeat_interval=0.1, ttl=0.5)
    m1.register()
    m2.register()
    assert m1.wait_for_np(2, timeout=5.0)
    assert m1.alive_nodes() == ["node-a", "node-b"]
    assert m1.health_status() == ElasticStatus.COMPLETED

    # node-b dies (heartbeat stops) -> lease expires -> scale-in restart
    m2.exit()
    time.sleep(1.0)
    assert m1.alive_nodes() == ["node-a"]
    m1.min_np = 1
    assert m1.health_status() == ElasticStatus.RESTART
    assert m1.endpoints_layout() == {"node-a": 0}
    m1.exit()


def test_elastic_watch_callback(store):
    events = []
    m1 = ElasticManager(store, "job2", np=1, host="w-0",
                        heartbeat_interval=0.1, ttl=1.0)
    m1.register()
    m1.watch(lambda members: events.append(list(members)))
    m2 = ElasticManager(store, "job2", np=1, host="w-1",
                        heartbeat_interval=0.1, ttl=1.0)
    m2.register()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not any("w-1" in e for e in events):
        time.sleep(0.05)
    assert any(e == ["w-0", "w-1"] for e in events), events
    m1.exit()
    m2.exit()


def test_elastic_hold_below_min(store):
    m = ElasticManager(store, "job3", np=4, min_np=2, host="solo",
                       heartbeat_interval=0.1, ttl=1.0)
    m.register()
    time.sleep(0.2)
    assert m.health_status() == ElasticStatus.HOLD  # 1 < min_np=2
    m.exit()


def test_multinode_endpoint_consistency(tmp_path):
    """Two launcher invocations (--nnodes 2) must hand every worker the SAME
    endpoint list and a worker MASTER_PORT distinct from the store port."""
    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "t.py"
    script.write_text(textwrap.dedent("""
        import os
        print("EPS=" + os.environ["PADDLE_TRAINER_ENDPOINTS"])
        print("MP=" + os.environ["MASTER_PORT"])
    """))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cmds = [[sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(nr), "--master",
             f"127.0.0.1:{port}", "--nproc_per_node", "2",
             "--job_id", "epjob", "--log_dir", str(tmp_path / f"log{nr}"),
             str(script)] for nr in range(2)]
    procs = [subprocess.Popen(c, env=env, cwd=REPO, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True) for c in cmds]
    for p in procs:
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0, out
    eps, mports = set(), set()
    for nr in range(2):
        for i in range(2):
            text = (tmp_path / f"log{nr}" / f"workerlog.{i}.log").read_text()
            eps.add([l for l in text.splitlines() if l.startswith("EPS=")][0])
            mports.add([l for l in text.splitlines() if l.startswith("MP=")][0])
    assert len(eps) == 1, f"endpoint lists disagree: {eps}"
    assert len(next(iter(eps)).removeprefix("EPS=").split(",")) == 4
    assert len(mports) == 1
    assert next(iter(mports)) != f"MP={port}", "worker MASTER_PORT = store port"


# ---- preemption notices (VERDICT r1 item #8, SURVEY §5.3) ----

def test_launcher_preemption_checkpoint_respawn_loss_continuity(tmp_path):
    """A preemption notice (file in log_dir) must make the launcher flag the
    workers, let them checkpoint, and respawn them; training resumes from the
    checkpoint — steps continue, loss keeps decreasing across the restart."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import json, os, time
        from paddle_tpu.distributed.fleet.elastic import preemption_requested

        ckpt = os.environ["CKPT_PATH"]
        step, w = 0, 10.0
        if os.path.exists(ckpt):
            state = json.load(open(ckpt))
            step, w = state["step"], state["w"]
            print(f"RESUMED step={step} w={w}", flush=True)
        while step < 10:
            if preemption_requested():
                print(f"PREEMPTED at step={step}", flush=True)
                raise SystemExit(0)
            step += 1
            w = w - 0.2 * w          # toy GD on f(w)=w^2/2... loss=w^2
            json.dump({"step": step, "w": w}, open(ckpt, "w"))
            print(f"STEP {step} LOSS {w*w:.6f}", flush=True)
            time.sleep(0.4)
        print("DONE", flush=True)
    """))
    log_dir = tmp_path / "log"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "CKPT_PATH": str(tmp_path / "ckpt.json")}
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(log_dir), "--elastic_level", "1",
           "--max_restarts", "3", str(script)]
    proc = subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)
    try:
        # wait until the worker is actually a few steps in
        wlog = log_dir / "workerlog.0.log"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if wlog.exists() and "STEP 2 " in wlog.read_text():
                break
            time.sleep(0.2)
        else:
            raise AssertionError("worker never reached step 2")
        (log_dir / "preempt.notice").write_text("maintenance in 30s")
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-3000:]
    text = (log_dir / "workerlog.0.log").read_text()
    assert "PREEMPTED at step=" in text          # worker saw the notice
    assert "RESUMED step=" in text               # ...and resumed from ckpt
    assert "DONE" in text
    steps = [int(l.split()[1]) for l in text.splitlines() if l.startswith("STEP")]
    assert steps == sorted(steps) and len(set(steps)) == 10, steps  # no reset
    losses = [float(l.split()[3]) for l in text.splitlines()
              if l.startswith("STEP")]
    assert all(b < a for a, b in zip(losses, losses[1:])), losses  # continuity
    assert "preemption notice" in out            # launcher logged the path


def test_manager_preemption_scale_in_two_nodes(store, tmp_path):
    """Store-key preemption notice on node-b: checkpoint, deregister, and the
    surviving node re-layouts endpoints and resumes from the checkpoint."""
    import json

    ma = ElasticManager(store, "jobP", np=2, min_np=1, host="node-a",
                        heartbeat_interval=0.1, ttl=0.5)
    mb = ElasticManager(store, "jobP", np=2, min_np=1, host="node-b",
                        heartbeat_interval=0.1, ttl=0.5)
    ma.register()
    mb.register()
    assert ma.wait_for_np(2, timeout=5.0)

    # phase 1: "training" on 2 nodes; node-b owns the shard state
    ckpt = tmp_path / "b.ckpt"
    w, losses = 8.0, []
    for step in range(3):
        w = w - 0.25 * w
        losses.append(w * w)
    ckpt.write_text(json.dumps({"step": 3, "w": w}))

    # infra preempts node-b; its watcher checkpoints + exits
    drained = []
    # clear=True: no launcher owns this notice in the manager-only scenario
    mb.on_preemption(lambda notice: drained.append(notice), clear=True)
    ma.announce_preemption(host="node-b", deadline_s=5.0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not drained:
        time.sleep(0.05)
    assert drained and drained[0]["deadline_s"] == 5.0
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and mb.preemption_notice() is not None:
        time.sleep(0.05)
    assert mb.preemption_notice() is None       # watcher cleared it
    mb.exit()

    # node-a notices the departure, re-layouts, resumes from b's checkpoint
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and ma.alive_nodes() != ["node-a"]:
        time.sleep(0.05)
    assert ma.alive_nodes() == ["node-a"]
    assert ma.health_status() == ElasticStatus.RESTART
    assert ma.endpoints_layout() == {"node-a": 0}
    state = json.loads(ckpt.read_text())
    assert state["step"] == 3
    w2 = state["w"]
    for step in range(3):
        w2 = w2 - 0.25 * w2
        losses.append(w2 * w2)
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
    ma.exit()

"""Semi-auto parallel: ProcessMesh, shard annotations, Engine fit/evaluate.

Mirrors reference auto_parallel tests (test_engine_api.py, completion/reshard
tests) on the virtual 8-device CPU mesh."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import (Engine, ProcessMesh, reshard,
                                                  shard_op, shard_tensor)
from paddle_tpu.io import Dataset


class RegDataset(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 16).astype("float32")
        w = rng.randn(16, 1).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(n, 1)).astype("float32")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def test_process_mesh_basics():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4]
    assert pm.get_dim_size("y") == 4
    assert pm.process_ids == list(range(8))
    mesh = pm.to_jax_mesh()
    assert mesh.axis_names == ("x", "y")
    assert mesh.devices.shape == (2, 4)


def test_shard_tensor_attaches_dist_attr():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    w = paddle.to_tensor(np.zeros((8, 4), dtype="float32"))
    shard_tensor(w, pm, ["x", None])
    from jax.sharding import PartitionSpec as P

    assert w.dist_attr == P("x", None)
    assert w.process_mesh is pm


def test_reshard_moves_to_new_spec():
    pm = ProcessMesh(list(range(8)), dim_names=["x"])
    t = paddle.to_tensor(np.arange(32, dtype="float32").reshape(8, 4))
    out = reshard(t, pm, ["x", None])
    np.testing.assert_array_equal(out.numpy(), t.numpy())
    assert "x" in str(out._data.sharding.spec)


def test_engine_fit_dp_default_mesh():
    """No annotations at all: Engine completes to data parallelism."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.Adam(
                        learning_rate=0.01, parameters=net.parameters()))
    history = engine.fit(RegDataset(), epochs=4, batch_size=16)
    assert history[-1] < history[0] * 0.5, history
    res = engine.evaluate(RegDataset(), batch_size=32)
    assert res["loss"] == pytest.approx(history[-1], rel=1.0)


def test_engine_fit_with_mp_annotations():
    """Column-sharded weights over a 2-D mesh: GSPMD completes the rest."""
    paddle.seed(0)
    pm = ProcessMesh(np.arange(8).reshape(2, 4).tolist(), dim_names=["dp", "mp"])
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 1))
    # column-parallel first layer, row-parallel second (reference dist_matmul)
    shard_tensor(net[0].weight, pm, [None, "mp"])
    shard_tensor(net[0].bias, pm, ["mp"])
    shard_tensor(net[2].weight, pm, ["mp", None])
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.Adam(
                        learning_rate=0.01, parameters=net.parameters()),
                    process_mesh=pm)
    engine.prepare()
    # param arrays materialized with the annotated shardings
    w0 = engine.params[[n for n in engine._param_names if n.endswith("0.weight")][0]]
    assert "mp" in str(w0.sharding.spec)
    history = engine.fit(RegDataset(), epochs=4, batch_size=16)
    # sharded reduction order shifts f32 rounding; assert convergence with a
    # margin rather than a knife-edge 2x (flaked at 6.533 vs 6.5025 in r2)
    assert history[-1] < history[0] * 0.7, history

    # parity: same model/data trained without any sharding
    paddle.seed(0)
    net2 = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                                paddle.nn.Linear(32, 1))
    engine2 = Engine(model=net2, loss=paddle.nn.MSELoss(),
                     optimizer=paddle.optimizer.Adam(
                         learning_rate=0.01, parameters=net2.parameters()))
    history2 = engine2.fit(RegDataset(), epochs=4, batch_size=16)
    # sharded matmuls reduce in a different order; small f32 drift compounds
    # across optimizer steps (chaotically near convergence), so parity is
    # statistical: same trajectory early, same order of magnitude late
    np.testing.assert_allclose(history[:2], history2[:2], rtol=0.1)
    assert history[-1] < history[0] * 0.7
    assert history2[-1] < history2[0] * 0.7


def test_engine_predict_and_save_load(tmp_path):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.1, parameters=net.parameters()))
    ds = RegDataset(n=32)
    engine.fit(ds, epochs=2, batch_size=16)
    preds = engine.predict(ds, batch_size=32)
    assert preds[0].shape == (32, 1)
    engine.save(str(tmp_path / "ap"))
    # a fresh engine loads the weights and predicts identically
    paddle.seed(1)
    net2 = paddle.nn.Sequential(paddle.nn.Linear(16, 8), paddle.nn.ReLU(),
                                paddle.nn.Linear(8, 1))
    engine2 = Engine(model=net2, loss=paddle.nn.MSELoss(),
                     optimizer=paddle.optimizer.SGD(
                         learning_rate=0.1, parameters=net2.parameters()))
    engine2.load(str(tmp_path / "ap"))
    preds2 = engine2.predict(ds, batch_size=32)
    np.testing.assert_allclose(preds[0], preds2[0], rtol=1e-5)


def test_shard_op_annotates_output():
    pm = ProcessMesh(list(range(8)), dim_names=["x"])
    matmul = shard_op(paddle.matmul, pm, out_shard_specs=[["x", None]])
    a = paddle.to_tensor(np.ones((8, 4), dtype="float32"))
    b = paddle.to_tensor(np.ones((4, 2), dtype="float32"))
    out = matmul(a, b)
    from jax.sharding import PartitionSpec as P

    assert out.dist_attr == P("x", None)


def test_engine_updates_batchnorm_running_stats():
    """Buffers thread through the pjit step: BN stats move during fit and are
    written back to the eager model."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 8), paddle.nn.BatchNorm1D(8),
                               paddle.nn.Linear(8, 1))
    bn = net[1]
    mean_before = bn._mean.numpy().copy()
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.01, parameters=net.parameters()))
    engine.fit(RegDataset(n=32), epochs=2, batch_size=16)
    assert not np.allclose(bn._mean.numpy(), mean_before), \
        "BatchNorm running mean never updated through the traced step"


def test_write_back_copies_not_aliases():
    """After fit, model params must survive a subsequent donated step."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 4), paddle.nn.ReLU(),
                               paddle.nn.Linear(4, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.01, parameters=net.parameters()))
    ds = RegDataset(n=32)
    engine.fit(ds, epochs=1, batch_size=16)
    snapshot = net[0].weight.numpy().copy()  # _write_back ran
    engine.fit(ds, epochs=1, batch_size=16)  # donates the engine buffers again
    _ = net.state_dict()  # must not raise "Array has been deleted"
    assert np.isfinite(snapshot).all()


def test_predict_restores_train_mode():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 4), paddle.nn.Dropout(0.5),
                               paddle.nn.Linear(4, 1))
    net.train()
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.01, parameters=net.parameters()))
    ds = RegDataset(n=32)
    engine.fit(ds, epochs=1, batch_size=16)
    engine.predict(ds, batch_size=16)
    assert net.training, "predict() leaked eval mode into the model"


def test_evaluate_runs_in_eval_mode():
    """Dropout must be off during evaluate(); repeated evals are deterministic."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 16), paddle.nn.Dropout(0.9),
                               paddle.nn.Linear(16, 1))
    net.train()
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.0, parameters=net.parameters()))
    ds = RegDataset(n=32)
    r1 = engine.evaluate(ds, batch_size=16)
    r2 = engine.evaluate(ds, batch_size=16)
    assert r1["loss"] == pytest.approx(r2["loss"], rel=1e-6)
    assert net.training  # restored


def test_partial_batch_raises_clear_error():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.1, parameters=net.parameters()))
    engine.prepare()
    engine._step_fn = engine._build(train=True)
    bad = [np.zeros((4, 16), "float32"), np.zeros((4, 1), "float32")]  # 4 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        engine._run_step(bad)


def test_fit_drops_partial_last_batch():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.1, parameters=net.parameters()))
    history = engine.fit(RegDataset(n=40), epochs=1, batch_size=16)  # 40 = 2x16 + 8
    assert np.isfinite(history[0])

"""BASELINE.md config analogues on the 8-device virtual CPU mesh.

Config 1 (MNIST LeNet dygraph) lives in test_mnist_e2e; config 4 (GPT hybrid
dp+mp+pp) in test_pipeline + __graft_entry__.dryrun_multichip; config 5
(Wide&Deep PS) in test_ps. This file adds the engine-path coverage for:
- config 2: ResNet DataParallel over the dp axis (imgs/sec path)
- config 3: ERNIE with ZeRO sharding (fleet sharding_stage2 analogue)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import set_hybrid_communicate_group


def _init(configs, sharding=False):
    set_hybrid_communicate_group(None)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = configs
    if sharding:
        strategy.sharding = True
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


def test_resnet_dp8_engine_step():
    """BASELINE config 2 analogue: ResNet18 DataParallel, batch sharded over
    dp=8; loss decreases over steps on a fixed batch."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    strategy = _init({"dp_degree": 8})
    paddle.seed(0)
    model = paddle.vision.models.resnet18(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    # loss_fn convention: model eats batch[:-1], loss_fn(outputs, labels)
    engine = fleet.distributed_engine(model, opt,
                                      loss_fn=paddle.nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.randn(16, 3, 32, 32).astype(np.float32))
    labels = paddle.to_tensor(rng.randint(0, 10, (16,)).astype(np.int64))
    losses = [float(engine.step(imgs, labels).item()) for _ in range(4)]
    assert losses[-1] < losses[0], losses


def test_ernie_sharding_engine_step():
    """BASELINE config 3 analogue: ERNIE pretraining objective under ZeRO
    optimizer-state sharding (sharding axis) x dp."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining

    strategy = _init({"dp_degree": 2, "sharding_degree": 4}, sharding=True)
    paddle.seed(0)
    cfg = ErnieConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                      num_heads=2, max_seq_len=64)
    model = ErnieForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    engine = fleet.distributed_engine(model, opt)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (8, 64)).astype(np.int64)
    mlm_labels = np.where(rng.rand(8, 64) < 0.15, ids, -100).astype(np.int64)
    losses = [float(engine.step(paddle.to_tensor(ids),
                                paddle.to_tensor(mlm_labels)).item())
              for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses

    # ZeRO check: optimizer states actually sharded over the sharding axis
    sharded = [n for n, spec in engine.opt_specs.items()
               if any(e == "sharding" for e in spec)]
    assert sharded, "no optimizer state carries the sharding axis"

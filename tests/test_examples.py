"""Smoke-run the examples/ scripts (subprocess, CPU) so they can't rot.

The two training-loop examples with heavier compiles (mnist dygraph, gpt
hybrid) are functionally covered by test_mnist_e2e / test_distributed; the
three here each exercise a surface no other example covers end-to-end:
static+dataset trainer stack, PS standalone mode, export->serve.
"""
import os
import subprocess
import sys

import pytest


pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BOOT = "import jax; jax.config.update('jax_platforms', 'cpu'); " \
        "import runpy; runpy.run_path(r'{path}', run_name='__main__')"


@pytest.mark.parametrize("example,expect", [
    ("static_train_from_dataset.py", "eval mse (no update):"),
    ("train_widedeep_ps.py", "step 8: loss"),
    ("export_and_serve.py", "predictor output matches eager forward"),
    ("generate_gpt.py", "decode ok: prompt"),
    ("serve_engine.py", "serving ok:"),
    ("quantize_int8.py", "ptq int8 output shape ok"),
    ("pallas_library_ops.py", "pallas layer_norm ok"),
])
def test_example_runs(example, expect):
    path = os.path.join(REPO, "examples", example)
    env = {**os.environ}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_PSERVERS_IP_PORT_LIST", None)  # force standalone PS mode
    res = subprocess.run(
        [sys.executable, "-c", _BOOT.format(path=path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert expect in res.stdout, res.stdout[-2000:]

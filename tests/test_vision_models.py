"""Model zoo forward-shape + grad smoke tests.

Mirrors reference python/paddle/tests/test_vision_models.py (instantiate each arch,
forward a small batch, check the logits shape)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models



pytestmark = pytest.mark.slow  # subprocess/e2e heavy: -m "not slow" skips

def _check(model, num_classes=10, size=64, in_ch=3, tuple_out=False):
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, in_ch, size, size)
                         .astype("float32"))
    model.eval()
    out = model(x)
    if tuple_out:
        out = out[0]
    assert tuple(out.shape) == (2, num_classes)
    return out


@pytest.mark.parametrize("factory", [
    models.resnet18, models.resnet34, models.resnet50,
    models.resnext50_32x4d, models.wide_resnet50_2,
])
def test_resnet_family(factory):
    _check(factory(num_classes=10), size=64)


@pytest.mark.parametrize("factory,bn", [(models.vgg11, False), (models.vgg16, True)])
def test_vgg(factory, bn):
    _check(factory(batch_norm=bn, num_classes=10), size=224)


def test_mobilenet_v1():
    _check(models.mobilenet_v1(num_classes=10), size=64)


def test_mobilenet_v2():
    _check(models.mobilenet_v2(num_classes=10), size=64)


@pytest.mark.parametrize("factory", [models.mobilenet_v3_small,
                                     models.mobilenet_v3_large])
def test_mobilenet_v3(factory):
    _check(factory(num_classes=10), size=64)


def test_densenet():
    _check(models.densenet121(num_classes=10), size=64)


def test_alexnet():
    _check(models.alexnet(num_classes=10), size=224)


def test_squeezenet():
    _check(models.squeezenet1_1(num_classes=10), size=224)


def test_googlenet_returns_aux_heads():
    model = models.googlenet(num_classes=10)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 224, 224)
                         .astype("float32"))
    model.eval()
    out, aux1, aux2 = model(x)
    assert tuple(out.shape) == (2, 10)
    assert tuple(aux1.shape) == (2, 10)
    assert tuple(aux2.shape) == (2, 10)


def test_inception_v3():
    _check(models.inception_v3(num_classes=10), size=299)


def test_shufflenet_v2():
    _check(models.shufflenet_v2_x0_25(num_classes=10), size=64)


def test_scaled_variants_build():
    models.mobilenet_v1(scale=0.5, num_classes=4)
    models.mobilenet_v2(scale=0.5, num_classes=4)
    models.shufflenet_v2_x0_5(num_classes=4)


def test_mobilenet_v2_grads_flow():
    paddle.seed(0)
    model = models.mobilenet_v2(scale=0.25, num_classes=4)
    model.train()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 32, 32)
                         .astype("float32"))
    y = paddle.to_tensor(np.array([0, 1], dtype="int64"))
    loss = paddle.nn.CrossEntropyLoss()(model(x), y)
    loss.backward()
    grads = [p.grad for p in model.parameters()]
    n_with_grad = sum(g is not None for g in grads)
    assert n_with_grad == len(grads), f"{len(grads) - n_with_grad} params missing grads"


def test_with_pool_false_and_no_classifier():
    model = models.resnet18(num_classes=0, with_pool=False)
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), dtype="float32"))
    out = model(x)
    assert out.ndim == 4  # raw feature map


def test_pretrained_asserts_everywhere():
    for factory in [models.resnet18, models.wide_resnet50_2, models.vgg11,
                    models.mobilenet_v1, models.alexnet, models.googlenet]:
        with pytest.raises(AssertionError):
            factory(pretrained=True)


def test_googlenet_aux_heads_without_pool():
    model = models.GoogLeNet(num_classes=5, with_pool=False)
    assert hasattr(model, "_pool_o1")  # aux pools exist even when with_pool=False


def test_shufflenet_swish_activation():
    from paddle_tpu import nn

    model = models.shufflenet_v2_swish(num_classes=4)
    acts = [type(s).__name__ for s in model.sublayers()]
    assert "Swish" in acts and "ReLU" not in acts


def test_squeezenet_feature_map_contract():
    model = models.SqueezeNet(version="1.1", num_classes=0, with_pool=False)
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), dtype="float32"))
    assert model(x).ndim == 4


def test_transforms_pipeline():
    from paddle_tpu.vision import transforms as T

    np.random.seed(0)
    img = np.random.rand(3, 32, 32).astype("float32")
    pipe = T.Compose([
        T.RandomCrop(28, padding=2), T.RandomHorizontalFlip(),
        T.RandomVerticalFlip(), T.ColorJitter(0.4, 0.4, 0.4),
        T.RandomRotation(15), T.Resize(32), T.Normalize(0.5, 0.5)])
    out = pipe(img)
    assert out.shape == (3, 32, 32) and np.isfinite(out).all()
    assert T.Grayscale(3)(img).shape == (3, 32, 32)
    assert T.RandomResizedCrop(24)(img).shape == (3, 24, 24)
    assert T.Pad(4)(img).shape == (3, 40, 40)
    assert T.CenterCrop(16)(img).shape == (3, 16, 16)


def test_transforms_edge_cases():
    from paddle_tpu.vision import transforms as T

    np.random.seed(0)
    # Grayscale on 2D / (1,H,W) inputs produces channel dims, not wide images
    assert T.Grayscale(3)(np.zeros((32, 32), "float32")).shape == (32, 32, 3)
    assert T.Grayscale(3)(np.zeros((1, 32, 32), "float32")).shape == (3, 32, 32)
    # asymmetric padding honored: (w=0, h=4) -> 28x28 grows to 36 high only
    out = T.RandomCrop(28, padding=(0, 4))(np.zeros((3, 28, 28), "float32"))
    assert out.shape == (3, 28, 28)
    # too-small image gives an actionable error
    import pytest as _pytest

    with _pytest.raises(ValueError, match="smaller than crop"):
        T.RandomCrop(32)(np.zeros((3, 28, 28), "float32"))
    # jitter factors never invert pixels even with value > 1
    img = np.full((3, 8, 8), 0.5, "float32")
    for _ in range(20):
        assert (T.BrightnessTransform(2.0)(img) >= 0).all()
    # hue jitter is wired through ColorJitter and preserves shape
    cj = T.ColorJitter(hue=0.4)
    assert cj(np.random.rand(3, 8, 8).astype("float32")).shape == (3, 8, 8)


def test_engine_small_dataset_trains_single_batch():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.io import Dataset

    class Tiny(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(8, 16).astype("float32")
            self.y = rng.randn(8, 1).astype("float32")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 8

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 1))
    engine = Engine(model=net, loss=paddle.nn.MSELoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.1, parameters=net.parameters()))
    history = engine.fit(Tiny(), epochs=1, batch_size=16)  # 8 < 16
    assert np.isfinite(history[0])

"""Static-mode extras: persistence (save/load, inference model round-trip),
utility ops (accuracy/auc/EMA/Print/py_func), control flow, and the LoD
sequence_* family (reference static/io.py, static/nn surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static

snn = static.nn


def t(a):
    return paddle.to_tensor(np.asarray(a))


def _seq(arr, lod):
    x = t(np.asarray(arr, np.float32))
    return snn.set_lod(x, lod)


class TestSequenceOps:
    def test_pool_variants(self):
        x = _seq(np.arange(10).reshape(5, 2), [0, 2, 5])
        np.testing.assert_allclose(snn.sequence_pool(x, "sum").numpy(),
                                   [[2, 4], [18, 21]])
        np.testing.assert_allclose(snn.sequence_pool(x, "average").numpy(),
                                   [[1, 2], [6, 7]])
        np.testing.assert_allclose(snn.sequence_pool(x, "max").numpy(),
                                   [[2, 3], [8, 9]])
        np.testing.assert_allclose(snn.sequence_first_step(x).numpy(),
                                   [[0, 1], [4, 5]])
        np.testing.assert_allclose(snn.sequence_last_step(x).numpy(),
                                   [[2, 3], [8, 9]])

    def test_softmax_per_sequence(self):
        x = _seq(np.zeros((5, 1)), [0, 2, 5])
        out = snn.sequence_softmax(x).numpy().reshape(-1)
        np.testing.assert_allclose(out[:2], [0.5, 0.5], rtol=1e-6)
        np.testing.assert_allclose(out[2:], [1 / 3] * 3, rtol=1e-6)

    def test_reverse_concat(self):
        x = _seq(np.arange(6).reshape(3, 2), [0, 1, 3])
        rev = snn.sequence_reverse(x).numpy()
        np.testing.assert_allclose(rev, [[0, 1], [4, 5], [2, 3]])
        y = _seq(np.arange(6, 10).reshape(2, 2), [0, 1, 2])
        cat = snn.sequence_concat([x, y])
        np.testing.assert_allclose(
            cat.numpy(), [[0, 1], [6, 7], [2, 3], [4, 5], [8, 9]])
        assert cat.lod == [0, 2, 5]

    def test_pad_unpad_roundtrip(self):
        x = _seq(np.arange(10).reshape(5, 2), [0, 2, 5])
        padded, lens = snn.sequence_pad(x, -1.0)
        assert padded.shape == [2, 3, 2]
        np.testing.assert_allclose(padded.numpy()[0, 2], [-1, -1])
        back = snn.sequence_unpad(padded, lens)
        np.testing.assert_allclose(back.numpy(), x.numpy())
        assert back.lod == [0, 2, 5]

    def test_expand_as(self):
        x = _seq(np.array([[1.0], [2.0]]), [0, 1, 2])
        y = _seq(np.zeros((5, 1)), [0, 2, 5])
        out = snn.sequence_expand_as(x, y)
        np.testing.assert_allclose(out.numpy().reshape(-1), [1, 1, 2, 2, 2])

    def test_slice_and_scatter(self):
        x = _seq(np.arange(10).reshape(5, 2), [0, 2, 5])
        out = snn.sequence_slice(x, t(np.array([0, 1])), t(np.array([1, 2])))
        np.testing.assert_allclose(out.numpy(), [[0, 1], [6, 7], [8, 9]])
        base = t(np.zeros((2, 4), np.float32))
        idx = snn.set_lod(t(np.array([[0], [3], [1]], np.int64)), [0, 1, 3])
        upd = snn.set_lod(t(np.array([[5.0], [6.0], [7.0]], np.float32)),
                          [0, 1, 3])
        res = snn.sequence_scatter(base, idx, upd)
        np.testing.assert_allclose(res.numpy(), [[5, 0, 0, 0], [0, 7, 0, 6]])

    def test_enumerate(self):
        x = snn.set_lod(t(np.array([[1], [2], [3], [4]], np.int64)), [0, 2, 4])
        out = snn.sequence_enumerate(x, 2, pad_value=0).numpy()
        np.testing.assert_array_equal(out, [[1, 2], [2, 0], [3, 4], [4, 0]])

    def test_conv_and_grad(self):
        paddle.seed(0)
        x = _seq(np.random.RandomState(0).rand(5, 3), [0, 2, 5])
        x.stop_gradient = False
        out = snn.sequence_conv(x, num_filters=4, filter_size=3)
        assert out.shape == [5, 4]
        out.sum().backward()
        assert x.grad is not None and x.grad.shape == [5, 3]

    def test_expand(self):
        x = _seq(np.array([[1.0], [2.0], [3.0]]), [0, 1, 3])
        y = _seq(np.zeros((5, 1)), [0, 2, 5])
        out = snn.sequence_expand(x, y)
        # seq0 ([1]) x2, seq1 ([2,3]) x3
        np.testing.assert_allclose(out.numpy().reshape(-1),
                                   [1, 1, 2, 3, 2, 3, 2, 3])

    def test_reshape(self):
        x = _seq(np.arange(12).reshape(6, 2), [0, 2, 6])
        out = snn.sequence_reshape(x, 4)
        assert out.shape == [3, 4] and out.lod == [0, 1, 3]


class TestControlFlowAPI:
    def test_cond_python(self):
        assert snn.cond(True, lambda: 1, lambda: 2) == 1

    def test_switch_case(self):
        out = snn.switch_case(t(np.int64(2)),
                              {1: lambda: t(np.float32(10.0)),
                               2: lambda: t(np.float32(20.0))})
        assert float(out) == 20.0
        out = snn.switch_case(5, {1: lambda: 1.0}, default=lambda: -1.0)
        assert out == -1.0

    def test_while_loop(self):
        out = snn.while_loop(lambda i, s: i < 4, lambda i, s: (i + 1, s + i),
                             [0, 0])
        assert tuple(out) == (4, 6)


class TestStaticLayersMisc:
    def test_prelu_spectral(self):
        paddle.seed(0)
        x = t(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        assert snn.prelu(x).shape == [2, 4]
        w = t(np.random.RandomState(0).rand(4, 6).astype(np.float32))
        sn = snn.spectral_norm(w, power_iters=10)
        s = np.linalg.svd(sn.numpy(), compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=5e-2)

    def test_bilinear_tensor_product(self):
        paddle.seed(0)
        x = t(np.random.RandomState(0).rand(3, 4).astype(np.float32))
        y = t(np.random.RandomState(1).rand(3, 5).astype(np.float32))
        assert snn.bilinear_tensor_product(x, y, 6).shape == [3, 6]

    def test_row_conv(self):
        paddle.seed(0)
        x = t(np.random.RandomState(0).rand(2, 5, 3).astype(np.float32))
        assert snn.row_conv(x, 2).shape == [2, 5, 3]

    def test_nce_trains(self):
        paddle.seed(0)
        x = t(np.random.RandomState(0).rand(8, 6).astype(np.float32))
        lab = t(np.random.RandomState(1).randint(0, 50, (8, 1)).astype(np.int64))
        loss = snn.nce(x, lab, num_total_classes=50, num_neg_samples=5)
        assert loss.shape == [8, 1]
        assert np.isfinite(loss.numpy()).all()

    def test_crf_decoding(self):
        # transitions force tag alternation
        em = np.zeros((1, 4, 2), np.float32)
        trans = np.array([[1.0, 0.0],   # start: prefer tag 0
                          [0.0, 0.0],   # stop
                          [-5.0, 5.0],  # from 0 -> 1
                          [5.0, -5.0]], np.float32)  # from 1 -> 0
        path = snn.crf_decoding(t(em), transition=t(trans)).numpy()
        np.testing.assert_array_equal(path[0], [0, 1, 0, 1])

    def test_accuracy_auc(self):
        pred = t(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
        lab = t(np.array([[1], [0]], np.int64))
        assert float(static.accuracy(pred, lab)) == 1.0
        auc_v = float(static.auc(pred, lab))
        assert 0.99 <= auc_v <= 1.0

    def test_multi_box_head(self):
        paddle.seed(0)
        feats = [t(np.random.RandomState(i).rand(1, 8, s, s).astype(np.float32))
                 for i, s in enumerate([8, 4])]
        img = t(np.zeros((1, 3, 64, 64), np.float32))
        locs, confs, boxes, var = snn.multi_box_head(
            feats, img, base_size=64, num_classes=3, aspect_ratios=[[2.0], [2.0]],
            min_ratio=20, max_ratio=90)
        assert locs.shape[0] == 1 and locs.shape[2] == 4
        assert confs.shape[2] == 3
        assert boxes.shape[0] == locs.shape[1]


class TestStaticPersistence:
    def _train_program(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [4, 8], "float32")
            y = snn.fc(x, 2)
        paddle.disable_static()
        return main, y

    def test_save_load_roundtrip(self, tmp_path):
        main, y = self._train_program()
        exe = static.Executor()
        feed = {"x": np.random.RandomState(0).rand(4, 8).astype(np.float32)}
        out1 = exe.run(main, feed=feed, fetch_list=[y])
        static.save(main, str(tmp_path / "model"))
        state = static.load_program_state(str(tmp_path / "model"))
        assert state  # params present
        # perturb then restore
        for n, v in main._captures.items():
            v._data = v._data * 0
        static.set_program_state(main, state)
        out2 = static.Executor().run(main, feed=feed, fetch_list=[y])
        np.testing.assert_allclose(out1[0], out2[0], rtol=1e-6)

    def test_inference_model_roundtrip(self, tmp_path):
        main, y = self._train_program()
        exe = static.Executor()
        feed = {"x": np.random.RandomState(0).rand(4, 8).astype(np.float32)}
        ref = exe.run(main, feed=feed, fetch_list=[y])
        xvar = main.global_block().var("x")
        static.save_inference_model(str(tmp_path / "inf"), [xvar], [y],
                                    program=main)
        prog2, feeds, fetches = static.load_inference_model(str(tmp_path / "inf"))
        assert feeds == ["x"]
        out = static.Executor().run(prog2, feed=feed, fetch_list=fetches)
        np.testing.assert_allclose(ref[0], out[0], rtol=1e-6)

    def test_ema(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False)
        ema = static.ExponentialMovingAverage(decay=0.5).bind(lin.parameters())
        w0 = lin.weight.numpy().copy()
        ema.update()
        lin.weight.set_value(w0 * 3)
        ema.update()
        with ema.apply():
            inside = lin.weight.numpy().copy()
        outside = lin.weight.numpy()
        np.testing.assert_allclose(outside, w0 * 3, rtol=1e-6)
        assert not np.allclose(inside, outside)  # shadow applied inside

    def test_py_func_and_print(self):
        x = t(np.array([1.0, 2.0], np.float32))
        out = static.py_func(lambda a: a * 3, x, x)
        np.testing.assert_allclose(out.numpy(), [3.0, 6.0])
        static.Print(x, message="dbg")  # must not crash

    def test_places_helpers(self):
        assert len(static.cpu_places(2)) == 2
        assert static.cuda_places([0])[0].device_id == 0
        assert static.xpu_places() and static.npu_places() and static.mlu_places()

    def test_create_global_var(self):
        paddle.enable_static()
        main = static.Program()
        with static.program_guard(main, static.Program()):
            v = static.create_global_var([2, 2], 1.5, "float32", persistable=True)
        paddle.disable_static()
        np.testing.assert_allclose(v.numpy(), np.full((2, 2), 1.5))

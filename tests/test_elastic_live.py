"""Live elastic autoscaling (distributed/membership.py + engine.reform_mesh).

The contract under test: an in-memory mesh reformation (dp4→dp2→dp4) is
bit-identical — params, optimizer state, and the continued loss curve — to
the checkpoint-restore path onto the same topology change, for both the
replicated and ZeRO optimizer layouts. Plus the membership protocol itself
(leases, expiry eviction, generation bumps + GC), the failure path (flight
dump + restore_latest fallback instead of a hang), and the serving-replica
drain. The full SIGTERM dp8→dp6→dp8 drill with real worker processes lives
in tools/elastic_drill.py / __graft_entry__ phase 12; these tests pin every
branch on cheap engines.
"""
import json
import os
import signal
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.distributed import membership
from paddle_tpu.distributed.elastic import (CheckpointManager, live_reshard,
                                            restore_latest)
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.membership import (ElasticCoordinator,
                                               WorkerAgent,
                                               bump_generation,
                                               current_generation)
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)
from paddle_tpu.distributed.store import FileStore


def _hcg(dp):
    set_hybrid_communicate_group(None)
    return HybridCommunicateGroup(dp_degree=dp, devices=jax.devices()[:dp])


def _make(dp=4, zero=False, seed=0):
    hcg = _hcg(dp)
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           hcg=hcg, zero_update=zero)


def _batch(n=32):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def _losses(eng, x, y, steps):
    return [float(eng.step(x, y).item()) for _ in range(steps)]


def _param_bytes(eng):
    return {n: np.asarray(eng.params[n]).tobytes()
            for n in eng._param_names}


def _opt_bytes(eng):
    if eng._zero_opt is not None:
        n = eng._n_grad_elems()
        return tuple(np.asarray(f)[:n].tobytes() for f in eng._zero_opt)
    return {n: tuple(np.asarray(s).tobytes() for s in eng.opt_state[n])
            for n in eng._param_names}


def _stat(name):
    return monitor.stat(name).get()


# --------------------------------------------- live reshard bit-equality

@pytest.mark.parametrize("zero", [False, True], ids=["replicated", "zero"])
def test_live_reshard_bit_identical_to_restore(tmp_path, zero):
    """dp4→dp2→dp4: at each boundary the live in-memory reshard must land
    exactly where checkpoint-restore onto the same topology lands —
    params, opt state, and every continued loss bit-for-bit."""
    x, y = _batch()
    live = _make(dp=4, zero=zero)
    _losses(live, x, y, 3)

    for leg, dp in enumerate((2, 4)):
        ckdir = str(tmp_path / f"leg{leg}")
        mgr = CheckpointManager(ckdir, async_save=False)
        mgr.save(live, block=True)
        mgr.close()
        ctrl = _make(dp=dp, zero=zero, seed=7)  # different init on purpose
        if zero:
            _losses(ctrl, x, y, 1)  # engage ZeRO so the target layout exists
        restore_latest(ctrl, ckdir)

        pause_ms = live_reshard(live, _hcg(dp))
        assert pause_ms >= 0.0
        assert live.hcg.degrees["dp"] == dp
        assert live.mesh.devices.size == dp

        assert _param_bytes(live) == _param_bytes(ctrl)
        assert _opt_bytes(live) == _opt_bytes(ctrl)
        assert _losses(live, x, y, 3) == _losses(ctrl, x, y, 3)


def test_reform_mesh_drops_compiled_state():
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 1)
    assert eng._step_fn is not None
    eng.reform_mesh(_hcg(2))
    assert eng._step_fn is None
    assert eng._batch_shardings is None
    assert eng._lr_cache == (None, None)
    assert eng._zero_reason == "unset"
    # and it still trains at the new world size
    _losses(eng, x, y, 1)


def test_reform_mesh_zero_repads_flat_shards():
    """The ZeRO flat buffer re-pads to the new replica count; real elements
    survive exactly, the pad tail is zeros."""
    eng = _make(dp=4, zero=True)
    x, y = _batch()
    _losses(eng, x, y, 2)
    n = eng._n_grad_elems()
    before = [np.asarray(f)[:n].copy() for f in eng._zero_opt]
    eng.reform_mesh(_hcg(2))
    for f, b in zip(eng._zero_opt, before):
        host = np.asarray(f)
        assert host[:n].tobytes() == b.tobytes()
        assert not host[n:].any()


# ----------------------------------------------------- membership protocol

def test_worker_agent_lease_lifecycle(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    a = WorkerAgent(store, "w0", lease_s=5.0)
    b = WorkerAgent(store, "w1", lease_s=5.0)
    a.register()
    b.register()
    assert sorted(coord.live_members()) == ["w0", "w1"]

    joins0 = _stat("elastic.leaves")
    b.announce_leave("sigterm")
    assert sorted(coord.live_members()) == ["w0"]
    assert _stat("elastic.leaves") == joins0 + 1
    raw = store.get(membership.member_key(0, "w1", "leave"), wait=False)
    assert json.loads(raw.decode())["reason"] == "sigterm"


def test_lease_expiry_evicts_and_counts(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=0.05)
    a = WorkerAgent(store, "w0", lease_s=0.05)
    a.register()
    exp0 = _stat("elastic.lease_expiries")
    sexp0 = _stat("store.lease_expiries")
    time.sleep(0.1)  # no heartbeat: the lease lapses
    assert coord.live_members() == {}
    assert _stat("elastic.lease_expiries") == exp0 + 1
    assert _stat("store.lease_expiries") == sexp0 + 1
    # the expired key was evicted, not just skipped
    assert store.list_keys("__elastic__/gen0/member/") == []


def test_heartbeat_follows_generation_bump(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    a = WorkerAgent(store, "w0", lease_s=5.0)
    a.register()
    g1 = bump_generation(store)
    assert current_generation(store) == g1
    a.heartbeat()  # re-registers under the new generation
    assert store.list_keys(f"__elastic__/gen{g1}/member/") == [
        f"__elastic__/gen{g1}/member/w0"]


def test_generation_scoped_barrier_and_gc(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    # same name, different generations: fully independent namespaces
    store.barrier("sync", world_size=1, generation=1)
    store.barrier("sync", world_size=1, generation=2)
    assert store.list_keys("__barrier__/gen1/") != []
    gc0 = _stat("store.gc_keys")
    removed = store.gc_generation(1)
    assert removed >= 1
    assert store.list_keys("__barrier__/gen1/") == []
    assert store.list_keys("__barrier__/gen2/") != []
    assert _stat("store.gc_keys") == gc0 + removed


def test_coordinator_reforms_on_membership_change(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    agents = [WorkerAgent(store, f"w{i}", lease_s=5.0) for i in range(4)]
    for a in agents:
        a.register()

    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 2)
    assert coord.maybe_reform(eng) is False  # 4 live == dp4: no change

    ref0 = _stat("elastic.reformations")
    agents[3].announce_leave("sigterm")
    agents[2].announce_leave("sigterm")
    gen_before = coord.generation()
    assert coord.maybe_reform(eng) is True
    assert eng.hcg.degrees["dp"] == 2
    assert coord.generation() == gen_before + 1
    assert _stat("elastic.reformations") == ref0 + 1
    assert coord.last_pause_ms is not None and coord.last_pause_ms >= 0.0
    # dead generation's keys are GC'd; survivors carried into the new one
    assert store.list_keys(f"__elastic__/gen{gen_before}/") == []
    assert sorted(coord.live_members()) == ["w0", "w1"]
    _losses(eng, x, y, 1)  # trains at the new world size

    # grow back: two new workers join
    for i in (2, 3):
        WorkerAgent(store, f"w{i}", lease_s=5.0).register()
    assert coord.maybe_reform(eng) is True
    assert eng.hcg.degrees["dp"] == 4
    _losses(eng, x, y, 1)


def test_on_step_counts_resumed_steps(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0, check_interval=1)
    for i in range(2):
        WorkerAgent(store, f"w{i}", lease_s=5.0).register()
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 1)
    r0 = _stat("elastic.resumed_steps")
    assert coord.on_step(eng) is True  # 2 live members -> dp2
    _losses(eng, x, y, 2)
    coord.on_step(eng)
    coord.on_step(eng)
    assert _stat("elastic.resumed_steps") == r0 + 2


# ------------------------------------------------------------ failure path

def test_failed_reform_dumps_flight_and_falls_back(tmp_path, monkeypatch):
    """Lease timeout mid-reshard: the coordinator must dump an
    elastic_reform_<gen> ring and restore_latest instead of hanging —
    and the engine must still be usable."""
    from paddle_tpu.observability import flight_recorder as fl

    flight_dir = tmp_path / "flight"
    fl.enable(str(flight_dir))
    try:
        store = FileStore(str(tmp_path / "store"), timeout=2.0)
        ckdir = str(tmp_path / "ckpt")
        eng = _make(dp=4)
        x, y = _batch()
        _losses(eng, x, y, 3)
        mgr = CheckpointManager(ckdir, async_save=False)
        mgr.save(eng, block=True)
        mgr.close()

        coord = ElasticCoordinator(store, lease_s=5.0, ckpt_dir=ckdir)
        for i in range(2):
            WorkerAgent(store, f"w{i}", lease_s=5.0).register()

        def _boom():
            raise TimeoutError("lease expired mid-reshard")

        coord._fault_hook = _boom
        fails0 = _stat("elastic.reform_failures")
        assert coord.maybe_reform(eng) is False  # fell back, no reform
        assert _stat("elastic.reform_failures") == fails0 + 1
        assert eng.hcg.degrees["dp"] == 4        # still on the old mesh
        assert eng._step_count == 3              # restored, not lost
        dumps = [p for p in os.listdir(flight_dir)
                 if "elastic_reform_" in p]
        assert dumps, os.listdir(flight_dir)
        payload = json.loads(
            (flight_dir / dumps[0] / "state.json").read_text())
        extra = payload["extra"]
        assert "lease expired" in extra["error"]
        assert extra["membership"]["members"]
        _losses(eng, x, y, 1)
    finally:
        fl.disable()


def test_failed_reform_without_ckpt_raises(tmp_path):
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    for i in range(2):
        WorkerAgent(store, f"w{i}", lease_s=5.0).register()
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 1)

    def _boom():
        raise TimeoutError("lease expired mid-reshard")

    coord._fault_hook = _boom
    with pytest.raises(TimeoutError):
        coord.maybe_reform(eng)
    assert eng.hcg.degrees["dp"] == 4  # atomic: old mesh intact


def test_mismatched_generation_fails_reform(tmp_path):
    """A second generation bump landing mid-reshard (another coordinator,
    a racing join) must fail the reformation loudly, not silently commit."""
    store = FileStore(str(tmp_path), timeout=2.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    for i in range(2):
        WorkerAgent(store, f"w{i}", lease_s=5.0).register()
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 1)
    coord._fault_hook = lambda: bump_generation(store)
    with pytest.raises(RuntimeError, match="generation moved"):
        coord.maybe_reform(eng)


# ------------------------------------------------------------ serving drain

def _tiny_serving():
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving.engine import ServingEngine

    set_hybrid_communicate_group(None)
    paddle.seed(0)
    model = GPTForPretraining(gpt_tiny()).eval()
    return ServingEngine(model, slot_count=2, ladder=(8,), max_new_cap=8,
                         steps_per_dispatch=2)


def test_serving_drain_completes_active_refuses_new(tmp_path):
    eng = _tiny_serving()
    store = FileStore(str(tmp_path), timeout=2.0)
    eng.register_replica(store, "r0", lease_s=5.0)
    coord = ElasticCoordinator(store, lease_s=5.0)
    assert sorted(coord.live_members(kind="replica")) == ["r0"]

    r1 = eng.submit([1, 2, 3], max_new_tokens=4)
    eng.step()  # admit + first decode chunk
    eng.begin_drain()
    with pytest.raises(RuntimeError, match="draining"):
        eng.submit([4, 5], max_new_tokens=2)
    done = eng.drain(timeout_s=30.0)
    assert r1 in done and r1.done
    assert not eng._active.any()
    assert eng.stats()["draining"] is True
    # the replica lease is gone and the leave announcement is a preemption-
    # style record the coordinator can read
    assert coord.live_members(kind="replica") == {}


def test_serving_sigterm_sets_drain_flag(tmp_path):
    eng = _tiny_serving()
    eng.install_sigterm_handler()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)
        assert eng._draining is True
        with pytest.raises(RuntimeError, match="draining"):
            eng.submit([1, 2], max_new_tokens=2)
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)


# ------------------------------------------------------- FileStore parity

def test_filestore_bounded_get_and_wait(tmp_path):
    store = FileStore(str(tmp_path), timeout=0.2)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        store.get("nope")          # store-level default bound
    with pytest.raises(TimeoutError):
        store.wait(["nope"], timeout=0.1)
    assert time.monotonic() - t0 < 5.0
    with pytest.raises(KeyError):
        store.get("nope", wait=False)


def test_filestore_delete_and_list(tmp_path):
    store = FileStore(str(tmp_path))
    store.set("a/b", b"1")
    store.set("a/c", b"2")
    store.set("z", b"3")
    store.add("ctr", 1)  # exercises the .lock file: must stay invisible
    assert store.list_keys("a/") == ["a/b", "a/c"]
    assert store.num_keys() == 4
    assert store.delete_key("a/b") is True
    assert store.delete_key("a/b") is False
    assert store.list_keys("a/") == ["a/c"]

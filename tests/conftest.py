"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax import so multi-chip
sharding tests run without TPU hardware (SURVEY.md §4 test pyramid, level 2)."""
import os

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
# The session may pre-set JAX_PLATFORMS to the real accelerator (and a sitecustomize may
# import jax at interpreter start, freezing the env value into jax config) — so force the
# platform through jax.config. Unit tests always run on the virtual CPU mesh (fast,
# deterministic f32). Set PADDLE_TPU_TEST_DEVICE=tpu to run against the real chip.
if os.environ.get("PADDLE_TPU_TEST_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # fast subset for 1-core bench boxes (README "Testing"):
    #   python -m pytest tests -m "not slow" -q     (~ minutes)
    # full suite spawns subprocess clusters and e2e training runs (~20 min).
    config.addinivalue_line(
        "markers", "slow: subprocess-cluster / end-to-end tests; deselect "
        "with -m 'not slow' on constrained machines")

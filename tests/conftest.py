"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax import so multi-chip
sharding tests run without TPU hardware (SURVEY.md §4 test pyramid, level 2)."""
import os

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
# The session may pre-set JAX_PLATFORMS to the real accelerator (and a sitecustomize may
# import jax at interpreter start, freezing the env value into jax config) — so force the
# platform through jax.config. Unit tests always run on the virtual CPU mesh (fast,
# deterministic f32). Set PADDLE_TPU_TEST_DEVICE=tpu to run against the real chip.
if os.environ.get("PADDLE_TPU_TEST_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


import sys

import pytest

# jax tracing is deeply recursive (export -> grad of custom_vjp -> pallas
# index-map traces nest hundreds of frames) and pytest adds its own stack on
# top; the lm_loss Mosaic-export gate sat within ~100 frames of CPython's
# default 1000 and tipped over. Match the reference's posture of configuring
# interpreter limits for the test run (its dy2static tests raise the limit
# for AST recursion the same way).
if sys.getrecursionlimit() < 3000:
    sys.setrecursionlimit(3000)


@pytest.fixture(autouse=True)
def _isolate_global_state():
    """Reset process-wide state before every test (VERDICT r2 #6).

    Tests previously leaked HCG topology, FLAGS values, the global RNG, and
    the default float dtype into later tests, making the suite
    order-dependent (test_engine_fit_with_mp_annotations failed only in the
    full run). Mirrors the reference's per-test scope guard
    (`test/legacy_test/op_test.py` fresh-scope-per-test discipline).
    """
    import paddle_tpu as paddle
    from paddle_tpu.core import dtype as _dtype, flags as _flags
    from paddle_tpu.distributed import fleet as _fleet_mod
    from paddle_tpu.distributed.mesh import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    # the Fleet singleton caches _hcg/_strategy/_is_initialized independently
    # of the global HCG — reset it too or fleet-lazy-init tests inherit the
    # previous test's topology
    _fleet_mod.fleet.__init__()
    # restore flags to their bootstrap values through set_flags so value-keyed
    # caches (dispatch rule cache) are invalidated, never silently stale
    snap = dict(_FLAG_SNAPSHOT)
    changed = {k: v for k, v in snap.items() if _flags._REGISTRY.get(k) != v}
    if changed:
        _flags.set_flags(changed)
    # the restore itself must not count as "explicitly set" (flags.was_set)
    _flags._explicitly_set.clear()
    _flags._explicitly_set.update(_EXPLICIT_SNAPSHOT)
    _dtype._default_float_dtype = _dtype.float32
    paddle.seed(0)
    yield


def pytest_collection_modifyitems(config, items):
    # PADDLE_TPU_TEST_SHUFFLE=<seed> runs the suite in a seeded random order
    # to prove order-independence (VERDICT r2 #6 acceptance).
    shuf = os.environ.get("PADDLE_TPU_TEST_SHUFFLE")
    if shuf:
        import random

        random.Random(int(shuf)).shuffle(items)


def pytest_configure(config):
    from paddle_tpu.core import flags as _flags

    global _FLAG_SNAPSHOT, _EXPLICIT_SNAPSHOT
    _FLAG_SNAPSHOT = dict(_flags._REGISTRY)
    _EXPLICIT_SNAPSHOT = frozenset(_flags._explicitly_set)
    # fast subset for 1-core bench boxes (README "Testing"):
    #   python -m pytest tests -m "not slow" -q     (~ minutes)
    # full suite spawns subprocess clusters and e2e training runs (~20 min).
    config.addinivalue_line(
        "markers", "slow: subprocess-cluster / end-to-end tests; deselect "
        "with -m 'not slow' on constrained machines")

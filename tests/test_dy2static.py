"""Dygraph->static AST transpiler tests: tensor-dependent if/while/for under
@to_static become lax.cond/while_loop inside the traced program (reference
dygraph_to_static transformer suite)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import dy2static


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestRuntimeOps:
    def test_convert_ifelse_python_pred(self):
        assert dy2static.convert_ifelse(True, lambda: (1,), lambda: (2,)) == (1,)
        assert dy2static.convert_ifelse(False, lambda: (1,), lambda: (2,)) == (2,)

    def test_convert_ifelse_tensor_pred(self):
        out = dy2static.convert_ifelse(
            t(np.asarray(True)),
            lambda: (t(np.float32(1.0)),), lambda: (t(np.float32(2.0)),))
        assert float(out[0]) == 1.0

    def test_convert_while_python(self):
        out = dy2static.convert_while_loop(
            lambda i, s: i < 3, lambda i, s: (i + 1, s + i), (0, 0))
        assert out == (3, 3)

    def test_logical_shortcircuit_python(self):
        calls = []

        def rhs():
            calls.append(1)
            return True

        assert dy2static.convert_logical_and(lambda: False, rhs) is False
        assert calls == []  # short circuit preserved for python values
        assert dy2static.convert_logical_or(lambda: True, rhs) is True
        assert calls == []


class TestTensorControlFlowUnderToStatic:
    def test_tensor_if(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2
            else:
                y = x - 100
            return y

        st = paddle.jit.to_static(f)
        pos = st(t(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(pos.numpy(), [2.0, 4.0])
        neg = st(t(np.array([-1.0, -2.0], np.float32)))
        np.testing.assert_allclose(neg.numpy(), [-101.0, -102.0])

    def test_tensor_if_elif(self):
        def f(x):
            s = x.sum()
            if (s > 10):
                r = x * 0
            elif (s > 0):
                r = x * 2
            else:
                r = x * -1
            return r

        st = paddle.jit.to_static(f)
        np.testing.assert_allclose(
            st(t(np.array([100.0], np.float32))).numpy(), [0.0])
        np.testing.assert_allclose(
            st(t(np.array([1.0], np.float32))).numpy(), [2.0])
        np.testing.assert_allclose(
            st(t(np.array([-5.0], np.float32))).numpy(), [5.0])

    def test_tensor_while(self):
        def f(x):
            s = x * 0
            i = x * 0
            while (i.sum() < 5):
                s = s + x
                i = i + 1
            return s

        st = paddle.jit.to_static(f)
        out = st(t(np.array([1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [5.0])

    def test_for_range_tensor_bound(self):
        def f(x, n):
            acc = x * 0
            for i in range(n):
                acc = acc + x
            return acc

        st = paddle.jit.to_static(f)
        out = st(t(np.array([2.0], np.float32)), t(np.int64(4)))
        np.testing.assert_allclose(out.numpy(), [8.0])

    def test_grad_through_cond(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 3
            else:
                y = x * 5
            return y.sum()

        st = paddle.jit.to_static(f)
        x = t(np.array([1.0, 1.0], np.float32))
        x.stop_gradient = False
        st(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
        x2 = t(np.array([-1.0, -1.0], np.float32))
        x2.stop_gradient = False
        st(x2).backward()
        np.testing.assert_allclose(x2.grad.numpy(), [5.0, 5.0])

    def test_layer_with_control_flow(self):
        class GateNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(4, 4)
                self.b = nn.Linear(4, 4)

            def forward(self, x):
                if (x.mean() > 0):
                    h = self.a(x)
                else:
                    h = self.b(x)
                return h.sum()

        paddle.seed(0)
        net = GateNet()
        st = paddle.jit.to_static(net.forward)
        xp = t(np.full((2, 4), 0.5, np.float32))
        xn = t(np.full((2, 4), -0.5, np.float32))
        # parity with eager on both paths
        np.testing.assert_allclose(float(st(xp)), float(net.a(xp).sum()),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(st(xn)), float(net.b(xn).sum()),
                                   rtol=1e-5)

    def test_bool_ops_on_tensors(self):
        def f(x):
            if (x.sum() > 0) and (x.max() < 10):
                y = x + 1
            else:
                y = x - 1
            return y

        st = paddle.jit.to_static(f)
        np.testing.assert_allclose(
            st(t(np.array([1.0], np.float32))).numpy(), [2.0])
        np.testing.assert_allclose(
            st(t(np.array([100.0], np.float32))).numpy(), [99.0])

    def test_python_control_flow_still_works(self):
        def f(x, flag=True):
            if flag:  # python bool: no lax.cond needed
                return x * 2
            return x

        st = paddle.jit.to_static(f)
        np.testing.assert_allclose(
            st(t(np.array([3.0], np.float32))).numpy(), [6.0])

    def test_code_property_shows_transform(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2
            else:
                y = x
            return y

        code = dy2static.get_code(f)
        assert "convert_ifelse" in code

    def test_enable_to_static_switch(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2
            else:
                y = x
            return y

        paddle.jit.enable_to_static(False)
        try:
            raw = dy2static._convert(f)  # conversion path
            converted = dy2static.convert_to_static(f)
            assert converted is f  # disabled -> untouched
        finally:
            paddle.jit.enable_to_static(True)

    def test_translator_singleton(self):
        tr = paddle.jit.ProgramTranslator.get_instance()
        assert tr is paddle.jit.ProgramTranslator()
        code = tr.get_code(lambda x: x)  # lambda: falls back to original
        assert code is not None or code is None  # no crash


class TestBranchReadWrite:
    def test_read_then_write_in_branch(self):
        """LeNet pattern: `x = f(x)` inside `if` reads the OUTER x (was an
        UnboundLocalError when branches were hoisted to nested functions)."""
        import paddle_tpu as paddle

        def fn(x, flag):
            x = x + 1.0
            if flag > 0:  # python-static predicate
                x = x * 2.0
                x = x + 3.0
            return x

        st = paddle.jit.to_static(fn)
        a = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(st(a, 1).numpy(), [7.0])   # (1+1)*2+3
        np.testing.assert_allclose(st(a, 0).numpy(), [2.0])

    def test_tensor_pred_branch_isolation(self):
        """Under lax.cond both branches trace; each must see the pre-branch
        value, not the other branch's mutation. stop_gradient=False forces the
        kernel through jax tracing so the predicate really is a Tracer."""
        import paddle_tpu as paddle

        def fn(x):
            y = x + 1.0
            if (x.sum() > 0):  # traced predicate -> lax.cond
                y = y * 10.0
            else:
                y = y - 1.0
            return y

        st = paddle.jit.to_static(fn)
        pos = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        neg = paddle.to_tensor(np.array([-2.0], np.float32), stop_gradient=False)
        out = st(pos)
        np.testing.assert_allclose(out.numpy(), [30.0])
        np.testing.assert_allclose(st(neg).numpy(), [-2.0])
        out.sum().backward()
        np.testing.assert_allclose(pos.grad.numpy(), [10.0])  # grads flow via cond

    def test_var_defined_only_in_branch(self):
        import paddle_tpu as paddle

        def fn(x, flag):
            if flag:
                z = x * 2.0
            else:
                z = x * 3.0
            return z

        st = paddle.jit.to_static(fn)
        a = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(st(a, True).numpy(), [2.0])
        np.testing.assert_allclose(st(a, False).numpy(), [3.0])


def test_undefined_branch_var_raises_on_use():
    """A var assigned only in the untaken branch must raise when USED
    (python read-time semantics), not silently propagate a sentinel."""
    import paddle_tpu as paddle

    def fn(x, flag):
        if flag:
            z = x * 2.0
        return z + 1.0  # read: must raise when flag is falsy

    st = paddle.jit.to_static(fn)
    a = paddle.to_tensor(np.array([1.0], np.float32))
    np.testing.assert_allclose(st(a, True).numpy(), [3.0])
    with pytest.raises(UnboundLocalError, match="only.*assigned in one branch"):
        st(a, False)

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * 3
    z = y * y
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 36.0)  # d(9x^2)/dx = 18x


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    c = (a + b).sum()
    c.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_shared_input_twice():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * x).sum()  # x used twice in same op
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y = y.detach()
    z = (y * 3).sum()
    assert z.stop_gradient


def test_backward_through_matmul():
    a = paddle.to_tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(np.random.RandomState(1).rand(4, 5).astype(np.float32),
                         stop_gradient=False)
    out = paddle.matmul(a, b).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(), np.asarray(b.numpy()).sum(1)[None, :].repeat(3, 0),
                               rtol=1e-5)


def test_multi_output_op_backward():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3), stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    loss = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 3], [1, 2, 3]])


def test_partial_output_use():
    x = paddle.to_tensor(np.ones((2, 4), np.float32), stop_gradient=False)
    a, b = paddle.split(x, 2, axis=1)
    loss = (a * 5).sum()  # b unused
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[5, 5, 0, 0], [5, 5, 0, 0]])


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_double_backward_raises_without_retain():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_non_scalar_backward_needs_grad():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y.backward(paddle.ones([2]))
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 10

    x.register_hook(hook)
    (x * 2).sum().backward()
    assert seen and seen[0][0] == 2.0
    np.testing.assert_allclose(x.grad.numpy(), [20.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x ** 3).sum()
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # functional API must not pollute .grad


def test_pylayer():
    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            return dy * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [6.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_int_inputs_skipped():
    ids = paddle.to_tensor([0, 1], dtype="int64")
    table = paddle.to_tensor(np.eye(3, dtype=np.float32), stop_gradient=False)
    out = paddle.nn.functional.embedding(ids, table)
    out.sum().backward()
    g = table.grad.numpy()
    np.testing.assert_allclose(g, [[1, 1, 1], [1, 1, 1], [0, 0, 0]])

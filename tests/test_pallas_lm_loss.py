"""Online Pallas LM-head cross-entropy (ops/pallas/lm_loss.py) vs dense math
(interpret mode on CPU). Round 5: RETIRED from the fused_linear_cross_entropy
route (BASELINE.md retirement note) — called DIRECTLY here, keeping the math
pinned as a library kernel."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.lm_loss import lm_head_cross_entropy, supported


def _dense(h, w, lab):
    logits = h @ w.T
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, lab[:, None], axis=1)[:, 0]
    return lse - picked


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-4), (jnp.bfloat16, 8e-2)])
def test_kernel_matches_dense(dtype, atol):
    rng = np.random.RandomState(0)
    N, V, H = 1024, 512, 128
    h = jnp.asarray(rng.randn(N, H), dtype)
    w = jnp.asarray(rng.randn(V, H) * 0.05, dtype)
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))

    loss = lm_head_cross_entropy(h, w, lab)
    assert loss.dtype == jnp.float32
    ref = _dense(h.astype(jnp.float32), w.astype(jnp.float32), lab)
    np.testing.assert_allclose(loss, ref, atol=atol, rtol=1e-2)


def test_kernel_grads_match_dense():
    rng = np.random.RandomState(1)
    N, V, H = 1024, 256, 128
    h = jnp.asarray(rng.randn(N, H).astype(np.float32))
    w = jnp.asarray((rng.randn(V, H) * 0.05).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))

    gp = jax.grad(lambda a, b: lm_head_cross_entropy(a, b, lab).mean(),
                  argnums=(0, 1))(h, w)
    gr = jax.grad(lambda a, b: _dense(a, b, lab).mean(), argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gp[0], gr[0], atol=1e-6)
    np.testing.assert_allclose(gp[1], gr[1], atol=1e-6)


def test_supported_predicate():
    assert supported(8192, 50304, 768)    # bench shapes (vocab padded to 50688)
    assert supported(16384, 50304, 768)
    # rows tile the 1D labels/loss/lse operands whose XLA layout is 1024-wide:
    # anything below/off the 1024 grid fails Mosaic layout verification on TPU
    assert not supported(512, 50304, 768)
    assert not supported(100, 512, 128)   # rows not tileable
    assert supported(1024, 500, 128)      # unaligned vocab: padded internally
    assert not supported(1024, 512, 100)  # hidden not lane-aligned


def test_unaligned_vocab_padded():
    """Vocab not divisible by 512: W is padded and masked; results must match
    the dense reference exactly on the true vocab, grads flow only to W[:V]."""
    rng = np.random.RandomState(5)
    N, V, H = 1024, 500, 128
    h = jnp.asarray(rng.randn(N, H).astype(np.float32))
    w = jnp.asarray((rng.randn(V, H) * 0.05).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))

    loss = lm_head_cross_entropy(h, w, lab)
    ref = _dense(h, w, lab)
    np.testing.assert_allclose(loss, ref, atol=1e-4, rtol=1e-4)

    gp = jax.grad(lambda a, b: lm_head_cross_entropy(a, b, lab).mean(),
                  argnums=(0, 1))(h, w)
    gr = jax.grad(lambda a, b: _dense(a, b, lab).mean(), argnums=(0, 1))(h, w)
    assert gp[1].shape == (V, H)  # pad sliced off by autodiff of the concat
    np.testing.assert_allclose(gp[0], gr[0], atol=1e-5)
    np.testing.assert_allclose(gp[1], gr[1], atol=1e-5)


def test_mixed_dtype_bf16_h_f32_w():
    """The on-chip amp config: bf16 activations against the f32 master
    embedding weight — the kernel must unify dtypes, dW back in f32."""
    rng = np.random.RandomState(4)
    N, V, H = 1024, 256, 128
    h = jnp.asarray(rng.randn(N, H), jnp.bfloat16)
    w = jnp.asarray(rng.randn(V, H) * 0.05, jnp.float32)
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))

    loss = lm_head_cross_entropy(h, w, lab)
    ref = _dense(h.astype(jnp.float32), w, lab)
    np.testing.assert_allclose(loss, ref, atol=8e-2, rtol=1e-2)

    gh, gw = jax.grad(lambda a, b: lm_head_cross_entropy(a, b, lab).mean(),
                      argnums=(0, 1))(h, w)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.float32
    gr = jax.grad(lambda a, b: _dense(a.astype(jnp.float32), b, lab).mean(),
                  argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gw, gr[1], atol=5e-3, rtol=5e-2)


@pytest.mark.parametrize("block_n", [256, 512])
def test_small_compute_blocks_match_dense(block_n):
    """block_n shrinks the 2D compute tiles while the 1D operands stay on
    their 1024-element XLA-tile blocks (revisit sub-slices) — value and both
    grads must match the dense reference at every supported block size.
    (The knob exists because Mosaic compile time grows superlinearly in
    per-block vector ops — BASELINE.md round 3.)"""
    rng = np.random.RandomState(7)
    N, V, H = 2048, 640, 128  # N spans 2 revisit groups at block 256
    h = jnp.asarray(rng.randn(N, H).astype(np.float32))
    w = jnp.asarray((rng.randn(V, H) * 0.05).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))
    loss = lm_head_cross_entropy(h, w, lab, block_n=block_n)
    ref = _dense(h, w, lab)
    np.testing.assert_allclose(loss, ref, atol=1e-4, rtol=1e-4)
    gp = jax.grad(lambda a, b: lm_head_cross_entropy(
        a, b, lab, block_n=block_n).mean(), argnums=(0, 1))(h, w)
    gr = jax.grad(lambda a, b: _dense(a, b, lab).mean(),
                  argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gp[0], gr[0], atol=1e-5)
    np.testing.assert_allclose(gp[1], gr[1], atol=1e-5)

"""Numeric sweep 1/2 — elementwise, comparison, creation, random ops from the
reference api.yaml surface that had no per-op test (VERDICT r1 weak #5).

Pattern follows the reference op_test culture
(python/paddle/fluid/tests/unittests/op_test.py:289): every op checks against
an independent numpy/scipy reference; differentiable ops also run the numeric
central-difference vs analytic-tape gradient check in op_test.check_grad.
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
from op_test import check_grad, check_output

F = paddle.nn.functional


def t(a):
    return paddle.to_tensor(a)


def _rand(shape, lo=-1.0, hi=1.0, seed=0):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype(np.float32)


# ---- unary elementwise: (api, paddle_fn, np_ref, input, grad?) -------------
UNARY = [
    ("acosh", paddle.acosh, np.arccosh, _rand((2, 3), 1.2, 3.0), True),
    ("asin", paddle.asin, np.arcsin, _rand((2, 3), -0.9, 0.9), True),
    ("asinh", paddle.asinh, np.arcsinh, _rand((2, 3), -2, 2), True),
    ("atan", paddle.atan, np.arctan, _rand((2, 3), -2, 2), True),
    ("atanh", paddle.atanh, np.arctanh, _rand((2, 3), -0.9, 0.9), True),
    ("cosh", paddle.cosh, np.cosh, _rand((2, 3), -2, 2), True),
    ("tan", paddle.tan, np.tan, _rand((2, 3), -1.2, 1.2), True),
    ("expm1", paddle.expm1, np.expm1, _rand((2, 3), -1, 1), True),
    ("log10", paddle.log10, np.log10, _rand((2, 3), 0.1, 5.0), True),
    ("log2", paddle.log2, np.log2, _rand((2, 3), 0.1, 5.0), True),
    ("reciprocal", paddle.reciprocal, lambda x: 1.0 / x,
     _rand((2, 3), 0.5, 2.0), True),
    ("rsqrt", paddle.rsqrt, lambda x: 1.0 / np.sqrt(x),
     _rand((2, 3), 0.5, 2.0), True),
    ("trunc", paddle.trunc, np.trunc, _rand((2, 3), -3, 3), False),
    ("digamma", paddle.digamma, sps.digamma, _rand((2, 3), 0.5, 3.0), True),
    ("erfinv", paddle.erfinv, sps.erfinv, _rand((2, 3), -0.9, 0.9), True),
]


@pytest.mark.parametrize("name,fn,ref,x,diff", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary(name, fn, ref, x, diff):
    check_output(fn, ref, [x], rtol=2e-5, atol=2e-5)
    if diff:
        check_grad(fn, [x.astype(np.float64)])


def test_cumsum_cumprod():
    x = _rand((3, 4), 0.5, 1.5)
    check_output(paddle.cumsum, lambda a, axis: np.cumsum(a, axis),
                 [x], {"axis": 1})
    check_output(paddle.cumprod, lambda a, dim: np.cumprod(a, dim),
                 [x], {"dim": 1})
    check_grad(paddle.cumsum, [x.astype(np.float64)], {"axis": 0})
    check_grad(paddle.cumprod, [x.astype(np.float64)], {"dim": 1})


# ---- binary / comparison ----------------------------------------------------
def test_elementwise_pow_and_mod():
    x, y = _rand((2, 3), 0.5, 2.0), _rand((2, 3), -1, 2, seed=1)
    check_output(paddle.pow, np.power, [x, y], rtol=1e-5)
    check_grad(paddle.pow, [x.astype(np.float64), y.astype(np.float64)])
    a = np.array([[7, -7], [5, 3]], np.float32)
    b = np.array([[3, 3], [-2, 2]], np.float32)
    check_output(paddle.remainder, np.mod, [a, b])
    check_output(paddle.floor_divide, np.floor_divide, [a, b])


def test_fmax_fmin_propagate_non_nan():
    x = np.array([1.0, np.nan, 3.0, np.nan], np.float32)
    y = np.array([2.0, 5.0, np.nan, np.nan], np.float32)
    check_output(paddle.fmax, np.fmax, [x, y])
    check_output(paddle.fmin, np.fmin, [x, y])


def test_lerp():
    x, y, w = _rand((2, 3)), _rand((2, 3), seed=1), _rand((2, 3), 0, 1, seed=2)
    check_output(paddle.lerp, lambda a, b, c: a + c * (b - a), [x, y, w])
    check_grad(paddle.lerp, [x.astype(np.float64), y.astype(np.float64),
                             w.astype(np.float64)], input_idx=1)


LOGICAL = [
    ("logical_and", paddle.logical_and, np.logical_and),
    ("logical_or", paddle.logical_or, np.logical_or),
    ("logical_xor", paddle.logical_xor, np.logical_xor),
]


@pytest.mark.parametrize("name,fn,ref", LOGICAL, ids=[c[0] for c in LOGICAL])
def test_logical_binary(name, fn, ref):
    a = np.array([True, True, False, False])
    b = np.array([True, False, True, False])
    check_output(fn, ref, [a, b])


def test_logical_not_bitwise_not():
    check_output(paddle.logical_not, np.logical_not,
                 [np.array([True, False])])
    check_output(paddle.bitwise_not, np.invert,
                 [np.array([0, 5, -3], np.int32)])


CMP = [
    ("less_than", paddle.less_than, np.less),
    ("less_equal", paddle.less_equal, np.less_equal),
    ("greater_than", paddle.greater_than, np.greater),
    ("greater_equal", paddle.greater_equal, np.greater_equal),
    ("not_equal", paddle.not_equal, np.not_equal),
]


@pytest.mark.parametrize("name,fn,ref", CMP, ids=[c[0] for c in CMP])
def test_comparisons(name, fn, ref):
    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[1.0, 3.0], [2.0, 4.0]], np.float32)
    check_output(fn, ref, [a, b])


def test_equal_all_isclose_isinf_isnan():
    a = np.array([1.0, 2.0], np.float32)
    assert bool(paddle.equal_all(t(a), t(a.copy())))
    assert not bool(paddle.equal_all(t(a), t(a + 1)))
    b = a + 1e-9
    np.testing.assert_array_equal(paddle.isclose(t(a), t(b)).numpy(),
                                  np.isclose(a, b))
    c = np.array([1.0, np.inf, np.nan, -np.inf], np.float32)
    np.testing.assert_array_equal(paddle.isinf(t(c)).numpy(), np.isinf(c))
    np.testing.assert_array_equal(paddle.isnan(t(c)).numpy(), np.isnan(c))


# ---- creation / assign ------------------------------------------------------
def test_empty_full_like_assign_increment():
    e = paddle.empty([2, 3], dtype="float32")
    assert tuple(e.shape) == (2, 3) and e.dtype == paddle.float32
    el = paddle.empty_like(t(np.zeros((4, 2), np.int64)))
    assert tuple(el.shape) == (4, 2) and "int64" in str(el.dtype)
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    check_output(paddle.full_like, lambda a, fill_value: np.full_like(a, fill_value),
                 [x], {"fill_value": 2.5})
    check_output(paddle.assign, lambda a: a.copy(), [x])
    y = paddle.increment(t(np.array([1.0], np.float32)), value=2.0)
    np.testing.assert_allclose(y.numpy(), [3.0])


def test_logit_equal_dist_cross_trace_pad():
    p = _rand((2, 4), 0.05, 0.95)
    check_output(paddle.logit, lambda x: np.log(x / (1 - x)), [p], rtol=1e-5)
    check_grad(paddle.logit, [p.astype(np.float64)])

    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[1.0, 3.0], [3.0, 4.0]], np.float32)
    check_output(paddle.equal, np.equal, [a, b])

    x, y = _rand((3, 4)), _rand((3, 4), seed=1)
    np.testing.assert_allclose(paddle.dist(t(x), t(y), p=2).numpy(),
                               np.linalg.norm((x - y).ravel()), rtol=1e-5)
    np.testing.assert_allclose(paddle.dist(t(x), t(y), p=float("inf")).numpy(),
                               np.abs(x - y).max(), rtol=1e-6)

    u, v = _rand((4, 3)), _rand((4, 3), seed=2)
    check_output(paddle.cross, lambda m, n, axis: np.cross(m, n, axis=axis),
                 [u, v], {"axis": 1})

    sq = _rand((4, 4))
    check_output(paddle.trace, lambda m: np.trace(m), [sq])
    check_grad(paddle.trace, [sq.astype(np.float64)])

    check_output(lambda m, pad, value: paddle.nn.functional.pad(
                     m, pad, mode="constant", value=value),
                 lambda m, pad, value: np.pad(
                     m, [(pad[0], pad[1]), (pad[2], pad[3])],
                     constant_values=value),
                 [_rand((2, 3))], {"pad": [1, 1, 0, 2], "value": 0.5})


def test_batch_norm_functional():
    F = paddle.nn.functional
    x = _rand((4, 3, 2, 2))
    rm = np.zeros((3,), np.float32)
    rv = np.ones((3,), np.float32)
    w = _rand((3,), 0.5, 1.5, seed=1)
    b = _rand((3,), -0.5, 0.5, seed=2)
    out = F.batch_norm(t(x), t(rm), t(rv), weight=t(w), bias=t(b),
                       training=False, epsilon=1e-5).numpy()
    expect = ((x - rm[None, :, None, None]) /
              np.sqrt(rv[None, :, None, None] + 1e-5) *
              w[None, :, None, None] + b[None, :, None, None])
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


# ---- random ops: distributional checks (deterministic under paddle.seed) ---
def test_uniform_moments():
    paddle.seed(21)
    s = paddle.uniform([20000], min=-2.0, max=4.0).numpy()
    assert s.min() >= -2.0 and s.max() <= 4.0
    assert abs(s.mean() - 1.0) < 0.1

def test_normal_moments():
    paddle.seed(1234)
    s = paddle.normal(mean=1.0, std=2.0, shape=[20000]).numpy()
    assert abs(s.mean() - 1.0) < 0.1 and abs(s.std() - 2.0) < 0.1


def test_randperm_is_permutation():
    paddle.seed(7)
    p = paddle.randperm(64).numpy()
    np.testing.assert_array_equal(np.sort(p), np.arange(64))


def test_bernoulli_poisson():
    paddle.seed(11)
    probs = np.full((5000,), 0.3, np.float32)
    b = paddle.bernoulli(t(probs)).numpy()
    assert set(np.unique(b)) <= {0.0, 1.0}
    assert abs(b.mean() - 0.3) < 0.05
    lam = np.full((5000,), 4.0, np.float32)
    po = paddle.poisson(t(lam)).numpy()
    assert po.min() >= 0 and abs(po.mean() - 4.0) < 0.2


def test_multinomial():
    paddle.seed(5)
    probs = np.array([0.1, 0.0, 0.6, 0.3], np.float32)
    s = paddle.multinomial(t(probs), num_samples=4000,
                           replacement=True).numpy()
    assert s.shape == (4000,) and set(np.unique(s)) <= {0, 2, 3}
    frac2 = (s == 2).mean()
    assert abs(frac2 - 0.6) < 0.06


def test_truncated_normal_initializer_bounds():
    paddle.seed(3)
    init = paddle.nn.initializer.TruncatedNormal(mean=0.0, std=1.0)
    v = np.asarray(init([4000], "float32"))
    assert np.all(np.abs(v) <= 2.0 + 1e-6)  # truncated at 2 std
    assert abs(v.mean()) < 0.08

"""Profiler + observability subsystem (ISSUE 1).

Covers the make_scheduler state machine, RecordEvent/tracer span nesting,
chrome-trace export round-tripped through load_profiler_result, StepTelemetry
JSONL emission from a real CPU train step, compile/dispatch counters, and the
disabled-path overhead contract (no spans, no file I/O, no jax import).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability.step_telemetry import InMemorySink, JsonlSink
from paddle_tpu.profiler import (
    Benchmark, Profiler, ProfilerState, RecordEvent, export_chrome_tracing,
    get_event_stats, load_profiler_result, make_scheduler, reset_event_stats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracer():
    tr = obs.get_tracer()
    tr.disable()
    tr.clear()
    tr.clear_stats()
    yield
    tr.disable()
    tr.clear()
    tr.clear_stats()


def _tiny_engine(seed=0):
    from paddle_tpu.distributed.engine import TrainStepEngine

    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss())


def _batch(n=8):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


# ---------------- make_scheduler state machine ----------------

def test_scheduler_skip_first_and_cycle():
    sched = make_scheduler(closed=1, ready=1, record=2, skip_first=3)
    assert [sched(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    # one period: closed, ready, record, record_and_return
    assert sched(3) == ProfilerState.CLOSED
    assert sched(4) == ProfilerState.READY
    assert sched(5) == ProfilerState.RECORD
    assert sched(6) == ProfilerState.RECORD_AND_RETURN
    # cycles repeat indefinitely with repeat=0
    assert sched(7) == ProfilerState.CLOSED
    assert sched(10) == ProfilerState.RECORD_AND_RETURN


def test_scheduler_repeat_exhausts():
    sched = make_scheduler(closed=0, ready=1, record=1, repeat=2)
    assert sched(0) == ProfilerState.READY
    assert sched(1) == ProfilerState.RECORD_AND_RETURN
    assert sched(2) == ProfilerState.READY
    assert sched(3) == ProfilerState.RECORD_AND_RETURN
    # after `repeat` periods the profiler stays closed forever
    assert sched(4) == ProfilerState.CLOSED
    assert sched(100) == ProfilerState.CLOSED


def test_scheduler_single_record_is_record_and_return():
    sched = make_scheduler(closed=0, ready=0, record=1)
    assert sched(0) == ProfilerState.RECORD_AND_RETURN


# ---------------- tracer spans + RecordEvent ----------------

def test_record_event_nesting_and_aggregates():
    tr = obs.get_tracer()
    tr.enable()
    with RecordEvent("outer"):
        for _ in range(3):
            with RecordEvent("inner"):
                pass
    tr.disable()
    evs = tr.events()
    names = [e["name"] for e in evs]
    assert names.count("inner") == 3 and names.count("outer") == 1
    outer = next(e for e in evs if e["name"] == "outer")
    inners = [e for e in evs if e["name"] == "inner"]
    # nesting: every inner interval is contained in outer's, same thread
    for i in inners:
        assert i["tid"] == outer["tid"]
        assert outer["ts"] <= i["ts"]
        assert i["ts"] + i["dur"] <= outer["ts"] + outer["dur"] + 1e-9
    # aggregates (the summary() data source) saw the same counts
    st = get_event_stats()
    assert st["inner"][0] == 3 and st["outer"][0] == 1
    assert st["outer"][1] >= st["inner"][1]  # total time contains children


def test_record_event_aggregates_without_tracing():
    # aggregates are always on (summary works outside a trace window) but no
    # timeline events accumulate while disabled
    with RecordEvent("agg_only"):
        pass
    assert get_event_stats()["agg_only"][0] == 1
    assert obs.get_tracer().events() == []


def test_tracer_span_api_and_ring_buffer_bound():
    tr = obs.Tracer(capacity=4)
    tr.enable()
    for i in range(10):
        with tr.span("s", i=i):
            pass
    evs = tr.events()
    assert len(evs) == 4  # ring buffer dropped the oldest
    assert tr.dropped == 6
    assert [e["args"]["i"] for e in evs] == [6, 7, 8, 9]


def test_disabled_span_is_noop_singleton():
    tr = obs.Tracer()
    s1, s2 = tr.span("a"), tr.span("b")
    assert s1 is s2  # shared null object: no allocation on the off path
    assert tr.events() == [] and tr.stats() == {}


# ---------------- chrome trace export round-trip ----------------

def test_chrome_trace_roundtrip(tmp_path):
    tr = obs.get_tracer()
    tr.enable()
    with RecordEvent("step"):
        with RecordEvent("matmul"):
            pass
        with RecordEvent("matmul"):
            pass
    tr.disable()
    path = tr.export_chrome_trace(str(tmp_path / "host.json"))
    doc = json.load(open(path))
    assert "traceEvents" in doc
    complete = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {"step", "matmul"}
    assert all("ts" in e and "dur" in e and "tid" in e for e in complete)

    res = load_profiler_result(path)
    st = res.stats()
    assert st["matmul"][0] == 2 and st["step"][0] == 1
    # loaded aggregates match the live tracer's within export rounding
    live = get_event_stats()
    assert abs(live["step"][1] - st["step"][1]) < 1e-3
    t0, t1 = res.time_range()
    assert t1 >= t0


def test_load_profiler_result_from_directory(tmp_path):
    tr = obs.Tracer()
    tr.enable()
    with tr.span("a"):
        pass
    tr.export_chrome_trace(str(tmp_path / "w0.json"))
    tr.export_chrome_trace(str(tmp_path / "w1.json"))
    res = load_profiler_result(str(tmp_path))
    assert res.stats()["a"][0] == 2  # merged across worker files


def test_load_profiler_result_rejects_non_trace(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"not_a_trace": 1}')
    with pytest.raises(ValueError, match="traceEvents"):
        load_profiler_result(str(p))


# ---------------- export_chrome_tracing ordering fix ----------------

def test_export_dir_applied_at_construction(tmp_path):
    # the requested dir must be in force BEFORE the first trace window opens
    # (previously assigned on trace-ready, after _start_trace had already
    # written to the old directory)
    want = str(tmp_path / "requested")
    prof = Profiler(on_trace_ready=export_chrome_tracing(want),
                    scheduler=make_scheduler(closed=0, ready=0, record=1),
                    use_device_profiler=False)
    assert prof._export_dir == want
    prof.start()   # immediately RECORD_AND_RETURN: opens + closes one window
    with RecordEvent("in_window"):
        pass
    prof.step()
    prof.stop()
    files = os.listdir(want)
    assert any(f.endswith(".json") for f in files)
    res = load_profiler_result(want)
    assert "in_window" in res.stats()


def test_profiler_summary_reads_tracer(capsys):
    prof = Profiler(timer_only=True)
    prof.start()
    with RecordEvent("ev"):
        pass
    prof.step()
    prof.stop()
    prof.summary()
    out = capsys.readouterr().out
    assert "ips:" in out and "ev" in out


# ---------------- Benchmark reader_cost ----------------

def test_benchmark_tracks_reader_cost():
    b = Benchmark()
    b.begin()
    b.step(num_samples=4, reader_cost=0.01)
    b.step(num_samples=4, reader_cost=0.03)
    b.end()
    rep = b.report()
    assert rep["steps"] == 2
    assert rep["reader_cost"] == pytest.approx(0.02)  # tracked avg, not 0.0


def test_benchmark_reader_cost_defaults_to_zero():
    b = Benchmark()
    b.begin()
    b.step()
    rep = b.report()
    assert rep["reader_cost"] == 0.0


# ---------------- StepTelemetry + engine integration ----------------

def test_engine_step_telemetry_jsonl_and_trace(tmp_path):
    """The acceptance path: one CPU train step with telemetry on yields a
    loadable chrome trace AND a JSONL record with wall time, throughput,
    compile count, and memory stats."""
    e = _tiny_engine()
    jsonl = str(tmp_path / "steps.jsonl")
    e.enable_telemetry(path=jsonl)
    tr = obs.get_tracer()
    tr.enable()
    x, y = _batch()
    e.step(x, y)
    e.step(x, y)
    tr.disable()
    e.disable_telemetry()

    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 2
    r0, r1 = recs
    assert r0["event"] == "train_step" and r0["step"] == 1
    assert r0["wall_time_s"] > 0
    assert r0["samples"] == 8 and r0["samples_per_sec"] > 0
    assert r0["jit_compiles"] >= 1  # first step compiled
    assert "device_memory" in r0  # {} on the CPU mesh, populated on TPU
    assert r0["dispatch_calls"] >= 1
    # second step hit the executable cache: no new compile
    assert r1.get("jit_compiles_delta", 0) == 0
    assert r0["loss"] == pytest.approx(float(np.asarray(e.last_loss._data)),
                                       rel=1.0)  # same scale, both finite

    # the same window produced a loadable chrome trace with the step span
    path = tr.export_chrome_trace(str(tmp_path / "host.json"))
    st = load_profiler_result(path).stats()
    assert "engine.step" in st and st["engine.step"][0] == 2


def test_engine_run_steps_telemetry():
    e = _tiny_engine()
    sink = InMemorySink()
    e.telemetry = obs.StepTelemetry(sink=sink)
    x, y = _batch()
    e.run_steps(x, y, steps=3)
    assert len(sink.records) == 1
    rec = sink.records[0]
    assert rec["steps_fused"] == 3
    assert rec["samples"] == 24  # 3 fused steps x batch 8
    assert rec["jit_compiles"] >= 1


def test_engine_telemetry_flop_model():
    e = _tiny_engine()
    e.enable_telemetry(sink=InMemorySink())
    # default model is parameter-only 6*N
    n_params = sum(int(np.prod(p.shape)) for p in e.model.parameters())
    assert e.telemetry.flops_per_token == 6 * n_params

    assert (obs.transformer_flops_per_token(
        n_params, num_layers=2, hidden_size=8, seq_len=4)
        == 6 * n_params + 12 * 2 * 8 * 4)  # the bench.py convention
    # clean numbers: 2 GFLOP/token, 2000 tok/s -> 4 TFLOP/s; peak 8 -> mfu 0.5
    tele = obs.StepTelemetry(sink=InMemorySink(),
                             flops_per_token=2_000_000_000, peak_flops=8e12)
    rec = tele.record_step(step=1, wall_time=0.5, tokens=1000)
    assert rec["tokens_per_sec"] == 2000.0
    assert rec["tflops_per_sec"] == pytest.approx(4.0)
    assert rec["mfu"] == pytest.approx(0.5)


def test_telemetry_off_no_spans_no_io(tmp_path, monkeypatch):
    """Overhead honesty: telemetry off means the step path records no spans
    and opens no files."""
    monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
    e = _tiny_engine()
    assert e.telemetry is None  # env unset -> nothing attached
    tr = obs.get_tracer()
    n_before = len(tr.events())

    import builtins

    opened = []
    real_open = builtins.open

    def spy_open(file, *a, **k):
        opened.append(str(file))
        return real_open(file, *a, **k)

    monkeypatch.setattr(builtins, "open", spy_open)
    x, y = _batch()
    e.step(x, y)
    monkeypatch.setattr(builtins, "open", real_open)

    assert len(tr.events()) == n_before  # no spans with tracer disabled
    # no telemetry/trace file writes on the step path (jax may read its own
    # package data; what matters is nothing under tmp and no .jsonl/.json)
    assert not any(p.endswith((".jsonl", ".json")) for p in opened)


def test_observability_is_stdlib_without_jax():
    """The disabled path must not even import jax: the observability modules
    are loadable standalone in a jax-free interpreter."""
    code = f"""
import importlib.util, os, sys
base = os.path.join({REPO!r}, "paddle_tpu", "observability")
mods = {{}}
for name in ("tracer", "step_telemetry", "flops", "metrics",
             "flight_recorder"):
    spec = importlib.util.spec_from_file_location(
        "obs_" + name, os.path.join(base, name + ".py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    mods[name] = m
t = mods["tracer"].Tracer()
with t.span("off"):
    pass          # disabled: no-op
t.enable()
with t.span("on"):
    pass
assert [e["name"] for e in t.events()] == ["on"]
s = mods["step_telemetry"].StepTelemetry(
    sink=mods["step_telemetry"].InMemorySink(), collect_memory=False)
h = mods["metrics"].MetricRegistry().histogram("lat_ms")
h.observe(1.5)
assert h.count == 1
fr = mods["flight_recorder"].FlightRecorder("/tmp/unused", capacity=4)
fr.record({{"event": "probe"}})
assert len(fr.records()) == 1
assert "jax" not in sys.modules, "observability pulled in jax"
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr


def test_env_var_attaches_jsonl_sink(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    e = _tiny_engine()
    assert e.telemetry is not None
    assert isinstance(e.telemetry.sink, JsonlSink)
    x, y = _batch()
    e.step(x, y)
    recs = [json.loads(l)
            for l in open(tmp_path / "step_telemetry.jsonl")]
    assert len(recs) == 1 and recs[0]["step"] == 1


# ---------------- dispatch counters ----------------

def test_dispatch_counters_and_per_op_stats():
    from paddle_tpu.core import monitor

    calls = monitor.stat("dispatch.calls")
    before = calls.get()
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    (x @ x + x).sum()
    assert calls.get() > before
    rep = monitor.registry().report()
    per_op = [k for k in rep if k.startswith("dispatch.op.")]
    assert per_op, "per-op dispatch counters missing"


def test_dispatch_spans_when_traced():
    tr = obs.get_tracer()
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    tr.enable()
    y = x @ x
    tr.disable()
    names = [e["name"] for e in tr.events()]
    assert any(n.startswith("op::") for n in names)


def test_nan_inf_counter():
    from paddle_tpu.core import monitor

    hits = monitor.stat("dispatch.nan_inf_hits")
    before = hits.get()
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    x = paddle.to_tensor(np.zeros((2,), np.float32))
    with pytest.raises(FloatingPointError):
        x / x  # 0/0 -> nan
    assert hits.get() == before + 1


# ---------------- hapi fit integration ----------------

def test_hapi_fit_telemetry_callback_and_reader_cost():
    from paddle_tpu.hapi.callbacks import TelemetryCallback

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    data = [(rng.randn(4, 4).astype(np.float32),
             rng.randint(0, 2, (4,)).astype(np.int64)) for _ in range(3)]
    cb = TelemetryCallback()
    # batch_size names the per-batch sample count for logging (the loader
    # here yields prebaked batches of 4 — hapi convention)
    model.fit(data, epochs=1, batch_size=4, verbose=0, callbacks=[cb])
    recs = cb.telemetry.sink.records
    assert len(recs) == 3
    for r in recs:
        assert r["wall_time_s"] > 0
        assert r["samples"] == 4
        assert "reader_cost_s" in r  # tracked, not hard-coded
        assert isinstance(r["loss"], float)

"""Elastic checkpointing (distributed/elastic.py): async crash-safe
snapshots, corruption fallback, cross-mesh + cross-dp ZeRO restore,
retention GC, rollback, and the mid-save SIGKILL protocol.

The heavyweight end-to-end proof (GPT dp4 x mp2 victim SIGKILLed mid-save,
survivor restores onto dp2 x mp4 bit-continuously) lives in the driver
dryrun (__graft_entry__.py phase 11); these tests cover the same contract
on cheap engines so CI exercises every branch.
"""
import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import time
import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.distributed import elastic
from paddle_tpu.distributed.elastic import (CheckpointCorrupt,
                                            CheckpointManager,
                                            restore_latest,
                                            verify_checkpoint)
from paddle_tpu.distributed.engine import TrainStepEngine
from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                         set_hybrid_communicate_group)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hcg(dp):
    set_hybrid_communicate_group(None)
    return HybridCommunicateGroup(dp_degree=dp, devices=jax.devices()[:dp])


def _make(dp=4, zero=False, k=1, seed=0):
    hcg = _hcg(dp)
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           hcg=hcg, microbatches=k, zero_update=zero)


def _batch(n=32):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def _losses(eng, x, y, steps):
    return [float(eng.step(x, y).item()) for _ in range(steps)]


def _stat(name):
    return monitor.stat(name).get()


# ------------------------------------------------------------ save/restore

def test_sync_save_restore_same_mesh(tmp_path):
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 3)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(eng, block=True)
    after = _losses(eng, x, y, 2)
    mgr.close()

    eng2 = _make(dp=4, seed=1)  # different init: restore must overwrite it
    assert restore_latest(eng2, str(tmp_path)) == 3
    assert eng2._step_count == 3
    assert eng2.optimizer._step_count == eng.optimizer._step_count - 2
    for n in eng.params:
        np.testing.assert_array_equal(np.asarray(eng2.params[n]).shape,
                                      np.asarray(eng.params[n]).shape)
    assert _losses(eng2, x, y, 2) == after  # bit-equal continuation


def test_async_save_is_bit_transparent_and_skips_when_busy(tmp_path):
    """Async snapshots must not perturb training (donation safety of the
    captured host copies), and a third save landing while two are in
    flight skips with a counter instead of stalling."""
    x, y = _batch()
    ref = _losses(_make(dp=4), x, y, 6)

    eng = _make(dp=4)
    mgr = CheckpointManager(str(tmp_path), interval=2, keep=10,
                            async_save=True)
    got = []
    for s in range(1, 7):
        loss = eng.step(x, y)
        got.append(float(loss.item()))
        mgr.on_step(eng, s, loss)
    assert got == ref, "async checkpointing perturbed the loss trajectory"
    assert mgr.wait(timeout=60)
    saves = [step for step, _ in mgr.checkpoints()]
    assert saves and all(step % 2 == 0 for step in saves)
    for _step, path in mgr.checkpoints():
        verify_checkpoint(path)
    mgr.close()

    # skip-when-busy: slow writer, three back-to-back saves -> third skips
    eng2 = _make(dp=4)
    float(eng2.step(x, y).item())
    mgr2 = CheckpointManager(str(tmp_path / "busy"), async_save=True,
                             slow_write_ms=150)
    k0 = _stat("ckpt.skipped")
    assert mgr2.save(eng2) is True
    assert mgr2.save(eng2) is True   # double buffer: one writing, one queued
    assert mgr2.save(eng2) is False  # full: skip, don't stall the step
    assert _stat("ckpt.skipped") == k0 + 1
    assert mgr2.wait(timeout=120)
    mgr2.close()


def test_restore_across_mesh_layouts(tmp_path):
    """dp4 save -> dp2 restore: merged host state is identical, the
    continued loss curve matches up to reduction-order ulps."""
    eng = _make(dp=4)
    x, y = _batch()
    _losses(eng, x, y, 3)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(eng, block=True)
    mgr.close()
    want = {n: np.asarray(eng.params[n]).copy() for n in eng.params}
    cont = _losses(eng, x, y, 2)

    eng2 = _make(dp=2, seed=1)
    assert restore_latest(eng2, str(tmp_path)) == 3
    for n in want:
        np.testing.assert_array_equal(np.asarray(eng2.params[n]), want[n])
    np.testing.assert_allclose(_losses(eng2, x, y, 2), cont, rtol=1e-5)


def test_checkpoint_prng_and_lr_state_roundtrip(tmp_path):
    """The engine PRNG key and optimizer step (lr schedule position)
    survive the roundtrip — dropout masks and warmup curves resume where
    they left off."""
    eng = _make(dp=2)
    x, y = _batch()
    _losses(eng, x, y, 2)
    key_before = np.asarray(jax.random.key_data(eng._key)).copy()
    CheckpointManager(str(tmp_path), async_save=False).save(eng, block=True)
    eng2 = _make(dp=2, seed=7)
    restore_latest(eng2, str(tmp_path))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(eng2._key)), key_before)
    assert eng2.optimizer._step_count == eng.optimizer._step_count


# ------------------------------------------------------------- corruption

def _corrupt_file(path, offset=64):
    with open(path, "r+b") as f:
        f.seek(offset)
        raw = f.read(4)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in raw))


def _two_checkpoints(tmp_path, eng, x, y):
    mgr = CheckpointManager(str(tmp_path), interval=1, keep=10,
                            async_save=False)
    eng.step(x, y)
    mgr.save(eng, block=True)
    eng.step(x, y)
    mgr.save(eng, block=True)
    mgr.close()
    return elastic.list_checkpoints(str(tmp_path))


def test_corrupt_payload_falls_back_to_previous(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    ckpts = _two_checkpoints(tmp_path, eng, x, y)
    assert [s for s, _ in ckpts] == [1, 2]
    newest = ckpts[-1][1]
    payload = sorted(n for n in os.listdir(newest) if n.endswith(".npy"))[0]
    _corrupt_file(os.path.join(newest, payload))
    with pytest.raises(CheckpointCorrupt, match="checksum mismatch"):
        verify_checkpoint(newest)

    c0 = _stat("ckpt.corrupt")
    eng2 = _make(dp=2, seed=1)
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        assert restore_latest(eng2, str(tmp_path)) == 1
    assert _stat("ckpt.corrupt") == c0 + 1
    assert any("corrupt" in str(w.message) for w in wlog)


def test_corrupt_manifest_falls_back(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    ckpts = _two_checkpoints(tmp_path, eng, x, y)
    mpath = os.path.join(ckpts[-1][1], elastic.MANIFEST)
    m = json.load(open(mpath))
    m["step"] = 999  # tampered body no longer matches the self-checksum
    json.dump(m, open(mpath, "w"))
    with pytest.raises(CheckpointCorrupt, match="manifest checksum"):
        verify_checkpoint(ckpts[-1][1])
    eng2 = _make(dp=2, seed=1)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert restore_latest(eng2, str(tmp_path)) == 1


def test_truncated_payload_detected(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    ckpts = _two_checkpoints(tmp_path, eng, x, y)
    newest = ckpts[-1][1]
    payload = sorted(n for n in os.listdir(newest) if n.endswith(".npy"))[0]
    fp = os.path.join(newest, payload)
    with open(fp, "r+b") as f:
        f.truncate(os.path.getsize(fp) - 8)
    with pytest.raises(CheckpointCorrupt, match="truncated"):
        verify_checkpoint(newest)


def test_all_corrupt_raises_filenotfound(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    for _s, path in _two_checkpoints(tmp_path, eng, x, y):
        os.remove(os.path.join(path, elastic.MANIFEST))
    eng2 = _make(dp=2, seed=1)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        with pytest.raises(FileNotFoundError):
            restore_latest(eng2, str(tmp_path))


# ---------------------------------------------------------- ZeRO reslice

def _gathered_flat(eng):
    n = eng._zero_layout()[0]
    return [np.asarray(flat)[:n] for flat in eng._zero_opt]


@pytest.mark.parametrize("dp_from,dp_to", [(4, 8), (8, 4)])
def test_zero_flat_reslice_across_dp(tmp_path, dp_from, dp_to):
    """ZeRO flat opt shards saved at one dp degree restore at another by
    re-padding + re-slicing at segment offsets — the gathered [0:n) state
    is bit-identical, the per-param dict never reconstructed."""
    src = _make(dp=dp_from, zero=True, k=2)
    x, y = _batch()
    _losses(src, x, y, 3)
    assert src._zero_opt is not None and src.opt_state is None
    CheckpointManager(str(tmp_path), async_save=False).save(src, block=True)
    want = _gathered_flat(src)

    dst = _make(dp=dp_to, zero=True, k=2, seed=1)
    _losses(dst, x, y, 1)  # engage ZeRO so the target layout exists
    assert restore_latest(dst, str(tmp_path)) == 3
    assert dst._zero_opt is not None and dst.opt_state is None
    got = _gathered_flat(dst)
    assert len(got) == len(want)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    # resliced engine keeps training sanely across the dp change
    cont = _losses(dst, x, y, 2)
    assert all(np.isfinite(cont))


def test_zero_restore_bit_equal_to_replicated_restore(tmp_path):
    """The same flat checkpoint restored into a ZeRO engine and into a
    replicated engine (flat -> dict split at segment_layout offsets) must
    continue with bit-identical losses at the same dp — the PR 8 ZeRO
    bit-equality claim carried through the restore path."""
    src = _make(dp=8, zero=True, k=2)
    x, y = _batch()
    _losses(src, x, y, 3)
    CheckpointManager(str(tmp_path), async_save=False).save(src, block=True)

    ez = _make(dp=8, zero=True, k=2, seed=1)
    _losses(ez, x, y, 1)
    restore_latest(ez, str(tmp_path))
    er = _make(dp=8, zero=False, k=2, seed=2)
    restore_latest(er, str(tmp_path))
    assert er.opt_state is not None and er._zero_opt is None
    assert _losses(ez, x, y, 3) == _losses(er, x, y, 3)


def test_dict_checkpoint_restores_into_zero_engine(tmp_path):
    """A replicated (dict) checkpoint restores into a ZeRO engine: the
    dict is installed and converted lazily on the next step, matching the
    replicated continuation bit for bit."""
    src = _make(dp=8, zero=False, k=2)
    x, y = _batch()
    _losses(src, x, y, 2)
    CheckpointManager(str(tmp_path), async_save=False).save(src, block=True)
    cont = _losses(src, x, y, 3)

    ez = _make(dp=8, zero=True, k=2, seed=1)
    restore_latest(ez, str(tmp_path))
    assert ez.opt_state is not None  # dict installed, conversion is lazy
    assert _losses(ez, x, y, 3) == cont
    assert ez._zero_opt is not None and ez.opt_state is None  # re-engaged


# ------------------------------------------------- retention / GC / hooks

def test_retention_gc_keeps_newest(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    mgr = CheckpointManager(str(tmp_path), interval=1, keep=2,
                            async_save=False)
    g0 = _stat("ckpt.gc_removed")
    for _ in range(4):
        eng.step(x, y)
        mgr.save(eng, block=True)
    assert [s for s, _ in mgr.checkpoints()] == [3, 4]
    assert _stat("ckpt.gc_removed") == g0 + 2
    # dead-pid tmp sweep: a crashed writer's leftover dir is collected
    stale = os.path.join(str(tmp_path), f"{elastic.TMP_PREFIX}ckpt_9.999999")
    os.makedirs(stale)
    eng.step(x, y)
    mgr.save(eng, block=True)
    assert not os.path.isdir(stale)
    mgr.close()


def test_engine_hook_and_flags_wiring(tmp_path):
    """enable_checkpointing saves on the interval through the step tail,
    run_steps covers its fused window, and FLAGS_ckpt_dir arms the manager
    at engine construction."""
    eng = _make(dp=2)
    x, y = _batch()
    mgr = eng.enable_checkpointing(str(tmp_path), interval=2, keep=10,
                                   async_save=False)
    for _ in range(3):
        eng.step(x, y)
    assert [s for s, _ in mgr.checkpoints()] == [2]
    eng.run_steps(x, y, steps=3)  # steps 4..6: interval hits at 4 and 6
    assert [s for s, _ in mgr.checkpoints()] == [2, 6]
    eng.disable_checkpointing()
    assert eng._ckpt is None

    from paddle_tpu.core import flags as _flags
    saved = _flags.flag("ckpt_dir")
    paddle.set_flags({"ckpt_dir": str(tmp_path / "auto")})
    try:
        eng2 = _make(dp=2)
        assert eng2._ckpt is not None
        assert eng2._ckpt.dirname == str(tmp_path / "auto")
        eng2.disable_checkpointing()
    finally:
        paddle.set_flags({"ckpt_dir": saved})


def test_rollback_on_nonfinite_loss(tmp_path):
    eng = _make(dp=2)
    x, y = _batch()
    mgr = CheckpointManager(str(tmp_path), interval=1, keep=3,
                            async_save=False, rollback_on_nonfinite=True)
    loss = eng.step(x, y)
    mgr.on_step(eng, 1, loss)          # commits ckpt_00000001
    eng.step(x, y)
    r0 = _stat("ckpt.rollbacks")
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        restored = mgr.on_step(eng, 2, float("nan"))
    assert restored == 1 and eng._step_count == 1
    assert _stat("ckpt.rollbacks") == r0 + 1
    assert any("rolled back" in str(w.message) for w in wlog)
    mgr.close()


# ------------------------------------------------------------ fsck + kill

def _fsck(argv):
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        spec = importlib.util.spec_from_file_location(
            "ckpt_fsck", os.path.join(tools, "ckpt_fsck.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main(argv)
    finally:
        sys.path.remove(tools)


def test_fsck_exit_codes(tmp_path, capsys):
    eng = _make(dp=2)
    x, y = _batch()
    ckpts = _two_checkpoints(tmp_path, eng, x, y)
    assert _fsck([str(tmp_path)]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["checked"] == 2 and summary["corrupt"] == 0
    # single-dir mode
    assert _fsck([str(ckpts[0][1]), "--quiet"]) == 0
    capsys.readouterr()
    # corrupt one -> exit 1 and the bad row names it
    payload = sorted(n for n in os.listdir(ckpts[-1][1])
                     if n.endswith(".npy"))[0]
    _corrupt_file(os.path.join(ckpts[-1][1], payload))
    assert _fsck([str(tmp_path)]) == 1
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    assert any(r.get("ok") is False for r in rows[:-1])
    # nothing to verify -> exit 2
    assert _fsck([str(tmp_path / "empty")]) == 2


_VICTIM = textwrap.dedent("""
    import sys

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import TrainStepEngine
    from paddle_tpu.distributed.mesh import (HybridCommunicateGroup,
                                             set_hybrid_communicate_group)

    set_hybrid_communicate_group(None)
    hcg = HybridCommunicateGroup(dp_degree=1, devices=jax.devices()[:1])
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    eng = TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                          hcg=hcg)
    eng.enable_checkpointing(sys.argv[1], interval=1, keep=100,
                             async_save=True)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (32,)).astype(np.int64))
    while True:  # the parent always SIGKILLs; steps are ~ms, saves ~1s
        eng.step(x, y)
        print("STEP", eng._step_count, flush=True)
""")


def test_mid_save_sigkill_leaves_no_torn_checkpoint(tmp_path):
    """SIGKILL a training subprocess while its slowed async writer has an
    uncommitted .tmp dir on disk: every COMMITTED checkpoint still fully
    verifies and restores — the atomic-rename commit point at work."""
    script = tmp_path / "victim.py"
    script.write_text(_VICTIM)
    ckpt_dir = str(tmp_path / "ckpts")
    pp = os.environ.get("PYTHONPATH")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + (os.pathsep + pp if pp else ""),
           "PADDLE_TPU_CKPT_SLOW_WRITE_MS": "60"}
    env.pop("PADDLE_TPU_CKPT_DIR", None)
    proc = subprocess.Popen([sys.executable, str(script), ckpt_dir],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    try:
        last = 0
        for line in proc.stdout:
            if line.startswith("STEP"):
                last = int(line.split()[1])
            if last >= 2 and len(elastic.list_checkpoints(ckpt_dir)) >= 2:
                break
        else:
            pytest.fail(f"victim exited early (rc={proc.wait()})")
        deadline = time.monotonic() + 30.0
        mid_save = False
        while time.monotonic() < deadline:
            if any(n.startswith(elastic.TMP_PREFIX)
                   for n in os.listdir(ckpt_dir)):
                mid_save = True
                break
            time.sleep(0.002)
        assert mid_save, "never caught the writer mid-save (slowed to 60ms/file)"
    finally:
        proc.kill()
        proc.wait()
        proc.stdout.close()

    committed = elastic.list_checkpoints(ckpt_dir)
    assert len(committed) >= 2
    for _step, path in committed:  # crash left zero torn committed state
        verify_checkpoint(path)
    eng = _make(dp=1)
    restored = restore_latest(eng, ckpt_dir)
    assert restored == committed[-1][0] <= last
    x, y = _batch()
    assert np.isfinite(float(eng.step(x, y).item()))

"""Incubate extras: segment/graph ops, fused softmax-mask, fused transformer
layers, functional autograd, auto checkpoint, shared-memory multiprocessing."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSegmentOps:
    def test_segment_sum_mean_max_min(self):
        data = t(np.array([[1.0, 2], [3, 4], [5, 6], [7, 8]], np.float32))
        ids = t(np.array([0, 0, 1, 1], np.int64))
        np.testing.assert_allclose(incubate.segment_sum(data, ids).numpy(),
                                   [[4, 6], [12, 14]])
        np.testing.assert_allclose(incubate.segment_mean(data, ids).numpy(),
                                   [[2, 3], [6, 7]])
        np.testing.assert_allclose(incubate.segment_max(data, ids).numpy(),
                                   [[3, 4], [7, 8]])
        np.testing.assert_allclose(incubate.segment_min(data, ids).numpy(),
                                   [[1, 2], [5, 6]])

    def test_segment_sum_grad(self):
        data = t(np.ones((4, 2), np.float32))
        data.stop_gradient = False
        ids = t(np.array([0, 1, 1, 1], np.int64))
        incubate.segment_sum(data, ids).sum().backward()
        np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 2)))


class TestSoftmaxMaskFuse:
    def test_fuse_matches_composed(self):
        rs = np.random.RandomState(0)
        x = rs.randn(2, 4, 8, 8).astype(np.float32)
        mask = (rs.rand(2, 1, 8, 8) > 0.5).astype(np.float32) * -1e4
        out = incubate.softmax_mask_fuse(t(x), t(mask)).numpy()
        ref = x + mask
        ref = np.exp(ref - ref.max(-1, keepdims=True))
        ref /= ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)

    def test_upper_triangle(self):
        x = t(np.zeros((1, 1, 4, 4), np.float32))
        out = incubate.softmax_mask_fuse_upper_triangle(x).numpy()[0, 0]
        # row i: uniform over first i+1 positions
        for i in range(4):
            np.testing.assert_allclose(out[i, :i + 1], 1.0 / (i + 1), rtol=1e-5)
            np.testing.assert_allclose(out[i, i + 1:], 0.0, atol=1e-7)


class TestGraphOps:
    def test_send_recv_sum_mean(self):
        x = t(np.array([[1.0], [2], [3]], np.float32))
        src = t(np.array([0, 1, 2, 0], np.int64))
        dst = t(np.array([1, 2, 1, 0], np.int64))
        out = incubate.graph_send_recv(x, src, dst, "sum").numpy()
        np.testing.assert_allclose(out, [[1], [4], [2]])
        out_m = incubate.graph_send_recv(x, src, dst, "mean").numpy()
        np.testing.assert_allclose(out_m, [[1], [2], [2]])

    def test_sample_and_reindex(self):
        # CSC graph: node n's neighbors = row[colptr[n]:colptr[n+1]]
        row = t(np.array([1, 2, 0, 2, 0, 1], np.int64))
        colptr = t(np.array([0, 2, 4, 6], np.int64))
        nodes = t(np.array([0], np.int64))
        neigh, cnt = incubate.graph_sample_neighbors(row, colptr, nodes,
                                                     sample_size=-1)
        np.testing.assert_array_equal(np.sort(neigh.numpy()), [1, 2])
        assert cnt.numpy()[0] == 2
        r_src, r_dst, out_nodes = incubate.graph_reindex(nodes, neigh, cnt)
        assert out_nodes.numpy()[0] == 0
        assert (r_dst.numpy() == 0).all()

    def test_khop(self):
        row = t(np.array([1, 2, 0, 2, 0, 1], np.int64))
        colptr = t(np.array([0, 2, 4, 6], np.int64))
        nodes, src, dst = incubate.graph_khop_sampler(
            row, colptr, t(np.array([0], np.int64)), [2, 2])
        assert set(nodes.numpy().tolist()) == {0, 1, 2}
        assert len(src.numpy()) == len(dst.numpy()) > 0


class TestFusedLayers:
    def test_fused_mha_shapes_and_grad(self):
        paddle.seed(0)
        m = incubate.nn.FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                                attn_dropout_rate=0.0)
        x = t(np.random.RandomState(0).randn(2, 6, 32).astype(np.float32))
        x.stop_gradient = False
        out = m(x)
        assert out.shape == [2, 6, 32]
        out.sum().backward()
        assert m.qkv_weight.grad is not None

    def test_fused_encoder_layer_trains(self):
        paddle.seed(0)
        layer = incubate.nn.FusedTransformerEncoderLayer(
            32, 4, 64, dropout_rate=0.0)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=layer.parameters())
        rs = np.random.RandomState(0)
        x = t(rs.randn(4, 6, 32).astype(np.float32))
        y = t(rs.randn(4, 6, 32).astype(np.float32))
        losses = []
        for _ in range(10):
            loss = ((layer(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_normalize_before(self):
        m = incubate.nn.FusedFeedForward(16, 32, dropout_rate=0.0,
                                         normalize_before=True)
        x = t(np.random.RandomState(0).randn(2, 3, 16).astype(np.float32))
        assert m(x).shape == [2, 3, 16]


class TestFunctionalAutograd:
    def test_vjp(self):
        func = lambda x: (x * x).sum()
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        out, grad = incubate.autograd.vjp(func, x)
        np.testing.assert_allclose(float(out), 14.0)
        np.testing.assert_allclose(grad.numpy(), [2, 4, 6])

    def test_jvp(self):
        func = lambda x: x * x
        x = t(np.array([1.0, 2.0], np.float32))
        v = t(np.array([1.0, 0.0], np.float32))
        out, jv = incubate.autograd.jvp(func, x, v)
        np.testing.assert_allclose(jv.numpy(), [2.0, 0.0])

    def test_jacobian(self):
        func = lambda x: x * x
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        J = incubate.autograd.Jacobian(func, x)
        np.testing.assert_allclose(J[:].numpy(), np.diag([2.0, 4.0, 6.0]))
        assert J.shape == [3, 3]

    def test_hessian(self):
        func = lambda x: (x * x).sum()
        x = t(np.array([1.0, 2.0], np.float32))
        H = incubate.autograd.Hessian(func, x)
        np.testing.assert_allclose(H[:].numpy(), 2 * np.eye(2), atol=1e-6)


class TestAutoCheckpoint:
    def test_resume_epoch_range(self, tmp_path):
        import paddle_tpu.nn as nn

        epochs_run = []
        paddle.seed(0)
        m = nn.Linear(2, 2)
        rng = incubate.checkpoint.train_epoch_range(5, save_dir=str(tmp_path),
                                                    name="job1").bind(model=m)
        for epoch in rng:
            epochs_run.append(epoch)
            m.weight.set_value(np.full((2, 2), float(epoch), np.float32))
            if epoch == 2:
                break  # simulated crash DURING epoch 2 (before its snapshot)

        # "restart": epoch 2 wasn't snapshotted, so it reruns; weights restore
        # from the last completed epoch (1)
        m2 = nn.Linear(2, 2)
        rng2 = incubate.checkpoint.train_epoch_range(5, save_dir=str(tmp_path),
                                                     name="job1").bind(model=m2)
        resumed = []
        for epoch in rng2:
            if not resumed:
                np.testing.assert_allclose(m2.weight.numpy()[0, 0], 1.0)
            resumed.append(epoch)
        assert resumed == [2, 3, 4]


class TestSharedMemory:
    def test_tensor_crosses_process(self):
        import multiprocessing as mp

        import paddle_tpu.incubate.multiprocessing  # installs reducers

        ctx = mp.get_context("spawn")
        q_in, q_out = ctx.Queue(), ctx.Queue()
        x = t(np.arange(6, dtype=np.float32).reshape(2, 3))
        p = ctx.Process(target=_echo_worker, args=(q_in, q_out))
        p.start()
        q_in.put(x)
        out = q_out.get(timeout=60)
        p.join(timeout=30)
        np.testing.assert_allclose(np.asarray(out), x.numpy() * 2)


def _echo_worker(q_in, q_out):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.incubate.multiprocessing  # noqa: F401

    t_in = q_in.get(timeout=30)
    q_out.put(t_in.numpy() * 2)
